#!/usr/bin/env python
"""graftd chaos harness (ISSUE 8): the Jepsen discipline applied to the
checking service itself — drive sustained load while injecting the
faults a production daemon actually meets, then assert the service
invariants over the whole request history.

Faults injected:
  * SIGKILL of the daemon process mid-flight (+ restart on the same
    store dir — the write-ahead journal's flagship case)
  * worker-thread death (a BaseException escaping batch execution —
    the poison-batch/crash-cap path)
  * injected device failures (RuntimeError mid-check — the
    degrade-to-host path)
  * slow + failing journal IO (fsync raising / stalling — durability
    degrades, availability must not)
  * a hung batch (wedged device-launch stand-in — the watchdog path)
  * SIGKILL of a WHOLE REPLICA in a shared-store cluster (ISSUE 11 —
    `--replicas N`): a surviving replica must claim and finish the
    dead one's journal.

Invariants asserted (the ISSUE-8 acceptance bar, held CLUSTER-WIDE by
the ISSUE-11 replica phase):
  1. NOTHING ACCEPTED IS LOST: every request the daemon 202'd reaches a
     terminal state, including across SIGKILL+restart — and across a
     whole-replica kill, via cross-replica journal handoff.
  2. RECOVERED VERDICTS ARE TRUE VERDICTS: every DONE verdict equals a
     direct `check_histories` of the same history.
  3. IDEMPOTENT RESUBMISSION EXECUTES AT MOST ONCE: a duplicate
     fingerprint attaches or cache-hits (cluster-wide: the shared
     result store answers it on ANY replica); the observed execution
     count does not grow.
  4. NO WEDGED QUEUES: after every fault phase the daemon still serves
     a fresh healthy submission and its queue drains.
  5. NO ORPHANED JOURNAL ENTRY AFTER LEASE EXPIRY (cluster): once the
     dead replica's lease expires and the handoff completes, no journal
     dir, claim dir, or lease of the dead replica remains.
  6. NO DOUBLE-OWNERSHIP OF A HANDED-OFF ENTRY (cluster): exactly one
     surviving replica claims the dead WAL (claims are atomic renames).
  Plus the ablation: JGRAFT_SERVICE_JOURNAL=0 restores the in-memory
  daemon (no journal dir; a kill loses pending work — today's
  behavior, on purpose; cluster-wide, a killed journal-less replica's
  pending work is lost by design too).

Usage:
  python scripts/chaos_graftd.py --quick          # CI-sized (~30 s)
  python scripts/chaos_graftd.py                  # fuller soak
  python scripts/chaos_graftd.py --replicas 3     # bigger cluster
  python scripts/chaos_graftd.py --cluster-only   # replica phase only
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from jepsen_jgroups_raft_tpu.platform import pin_cpu  # noqa: E402

FAILURES: list = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"  [{tag}] {what}")
    if not ok:
        FAILURES.append(what)


def make_histories(rng: random.Random, n: int):
    """(history, expected_valid) pairs — a mix of valid and impossible
    histories with DISTINCT content (so each is its own fingerprint)."""
    from jepsen_jgroups_raft_tpu.history.synth import (build_history,
                                                       random_valid_history)

    out = []
    for i in range(n):
        if i % 4 == 3:
            rows = []
            for j in range(19):
                v = i * 100_000 + j
                rows += [(0, "invoke", "write", v), (0, "ok", "write", v)]
            rows += [(1, "invoke", "read", None), (1, "ok", "read", -7)]
            out.append((build_history(rows), False))
        else:
            out.append((random_valid_history(
                random.Random(rng.randrange(1 << 30)), "register",
                n_ops=20, crash_p=0.0), True))
    return out


def direct_verdicts(pairs):
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_histories
    from jepsen_jgroups_raft_tpu.models import CasRegister

    got = [r["valid?"] for r in
           check_histories([h for h, _ in pairs], CasRegister())]
    want = [v for _, v in pairs]
    check(got == want, f"direct check_histories agrees with synthesis "
                       f"({sum(1 for v in want if v)} valid / "
                       f"{len(want) - sum(1 for v in want if v)} invalid)")
    return got


# --------------------------------------------------- phase 1: SIGKILL


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_daemon(store: str, extra_env: dict, client_timeout=120.0):
    from jepsen_jgroups_raft_tpu.service import ServiceClient

    port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", **extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "jepsen_jgroups_raft_tpu", "serve-checker",
         "--store", store, "--host", "127.0.0.1", "--port", str(port)],
        env=env, cwd=str(REPO),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        client = ServiceClient(f"http://127.0.0.1:{port}", max_attempts=4,
                               backoff_base_s=0.2, backoff_cap_s=1.0,
                               timeout=client_timeout)
        deadline = time.monotonic() + 120
        while True:
            try:
                client.healthz()
                return proc, client  # ownership transfers to the caller
            except OSError:
                if proc.poll() is not None:
                    raise RuntimeError("daemon died on boot")
                if time.monotonic() > deadline:
                    raise RuntimeError("daemon did not come up in 120s")
                time.sleep(0.3)
    except Exception:
        # a daemon we failed to hand to the caller must not outlive us
        proc.kill()  # lint: allow(unhealed) — boot failed; no restart
        raise


def await_terminal(client, request_id: str, timeout_s: float) -> dict:
    deadline = time.monotonic() + timeout_s
    rec = client.result(request_id, wait_s=30.0)
    while rec["status"] not in ("done", "failed", "cancelled"):
        if time.monotonic() > deadline:
            return rec
        rec = client.result(request_id, wait_s=30.0)
    return rec


def phase_sigkill(n_requests: int, rng: random.Random) -> None:
    print("phase 1: SIGKILL mid-flight + restart on the same store")
    pairs = make_histories(rng, n_requests)
    want = direct_verdicts(pairs)
    with tempfile.TemporaryDirectory(prefix="chaos-graftd-") as store:
        _phase_sigkill(store, pairs, want, rng)


def _phase_sigkill(store, pairs, want, rng) -> None:
    # long linger on the first daemon: every accepted request is still
    # pending when the kill lands — the worst case for durability
    proc, client = spawn_daemon(store,
                                {"JGRAFT_SERVICE_BATCH_WAIT_MS": "30000"})
    recs, dup_recs = [], []
    try:
        for h, _ in pairs:
            recs.append(client.submit([h], workload="register"))
        # idempotent duplicates of the first two payloads, accepted
        # BEFORE the kill: they must attach (not re-execute) and also
        # reach terminal states after the restart
        for h, _ in pairs[:2]:
            dup_recs.append(client.submit([h], workload="register"))
        check(all(r["status"] == "queued" for r in recs),
              f"{len(recs)} submissions accepted (202) and pending")
        check(all(r.get("attached_to") for r in dup_recs),
              "pre-kill duplicates attached to live primaries")
    finally:
        # the fault under test: heal = the restart two lines down
        os.kill(proc.pid, signal.SIGKILL)  # lint: allow(unhealed)
        proc.wait(30)
    print("  ... SIGKILL delivered; restarting on the same store")

    proc, client = spawn_daemon(store, {})
    try:
        outs = [await_terminal(client, r["id"], 600) for r in recs]
        check(all(o["status"] == "done" for o in outs),
              "invariant 1: every 202'd request reached a terminal "
              "state after restart "
              f"({[o['status'] for o in outs].count('done')}/{len(outs)} "
              "done)")
        got = [o.get("valid?") for o in outs]
        check(got == want,
              "invariant 2: recovered verdicts identical to direct "
              "check_histories")
        check(all(o.get("replayed") for o in outs),
              "recovered requests are journal replays, not re-submissions")
        dup_outs = [await_terminal(client, r["id"], 600)
                    for r in dup_recs]
        check([o.get("valid?") for o in dup_outs] == want[:2]
              and all(o["status"] == "done" for o in dup_outs),
              "pre-kill duplicates reached the same verdicts")
        stats = client.stats()
        check(stats["recovered_requests"] >= len(recs),
              f"journal replayed {stats['recovered_requests']} requests")
        check(stats["journal_enabled"] is True, "journal enabled")
        # invariant 3 across the restart: resubmit an already-verified
        # payload — it must short-circuit (cache hit), not re-execute
        batches_before = client.stats()["batches"]
        resub = client.submit([pairs[0][0]], workload="register")
        check(resub.get("cached") is True,
              "invariant 3: post-restart resubmission is a cache hit")
        check(client.stats()["batches"] == batches_before,
              "invariant 3: resubmission launched no new batch")
        # invariant 4: the restarted daemon still serves fresh work
        fresh = client.submit(
            [make_histories(rng, 1)[0][0]], workload="register")
        out = await_terminal(client, fresh["id"], 600)
        check(out["status"] == "done",
              "invariant 4: fresh submission after recovery completes")
    finally:
        proc.kill()  # lint: allow(unhealed) — phase over, harness exits
        proc.wait(30)


def phase_journal_off(rng: random.Random) -> None:
    print("phase 2: JGRAFT_SERVICE_JOURNAL=0 ablation "
          "(in-memory daemon, kill loses pending work — by design)")
    pairs = make_histories(rng, 2)
    with tempfile.TemporaryDirectory(
            prefix="chaos-graftd-nojournal-") as store:
        proc, client = spawn_daemon(store, {
            "JGRAFT_SERVICE_JOURNAL": "0",
            "JGRAFT_SERVICE_BATCH_WAIT_MS": "30000"})
        try:
            recs = [client.submit([h], workload="register")
                    for h, _ in pairs]
            check(client.stats()["journal_enabled"] is False,
                  "journal reported disabled")
        finally:
            # the fault under test; heal = the restart below
            os.kill(proc.pid, signal.SIGKILL)  # lint: allow(unhealed)
            proc.wait(30)
        check(not (Path(store) / "graftd" / "journal").exists(),
              "no journal directory created")
        proc, client = spawn_daemon(store, {"JGRAFT_SERVICE_JOURNAL": "0"})
        try:
            from jepsen_jgroups_raft_tpu.service import ServiceError

            lost = 0
            for r in recs:
                try:
                    client.result(r["id"])
                except ServiceError as e:
                    if e.status == 404:
                        lost += 1
            check(lost == len(recs)
                  and client.stats()["recovered_requests"] == 0,
                  "pending requests lost across the kill — today's "
                  "in-memory behavior restored")
        finally:
            proc.kill()  # lint: allow(unhealed) — phase over
            proc.wait(30)


# ------------------------------- phase 2b: streaming sessions (ISSUE 12)


def _chop(history, n_segments: int):
    ops = [op.to_dict() for op in history.client_ops()]
    k = max(1, -(-len(ops) // n_segments))
    return [ops[i:i + k] for i in range(0, len(ops), k)]


def phase_stream_sigkill(rng: random.Random) -> None:
    """SIGKILL mid-stream + kill-the-client (ISSUE 12): nothing
    appended is lost, the resumed session's verdict equals a direct
    check of the full history, and a violation already surfaced
    mid-run survives the restart at the same deciding segment."""
    print("phase 2b: streaming sessions — SIGKILL mid-stream, "
          "kill-the-client, violation-at-segment across restart")
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_histories
    from jepsen_jgroups_raft_tpu.history.synth import (build_history,
                                                       random_valid_history)
    from jepsen_jgroups_raft_tpu.models import CasRegister
    from jepsen_jgroups_raft_tpu.service import ServiceClient

    good = random_valid_history(random.Random(rng.randrange(1 << 30)),
                                "register", n_ops=48, crash_p=0.05)
    good_segs = _chop(good, 3)
    rows = []
    for j in range(8):
        rows += [(0, "invoke", "write", j), (0, "ok", "write", j)]
    bad = build_history(rows + [(1, "invoke", "read", None),
                                (1, "ok", "read", -7)])
    bad_ops = [op.to_dict() for op in bad.client_ops()]
    [want_good] = [r["valid?"] for r in
                   check_histories([good.client_ops()], CasRegister())]
    with tempfile.TemporaryDirectory(prefix="chaos-graftd-stream-") \
            as store:
        proc, client = spawn_daemon(store, {})
        try:
            s = client.stream(workload="register")
            for seg in good_segs[:2]:
                s.append(seg)
            sid_good = s.session_id
            # the seeded violation: surfaces at the SECOND append,
            # mid-run — not at finish
            v = client.stream(workload="register")
            out1 = v.append(bad_ops[:16])
            out2 = v.append(bad_ops[16:])
            sid_bad = v.session_id
            check("violation" not in out1 and
                  out2.get("violation", {}).get("decided-at-segment") == 2,
                  "violation surfaced mid-run at the deciding segment "
                  "(before any finish)")
        finally:
            # the fault under test; heal = the restart below
            os.kill(proc.pid, signal.SIGKILL)  # lint: allow(unhealed)
            proc.wait(30)
        print("  ... SIGKILL delivered mid-stream; restarting")
        proc, client2 = spawn_daemon(store, {})
        try:
            # kill-the-client is the same recovery shape: this is a NEW
            # client process resuming by session id
            s2 = client2.stream(workload="register",
                                session_id=sid_good, resume=True)
            check(s2.last_state.get("status") == "incomplete"
                  or s2.seq == 3,
                  "restored session is resumable with both pre-kill "
                  f"segments intact (next_seq={s2.seq})")
            check(s2.seq == 3,
                  "nothing appended was lost across the SIGKILL "
                  f"(next_seq={s2.seq})")
            for seg in good_segs[2:]:
                s2.append(seg)
            fin = s2.finish()
            check(fin["status"] == "done"
                  and fin["valid?"] is want_good and fin.get("resumed"),
                  "resumed stream verdict equals the direct "
                  "check_histories verdict")
            vstat = client2._call(
                "GET", f"/stream/status?session={sid_bad}")
            fin_bad = client2._call("POST", "/stream/finish",
                                    {"session": sid_bad})
            viol = fin_bad["results"][0]
            check(fin_bad["valid?"] is False
                  and viol.get("decided-at-segment") == 2,
                  "pre-kill violation survives the restart at the same "
                  "deciding segment "
                  f"(status-resumable={vstat.get('status')!r})")
            st = client2.stats()
            check(st["resumed_sessions"] >= 2,
                  f"journal resumed {st['resumed_sessions']} sessions")
        finally:
            proc.kill()  # lint: allow(unhealed) — phase over
            proc.wait(30)


# ------------------------------------- phase 3: in-process fault storm


class Boom(BaseException):
    """Escapes `except Exception` — kills the executor thread."""


def phase_fault_storm(n_requests: int, rng: random.Random) -> None:
    """Worker-thread death + injected device failures + flaky/slow
    journal IO under concurrent load; then a poison batch and a hung
    batch. In-process so the faults can be injected surgically."""
    print("phase 3: in-process fault storm "
          "(worker death, device failure, journal IO faults)")
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_encoded
    from jepsen_jgroups_raft_tpu.service import CheckingService

    pairs = make_histories(rng, n_requests)
    # deterministic fault plan, one entry consumed per check call
    plan = [rng.random() for _ in range(n_requests * 4)]
    calls = {"n": 0}
    lock = threading.Lock()

    def chaotic_check(encs, model, algorithm="auto", **kw):
        with lock:
            i = calls["n"]
            calls["n"] += 1
        p = plan[i % len(plan)]
        if p < 0.15:
            raise Boom("injected worker death")
        if p < 0.30:
            raise RuntimeError("injected device failure")
        return check_encoded(encs, model, algorithm=algorithm, **kw)

    with tempfile.TemporaryDirectory(
            prefix="chaos-graftd-storm-") as storm_root:
        _fault_storm(storm_root, chaotic_check, pairs, rng)


def _fault_storm(storm_root: str, chaotic_check, pairs,
                 rng: random.Random) -> None:
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_encoded
    from jepsen_jgroups_raft_tpu.service import CheckingService

    svc = CheckingService(store_root=storm_root, batch_wait=0.0,
                          check_fn=chaotic_check, crash_cap=3)

    # flaky + slow journal IO, injected UNDER the journal's own OSError
    # handling (_handle is called inside _append's try): durability
    # degrades (journal_errors counts), admission must not
    orig_handle = svc._journal._handle

    def flaky_handle():
        p = rng.random()
        if p < 0.10:
            time.sleep(0.05)  # slow disk
        if p < 0.20:
            raise OSError("injected journal IO failure")
        return orig_handle()

    svc._journal._handle = flaky_handle

    from jepsen_jgroups_raft_tpu.service import QueueFull

    reqs: list = []
    try:
        threads = []

        def submitter(lo, hi):
            for h, _ in pairs[lo:hi]:
                while True:
                    try:
                        reqs.append(svc.submit([h], workload="register"))
                        break
                    except QueueFull as e:
                        time.sleep(min(e.retry_after_s, 1.0))

        step = max(1, len(pairs) // 4)
        for lo in range(0, len(pairs), step):
            t = threading.Thread(
                target=submitter, args=(lo, lo + step), daemon=True)
            threads.append(t)
            t.start()
        for t in threads:
            t.join(120)
        check(len(reqs) == len(pairs),
              f"all {len(pairs)} submissions admitted under fault storm")
        done = all(r.wait(300) for r in reqs)
        check(done, "invariant 1: every admitted request reached a "
                    "terminal state under injected faults")
        want_by_fp = {_fp(h): v for h, v in pairs}
        mismatches = [r.id for r in reqs if r.status == "done"
                      and r.verdict() is not want_by_fp[r.fingerprint]]
        check(not mismatches,
              "invariant 2: every DONE verdict matches the direct check"
              + (f" (mismatched: {mismatches})" if mismatches else ""))
        st = svc.stats()
        check(st["journal_errors"] >= 1,
              f"journal IO faults were absorbed, not fatal "
              f"(journal_errors={st['journal_errors']})")
        # invariant 4: daemon not wedged — healthy request completes
        svc.scheduler.check_fn = check_encoded
        svc._journal._handle = orig_handle
        ok = svc.submit([make_histories(rng, 1)[0][0]],
                        workload="register")
        check(ok.wait(120) and ok.status == "done",
              "invariant 4: daemon serves cleanly after the storm "
              f"(worker_restarts={st['worker_restarts']}, "
              f"degraded_batches={st['degraded_batches']}, "
              f"quarantined={st['quarantined']})")
        check(svc.queue.depth == 0, "invariant 4: queue fully drained")
    finally:
        svc.shutdown(wait=True)


_FP_CACHE: dict = {}


def _fp(history) -> str:
    """Fingerprint a single-history register submission the same way
    admission does (for matching storm results back to expectations)."""
    key = id(history)
    if key not in _FP_CACHE:
        from jepsen_jgroups_raft_tpu.history.packing import encode_history
        from jepsen_jgroups_raft_tpu.models import CasRegister
        from jepsen_jgroups_raft_tpu.service.request import (
            fingerprint_encodings)

        m = CasRegister()
        _FP_CACHE[key] = fingerprint_encodings(
            m, "auto", [encode_history(history.client_ops(), m)])
    return _FP_CACHE[key]


def phase_poison_and_hang(rng: random.Random) -> None:
    print("phase 4: poison batch + hung batch")
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_encoded
    from jepsen_jgroups_raft_tpu.service import CheckingService

    # poison: a deterministically executor-killing batch must be
    # quarantined after the crash cap, not respawn the worker forever
    def dying(encs, model, algorithm="auto", **kw):
        raise Boom("deterministic poison")

    svc = CheckingService(store_root=None, batch_wait=0.0,
                          check_fn=dying, crash_cap=2)
    poison = svc.submit([make_histories(rng, 1)[0][0]],
                        workload="register")
    got = poison.wait(120)
    st = svc.stats()
    check(got and poison.status == "failed"
          and "quarantined" in (poison.error or ""),
          f"poison batch quarantined after {st['worker_restarts']} "
          "executor deaths (bounded, not forever)")
    svc.scheduler.check_fn = check_encoded
    ok = svc.submit([make_histories(rng, 1)[0][0]], workload="register")
    check(ok.wait(120) and ok.status == "done",
          "invariant 4: queue not wedged after quarantine")
    svc.shutdown(wait=True)

    # hung batch: the watchdog must rescue it via the host ladder
    release = threading.Event()

    def hanging(encs, model, algorithm="auto", **kw):
        release.wait(60)
        return check_encoded(encs, model, algorithm=algorithm, **kw)

    svc = CheckingService(store_root=None, batch_wait=0.0,
                          check_fn=hanging, watchdog_margin_s=0.3)
    try:
        req = svc.submit([make_histories(rng, 1)[0][0]],
                         workload="register", deadline_ms=300)
        got = req.wait(120)
        check(got and req.status == "done"
              and all("platform-degraded" in r for r in req.results),
              "hung batch rescued by the watchdog via the host ladder "
              f"(watchdog_requeues={svc.stats()['watchdog_requeues']})")
        svc.scheduler.check_fn = check_encoded
        ok = svc.submit([make_histories(rng, 1)[0][0]],
                        workload="register")
        check(ok.wait(120) and ok.status == "done",
              "invariant 4: queue not wedged after the hang")
    finally:
        release.set()
        svc.shutdown(wait=True)


# --------------------------------------- phase 5: whole-replica SIGKILL


def spawn_replica(cdir: str, store: str, rid: str, extra_env: dict):
    """One cluster member: a serve-checker subprocess registered in the
    shared cluster dir with a fast lease (ttl 1 s, skew 0.2 s, so a
    kill hands off within a couple of seconds)."""
    env = {
        "JGRAFT_SERVICE_CLUSTER_DIR": cdir,
        "JGRAFT_SERVICE_REPLICA_ID": rid,
        "JGRAFT_CLUSTER_TTL_S": "1.0",
        "JGRAFT_CLUSTER_SKEW_S": "0.2",
        **extra_env,
    }
    return spawn_daemon(store, env)


def await_cluster_terminal(client, request_id: str,
                           timeout_s: float) -> dict:
    """await_terminal that tolerates the handoff window: between the
    kill and the survivor's adoption the id answers 404 on every
    replica — keep polling until the claim lands or the deadline."""
    from jepsen_jgroups_raft_tpu.service import ServiceError

    deadline = time.monotonic() + timeout_s
    while True:
        try:
            rec = client.result(request_id, wait_s=10.0)
            if rec["status"] in ("done", "failed", "cancelled") \
                    or time.monotonic() > deadline:
                return rec
        except ServiceError as e:
            if e.status != 404 or time.monotonic() > deadline:
                raise
            time.sleep(0.25)


def phase_cluster(n_requests: int, rng: random.Random,
                  n_replicas: int) -> None:
    print(f"phase 5: clustered graftd — whole-replica SIGKILL "
          f"({n_replicas} replicas, shared store + journal handoff)")
    n = max(2, min(n_requests, 8))
    pairs = make_histories(rng, n)
    want = direct_verdicts(pairs)
    with tempfile.TemporaryDirectory(prefix="chaos-graftd-cluster-") \
            as cdir:
        _phase_cluster(cdir, pairs, want, rng, n_replicas)
    _phase_cluster_ablation(rng)


def _phase_cluster(cdir, pairs, want, rng, n_replicas: int) -> None:
    from jepsen_jgroups_raft_tpu.service import ServiceClient

    procs, clients = [], []
    try:
        # replica 0 is the victim: a huge batch-formation linger keeps
        # every accepted request pending when the kill lands (the worst
        # case for the handoff)
        for k in range(n_replicas):
            extra = ({"JGRAFT_SERVICE_BATCH_WAIT_MS": "30000"}
                     if k == 0 else {})
            p, c = spawn_replica(cdir, os.path.join(cdir, f"store-r{k}"),
                                 f"r{k}", extra)
            procs.append(p)
            clients.append(c)
        urls = [f"http://{c.netloc}" for c in clients]
        survivors = clients[1:]
        fleet = ServiceClient(urls[1], replicas=urls[2:] + [urls[0]],
                              max_attempts=6, backoff_base_s=0.2,
                              backoff_cap_s=1.0, timeout=120.0)

        # cross-replica store hit BEFORE the kill: a fingerprint first
        # checked on replica 1 must answer from replica 0 (the lingerer
        # — only a launch-free admission-time hit returns done there)
        first = survivors[0].submit([pairs[0][0]], workload="register")
        out = await_terminal(survivors[0], first["id"], 300)
        check(out["status"] == "done" and out.get("valid?") == want[0],
              "cluster store: replica 1 verified the seed fingerprint")
        b0 = clients[0].stats()["batches"]
        xrep = clients[0].submit([pairs[0][0]], workload="register")
        check(xrep.get("cached") is True
              and clients[0].stats()["batches"] == b0
              and clients[0].stats()["store_hits"] >= 1,
              "invariant 3 cluster-wide: replica 0 answered replica 1's "
              "fingerprint from the shared store without a kernel launch")

        # pending load on the victim, plus an idempotent duplicate
        recs = [clients[0].submit([h], workload="register")
                for h, _ in pairs[1:]]
        dup = clients[0].submit([pairs[1][0]], workload="register")
        check(all(r["status"] == "queued" for r in recs)
              and dup.get("attached_to"),
              f"{len(recs)} pending + 1 attached duplicate accepted on "
              "the victim replica")

        # an OPEN stream session on the victim (ISSUE 12): the claim
        # must carry it to a survivor, resumable, verdict intact
        from jepsen_jgroups_raft_tpu.history.synth import (
            random_valid_history)

        sh = random_valid_history(random.Random(20260812), "register",
                                  n_ops=36, crash_p=0.0)
        ssegs = _chop(sh, 3)
        vs = clients[0].stream(workload="register")
        for seg in ssegs[:2]:
            vs.append(seg)
        stream_sid = vs.session_id

        os.kill(procs[0].pid, signal.SIGKILL)  # lint: allow(unhealed)
        procs[0].wait(30)  # heal = the surviving replicas' handoff
        print("  ... replica r0 SIGKILL'd; awaiting lease expiry + "
              "journal handoff")

        outs = [await_cluster_terminal(fleet, r["id"], 120) for r in recs]
        check(all(o["status"] == "done" for o in outs),
              "invariant 1 cluster-wide: every request accepted by the "
              "dead replica reached a terminal state on a survivor "
              f"({[o['status'] for o in outs].count('done')}/{len(outs)} "
              "done)")
        got = [o.get("valid?") for o in outs]
        check(got == want[1:],
              "invariant 2 cluster-wide: handed-off verdicts identical "
              "to direct check_histories")
        dup_out = await_cluster_terminal(fleet, dup["id"], 120)
        check(dup_out["status"] == "done"
              and dup_out.get("valid?") == want[1],
              "the attached duplicate reached the same verdict via the "
              "handoff")

        stats = [c.stats() for c in survivors]
        claims = sum(s["handoff_claims"] for s in stats)
        check(claims == 1,
              "invariant 6: exactly one survivor claimed the dead WAL "
              f"(claims per survivor: {[s['handoff_claims'] for s in stats]})")
        handed = sum(s["handoff_requests"] for s in stats)
        check(handed == len(recs) + 1,
              f"all {len(recs) + 1} journaled entries were re-owned "
              f"(handoff_requests={handed})")

        # invariant 5: nothing orphaned once the handoff completed
        jroot = Path(cdir) / "journal"
        live_dirs = sorted(p.name for p in jroot.iterdir() if p.is_dir())
        check("r0" not in live_dirs
              and not any(".claim." in d for d in live_dirs),
              f"invariant 5: no orphaned journal/claim dir for the dead "
              f"replica (journal dirs: {live_dirs})")
        leases = sorted(p.name for p in (Path(cdir) / "leases").glob("*"))
        check("r0.json" not in leases,
              f"invariant 5: dead replica's lease reaped (leases: "
              f"{leases})")

        # invariant 3 again, across the kill: a payload the dead
        # replica completed via handoff must now be a store hit
        s0 = survivors[0].stats()
        resub = survivors[0].submit([pairs[1][0]], workload="register")
        s1 = survivors[0].stats()
        check(resub.get("cached") is True and s1["batches"] == s0["batches"],
              "invariant 3: post-kill resubmission is a cluster store/"
              "cache hit, no new batch")

        # the open stream session was claimed with the WAL: find the
        # survivor that adopted it and resume there (ISSUE 12)
        from jepsen_jgroups_raft_tpu.checker.linearizable import (
            check_histories)
        from jepsen_jgroups_raft_tpu.models import CasRegister
        from jepsen_jgroups_raft_tpu.service import ServiceError

        adopter = None
        for c in survivors:
            try:
                c._call("GET", f"/stream/status?session={stream_sid}")
                adopter = c
                break
            except (ServiceError, OSError):
                continue
        check(adopter is not None,
              "a survivor adopted the victim's open stream session")
        if adopter is not None:
            rs = adopter.stream(workload="register",
                                session_id=stream_sid, resume=True)
            check(rs.seq == 3,
                  "no appended stream segment lost across the replica "
                  f"kill (next_seq={rs.seq})")
            for seg in ssegs[2:]:
                rs.append(seg)
            sfin = rs.finish()
            [swant] = [r["valid?"] for r in check_histories(
                [sh.client_ops()], CasRegister())]
            check(sfin["status"] == "done" and sfin["valid?"] is swant,
                  "cross-replica-resumed stream verdict equals the "
                  "direct check")
            sstats = [c.stats() for c in survivors]
            check(sum(s.get("handoff_streams", 0) for s in sstats) >= 1,
                  "stream handoff counted on exactly the claiming "
                  f"survivor (handoff_streams="
                  f"{[s.get('handoff_streams', 0) for s in sstats]})")

        # invariant 4: every survivor still serves fresh work
        for i, c in enumerate(survivors):
            fresh = c.submit([make_histories(rng, 1)[0][0]],
                             workload="register")
            o = await_terminal(c, fresh["id"], 300)
            check(o["status"] == "done",
                  f"invariant 4: survivor r{i + 1} serves fresh work "
                  "after the kill")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()  # lint: allow(unhealed) — phase over
                p.wait(30)


def _phase_cluster_ablation(rng: random.Random) -> None:
    """JGRAFT_SERVICE_JOURNAL=0 across the cluster: a killed replica's
    pending work is LOST (no WAL to hand off) — by design; the
    survivors must stay healthy and claim nothing."""
    print("  ... cluster ablation: JGRAFT_SERVICE_JOURNAL=0 "
          "(kill loses the victim's pending work — by design)")
    from jepsen_jgroups_raft_tpu.service import ServiceError

    pairs = make_histories(rng, 2)
    with tempfile.TemporaryDirectory(
            prefix="chaos-graftd-cluster-nojournal-") as cdir:
        pv, cv = spawn_replica(cdir, os.path.join(cdir, "store-r0"), "r0",
                               {"JGRAFT_SERVICE_JOURNAL": "0",
                                "JGRAFT_SERVICE_BATCH_WAIT_MS": "30000"})
        ps, cs = spawn_replica(cdir, os.path.join(cdir, "store-r1"), "r1",
                               {"JGRAFT_SERVICE_JOURNAL": "0"})
        try:
            recs = [cv.submit([h], workload="register") for h, _ in pairs]
            os.kill(pv.pid, signal.SIGKILL)  # lint: allow(unhealed)
            pv.wait(30)
            time.sleep(3.0)  # lease expiry (1.2 s) + a scan period
            lost = 0
            for r in recs:
                try:
                    cs.result(r["id"])
                except ServiceError as e:
                    if e.status == 404:
                        lost += 1
            st = cs.stats()
            check(lost == len(recs) and st["handoff_claims"] == 0,
                  "ablation: journal-less victim's pending work lost, "
                  "survivor claimed nothing — losing work only where "
                  "designed")
            check(not (Path(cdir) / "journal" / "r0").exists(),
                  "ablation: no journal dir for the journal-less victim")
            fresh = cs.submit([make_histories(rng, 1)[0][0]],
                              workload="register")
            out = await_terminal(cs, fresh["id"], 300)
            check(out["status"] == "done",
                  "ablation: survivor still serves fresh work")
        finally:
            for p in (pv, ps):
                if p.poll() is None:
                    p.kill()  # lint: allow(unhealed) — phase over
                    p.wait(30)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized run (fewer requests, one kill cycle)")
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per phase (default 8 quick / 32 full)")
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--skip-subprocess", action="store_true",
                    help="skip the SIGKILL phases (in-process only)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="replica count for the cluster phase "
                         "(default 2; 0/1 skips it)")
    ap.add_argument("--cluster-only", action="store_true",
                    help="run only the cluster phase (the CI cluster "
                         "smoke stage)")
    ap.add_argument("--stream-only", action="store_true",
                    help="run only the streaming-session phase (the CI "
                         "streaming smoke stage)")
    args = ap.parse_args()
    n = args.requests or (8 if args.quick else 32)
    rng = random.Random(args.seed)
    n_replicas = args.replicas if args.replicas is not None else 2

    pin_cpu(8)
    t0 = time.monotonic()
    if args.cluster_only:
        phase_cluster(n, rng, max(2, n_replicas))
    elif args.stream_only:
        phase_stream_sigkill(rng)
    else:
        if not args.skip_subprocess:
            phase_sigkill(n, rng)
            phase_journal_off(rng)
            phase_stream_sigkill(rng)
        phase_fault_storm(n, rng)
        phase_poison_and_hang(rng)
        if n_replicas >= 2 and not args.skip_subprocess:
            phase_cluster(n, rng, n_replicas)

    wall = time.monotonic() - t0
    print(json.dumps({"chaos_graftd": "fail" if FAILURES else "pass",
                      "failures": FAILURES, "requests_per_phase": n,
                      "replicas": (max(2, n_replicas) if args.cluster_only
                                   else n_replicas
                                   if not args.skip_subprocess else 0),
                      "wall_s": round(wall, 1)}))
    return 1 if FAILURES else 0


if __name__ == "__main__":
    sys.exit(main())

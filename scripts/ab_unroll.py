"""Single-process A/B of JGRAFT_SCAN_UNROLL on the north-star batch.

The certify run showed ~2x inter-process variance on the tunneled chip
(identical dense benches: 475 / 400 / 249 hist/s), so cross-process
comparisons cannot resolve a 1.2-1.5x knob.  This script builds the
kernels for several unroll values in ONE process (the kernel caches key
on the unroll, so they coexist), then interleaves timed reps A/B/A/B...
and reports per-setting min and median — the only sound way to compare
on this deployment.

Usage: python scripts/ab_unroll.py [--unrolls 1,2,4] [--reps 5]
"""
import argparse
import os
import random
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--unrolls", default="1,2,4")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--n-histories", type=int, default=1000)
    ap.add_argument("--n-ops", type=int, default=1000)
    args = ap.parse_args()
    unrolls = [int(u) for u in args.unrolls.split(",")]

    from jepsen_jgroups_raft_tpu.history.packing import (encode_history,
                                                         pack_batch)
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models.register import CasRegister
    from jepsen_jgroups_raft_tpu.ops.dense_scan import dense_plans_grouped
    from jepsen_jgroups_raft_tpu.parallel.mesh import (check_batch_sharded,
                                                       make_mesh)

    rng = random.Random(20260729)
    model = CasRegister()
    hists = [random_valid_history(rng, "register", n_ops=args.n_ops,
                                  n_procs=5, crash_p=0.05, max_crashes=3)
             for _ in range(args.n_histories)]
    encs = [encode_history(h, model) for h in hists]
    mesh = make_mesh()
    grouped, rest = dense_plans_grouped(model, encs)
    assert not rest, "north-star batch should be fully dense-plannable"
    batch = pack_batch(encs)

    def timed(unroll: int) -> float:
        os.environ["JGRAFT_SCAN_UNROLL"] = str(unroll)
        t0 = time.perf_counter()
        fins = [check_batch_sharded(model, batch["events"][idxs], mesh,
                                    dense=plan, defer=True)
                for idxs, plan in grouped]
        for fin in fins:
            fin()
        return time.perf_counter() - t0

    for u in unrolls:          # warm-up: compile every cache entry
        timed(u)
    times: dict[int, list[float]] = {u: [] for u in unrolls}
    for _ in range(args.reps):  # interleaved: variance hits all settings
        for u in unrolls:
            times[u].append(timed(u))
    for u in unrolls:
        ts = times[u]
        print({"unroll": u, "min_s": round(min(ts), 3),
               "median_s": round(statistics.median(ts), 3),
               "hist_per_s_at_min": round(args.n_histories / min(ts), 1),
               "reps": [round(t, 3) for t in ts]})


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# One-command on-chip certification (VERDICT r4 #1/#2/#6 + config-4).
#
# Run on the first TPU-attached session. Produces a timestamped
# artifact directory under bench_runs/ with every measurement the
# round-4/5 perf work needs to graduate from "CPU-measured, chip
# pending":
#   1. north-star bench (BENCH json line, best-of-3 with rep spread)
#   2. full suite (configs 1-5, each row best-of-3)
#   3. pallas driver-path bench (JGRAFT_KERNEL=pallas through bench.py)
#   4. interleaved single-process A/Bs (ab_pallas.log, ab_unroll.log) —
#      the only comparisons that resolve engine/knob differences under
#      the tunnel's cross-process variance
#   5. routing calibration incl. the scan-unroll sweep (per-shape
#      LOWER bounds for JGRAFT_ROUTE_MIN_CELLS / JGRAFT_SCAN_UNROLL)
#   6. Pallas hardware (Mosaic) test
#   7. a profiler trace of the north-star run (JGRAFT_PROFILE_DIR)
#
# Afterwards: update BASELINE.md's canonical table + engine-ablation
# row, PLATFORM_ROUTE_MIN_CELLS and scan_unroll() defaults if the
# measurements move them, and doc/running.md's measured-gates table.
set -u  # not -e: later steps must run even if an earlier one degrades

cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%dT%H%M%S)
out="bench_runs/certify_${ts}"
mkdir -p "$out"
echo "artifacts -> $out"

probe() {
  timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.platform)" \
    2>/dev/null | tail -1
}

platform=$(probe)
echo "platform probe: ${platform:-TIMEOUT}" | tee "$out/platform.txt"
if [ "${platform:-}" != "tpu" ] && [ "${platform:-}" != "axon" ]; then
  echo "NO CHIP (tunnel down/wedged) — aborting; nothing recorded as" \
       "on-chip evidence" | tee -a "$out/platform.txt"
  exit 2
fi

echo "== 1/7 north-star bench"
python bench.py 2>&1 | tee "$out/bench_northstar.log"

echo "== 2/7 suite (configs 1-5)"
python bench.py --suite 2>&1 | tee "$out/bench_suite.log"

echo "== 3/7 pallas compete-or-retire (driver path)"
JGRAFT_KERNEL=pallas python bench.py 2>&1 | tee "$out/bench_pallas.log"

echo "== 4/7 interleaved engine + unroll A/Bs"
# The decisive comparisons: the 2026-07-31 session measured identical
# dense benches at 249-475 hist/s across processes (tunnel latency
# wander), so only single-process interleaved reps can resolve an
# engine or knob difference. bench.py rows are best-of-3 for the same
# reason.
python scripts/ab_pallas.py 2>&1 | tee "$out/ab_pallas.log"
python scripts/ab_unroll.py 2>&1 | tee "$out/ab_unroll.log"
python scripts/ab_merge_long.py 2>&1 | tee "$out/ab_merge_long.log"
# Open on-chip questions from the 2026-07-31 CPU-side work: does
# clustering SHORT histories win on the chip (the north-star batch's 4
# serial window groups vs one W=8 launch), and does the backend-keyed
# transition hoist hold on the production path?
python scripts/ab_merge_long.py --all 2>&1 | tee "$out/ab_merge_all.log"
JGRAFT_HOIST=0 python bench.py 2>&1 | tee "$out/bench_hoist_off.log"

echo "== 5/7 routing calibration (per-shape lower bounds) + unroll sweep"
# Treat recommendations as LOWER bounds: host-routed small groups
# overlap with big chip launches in the real pipeline (config 4:
# gate-64k 1.68 s vs all-chip 2.63 s, 2026-07-31), which isolated
# per-shape probes cannot see.
python scripts/calibrate_routing.py --unroll 2>&1 \
  | tee "$out/calibrate.log"

echo "== 6/7 pallas hardware (Mosaic) test"
python -m pytest tests/test_pallas_scan.py -q 2>&1 \
  | tee "$out/pallas_hw_test.log"

echo "== 7/7 profiler trace of the north-star run"
JGRAFT_PROFILE_DIR="$out/profile" python bench.py 2>&1 \
  | tee "$out/bench_profiled.log"

echo "done — review $out and promote BASELINE.md rows"

#!/usr/bin/env bash
# One-command on-chip certification (VERDICT r4 #1/#2/#6 + config-4).
#
# Run on the first TPU-attached session. Produces a timestamped
# artifact directory under bench_runs/ with every measurement the
# round-4/5 perf work needs to graduate from "CPU-measured, chip
# pending":
#   1. north-star bench (BENCH json line; columnar encode + async
#      window-group launches land here)
#   2. full suite (configs 1-5; config 4 is the many-long row whose
#      canonical number predates the async-launch fix)
#   3. pallas compete-or-retire (the round-5 batch-parallel tile kernel
#      vs the XLA dense kernel on the same bench)
#   4. routing calibration incl. the scan-unroll sweep (sets
#      JGRAFT_ROUTE_MIN_CELLS / JGRAFT_SCAN_UNROLL from measurement)
#   5. Pallas hardware (Mosaic) test
#   6. a profiler trace of the north-star run (JGRAFT_PROFILE_DIR)
#
# Afterwards: update BASELINE.md's canonical table + engine-ablation
# row, PLATFORM_ROUTE_MIN_CELLS and scan_unroll() defaults if the
# measurements move them, and doc/running.md's measured-gates table.
set -u  # not -e: later steps must run even if an earlier one degrades

cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%dT%H%M%S)
out="bench_runs/certify_${ts}"
mkdir -p "$out"
echo "artifacts -> $out"

probe() {
  timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.platform)" \
    2>/dev/null | tail -1
}

platform=$(probe)
echo "platform probe: ${platform:-TIMEOUT}" | tee "$out/platform.txt"
if [ "${platform:-}" != "tpu" ] && [ "${platform:-}" != "axon" ]; then
  echo "NO CHIP (tunnel down/wedged) — aborting; nothing recorded as" \
       "on-chip evidence" | tee -a "$out/platform.txt"
  exit 2
fi

echo "== 1/6 north-star bench"
python bench.py 2>&1 | tee "$out/bench_northstar.log"

echo "== 2/6 suite (configs 1-5)"
python bench.py --suite 2>&1 | tee "$out/bench_suite.log"

echo "== 3/6 pallas compete-or-retire"
JGRAFT_KERNEL=pallas python bench.py 2>&1 | tee "$out/bench_pallas.log"

echo "== 4/6 routing calibration + unroll sweep"
python scripts/calibrate_routing.py --unroll 2>&1 \
  | tee "$out/calibrate.log"

echo "== 5/6 pallas hardware (Mosaic) test"
python -m pytest tests/test_pallas_scan.py -q 2>&1 \
  | tee "$out/pallas_hw_test.log"

echo "== 6/6 profiler trace of the north-star run"
JGRAFT_PROFILE_DIR="$out/profile" python bench.py 2>&1 \
  | tee "$out/bench_profiled.log"

echo "done — review $out and promote BASELINE.md rows"

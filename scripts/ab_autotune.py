"""Single-process interleaved A/B: autotuned plans vs the fixed default
config (ISSUE-6 acceptance measurement).

Runs the PRODUCTION path (check_histories, auto routing) over ≥2
distinct shape buckets with JGRAFT_AUTOTUNE flipped per rep,
interleaved in one process — the methodology this repo requires for
perf claims (cross-process comparisons measure the host's mood;
identical benches have spanned 249-677 hist/s across processes).
Verdicts are asserted identical between the two variants before
anything is timed; the tuned variant's plan measurement happens in the
untimed warm-up, exactly where a production process pays it.

The acceptance bar (ISSUE 6): tuned ≥ 1.15× default histories/sec on
host CPU on at least 2 distinct shape buckets, verdicts
bitwise-identical, and JGRAFT_AUTOTUNE=0 restoring today's exact
behavior (the default variant IS that setting).

Usage: python scripts/ab_autotune.py [--reps 4] [--scale 1.0]
       [--store DIR]  (default: a fresh temp dir, so every invocation
       re-measures on the current host envelope)
"""
import argparse
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=4)
    ap.add_argument("--scale", type=float, default=1.0,
                    help="scale bucket sizes (CI smoke uses <1)")
    ap.add_argument("--store", default=None,
                    help="plan store dir (default: fresh temp dir)")
    args = ap.parse_args()

    os.environ.setdefault("JGRAFT_AUTOTUNE_STORE",
                          args.store or tempfile.mkdtemp(prefix="ab-at-"))
    # The buckets below are sized to clear the measurement work gates
    # at full scale; pin the gates so --scale smokes still measure.
    os.environ.setdefault("JGRAFT_AUTOTUNE_MIN_ROWS", "24")
    os.environ.setdefault("JGRAFT_AUTOTUNE_MIN_CELLS", "4096")
    os.environ.setdefault("JGRAFT_AUTOTUNE_SAMPLES", "2")

    import random

    # This script is the HOST-CPU acceptance bar: pin the same virtual
    # 8-device mesh the production CPU path uses (bench.py's
    # resolve_platform → pin_cpu, tests/conftest.py) — without it the
    # CPU backend exposes one device and the fan-out plan dimension
    # vanishes from both variants.
    from jepsen_jgroups_raft_tpu.platform import pin_cpu

    pin_cpu(8)

    from jepsen_jgroups_raft_tpu.checker import autotune
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_histories
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models.register import CasRegister

    rng = random.Random(7)
    model = CasRegister()

    def sz(n):
        return max(8, int(n * args.scale))

    # Two deliberately different shape buckets, both landing on the
    # SORT family (wide value domains make them dense-ineligible):
    # the pre-autotune sort rung is single-device, so the plan's
    # mesh_fanout dimension is a genuine per-bucket mis-calibration for
    # the tuner to find (measured 1.84× at fan-out 8 on the 8-vdev host
    # mesh for bucket-A's shape). Distinct (W, rows, events) bucket
    # signatures by construction — two independent plans.
    buckets = {
        "A sort 96x120": [
            random_valid_history(rng, "register", n_ops=sz(120),
                                 n_procs=5, value_range=40, crash_p=0.02,
                                 max_crashes=2)
            for _ in range(sz(96))],
        "B sort 64x80": [
            random_valid_history(rng, "register", n_ops=sz(80), n_procs=4,
                                 value_range=64, crash_p=0.05,
                                 max_crashes=2)
            for _ in range(sz(64))],
    }

    def run(hists, tuned: bool):
        os.environ["JGRAFT_AUTOTUNE"] = "1" if tuned else "0"
        t0 = time.perf_counter()
        rs = check_histories(hists, model, algorithm="jax")
        return time.perf_counter() - t0, [r["valid?"] for r in rs]

    results = {}
    for name, hists in buckets.items():
        # Warm-up both variants: XLA compiles + (tuned) plan
        # measurement — all untimed, like a production process.
        run(hists, False)
        run(hists, True)
        v_def = run(hists, False)[1]
        v_tuned = run(hists, True)[1]
        assert v_def == v_tuned, f"verdict mismatch in bucket {name}"
        times = {"default": [], "tuned": []}
        for rep in range(args.reps):         # interleaved, order rotating
            order = (("default", False), ("tuned", True))
            if rep % 2:                      # cancel monotone host drift
                order = order[::-1]
            for key, t in order:
                times[key].append(run(hists, t)[0])
        n = len(hists)
        speedup = min(times["default"]) / min(times["tuned"])
        results[name] = speedup
        print({"bucket": name, "histories": n,
               "default_min_s": round(min(times["default"]), 3),
               "default_median_s":
                   round(statistics.median(times["default"]), 3),
               "tuned_min_s": round(min(times["tuned"]), 3),
               "tuned_median_s": round(statistics.median(times["tuned"]),
                                       3),
               "hist_per_s_default": round(n / min(times["default"]), 2),
               "hist_per_s_tuned": round(n / min(times["tuned"]), 2),
               "speedup_at_min": round(speedup, 3),
               "default_reps": [round(t, 3) for t in times["default"]],
               "tuned_reps": [round(t, 3) for t in times["tuned"]]})

    plans = [e for e in autotune.applied_log() if e["source"] == "measured"]
    print({"measured_plans": [(e["signature"], e["plan"]) for e in plans],
           "counters": autotune.snapshot_counters(),
           "store": os.environ["JGRAFT_AUTOTUNE_STORE"]})
    ok = sum(1 for s in results.values() if s >= 1.15)
    print({"buckets_at_1_15x": ok,
           "acceptance_1_15x_on_2_buckets": ok >= 2})


if __name__ == "__main__":
    main()

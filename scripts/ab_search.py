"""Single-process interleaved A/B: guided vs random scenario search
(ISSUE-20 acceptance measurement).

Plants K proven-reachable violations (each plant carries an (operator,
edit-seed) pair verified INVALID at plant time — misses are search
failures, not planting failures), then runs the coverage-guided arm
against the `JGRAFT_SEARCH_GUIDED=0` random-ablation arm over the SAME
plant bases, operators, admission path and per-generation candidate
budget, in ONE process — the methodology this repo requires for perf
claims (cross-process comparisons measure the host/tunnel's mood).

Discipline, in order:

  1. corpus DETERMINISM is asserted before anything is timed: each
     arm's warm-up run and every timed rep must produce identical
     corpus fingerprints (same seed ⇒ same corpus, the tentpole's
     reproducibility contract);
  2. every archived entry must have re-verified INVALID after
     minimization (unconfirmed == 0), re-checked here from disk;
  3. one warm-up per arm absorbs XLA compiles — batch formation is
     linger-timing-dependent, so coalesced shapes (hence compile
     cache hits) vary run-to-run; medians over interleaved reps with
     order rotation absorb the residual recompile spikes;
  4. CPU time is `time.process_time` (the driver's own accounting),
     charging the in-process graftd workers to the run.

Acceptance bars (ISSUE 20): guided recall ≥ 0.9 over K ≥ 20 plants
spanning ≥ 3 families, and guided recall-per-CPU-minute ≥ 1.5× random
(medians). The defaults reproduce the tuned operating point: seed 0,
population 32, generations 4, survivors 8, edit space 16 → measured
guided recall 1.0 at ≈1.9× random.

Usage: python scripts/ab_search.py [--plants 20] [--reps 3] [--seed 0]
       [--population 32] [--generations 4] [--survivors 8]
       [--edit-space 16] [--n-ops 16] [--families a,b,...]
"""
import argparse
import os
import shutil
import statistics
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--plants", type=int, default=20)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--population", type=int, default=32)
    ap.add_argument("--generations", type=int, default=4)
    ap.add_argument("--survivors", type=int, default=8)
    ap.add_argument("--edit-space", type=int, default=16)
    ap.add_argument("--n-ops", type=int, default=16)
    ap.add_argument("--families",
                    default="register,set,queue,list-append")
    args = ap.parse_args()

    from jepsen_jgroups_raft_tpu.platform import pin_cpu

    pin_cpu(8)

    from jepsen_jgroups_raft_tpu.search import (Corpus, SearchConfig,
                                                plant_violations, run_recall)
    from jepsen_jgroups_raft_tpu.search.corpus import reverify_entry
    from jepsen_jgroups_raft_tpu.service.daemon import CheckingService

    families = tuple(f.strip() for f in args.families.split(",") if f.strip())
    assert len(families) >= 3, "acceptance needs plants across ≥3 families"
    assert args.plants >= 20, "acceptance needs K ≥ 20 plants"

    def config(guided: bool, corpus_dir: str) -> SearchConfig:
        return SearchConfig(
            families=families, population=args.population,
            generations=args.generations, survivors=args.survivors,
            edit_space=args.edit_space, seed=args.seed, guided=guided,
            corpus_dir=corpus_dir, n_ops=args.n_ops)

    scratch = tempfile.mkdtemp(prefix="ab-search-")
    print(f"planting {args.plants} violations across {families} "
          f"(seed {args.seed}) ...")
    plants = plant_violations(config(True, os.path.join(scratch, "plant")),
                              args.plants)
    fam_counts = {}
    for p in plants:
        fam_counts[p.base.family] = fam_counts.get(p.base.family, 0) + 1
    assert len(fam_counts) >= 3, fam_counts
    print(f"  planted: {fam_counts}")

    arms = {"guided": True, "random": False}
    fingerprints = {}  # arm -> corpus fingerprints of the FIRST run
    timed = {"guided": [], "random": []}

    def one_run(arm: str, tag: str):
        # a FRESH service per run: graftd dedupes byte-identical
        # resubmissions (ISSUE 8), so a shared service would hand later
        # reps cached verdicts and the timing would measure cache
        # lookups instead of checking CPU. The XLA compile cache is
        # process-global, so the warm-up still pays the compiles once.
        cdir = os.path.join(scratch, f"{arm}-{tag}")
        svc = CheckingService(store_root=None, batch_wait=0.02)
        try:
            rep = run_recall(config(arms[arm], cdir), plants=plants,
                             service=svc)
        finally:
            svc.shutdown(wait=True)
        fps = tuple(rep.report["corpus-fingerprints"])
        if arm in fingerprints:
            assert fps == fingerprints[arm], (
                f"{arm} corpus NOT deterministic across reps: "
                f"{len(fps)} vs {len(fingerprints[arm])} entries")
        else:
            fingerprints[arm] = fps
        assert rep.report["unconfirmed"] == 0, rep.report
        corpus = Corpus(cdir)
        for entry in corpus.entries():
            assert reverify_entry(entry), \
                f"{arm} archived a non-witness: {entry['fingerprint']}"
        shutil.rmtree(cdir, ignore_errors=True)
        return rep

    try:
        # warm-up (absorbs XLA compiles; also seeds the determinism ref)
        for arm in arms:
            r = one_run(arm, "warmup")
            print(f"  warmup {arm:6s}: recall {r.recall:.2f} "
                  f"cpu {r.cpu_s:.1f}s")
        # timed reps, interleaved, order rotated so neither arm always
        # rides the warmer cache
        orders = [("guided", "random"), ("random", "guided")]
        for i in range(args.reps):
            for arm in orders[i % len(orders)]:
                r = one_run(arm, f"rep{i}")
                timed[arm].append(r)
                print(f"  rep{i} {arm:6s}: recall {r.recall:.2f} "
                      f"cpu {r.cpu_s:.1f}s "
                      f"rpm {r.recall_per_cpu_min:.2f}")
    finally:
        shutil.rmtree(scratch, ignore_errors=True)

    rows = {}
    for arm in arms:
        reps = timed[arm]
        rows[arm] = {
            "arm": arm,
            "recall": reps[0].recall,  # deterministic across reps
            "found": len(reps[0].found),
            "planted": reps[0].planted,
            "corpus": len(fingerprints[arm]),
            "cpu_s_median": round(statistics.median(
                r.cpu_s for r in reps), 3),
            "recall_per_cpu_min_median": round(statistics.median(
                r.recall_per_cpu_min for r in reps), 4),
        }
        print(rows[arm])

    g, r = rows["guided"], rows["random"]
    ratio = g["recall_per_cpu_min_median"] / \
        max(1e-9, r["recall_per_cpu_min_median"])
    print({"metric": "guided_vs_random_recall_per_cpu_min",
           "ratio": round(ratio, 3),
           "plants": args.plants, "families": list(fam_counts),
           "seed": args.seed})

    ok = True
    if g["recall"] < 0.9:
        print(f"FAIL: guided recall {g['recall']:.2f} < 0.9")
        ok = False
    if ratio < 1.5:
        print(f"FAIL: guided/random recall-per-CPU-min {ratio:.2f} < 1.5")
        ok = False
    print("AB-SEARCH " + ("PASS" if ok else "FAIL"))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()

"""Single-process interleaved A/B: macro-event compaction vs the legacy
one-event-per-step stream (ISSUE-4 acceptance measurement).

Runs the PRODUCTION path (check_histories, auto routing, default
JGRAFT_SCAN_CHUNK) with JGRAFT_MACRO_EVENTS flipped per rep, interleaved
in one process — the methodology this repo requires for perf claims
(cross-process comparisons measure the host/tunnel's mood; identical
benches have spanned 249-677 hist/s across processes). Verdicts are
asserted identical between the two variants before anything is timed.

The acceptance bar (ISSUE 4): macro ≥ 1.25× legacy histories/sec on
host CPU at the north-star shape, with the scan length dropped to
#FORCEs + spill (reported here via pack_macro_batch row counts and in
the bench JSON's scan_steps field).

Usage: python scripts/ab_macro.py [--reps 3] [--n-histories 1000]
       [--n-ops 1000]
"""
import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--n-histories", type=int, default=1000)
    ap.add_argument("--n-ops", type=int, default=1000)
    args = ap.parse_args()

    import random

    from jepsen_jgroups_raft_tpu.checker.linearizable import check_histories
    from jepsen_jgroups_raft_tpu.history.packing import (encode_history,
                                                         pack_macro_batch)
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models.register import CasRegister

    rng = random.Random(3)
    model = CasRegister()
    hists = [random_valid_history(rng, "register", n_ops=args.n_ops,
                                  n_procs=5, crash_p=0.05, max_crashes=3)
             for _ in range(args.n_histories)]

    # Scan-length evidence: macro rows vs legacy events (the bench JSON
    # reports the same split as scan_steps / scan_steps_legacy).
    encs = [encode_history(h, model) for h in hists]
    legacy_steps = sum(e.n_events for e in encs)
    macro_steps = int(pack_macro_batch(encs)["n_events"].sum())
    print({"legacy_steps": legacy_steps, "macro_steps": macro_steps,
           "compaction": round(legacy_steps / max(macro_steps, 1), 3)})

    def run(macro: bool):
        os.environ["JGRAFT_MACRO_EVENTS"] = "1" if macro else "0"
        t0 = time.perf_counter()
        rs = check_histories(hists, model, algorithm="jax")
        dt = time.perf_counter() - t0
        return dt, [r["valid?"] for r in rs]

    variants = {"legacy": False, "macro": True}
    verdicts = {}
    for name, m in variants.items():        # warm-up: compile
        _, verdicts[name] = run(m)
    assert verdicts["legacy"] == verdicts["macro"], \
        "verdict mismatch between macro and legacy streams"
    times = {n: [] for n in variants}
    for _ in range(args.reps):              # interleaved
        for name, m in variants.items():
            times[name].append(run(m)[0])
    os.environ.pop("JGRAFT_MACRO_EVENTS", None)
    for name, ts in times.items():
        print({"variant": name, "min_s": round(min(ts), 3),
               "median_s": round(statistics.median(ts), 3),
               "hist_per_s_at_min": round(args.n_histories / min(ts), 2),
               "hist_per_s_at_median":
                   round(args.n_histories / statistics.median(ts), 2),
               "reps": [round(t, 3) for t in ts]})
    speedup = min(times["legacy"]) / min(times["macro"])
    print({"speedup_at_min": round(speedup, 3),
           "acceptance_1_25x": speedup >= 1.25})


if __name__ == "__main__":
    main()

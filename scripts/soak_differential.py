#!/usr/bin/env python
"""Randomized cross-engine differential soak for the linearizability
checkers — the repeatable form of the round-3 soundness campaign (74,688
histories, 0 mismatches, 0 unknowns; BASELINE.md cites the exact command).

Every generated history (linearizable-by-construction, with a configurable
fraction randomly corrupted — the oracle decides whether a corruption
actually breaks linearizability) is verified by three INDEPENDENT engines
and the verdicts must agree:

  * the product path  — `check_histories(algorithm="auto")`: on-device
    kernels + the sound escalation ladder (checker/linearizable.py),
  * the CPU oracle    — unbounded frontier search on the UNPRUNED
    encoding (checker/wgl_cpu.py), immune to routing/prune bugs,
  * the DFS engine    — knossos/porcupine-style DFS-with-undo
    (checker/dfs_cpu.py), a structurally different search.

Any verdict mismatch is a soundness bug: the soak prints the seed and the
history and exits 1. `unknown` from the product path is reported (it is a
routing-coverage gap, not unsoundness — round-3's one finding became the
DFS escalation rung) and fails the soak only with --strict-unknown.

Reference test-philosophy anchor: evidence must be re-runnable
(/root/reference/test/jepsen/jgroups/raft_test.clj drives the production
checker on pinned histories; this scales that idea to randomized volume).

Usage (the round-3-scale campaign ≈ ~40 min on an idle 8-core host):
  python scripts/soak_differential.py --count 16000
Quick CI-sized pass (also exposed as `pytest -m soak`):
  python scripts/soak_differential.py --count 300
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from jepsen_jgroups_raft_tpu.platform import pin_cpu  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0,
                   help="base seed; history i uses seed+i (default 0)")
    p.add_argument("--count", type=int, default=2000,
                   help="number of histories (default 2000)")
    p.add_argument("--workloads", default="register,counter",
                   help="comma list of register,counter (default both)")
    p.add_argument("--max-ops", type=int, default=60,
                   help="ops per history drawn from [4, max-ops]")
    p.add_argument("--max-procs", type=int, default=6,
                   help="concurrency drawn from [1, max-procs]")
    p.add_argument("--max-crash-p", type=float, default=0.35,
                   help="per-history crash prob drawn from [0, max]")
    p.add_argument("--corrupt-frac", type=float, default=0.5,
                   help="fraction of histories perturbed (default 0.5)")
    p.add_argument("--batch", type=int, default=64,
                   help="histories per product-path batch (default 64; "
                        "batching exercises the shared-window packing)")
    p.add_argument("--strict-unknown", action="store_true",
                   help="treat product-path unknown verdicts as failures")
    p.add_argument("--product-algorithm", default="auto",
                   choices=["auto", "jax", "pallas", "race", "dfs"],
                   help="algorithm for the product path — soaks every "
                        "engine behind the same oracle (default auto)")
    p.add_argument("--pin-capacity", type=int, default=None,
                   help="pin the sort-frontier kernel's capacity ladder "
                        "(n_configs) — routes kernel-checked histories "
                        "through the general sort kernel instead of the "
                        "dense planner (auto's wide-window DFS rung still "
                        "applies; incompatible with pallas/dfs, which "
                        "would silently ignore or bypass the pin)")
    p.add_argument("--platform", default="cpu", choices=["cpu", "default"],
                   help="cpu (default; pinned 8-device host mesh, "
                        "reproducible anywhere) or default backend (TPU "
                        "when attached)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.pin_capacity is not None and \
            args.product_algorithm in ("pallas", "dfs"):
        # A pinned capacity disables dense-group planning, so "pallas"
        # would silently measure the sort kernel (and dfs ignores the
        # pin entirely) — refuse rather than produce mislabeled
        # evidence (round-4 review finding).
        print("--pin-capacity is incompatible with "
              f"--product-algorithm {args.product_algorithm}",
              file=sys.stderr)
        return 2
    if args.platform == "cpu":
        pin_cpu(8)

    from jepsen_jgroups_raft_tpu.checker.dfs_cpu import (
        SearchBudgetExceeded, check_encoded_dfs)
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_histories
    from jepsen_jgroups_raft_tpu.checker.wgl_cpu import (FrontierOverflow,
                                                         check_encoded_cpu)
    from jepsen_jgroups_raft_tpu.history.packing import encode_history
    from jepsen_jgroups_raft_tpu.history.synth import (corrupt,
                                                       random_valid_history)
    from jepsen_jgroups_raft_tpu.models.counter import Counter
    from jepsen_jgroups_raft_tpu.models.register import CasRegister

    models = {"register": CasRegister, "counter": Counter}
    workloads = [w.strip() for w in args.workloads.split(",") if w.strip()]
    for w in workloads:
        if w not in models:
            print(f"unknown workload {w!r}", file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    n_done = n_corrupted = n_invalid = 0
    unknowns: list[int] = []
    mismatches: list[dict] = []

    def oracle_verdict(enc, model, seed):
        """Unpruned-unbounded frontier; None when genuinely infeasible
        (astronomically wide window — the generator's max_crashes cap
        makes this rare at soak shapes). Valid verdicts also have their
        WITNESS replayed through the sequential model — a witness that
        does not replay legally (or linearizes fewer ops than the
        history forces) is a soundness bug in the witness machinery
        even when the verdict itself is right."""
        try:
            r = check_encoded_cpu(enc, model, witness=True)
        except FrontierOverflow:
            return None
        if r.valid:
            from jepsen_jgroups_raft_tpu.history.packing import (EV_FORCE,
                                                                 EV_OPEN)

            fab = {}
            n_force = 0
            for row, oi in zip(enc.events, enc.op_index):
                if row[0] == EV_OPEN:
                    fab[int(oi)] = (int(row[2]), int(row[3]), int(row[4]))
                elif row[0] == EV_FORCE:
                    n_force += 1
            state = model.init_state()
            for oi in r.witness:
                if oi not in fab:
                    # An op index with no OPEN row is itself the
                    # witness-machinery breakage this check hunts —
                    # record it, don't crash the campaign on KeyError.
                    mismatches.append({
                        "seed": seed, "kind": "witness-unknown-op",
                        "witness": r.witness, "at": oi})
                    break
                f, a, b = fab[oi]
                state, legal = model.step(state, f, a, b)
                if not legal:
                    mismatches.append({
                        "seed": seed, "kind": "witness-replay-illegal",
                        "witness": r.witness, "at": oi})
                    break
            if len(r.witness) < n_force:
                mismatches.append({
                    "seed": seed, "kind": "witness-too-short",
                    "witness_len": len(r.witness), "n_force": n_force})
        return r.valid

    def dfs_verdict(enc, model):
        try:
            return check_encoded_dfs(enc, model, max_steps=5_000_000).valid
        except SearchBudgetExceeded:
            return None

    for start in range(0, args.count, args.batch):
        idxs = range(start, min(start + args.batch, args.count))
        batch = []  # (i, workload, history)
        for i in idxs:
            rng = random.Random(args.seed + i)
            wl = rng.choice(workloads)
            h = random_valid_history(
                rng, wl,
                n_ops=rng.randint(4, args.max_ops),
                n_procs=rng.randint(1, args.max_procs),
                crash_p=rng.uniform(0.0, args.max_crash_p),
                max_crashes=rng.randint(0, 5))
            was_corrupted = rng.random() < args.corrupt_frac
            if was_corrupted:
                h = corrupt(rng, h)
            batch.append((i, wl, h, was_corrupted))

        # Product path runs per-workload (one model per batch).
        for wl in workloads:
            rows = [(i, h, c) for i, w, h, c in batch if w == wl]
            if not rows:
                continue
            model = models[wl]()
            results = check_histories([h for _, h, _ in rows], model,
                                      algorithm=args.product_algorithm,
                                      n_configs=args.pin_capacity)
            for (i, h, was_corrupted), res in zip(rows, results):
                n_done += 1
                n_corrupted += was_corrupted
                auto = res["valid?"]
                enc_unpruned = encode_history(h, model, prune=False)
                oracle = oracle_verdict(enc_unpruned, model, args.seed + i)
                dfs = dfs_verdict(enc_unpruned, model)
                n_invalid += oracle is False
                if not was_corrupted and oracle is False:
                    mismatches.append({
                        "seed": args.seed + i, "workload": wl,
                        "kind": "generator-unsound",
                        "detail": "valid-by-construction history judged "
                                  "invalid by the oracle"})
                # The product path signals unknown with the UNKNOWN
                # sentinel ("unknown"), never None — compare on
                # bool-ness, not identity with None. Oracle overflow
                # (None) also lands here: with no ground truth the
                # comparison is a coverage gap, not a verdict.
                if not isinstance(auto, bool) or oracle is None:
                    unknowns.append(args.seed + i)
                    if args.strict_unknown:
                        mismatches.append({
                            "seed": args.seed + i, "workload": wl,
                            "kind": "unknown", "auto": repr(auto),
                            "oracle": oracle, "dfs": dfs})
                    continue
                disagree = [
                    name for name, v in
                    (("auto", auto), ("dfs", dfs))
                    if isinstance(v, bool) and v is not oracle
                ]
                if disagree:
                    mismatches.append({
                        "seed": args.seed + i, "workload": wl,
                        "kind": "verdict-mismatch", "auto": auto,
                        "oracle": oracle, "dfs": dfs,
                        "history": [(o.process, o.type, o.f, o.value)
                                    for o in h]})
        done = min(start + args.batch, args.count)
        if done % max(args.batch * 4, 256) < args.batch or done == args.count:
            dt = time.perf_counter() - t0
            print(f"  {done}/{args.count} histories  "
                  f"({done / dt:.0f}/s, {len(mismatches)} mismatches, "
                  f"{len(unknowns)} unknown)", flush=True)

    dt = time.perf_counter() - t0
    summary = {
        "histories": n_done,
        "corrupted": n_corrupted,
        "oracle_invalid": n_invalid,
        "mismatches": len(mismatches),
        "unknowns": len(unknowns),
        "time_s": round(dt, 1),
        "seed": args.seed,
        "count": args.count,
    }
    print(json.dumps(summary))
    for m in mismatches[:20]:
        print("MISMATCH:", json.dumps(m), file=sys.stderr)
    if unknowns:
        print(f"unknown seeds (routing-coverage gaps): {unknowns[:50]}",
              file=sys.stderr)
    return 1 if mismatches else 0


if __name__ == "__main__":
    sys.exit(main())

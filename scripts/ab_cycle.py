"""Single-process interleaved A/B: cycle tier at scale (ISSUE-19
acceptance measurement).

Four measurements, all in ONE process (cross-process comparisons
measure the host's mood), verdict identity asserted BEFORE anything is
timed:

  1. **closure-kernel** — the blocked transitive-closure kernel
     (ops/kernel_ir.make_cycle_closure_tiled) vs the host DFS at
     N ∈ {1024, 2048}: has-cycle flags must agree on every seeded
     graph (dense-ish digraphs, DAGs, and a long planted cycle — the
     shape tiling could silently lose), then both arms are timed
     interleaved with order rotation. The speedup is reported, not
     gated: the measured-routing stance keeps the DFS wherever matmul
     is not effectively free, and on a CPU host the DFS wins — the
     point of the row is that the kernel now DECIDES these buckets at
     all (the 512-cap tier skipped them) and agrees bit for bit.
  2. **condensation** — certify_history with the Tarjan condensation
     pre-pass on vs off (JGRAFT_CYCLE_CONDENSE=0) at the north-star
     transactional shape: a multi-key list-append history whose graph
     is a few thousand nodes with rw edges everywhere, so the direct
     arm pays the G-single reachability closure while the condense arm
     answers from the SCC structure alone. Acceptance bar: ≥ 1.3×.
  3. **ablation identity** — JGRAFT_CYCLE_TILE=0 / CONDENSE=0
     reproduce the default arms' verdicts at N ≤ 512 through the
     production find_cycles entry (witnesses are validated as genuine
     cycles in both arms), and the anomaly classes (G0/G1c/G-single)
     certify identically condensed vs direct on the planted fixtures.
  4. **anomaly rung** — the seeded cross-key G1c is refuted with a
     witness exactly where the per-key relaxation rungs cannot see it:
     every single-key projection passes the per-key sequential rung.

Also exercised: the size-skip contract — with the tiled kernel
disabled the node cap falls back to 512 and the north-star history is
stamped "cycle-skipped-size" (UNKNOWN), while the default arm decides
it outright.

Usage: python scripts/ab_cycle.py [--reps 3] [--sizes 1024,2048]
       [--n-ops 2000] [--n-keys 24] [--batch 2]
"""
import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _serial_listappend_rows(rng, n_ops: int, n_keys: int, n_procs: int,
                            read_p: float = 0.55):
    """A clean (serializable-by-construction) multi-key list-append
    history: serial keyed ops round-robined over processes. Reads
    observe prefixes that later appends extend, so rw edges abound —
    the shape where the direct arm must pay for a closure and the
    condensation arm must not."""
    state = {k: [] for k in range(n_keys)}
    next_elem = {k: 1 for k in range(n_keys)}
    rows = []
    for i in range(n_ops):
        p = i % n_procs
        k = rng.randrange(n_keys)
        if next_elem[k] <= 31 and rng.random() > read_p:
            e = next_elem[k]
            next_elem[k] += 1
            state[k] = state[k] + [e]
            rows.append((p, "invoke", "append", (k, e)))
            rows.append((p, "ok", "append", (k, list(state[k]))))
        else:
            rows.append((p, "invoke", "read", (k, None)))
            rows.append((p, "ok", "read", (k, list(state[k]))))
    return rows


def _g1c_rows():
    """The seeded cross-key G1c: each session reads the OTHER key's
    append before its own append lands — wr/po edges close a cross-key
    cycle while both single-key projections stay sequential."""
    return [
        (1, "invoke", "read", ("y", None)), (1, "ok", "read", ("y", [1])),
        (1, "invoke", "append", ("x", 1)), (1, "ok", "append", ("x", [1])),
        (2, "invoke", "read", ("x", None)), (2, "ok", "read", ("x", [1])),
        (2, "invoke", "append", ("y", 1)), (2, "ok", "append", ("y", [1])),
    ]


def _is_cycle(witness, adj_of) -> bool:
    """Every consecutive witness pair (wrapping) is a real edge."""
    if not witness:
        return False
    n = len(witness)
    return all(adj_of(witness[i], witness[(i + 1) % n]) for i in range(n))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--sizes", default="1024,2048")
    ap.add_argument("--n-ops", type=int, default=2000,
                    help="north-star transactional history length")
    ap.add_argument("--n-keys", type=int, default=24)
    ap.add_argument("--batch", type=int, default=2,
                    help="graphs per size in the kernel A/B")
    args = ap.parse_args()

    import random

    import numpy as np

    from jepsen_jgroups_raft_tpu.checker.anomaly import certify_history
    from jepsen_jgroups_raft_tpu.checker.cycle import (find_cycles,
                                                       host_has_cycle)
    from jepsen_jgroups_raft_tpu.checker.linearizable import check_histories
    from jepsen_jgroups_raft_tpu.history.packing import encode_history
    from jepsen_jgroups_raft_tpu.history.synth import (build_history,
                                                       corrupt,
                                                       random_valid_history)
    from jepsen_jgroups_raft_tpu.models import CasRegister
    from jepsen_jgroups_raft_tpu.models.listappend import ListAppend
    from jepsen_jgroups_raft_tpu.ops.kernel_ir import (
        CYCLE_TILE, cycle_closure_tile, make_cycle_closure_tiled)

    for k in ("JGRAFT_CYCLE_CONDENSE", "JGRAFT_CYCLE_TILE",
              "JGRAFT_CYCLE_KERNEL", "JGRAFT_CYCLE_MAX_OPS"):
        os.environ.pop(k, None)
    overall_ok = True

    # ------------------------------------------- 1. closure kernel A/B
    rng = random.Random(19)
    for N in (int(s) for s in args.sizes.split(",")):
        nrng = np.random.default_rng(N)
        graphs = []
        for b in range(args.batch):
            g = (nrng.random((N, N)) < 3.0 / N).astype(np.int32)
            np.fill_diagonal(g, 0)
            if b % 2 == 0:
                g = np.triu(g, 1)  # a DAG arm per size
            else:
                for i in range(N - 1):  # a planted Hamiltonian cycle
                    g[i, i + 1] = 1
                g[N - 1, 0] = 1
            graphs.append(g)
        t = cycle_closure_tile(N, CYCLE_TILE)
        kfn = make_cycle_closure_tiled(N, t)
        batch = np.stack(graphs)

        def run_kernel():
            t0 = time.perf_counter()
            has, _closed = kfn(batch)
            flags = [bool(v) for v in np.asarray(has)]
            return time.perf_counter() - t0, flags

        def run_dfs():
            t0 = time.perf_counter()
            flags = [host_has_cycle(g) for g in graphs]
            return time.perf_counter() - t0, flags

        # warm-up (compile) + verdict-identity gate BEFORE timing
        _, flags_k = run_kernel()
        _, flags_d = run_dfs()
        assert flags_k == flags_d, f"N={N}: kernel/DFS flags diverge"
        assert True in flags_d and False in flags_d, f"N={N}: one polarity"

        variants = [("tiled-kernel", run_kernel), ("host-dfs", run_dfs)]
        times = {name: [] for name, _ in variants}
        for rep in range(args.reps):          # interleaved, order rotated
            order = variants if rep % 2 == 0 else variants[::-1]
            for name, fn in order:
                times[name].append(fn()[0])
        for name, ts in times.items():
            print({"section": "closure-kernel", "N": N, "tile": t,
                   "variant": name, "min_s": round(min(ts), 4),
                   "median_s": round(statistics.median(ts), 4)})
        print({"section": "closure-kernel", "N": N,
               "graphs": len(graphs), "verdicts_identical": True,
               "dfs_over_kernel_at_min":
               round(min(times["host-dfs"]) / min(times["tiled-kernel"]), 3)})

    # -------------------------------------- 2. condensation A/B (bar)
    rng = random.Random(23)
    star = build_history(_serial_listappend_rows(rng, args.n_ops,
                                                 args.n_keys, 8))
    planted = build_history(_g1c_rows())

    def run_certify(condense: bool):
        os.environ["JGRAFT_CYCLE_CONDENSE"] = "1" if condense else "0"
        t0 = time.perf_counter()
        r = certify_history(star, kernel=False)
        return time.perf_counter() - t0, r

    _, r_on = run_certify(True)
    _, r_off = run_certify(False)
    assert r_on["valid?"] is True and r_off["valid?"] is True, \
        "north-star shape must certify clean on both arms"
    assert r_on["nodes"] == r_off["nodes"] >= args.n_ops // 2
    for condense in (True, False):
        os.environ["JGRAFT_CYCLE_CONDENSE"] = "1" if condense else "0"
        rp = certify_history(planted, kernel=False)
        assert rp["valid?"] is False and "G1c" in rp["anomalies"], condense

    times = {"condense-on": [], "condense-off": []}
    pairs = [("condense-on", True), ("condense-off", False)]
    for rep in range(args.reps):
        order = pairs if rep % 2 == 0 else pairs[::-1]
        for name, condense in order:
            times[name].append(run_certify(condense)[0])
    os.environ.pop("JGRAFT_CYCLE_CONDENSE", None)
    speedup = min(times["condense-off"]) / min(times["condense-on"])
    print({"section": "condensation", "nodes": r_on["nodes"],
           "on_min_s": round(min(times["condense-on"]), 4),
           "off_min_s": round(min(times["condense-off"]), 4),
           "speedup_at_min": round(speedup, 3),
           "acceptance_condense_1_3x": speedup >= 1.3})
    overall_ok &= speedup >= 1.3

    # ---------------------- size-skip contract: TILE=0 cap vs default
    os.environ["JGRAFT_CYCLE_TILE"] = "0"
    skipped = certify_history(star, kernel=False)
    os.environ.pop("JGRAFT_CYCLE_TILE", None)
    decided = certify_history(star, kernel=False)
    assert skipped["valid?"] == "unknown" and \
        skipped.get("cycle-skipped-size", 0) > 512, skipped
    assert decided["valid?"] is True
    print({"section": "size-skip", "tile0_valid": skipped["valid?"],
           "tile0_skipped_size": skipped["cycle-skipped-size"],
           "default_valid": decided["valid?"],
           "decided_where_cap_skips": True})

    # --------------------------- 3. ablation identity at N <= 512
    rng = random.Random(29)
    m = CasRegister()
    hists = []
    for i in range(24):
        h = random_valid_history(rng, "register", n_ops=48, n_procs=4,
                                 crash_p=0.1, max_crashes=2)
        hists.append(corrupt(rng, h) if i % 3 == 0 else h)
    # a guaranteed cycle-refuted row (same-process stale read), so both
    # polarities are exercised regardless of what corrupt() perturbed
    hists.append(build_history([
        (0, "invoke", "write", 1), (0, "ok", "write", 1),
        (0, "invoke", "read", None), (0, "ok", "read", None),
    ]))
    encs = [encode_history(h, m) for h in hists]

    def cycle_results():
        return find_cycles(encs, m)

    base = cycle_results()
    os.environ["JGRAFT_CYCLE_TILE"] = "0"
    os.environ["JGRAFT_CYCLE_CONDENSE"] = "0"
    ablated = cycle_results()
    for k in ("JGRAFT_CYCLE_TILE", "JGRAFT_CYCLE_CONDENSE"):
        os.environ.pop(k, None)
    verdicts_b = [c is None for c in base]
    verdicts_a = [c is None for c in ablated]
    assert verdicts_b == verdicts_a, "ablation arms diverge at N<=512"
    assert True in verdicts_b and False in verdicts_b
    refuted = sum(1 for v in verdicts_b if not v)
    print({"section": "ablation", "rows": len(hists), "refuted": refuted,
           "verdicts_identical": True})

    # -------------------------------- 4. the rung relaxation cannot see
    by_key: dict = {}
    for p, typ, f, v in _g1c_rows():
        k, payload = v
        by_key.setdefault(k, []).append((p, typ, f, payload))
    per_key_valid = {}
    for k, rows in sorted(by_key.items()):
        h = build_history(rows)
        [res] = check_histories([h], ListAppend(), algorithm="jax",
                                consistency="sequential")
        per_key_valid[k] = res["valid?"]
    anom = certify_history(planted, kernel=False)
    g1c = anom["anomalies"].get("G1c")
    rung_ok = (all(v is True for v in per_key_valid.values())
               and anom["valid?"] is False and g1c is not None
               and bool(g1c.get("cycle")))
    print({"section": "anomaly-rung", "per_key_sequential": per_key_valid,
           "txn_valid": anom["valid?"],
           "g1c_witness": g1c, "acceptance_refuted_beyond_rungs": rung_ok})
    overall_ok &= rung_ok

    print({"acceptance_all": overall_ok})


if __name__ == "__main__":
    main()

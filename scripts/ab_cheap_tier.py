"""Single-process interleaved A/B: cheap-decision tier on vs off
(ISSUE-13 acceptance measurement).

Measures the production weak-rung path (`check_histories`,
``consistency=sequential``) with the cheap tiers (value-guided
bounded-backtrack certifier + exact cycle tier) enabled vs disabled
(``JGRAFT_GREEDY_CERTIFY=0 JGRAFT_CYCLE_TIER=0``), interleaved with
candidate rotation in ONE process — the methodology this repo requires
for perf claims (cross-process comparisons measure the host/tunnel's
mood). Verdict identity between the arms is asserted before anything
is timed (the tier-soundness gate), and the per-family decided
fractions are reported from the cheap arm's verdicts.

Acceptance bars (ISSUE 13): register/cas ≥ 1.2× with the cheap tier on
(reversing PR-9's measured ≈0.77×, where mutator ambiguity defeated the
no-backtrack greedy), queue greedy decided-fraction ≥ 0.9 (crashed-op
landmines placed lazily). ``--with-lin`` additionally measures the rung
against full linearizability — the PR-9 regression's original axis.

Usage: python scripts/ab_cheap_tier.py [--reps 3] [--n-histories 400]
       [--n-ops 1000] [--rung sequential] [--families register,queue,set]
       [--with-lin]
"""
import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--n-histories", type=int, default=400)
    ap.add_argument("--n-ops", type=int, default=1000)
    ap.add_argument("--rung", default="sequential",
                    choices=["sequential", "session"])
    ap.add_argument("--families", default="register,queue,set")
    ap.add_argument("--with-lin", action="store_true",
                    help="also time the linearizable rung (the PR-9 axis)")
    args = ap.parse_args()

    import random

    from jepsen_jgroups_raft_tpu.checker.linearizable import check_histories
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models import (CasRegister, Counter, GSet,
                                                TicketQueue)

    factories = {"register": CasRegister, "counter": Counter, "set": GSet,
                 "queue": TicketQueue}
    overall_ok = True
    for family in args.families.split(","):
        family = family.strip()
        model = factories[family]()
        rng = random.Random(13)
        hists = [random_valid_history(rng, family, n_ops=args.n_ops,
                                      n_procs=5, crash_p=0.05,
                                      max_crashes=3)
                 for _ in range(args.n_histories)]

        def set_cheap(on: bool) -> None:
            os.environ["JGRAFT_GREEDY_CERTIFY"] = "1" if on else "0"
            os.environ["JGRAFT_CYCLE_TIER"] = "1" if on else "0"

        def run(cheap: bool, consistency: str = args.rung):
            set_cheap(cheap)
            t0 = time.perf_counter()
            rs = check_histories(hists, model, algorithm="jax",
                                 consistency=consistency)
            return time.perf_counter() - t0, rs

        # Warm-up (compile) + verdict-identity gate BEFORE timing.
        _, rs_on = run(True)
        _, rs_off = run(False)
        bad = [i for i, (a, b) in enumerate(zip(rs_on, rs_off))
               if a["valid?"] is not b["valid?"]]
        assert not bad, f"{family}: cheap-tier verdicts diverge at {bad[:5]}"

        tiers: dict = {}
        for r in rs_on:
            t = r.get("decided-tier", "?")
            tiers[t] = tiers.get(t, 0) + 1
        cheap_rows = sum(v for k, v in tiers.items()
                         if k in ("greedy", "backtrack", "cycle", "trivial"))
        decided_fraction = cheap_rows / len(rs_on)
        print({"family": family, "rung": args.rung, "rows": len(hists),
               "decided_by_tier": tiers,
               "cheap_decided_fraction": round(decided_fraction, 4)})

        variants = [("cheap-on", True), ("cheap-off", False)]
        times = {name: [] for name, _ in variants}
        for rep in range(args.reps):          # interleaved, order rotated
            order = variants if rep % 2 == 0 else variants[::-1]
            for name, cheap in order:
                times[name].append(run(cheap)[0])
        for name, ts in times.items():
            print({"family": family, "variant": name,
                   "min_s": round(min(ts), 3),
                   "median_s": round(statistics.median(ts), 3),
                   "hist_per_s_at_min": round(len(hists) / min(ts), 2),
                   "reps": [round(t, 3) for t in ts]})
        speedup = min(times["cheap-off"]) / min(times["cheap-on"])
        row = {"family": family,
               "speedup_at_min": round(speedup, 3)}
        if family == "register":
            row["acceptance_register_1_2x"] = speedup >= 1.2
            overall_ok &= speedup >= 1.2
        if family == "queue":
            row["acceptance_queue_decided_0_9"] = decided_fraction >= 0.9
            overall_ok &= decided_fraction >= 0.9
        print(row)

        if args.with_lin:
            # PR-9's original axis: the weak rung vs full linearizability
            # (cheap tier on) — the ≈0.77× register regression's A/B.
            set_cheap(True)
            lin_ts, rung_ts = [], []
            run(True, "linearizable")  # warm-up
            for rep in range(args.reps):
                pair = (("lin", "linearizable"), ("rung", args.rung))
                for name, c in pair if rep % 2 == 0 else pair[::-1]:
                    dt, _ = run(True, c)
                    (lin_ts if name == "lin" else rung_ts).append(dt)
            print({"family": family,
                   "rung_vs_lin_speedup_at_min":
                   round(min(lin_ts) / min(rung_ts), 3),
                   "lin_min_s": round(min(lin_ts), 3),
                   "rung_min_s": round(min(rung_ts), 3)})

    for k in ("JGRAFT_GREEDY_CERTIFY", "JGRAFT_CYCLE_TIER"):
        os.environ.pop(k, None)
    print({"acceptance_all": overall_ok})


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Seeded full-stack `hell` soak — the repeatable form of the round-3
campaign (110 hell runs, every history linearizable; BASELINE.md cites the
exact command).

Each iteration drives the COMPLETE stack the way the reference's own
product run does (SURVEY.md §3.1: compose → runner → concurrent clients
over real TCP → nemesis → checker): a real 5-node native raft cluster
(raft_server processes), the full fault set (partitions, kills, pauses,
membership churn — the reference's `hell` special, nemesis.clj:12-22),
aggressive log compaction, and post-hoc verification of the recorded
history through the production checker ladder. A run whose workload
checker reports invalid is a consensus bug (or checker bug — the
counterexample store dir is kept either way); unknown verdicts are
reported as routing gaps.

Seeding: run i uses --seed + i for BOTH the cluster fault schedule and the
generator, so any failure reproduces with
  python scripts/soak_hell.py --runs 1 --seed <failing-seed>

Usage (round-3 scale ≈ 110 runs):
  python scripts/soak_hell.py --runs 110
Quick pass:
  python scripts/soak_hell.py --runs 3
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from jepsen_jgroups_raft_tpu.platform import pin_cpu  # noqa: E402

WORKLOAD_SM = {"single-register": "map", "multi-register": "map",
               "counter": "counter", "election": "election"}


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--runs", type=int, default=10)
    p.add_argument("--seed", type=int, default=100)
    p.add_argument("--workloads",
                   default="single-register,multi-register,counter",
                   help="comma list cycled across runs (default the three "
                        "frontier-checked workloads; add election for the "
                        "invariant checker)")
    p.add_argument("--time-limit", type=float, default=10.0,
                   help="main phase seconds per run (default 10)")
    p.add_argument("--rate", type=float, default=60.0)
    p.add_argument("--concurrency", type=int, default=10)
    p.add_argument("--compact-every", type=int, default=24,
                   help="log-compaction threshold (0 disables; default 24 "
                        "keeps snapshot/InstallSnapshot paths under fire)")
    p.add_argument("--nemesis", default="hell")
    p.add_argument("--nodes", type=int, default=5,
                   help="cluster size (default 5); --vary-nodes overrides")
    p.add_argument("--vary-nodes", action="store_true",
                   help="cycle cluster sizes 3/5/7 across runs for "
                        "fault-space diversity (membership churn against "
                        "different majority thresholds)")
    p.add_argument("--keep-stores", action="store_true",
                   help="keep every run's store dir (default: only "
                        "failures are kept)")
    p.add_argument("--san", choices=["tsan", "asan"], default=None,
                   help="run the SUT under a sanitizer build "
                        "(native/build-<san>/raft_server): the full "
                        "stack with real faults becomes the race/memory "
                        "detector's workload. Expect 5-15x SUT slowdown; "
                        "size --runs/--time-limit accordingly")
    return p.parse_args(argv)


def scan_sanitizer_logs(cluster, nodes, san: str) -> int:
    """Count sanitizer reports in the SUT node logs (markers shared
    with tests/test_tsan.py via native.SAN_MARKERS). Called on BOTH the
    success and exception paths: a wedged run under --san is the most
    likely place for a race report to be waiting."""
    from jepsen_jgroups_raft_tpu.native import SAN_MARKERS

    hits = 0
    for node in nodes:
        try:
            text = Path(cluster.log_path(node)).read_text(errors="ignore")
        except OSError:
            continue
        for marker in SAN_MARKERS[san]:
            hits += text.count(marker)
    return hits


def one_run(i: int, args, workload: str, n: int, workdir: Path) -> dict:
    from jepsen_jgroups_raft_tpu.core.compose import compose_test
    from jepsen_jgroups_raft_tpu.core.runner import run_test
    from jepsen_jgroups_raft_tpu.deploy.local import (BlockNet, LocalCluster,
                                                      LocalRaftDB)

    seed = args.seed + i
    nodes = [f"n{k}" for k in range(1, n + 1)]
    server_bin = None
    if args.san:
        from jepsen_jgroups_raft_tpu.native import NATIVE_DIR, ensure_built
        ensure_built(args.san)
        server_bin = str(NATIVE_DIR / f"build-{args.san}" / "raft_server")
    cluster = LocalCluster(nodes, sm=WORKLOAD_SM[workload],
                           workdir=str(workdir / "sut"),
                           election_ms=150, heartbeat_ms=50,
                           repl_timeout_ms=3000,
                           compact_every=args.compact_every,
                           server_bin=server_bin)
    opts = {
        "name": f"soak-hell-{i}", "nodes": nodes,
        "workload": workload, "nemesis": args.nemesis,
        "conn_factory": cluster.conn_factory(),
        "rate": args.rate, "interval": 1.5,
        "time_limit": args.time_limit, "quiesce": 1.0,
        "operation_timeout": 2.0, "concurrency": args.concurrency,
        "store_root": str(workdir / "store"),
    }
    if workload == "election":
        # Wire the every-node views probe so election runs soak the
        # opt-in cross-node majority model, not just inspect parity
        # (same wiring as the CLI, cli.py election branch).
        opts["views_probe"] = cluster.views_probe
    test = compose_test(opts, db=LocalRaftDB(cluster, seed=seed),
                        net=BlockNet(cluster), seed=seed)
    err = None
    try:
        test = run_test(test)
    except Exception as e:  # noqa: BLE001 — a wedged run is a finding
        err = f"{type(e).__name__}: {e}"
    finally:
        cluster.shutdown()
    # The sanitizer reports and continues; a clean checker verdict — or
    # a WEDGED run, the likeliest place for a race report to be waiting
    # under a 5-15x-slowed SUT — with reports in the logs is a finding.
    san_warnings = (scan_sanitizer_logs(cluster, nodes, args.san)
                    if args.san else 0)
    if err is not None:
        return {"seed": seed, "workload": workload, "nodes": n,
                "valid": None, "error": err,
                "san_warnings": san_warnings, "store_dir": str(workdir)}
    res = test["results"]
    wl = res.get("workload", {})
    return {
        "seed": seed,
        "nodes": n,
        "workload": workload,
        "valid": wl.get("valid?"),
        "san_warnings": san_warnings,
        "ok_ops": sum(1 for op in test["history"] if op.type == "ok"),
        "info_ops": sum(1 for op in test["history"] if op.type == "info"),
        "store_dir": test["store_dir"],
        "pressure": _pressure(wl),
    }


def _pressure(wl: dict) -> dict:
    """Checker-pressure profile of one run (VERDICT r4 #4): how many
    per-key checks ran, which engine/kernel decided them, the
    concurrency-window distribution, and total checking time — the
    shape data that says what a canonical-envelope run actually asks
    of the linearizability ladder."""
    lin = wl.get("linear", {})
    interval = lin.get("checker") == "counter-interval"
    if interval:
        # Decided at the bounds tier AFTER the exact engines burned
        # their budgets — profile the exact attempt (it is the most
        # expensive part of exactly these runs) and mark the tier.
        lin = lin.get("exact", {})
    per_key = lin.get("results")
    rows = (list(per_key.values()) if isinstance(per_key, dict)
            else [lin] if lin.get("algorithm") else [])
    windows: dict = {}
    engines: dict = {}
    ops = 0
    t = 0.0
    for r in rows:
        if not isinstance(r, dict):
            continue
        w = r.get("concurrency-window")
        if w is not None:
            windows[str(w)] = windows.get(str(w), 0) + 1
        eng = r.get("kernel") or r.get("algorithm")
        if eng:
            engines[eng] = engines.get(eng, 0) + 1
        ops += int(r.get("op-count") or 0)
        t += float(r.get("time-s") or 0.0)
    if interval:
        engines["interval"] = 1  # the tier that actually decided
    return {"keys": len(rows), "checked_ops": ops,
            "check_time_s": round(t, 2),
            "windows": dict(sorted(windows.items(), key=lambda kv: int(kv[0]))),
            "engines": engines}


def main(argv=None) -> int:
    args = parse_args(argv)
    pin_cpu(8)  # the checker side; the cluster is real processes either way
    if args.san == "asan" and "ASAN_OPTIONS" not in os.environ:
        # ASAN halts the process on the first error by default — the
        # soak wants a full run of reports, not a dead node that caps
        # coverage at one finding (an operator-set ASAN_OPTIONS wins).
        os.environ["ASAN_OPTIONS"] = "halt_on_error=0"
    workloads = [w.strip() for w in args.workloads.split(",") if w.strip()]
    for w in workloads:
        if w not in WORKLOAD_SM:
            print(f"unknown workload {w!r}", file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    failures, unknowns = [], []
    for i in range(args.runs):
        workload = workloads[i % len(workloads)]
        # Size cycle advances once per FULL workload cycle so every
        # workload×size combination is reached (a lockstep i%3 cycle
        # would pin each workload to one fixed size — round-4 reviewer
        # finding).
        n = ((3, 5, 7)[(i // len(workloads)) % 3] if args.vary_nodes
             else args.nodes)
        workdir = Path(tempfile.mkdtemp(prefix=f"soak-hell-{i}-"))
        try:
            r = one_run(i, args, workload, n, workdir)
        except Exception as e:  # noqa: BLE001 — a wedged run is a finding
            r = {"seed": args.seed + i, "workload": workload, "nodes": n,
                 "valid": None, "error": f"{type(e).__name__}: {e}",
                 "store_dir": str(workdir)}
        if r.get("san_warnings"):
            r["valid"] = False
            msg = f"{r['san_warnings']} sanitizer report(s) in SUT logs"
            r["error"] = f"{r['error']}; {msg}" if r.get("error") else msg
        keep = args.keep_stores or r["valid"] is not True
        if not keep:
            shutil.rmtree(workdir, ignore_errors=True)
        if r["valid"] is True:
            status = "ok"
        elif r["valid"] is False:
            status = "INVALID"
            failures.append(r)
        else:
            status = "unknown/error"
            (failures if r.get("error") else unknowns).append(r)
        pr = r.get("pressure") or {}
        print(f"  run {i + 1}/{args.runs} seed={r['seed']} "
              f"{workload}: {status}"
              + (f" ok={r.get('ok_ops')} info={r.get('info_ops')} "
                 f"keys={pr.get('keys')} windows={pr.get('windows')} "
                 f"engines={pr.get('engines')} "
                 f"check_s={pr.get('check_time_s')}"
                 if "ok_ops" in r else "")
              + (f" (kept {r['store_dir']})" if keep else ""), flush=True)

    dt = time.perf_counter() - t0
    print(json.dumps({
        "runs": args.runs, "nemesis": args.nemesis,
        "failures": len(failures), "unknowns": len(unknowns),
        "time_s": round(dt, 1), "seed": args.seed,
        "workloads": workloads,
    }))
    for r in failures + unknowns:
        print("FINDING:", json.dumps(r), file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# The one-command static-analysis gate (ISSUE 1 tentpole + ISSUE 2 flow tier):
#   1. ruff       — generic Python hygiene (pyproject.toml config); skipped
#                   with a message when not installed (the container doesn't
#                   ship it; CI images may).
#   2. graftlint  — the pattern analyzers: taxonomy soundness, jit/trace
#                   hygiene, native lock discipline.
#   3. graftcheck — the CFG/dataflow tier (lint/flow/): Pallas kernel
#                   contracts, nemesis fault↔heal pairing, resource leaks
#                   across exception paths; gated on the checked-in
#                   baseline (lint/baseline.json) so only REGRESSIONS fail.
#   4. graftsync  — the concurrency + crash-consistency tier (ISSUE 16):
#                   guarded_by lock discipline, lock-order cycles against
#                   the documented hierarchy, WAL fsync/atomic-publish
#                   protocol, and the JGRAFT_* env-knob registry (emitted
#                   as build/knob_registry.json).
#   5. graftgate  — the verdict-integrity dataflow tier (ISSUE 17):
#                   fingerprint completeness, degraded-result quarantine,
#                   routing/verdict knob separation, tier-stamp totality,
#                   and the duplicated-certifier lock-step tripwire.
#   6. make tidy  — curated clang-tidy over native/src (self-skipping when
#                   clang-tidy is absent, same pattern as SKIP_TSAN=1).
# Stages 2-5 are pure stdlib (no jax import) so they never need skipping.
# Exit nonzero on any finding. tests/test_lint.py + tests/test_lint_flow.py
# keep stages 2-3 green by construction (self-hosting: the suite lints the
# repo that contains it).
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff =="
    ruff check .
else
    echo "== ruff: not installed — skipping (graftlint still runs) =="
fi

echo "== graftlint (pattern tier) =="
python -m jepsen_jgroups_raft_tpu.lint --rules taxonomy,jit,lock

echo "== graftcheck (CFG/dataflow tier) =="
python -m jepsen_jgroups_raft_tpu.lint --rules kernel,heal,resource \
    --baseline jepsen_jgroups_raft_tpu/lint/baseline.json

echo "== graftsync (concurrency + crash-consistency tier) =="
mkdir -p build
python -m jepsen_jgroups_raft_tpu.lint \
    --rules guarded,lockorder,crashproto,envknobs \
    --baseline jepsen_jgroups_raft_tpu/lint/baseline.json \
    --knob-registry build/knob_registry.json
test -s build/knob_registry.json  # the registry artifact must exist

echo "== graftgate (verdict-integrity tier) =="
python -m jepsen_jgroups_raft_tpu.lint \
    --rules fingerprint,degraded,knobclass,tierstamp,lockstep \
    --baseline jepsen_jgroups_raft_tpu/lint/baseline.json --timing

echo "== clang-tidy =="
make -C native tidy

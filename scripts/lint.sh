#!/usr/bin/env bash
# The one-command static-analysis gate (ISSUE 1 tentpole):
#   1. ruff     — generic Python hygiene (pyproject.toml config); skipped
#                 with a message when not installed (the container doesn't
#                 ship it; CI images may).
#   2. graftlint — the project-native analyzers: taxonomy soundness,
#                 jit/trace hygiene, native lock discipline.
#   3. make tidy — curated clang-tidy over native/src (self-skipping when
#                 clang-tidy is absent, same pattern as SKIP_TSAN=1).
# Exit nonzero on any finding. tests/test_lint.py keeps step 2 green by
# construction (self-hosting: the suite lints the repo that contains it).
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff =="
    ruff check .
else
    echo "== ruff: not installed — skipping (graftlint still runs) =="
fi

echo "== graftlint =="
python -m jepsen_jgroups_raft_tpu.lint

echo "== clang-tidy =="
make -C native tidy

"""Single-process A/B: Pallas tile kernel vs vmapped XLA dense kernel
on the north-star batch (the compete-or-retire measurement, VERDICT r4
#2). Cross-process comparison is meaningless on the tunneled chip
(identical dense benches spanned 249-475 hist/s), so both engines run
interleaved in ONE process and the per-engine min/median decide.

Usage: python scripts/ab_pallas.py [--reps 5]
"""
import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--n-histories", type=int, default=1000)
    ap.add_argument("--n-ops", type=int, default=1000)
    args = ap.parse_args()

    import random

    import numpy as np

    from jepsen_jgroups_raft_tpu.history.packing import (encode_history,
                                                         pack_batch,
                                                         pad_batch_bucketed)
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models.register import CasRegister
    from jepsen_jgroups_raft_tpu.ops.dense_scan import (
        dense_plans_grouped, make_dense_batch_checker)
    from jepsen_jgroups_raft_tpu.ops.pallas_scan import (
        make_pallas_batch_checker)

    rng = random.Random(20260729)
    model = CasRegister()
    hists = [random_valid_history(rng, "register", n_ops=args.n_ops,
                                  n_procs=5, crash_p=0.05, max_crashes=3)
             for _ in range(args.n_histories)]
    encs = [encode_history(h, model) for h in hists]
    grouped, rest = dense_plans_grouped(model, encs)
    assert not rest
    batch = pack_batch(encs)
    # Pre-pad once: both engines consume identical [B, E, 5] groups.
    padded = []
    for idxs, plan in grouped:
        ev, (val_of,), B = pad_batch_bucketed(batch["events"][idxs],
                                              (plan.val_of,))
        padded.append((plan, np.asarray(ev), np.asarray(val_of), B))

    def run_dense():
        t0 = time.perf_counter()
        outs = [(make_dense_batch_checker(model, p.kind, p.n_slots,
                                          p.n_states)(ev, vf), B)
                for p, ev, vf, B in padded]
        n = sum(int(np.asarray(ok)[:B].sum()) for (ok, _), B in outs)
        return time.perf_counter() - t0, n

    import jax

    interpret = jax.default_backend() != "tpu"  # smoke-testable off-chip

    def run_pallas():
        t0 = time.perf_counter()
        outs = [(make_pallas_batch_checker(model, p.n_slots, p.n_states,
                                           ev.shape[1],
                                           interpret=interpret)(ev, vf), B)
                for p, ev, vf, B in padded]
        n = sum(int(np.asarray(ok)[:B].sum()) for (ok, _), B in outs)
        return time.perf_counter() - t0, n

    engines = {"dense": run_dense, "pallas": run_pallas}
    valid = {}
    for name, fn in engines.items():        # warm-up: compile
        _, valid[name] = fn()
    assert valid["dense"] == valid["pallas"] == args.n_histories, valid
    times = {n: [] for n in engines}
    for _ in range(args.reps):              # interleaved
        for name, fn in engines.items():
            times[name].append(fn()[0])
    for name, ts in times.items():
        print({"engine": name, "min_s": round(min(ts), 3),
               "median_s": round(statistics.median(ts), 3),
               "hist_per_s_at_min": round(args.n_histories / min(ts), 1),
               "hist_per_s_at_median":
                   round(args.n_histories / statistics.median(ts), 1),
               "reps": [round(t, 3) for t in ts]})


if __name__ == "__main__":
    main()

"""Single-process interleaved A/B for wire-speed ingest (ISSUE-18
acceptance measurement).

The tentpole claim is about the INGEST path — client-side columnar
encode + binary frame transport + the same-host unix-socket lane — so
the timed waves isolate admission from checking: each wave drives a
fresh ``CheckingService(autostart=False)`` (scheduler parked, nothing
competes with the submitters for the CPU) behind a real HTTP listener,
with ``queue_capacity = 2 * n_requests`` so no wave ever sees a 429.
Every payload is unique (identical payloads would exercise idempotent
attach, not admission). Three phases:

1. **identity** — before any timing, the SAME histories go through a
   normal (checking) daemon as JSON, as binary frames over TCP, and as
   binary frames over the unix socket; all three must produce the same
   fingerprint and bitwise-identical verdict results. A transport that
   changes verdicts has no business being fast.
2. **encoding** — JSON bodies vs binary frames, both over TCP
   loopback, >= 16 concurrent submitters, interleaved with order
   rotated per rep. Bar: binary >= 1.5x JSON ingest req/s OR >= 1.5x
   lower p99 submit latency (the ISSUE-18 acceptance disjunction).
3. **lane** — binary frames over TCP loopback vs the same frames over
   the unix-domain socket. Bar: UDS > TCP.

Verdicts are judged on the MEDIAN of >= 3 interleaved reps (ingest
waves are N threads timeslicing one CPU — wall clocks are multi-modal
scheduler noise; min-of-few hands the verdict to the lucky rep — the
same mood-vs-median caveat scripts/ab_hostpath.py documents).

Usage: python scripts/ab_ingest.py [--reps 3] [--requests 64]
       [--n-histories 2] [--n-ops 200] [--clients 16]
"""
import argparse
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pct(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--n-histories", type=int, default=2)
    ap.add_argument("--n-ops", type=int, default=200)
    ap.add_argument("--clients", type=int, default=16)
    args = ap.parse_args()
    assert args.clients >= 16, \
        "the ISSUE-18 bar is defined at >= 16 concurrent submitters"

    import random
    import tempfile

    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.service import (CheckingService,
                                                 ServiceClient,
                                                 serve_in_thread)
    from jepsen_jgroups_raft_tpu.service.http import serve_uds_in_thread

    overall_ok = True
    rng = random.Random(20260807)

    def payload(i):
        # unique per request AND per wave arm: a repeated payload would
        # hit idempotent attach and measure the dedup index, not ingest
        return [random_valid_history(rng, "register", n_ops=args.n_ops,
                                     n_procs=5, crash_p=0.05,
                                     max_crashes=3)
                for _ in range(args.n_histories)]

    # ------------------------------------------------- 1. identity
    svc = CheckingService(store_root=None, name="ab-ingest-id")
    httpd, port, _t = serve_in_thread(svc)
    sock = os.path.join(tempfile.mkdtemp(prefix="ab-ingest-uds-"),
                        "graftd.sock")
    uds_httpd, _ut = serve_uds_in_thread(svc, sock)
    tcp = ServiceClient(f"http://127.0.0.1:{port}", timeout=60.0)
    uds = ServiceClient("unix:" + sock, timeout=60.0)
    probe = payload(0)
    recs = [tcp.submit(probe, workload="register", binary=False),
            tcp.submit(probe, workload="register", binary=True),
            uds.submit(probe, workload="register", binary=True)]
    fps = {r["fingerprint"] for r in recs}
    assert len(fps) == 1, f"fingerprints diverge across transports: {fps}"
    results = []
    for r in recs:
        out = tcp.result(r["id"], wait_s=120.0)
        while out["status"] not in ("done", "failed", "cancelled"):
            out = tcp.result(r["id"], wait_s=120.0)
        assert out["status"] == "done", out
        results.append(out["results"])
    assert results[0] == results[1] == results[2], \
        "verdict results diverge across transports"
    httpd.shutdown(); httpd.server_close()
    uds_httpd.shutdown(); uds_httpd.server_close()
    svc.shutdown(wait=True)
    print({"phase": "identity", "fingerprint": recs[0]["fingerprint"],
           "verdicts_identical": True,
           "transports": ["json+tcp", "binary+tcp", "binary+uds"]})

    # ------------------------------------------- timed ingest waves
    def wave(binary: bool, lane: str):
        """One ingest-only wave: fresh parked daemon, fresh listener,
        args.requests unique submissions from args.clients threads.
        Returns (wall_s, submit latencies)."""
        service = CheckingService(store_root=None, name="ab-ingest",
                                  cache_capacity=0,
                                  queue_capacity=args.requests * 2,
                                  autostart=False)
        if lane == "uds":
            d = tempfile.mkdtemp(prefix="ab-ingest-uds-")
            spath = os.path.join(d, "graftd.sock")
            srv, _th = serve_uds_in_thread(service, spath)
            url = "unix:" + spath
        else:
            srv, p, _th = serve_in_thread(service)
            url = f"http://127.0.0.1:{p}"
        pls = [payload(i) for i in range(args.requests)]
        idx = iter(range(args.requests))
        lock = threading.Lock()
        lats: list = []

        def submitter():
            cl = ServiceClient(url, timeout=60.0)
            while True:
                with lock:
                    i = next(idx, None)
                if i is None:
                    return
                t0 = time.perf_counter()
                cl.submit(pls[i], workload="register", binary=binary)
                dt = time.perf_counter() - t0
                with lock:
                    lats.append(dt)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=submitter, daemon=True)
                   for _ in range(args.clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        srv.shutdown()
        srv.server_close()
        service.shutdown(wait=True)
        assert len(lats) == args.requests
        return wall, lats

    def ab(label, arms, bar_note):
        """Interleaved A/B over `arms` ({name: (binary, lane)}),
        median-of-reps; returns {name: (med_wall, p99)}."""
        names = list(arms)
        for n in names:               # warm-up both arms, uncounted
            wave(*arms[n])
        walls = {n: [] for n in names}
        p99s = {n: [] for n in names}
        for rep in range(max(3, args.reps)):
            order = names if rep % 2 == 0 else names[::-1]
            for n in order:
                w, lats = wave(*arms[n])
                walls[n].append(w)
                p99s[n].append(pct(lats, 0.99))
        out = {}
        for n in names:
            out[n] = (statistics.median(walls[n]),
                      statistics.median(p99s[n]))
        print({"phase": label, "bar": bar_note,
               "n_requests": args.requests,
               "histories_per_request": args.n_histories,
               "n_ops": args.n_ops, "client_concurrency": args.clients,
               **{f"{n}_req_s": round(args.requests / out[n][0], 2)
                  for n in names},
               **{f"{n}_p99_s": round(out[n][1], 4) for n in names},
               "rep_walls_s": {n: [round(t, 3) for t in walls[n]]
                               for n in names}})
        return out

    # ------------------------------------------------- 2. encoding
    enc = ab("encoding", {"binary": (True, "tcp"), "json": (False, "tcp")},
             "binary >= 1.5x json req/s OR >= 1.5x lower p99 @ >=16 subs")
    sp_req = enc["json"][0] / enc["binary"][0]
    sp_p99 = enc["json"][1] / max(enc["binary"][1], 1e-9)
    enc_ok = sp_req >= 1.5 or sp_p99 >= 1.5
    print({"phase": "encoding", "req_s_speedup": round(sp_req, 3),
           "p99_speedup": round(sp_p99, 3), "acceptance_1_5x": enc_ok})
    overall_ok &= enc_ok

    # ----------------------------------------------------- 3. lane
    lane = ab("lane", {"uds": (True, "uds"), "tcp": (True, "tcp")},
              "binary over UDS beats binary over TCP loopback")
    sp_lane = lane["tcp"][0] / lane["uds"][0]
    lane_ok = sp_lane > 1.0
    print({"phase": "lane", "uds_speedup": round(sp_lane, 3),
           "acceptance_uds_beats_tcp": lane_ok})
    overall_ok &= lane_ok

    print({"acceptance_all": overall_ok})


if __name__ == "__main__":
    main()

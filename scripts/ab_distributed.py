"""Distributed-tier A/B (ISSUE 7 acceptance measurements).

Methodology per the repo's perf rules (cross-process comparisons
measure the host's mood — BENCH_r05→PR3 drift notes): everything that
CAN be same-process is same-process and interleaved with per-rep order
rotation; the one genuinely multi-process comparison (part 3) runs
fresh interpreters for BOTH variants, alternating order per rep, so
neither side systematically owns the warm cache.

1. **Per-host packing** — global `pack_macro_batch` of the full batch
   vs per-shard `pack_macro_batch_shard`; the per-host wall is the MAX
   shard time (shards run concurrently on different hosts' CPUs in the
   real topology). Acceptance: ≥ 1.3× at the north-star 1000×1k shape.
2. **Row-shard verdict identity** — `check_encoded` of the full batch
   vs the concatenation of per-shard `check_encoded` runs, asserted
   bitwise-identical BEFORE anything is timed (the same-process half
   of the acceptance pin; the real 2-process transport differential
   lives in tests/test_distributed.py).
3. **End-to-end** — `bench.py H W` (1 process, 8 vdevs) vs
   `bench.py --distributed 2 H W` (2 processes, 4 vdevs each),
   interleaved. Acceptance: 2-process ≥ 0.9× single-process hist/s on
   this TPU-less host (overhead bound — the fan-out win is claimed on
   real pods, per ROADMAP's degraded-host caveat).

Usage: python scripts/ab_distributed.py [--reps 3] [--n-histories 1000]
       [--n-ops 1000] [--processes 2] [--identity-histories 64]
       [--skip-e2e]
"""
import argparse
import json
import os
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--n-histories", type=int, default=1000)
    ap.add_argument("--n-ops", type=int, default=1000)
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--identity-histories", type=int, default=64)
    ap.add_argument("--skip-e2e", action="store_true")
    args = ap.parse_args()

    from jepsen_jgroups_raft_tpu.platform import pin_cpu

    pin_cpu(8)  # the production CPU mesh the single-process bench uses

    import random

    from jepsen_jgroups_raft_tpu.checker.linearizable import check_encoded
    from jepsen_jgroups_raft_tpu.history.packing import (
        encode_history, pack_macro_batch, pack_macro_batch_shard)
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models.register import CasRegister
    from jepsen_jgroups_raft_tpu.parallel.distributed import shard_bounds

    N = args.processes
    rng = random.Random(3)
    model = CasRegister()
    hists = [random_valid_history(rng, "register", n_ops=args.n_ops,
                                  n_procs=5, crash_p=0.05, max_crashes=3)
             for _ in range(args.n_histories)]
    encs = [encode_history(h, model) for h in hists]

    # ---- part 1: per-host packing -----------------------------------
    def pack_global():
        t0 = time.perf_counter()
        pack_macro_batch(encs)
        return time.perf_counter() - t0

    def pack_per_host():
        walls = []
        for p in range(N):
            t0 = time.perf_counter()
            pack_macro_batch_shard(encs, p, N)
            walls.append(time.perf_counter() - t0)
        return max(walls)  # concurrent shards: the slowest host gates

    variants = {"global": pack_global, "per_host": pack_per_host}
    for fn in variants.values():        # warm-up (allocator, caches)
        fn()
    times = {name: [] for name in variants}
    for rep in range(args.reps):        # interleaved, order rotating
        order = list(variants)[rep % 2:] + list(variants)[:rep % 2]
        for name in order:
            times[name].append(variants[name]())
    for name, ts in times.items():
        print({"variant": f"pack-{name}", "min_s": round(min(ts), 3),
               "median_s": round(statistics.median(ts), 3),
               "reps": [round(t, 3) for t in ts]})
    pack_speedup = min(times["global"]) / min(times["per_host"])
    print({"pack_speedup_at_min": round(pack_speedup, 3),
           "pack_acceptance_1_3x": pack_speedup >= 1.3,
           "n_shards": N})

    # ---- part 2: row-shard verdict identity -------------------------
    sub = encs[:args.identity_histories]
    full = [r["valid?"] for r in check_encoded(sub, model)]
    sharded = []
    for p in range(N):
        lo, hi = shard_bounds(len(sub), N, p)
        sharded.extend(r["valid?"] for r in check_encoded(sub[lo:hi], model))
    assert full == sharded, "row-shard verdicts diverged from full batch"
    print({"identity_rows": len(sub), "verdicts_identical": True})

    if args.skip_e2e:
        return

    # ---- part 3: end-to-end 1-process vs N-process ------------------
    env = dict(os.environ)
    env.update({"JGRAFT_BENCH_PLATFORM": "cpu", "JGRAFT_BENCH_REPS": "1",
                "JGRAFT_AUTOTUNE": "0"})

    def bench_once(distributed: bool) -> float:
        cmd = [sys.executable, os.path.join(REPO, "bench.py")]
        if distributed:
            cmd += ["--distributed", str(N)]
        cmd += [str(args.n_histories), str(args.n_ops)]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=3600, env=env)
        if out.returncode != 0:
            raise RuntimeError(f"bench rc={out.returncode}: "
                               f"{out.stderr[-1000:]}")
        rows = [json.loads(ln) for ln in out.stdout.splitlines()
                if ln.strip().startswith("{")]
        [row] = [r for r in rows if r.get("metric") == "histories_per_sec"]
        if "error" in row:
            raise RuntimeError(f"bench error: {row['error']}")
        return float(row["value"])

    e2e = {"1p": [], f"{N}p": []}
    for rep in range(args.reps):
        order = [False, True] if rep % 2 == 0 else [True, False]
        for dist in order:
            key = f"{N}p" if dist else "1p"
            e2e[key].append(bench_once(dist))
            print({"variant": f"e2e-{key}", "rep": rep,
                   "hist_per_s": round(e2e[key][-1], 2)})
    best_1p, best_np = max(e2e["1p"]), max(e2e[f"{N}p"])
    ratio = best_np / best_1p
    print({"e2e_1p_hist_per_s": round(best_1p, 2),
           f"e2e_{N}p_hist_per_s": round(best_np, 2),
           "ratio": round(ratio, 3),
           "e2e_acceptance_0_9x": ratio >= 0.9})


if __name__ == "__main__":
    main()

"""Single-process interleaved A/B for the host-path turbo (ISSUE-15
acceptance measurement).

PRs 13-14 moved most production verdicts off the kernels and onto the
host; this PR vectorizes that host path. Four phases, every one
measured same-process with candidate rotation (the methodology this
repo requires for perf claims — cross-process comparisons measure the
host's mood), and every one asserting IDENTITY before timing:

1. **encode** — `encode_history` vectorized columnar (default) vs the
   per-pair Python oracle (``JGRAFT_ENCODE_VECTOR=0``) at the
   1000×1k-op north-star register shape; packed tensors asserted
   byte-identical first. Bar: >= 2.0x.
2. **certify** — the batched NumPy certifier core
   (`checker.certify_batch.certify_many`, default) vs the row-by-row
   scalar engine (``JGRAFT_CERTIFY_BATCH=0``) on the register / set /
   queue families at 200×1k; per-row (verdict, tier, flips) triples
   asserted identical first. Bar: >= 1.5x on at least TWO families
   (register is the known backtrack-dominated boundary family — the
   batch core hands its rows to the scalar engine and roughly breaks
   even there by design).
3. **fingerprints** — the zero-copy (memoryview-fed) sha256 digests
   asserted byte-identical to a `tobytes()` reference implementation
   (the cache/WAL key must never move), wall reported.
4. **service** — `bench.py --service`-shaped load (8 concurrent
   submitters, journal ON) against one live graftd daemon at its
   admission surface (`CheckingService.submit`): host-path turbo on
   (defaults) vs all three knobs pinned to today's scalar behavior
   (``JGRAFT_ENCODE_VECTOR=0 JGRAFT_CERTIFY_BATCH=0
   JGRAFT_JOURNAL_GROUP_MS=0``), interleaved; every verdict asserted
   DONE+valid in both arms. Bar: >= 1.3x req/s on the MEDIAN of >= 3
   interleaved reps (wave walls on a 1-CPU host are multi-modal
   scheduler noise; min-of-few hands the verdict to the lucky rep —
   see the in-code note). Two deliberate
   measurement choices: (a) the payload is the queue family at 128
   histories/request — every row decides host-side (the PR-14 fast
   lane), so the A/B measures the HOST path this PR vectorizes (a
   kernel-routed payload would measure XLA launches the PR does not
   touch), and 128 rows clears the batch core's measured engagement
   floor (`JGRAFT_CERTIFY_BATCH_MIN`, crossover ~96-128 rows on this
   host); (b) submissions ride the in-process admission surface, not
   HTTP — serializing 128x200-op histories to JSON in the client
   threads costs ~3x the entire checked path PER REQUEST, identical
   bytes in both arms, and on the 1-CPU host that harness wall
   drowns the effect under scheduler noise (measured: same change
   reads 0.9-1.2x over HTTP, 1.4-1.5x at the surface where all four
   turbo legs actually live — encode-once, certify, WAL fsync). The
   HTTP surface itself is covered by CI's service smokes and
   `bench.py --service`.

Usage: python scripts/ab_hostpath.py [--reps 3] [--n-histories 1000]
       [--n-ops 1000] [--cert-histories 200] [--requests 16]
       [--skip-service]
"""
import argparse
import os
import statistics
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TURBO_KNOBS = ("JGRAFT_ENCODE_VECTOR", "JGRAFT_CERTIFY_BATCH",
               "JGRAFT_JOURNAL_GROUP_MS")


def _set_arm(on: bool) -> None:
    for k in TURBO_KNOBS:
        if on:
            os.environ.pop(k, None)      # defaults = turbo on
        else:
            os.environ[k] = "0"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--n-histories", type=int, default=1000)
    ap.add_argument("--n-ops", type=int, default=1000)
    ap.add_argument("--cert-histories", type=int, default=200)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--skip-service", action="store_true")
    args = ap.parse_args()

    import random

    import numpy as np

    from jepsen_jgroups_raft_tpu.checker.certify_batch import certify_many
    from jepsen_jgroups_raft_tpu.history.packing import encode_history
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models import CasRegister, GSet, \
        TicketQueue

    overall_ok = True

    # ---------------------------------------------------- 1. encode
    rng = random.Random(20260804)
    model = CasRegister()
    hists = [random_valid_history(rng, "register", n_ops=args.n_ops,
                                  n_procs=5, crash_p=0.05, max_crashes=3)
             for _ in range(args.n_histories)]

    def encode_all():
        return [encode_history(h, model) for h in hists]

    _set_arm(True)
    enc_on = encode_all()
    os.environ["JGRAFT_ENCODE_VECTOR"] = "0"
    enc_off = encode_all()
    os.environ.pop("JGRAFT_ENCODE_VECTOR")
    for a, b in zip(enc_on, enc_off):
        assert np.array_equal(a.events, b.events) \
            and np.array_equal(a.op_index, b.op_index) \
            and np.array_equal(a.proc, b.proc) \
            and a.n_slots == b.n_slots and a.n_ops == b.n_ops, \
            "encode vector/oracle packed tensors diverge"
    times = {"vector": [], "oracle": []}
    for rep in range(args.reps):
        order = [("vector", "1"), ("oracle", "0")]
        if rep % 2:
            order.reverse()
        for name, v in order:
            os.environ["JGRAFT_ENCODE_VECTOR"] = v
            t0 = time.perf_counter()
            encode_all()
            times[name].append(time.perf_counter() - t0)
    os.environ.pop("JGRAFT_ENCODE_VECTOR")
    sp_enc = min(times["oracle"]) / min(times["vector"])
    print({"phase": "encode", "shape": f"{args.n_histories}x{args.n_ops}",
           "vector_min_s": round(min(times["vector"]), 3),
           "oracle_min_s": round(min(times["oracle"]), 3),
           "reps": {k: [round(t, 3) for t in v] for k, v in times.items()},
           "speedup": round(sp_enc, 3),
           "acceptance_2_0x": sp_enc >= 2.0})
    overall_ok &= sp_enc >= 2.0

    # --------------------------------------------------- 2. certify
    wins = 0
    for fam, cls in (("register", CasRegister), ("set", GSet),
                     ("queue", TicketQueue)):
        m = cls()
        rng = random.Random(13)
        hs = [random_valid_history(rng, fam, n_ops=args.n_ops, n_procs=5,
                                   crash_p=0.05, max_crashes=3)
              for _ in range(args.cert_histories)]
        encs = [encode_history(h, m) for h in hs]
        _set_arm(True)
        res_on = certify_many(encs, m)
        os.environ["JGRAFT_CERTIFY_BATCH"] = "0"
        res_off = certify_many(encs, m)
        os.environ.pop("JGRAFT_CERTIFY_BATCH")
        assert res_on == res_off, \
            f"{fam}: batched/scalar certifier outcomes diverge"
        certified = sum(1 for ok, _, _ in res_on if ok)
        t_ab = {"batch": [], "scalar": []}
        for rep in range(args.reps):
            order = [("batch", None), ("scalar", "0")]
            if rep % 2:
                order.reverse()
            for name, v in order:
                if v is None:
                    os.environ.pop("JGRAFT_CERTIFY_BATCH", None)
                else:
                    os.environ["JGRAFT_CERTIFY_BATCH"] = v
                t0 = time.perf_counter()
                certify_many(encs, m)
                t_ab[name].append(time.perf_counter() - t0)
        os.environ.pop("JGRAFT_CERTIFY_BATCH", None)
        sp = min(t_ab["scalar"]) / min(t_ab["batch"])
        row = {"phase": "certify", "family": fam,
               "rows": len(encs),
               "certified_fraction": round(certified / len(encs), 4),
               "batch_min_s": round(min(t_ab["batch"]), 3),
               "scalar_min_s": round(min(t_ab["scalar"]), 3),
               "speedup": round(sp, 3), "clears_1_5x": sp >= 1.5}
        wins += int(sp >= 1.5)
        print(row)
    print({"phase": "certify", "families_clearing_1_5x": wins,
           "acceptance_two_families_1_5x": wins >= 2})
    overall_ok &= wins >= 2

    # ----------------------------------------------- 3. fingerprints
    import hashlib

    from jepsen_jgroups_raft_tpu.service.request import \
        fingerprint_encodings

    sub = enc_on[:64]

    def reference_fp(mdl, algorithm, encs, consistency):
        h = hashlib.sha256()
        h.update(type(mdl).__name__.encode())
        h.update(b"\x00")
        h.update(algorithm.encode())
        weak = consistency != "linearizable"
        if weak:
            h.update(b"\x00")
            h.update(consistency.encode())
        for e in encs:
            h.update(np.asarray(e.events.shape, dtype=np.int64).tobytes())
            h.update(np.ascontiguousarray(e.events).tobytes())
            h.update(np.int64(e.n_slots).tobytes())
            if weak:
                h.update(b"\x01" if e.proc is not None else b"\x00")
                if e.proc is not None:
                    h.update(np.ascontiguousarray(
                        np.asarray(e.proc, dtype=np.int32)).tobytes())
        return h.hexdigest()

    fp_same = all(
        fingerprint_encodings(model, "auto", sub, c)
        == reference_fp(model, "auto", sub, c)
        for c in ("linearizable", "sequential", "session"))
    t0 = time.perf_counter()
    for _ in range(5):
        fingerprint_encodings(model, "auto", sub)
    fp_wall = (time.perf_counter() - t0) / 5
    print({"phase": "fingerprint", "rows": len(sub),
           "byte_identical": fp_same,
           "hash_wall_s": round(fp_wall, 4)})
    overall_ok &= fp_same

    # -------------------------------------------------- 4. service
    if not args.skip_service:
        from jepsen_jgroups_raft_tpu.service import CheckingService

        n_requests = args.requests
        n_hists, svc_ops, n_clients = 128, 200, 8
        rng = random.Random(20260805)
        pool = [random_valid_history(rng, "queue", n_ops=svc_ops,
                                     n_procs=5, crash_p=0.05,
                                     max_crashes=3)
                for _ in range(n_requests * n_hists)]
        payloads = [pool[i * n_hists:(i + 1) * n_hists]
                    for i in range(n_requests)]
        def wave():
            # Fresh daemon + journal dir PER WAVE: each submit journals
            # ~1 MB of b64-packed events, so a shared WAL grows by
            # ~n_requests MB per wave and compaction cost rises
            # monotonically across reps (measured: wave walls drifting
            # 5s -> 9s over 3 reps in BOTH arms) — a fresh WAL makes
            # the reps stationary. Construction is ms-cheap; the warm
            # state that matters (jax/XLA caches, the certify-batch
            # gate) is process-wide and survives.
            import shutil

            journal_tmp = tempfile.mkdtemp(prefix="ab-hostpath-journal-")
            service = CheckingService(store_root=None,
                                      name="ab-hostpath",
                                      cache_capacity=0,
                                      journal_dir=journal_tmp)
            idx = iter(range(n_requests))
            lock = threading.Lock()
            bad: list = []

            def submitter():
                while True:
                    with lock:
                        i = next(idx, None)
                    if i is None:
                        return
                    req = service.submit(payloads[i], workload="queue")
                    if not req.wait(300.0) or req.verdict() is not True:
                        with lock:
                            bad.append(req.id)

            t0 = time.perf_counter()
            threads = [threading.Thread(target=submitter, daemon=True)
                       for _ in range(n_clients)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            service.shutdown(wait=True)
            shutil.rmtree(journal_tmp, ignore_errors=True)
            assert not bad, f"non-done/invalid verdicts: {bad[:5]}"
            return wall

        try:
            for on in (True, False):   # warm both arms (XLA + daemon)
                _set_arm(on)
                wave()
            t_svc = {"turbo": [], "scalar": []}
            for rep in range(max(3, args.reps)):
                order = [("turbo", True), ("scalar", False)]
                if rep % 2:
                    order.reverse()
                for name, on in order:
                    _set_arm(on)
                    t_svc[name].append(wave())
        finally:
            _set_arm(True)
        # The service bar is judged on the MEDIAN of >=3 interleaved
        # reps, not the min: a wave is 8 threads timeslicing one CPU
        # with the daemon, so its wall is multi-modal scheduler noise
        # (observed same-arm spreads of 1.5x rep to rep) — min-of-few
        # hands the verdict to whichever arm drew the lucky rep, while
        # the median of interleaved reps is stable run to run. The
        # kernel-style phases above keep min (their noise is strictly
        # additive); this is the same mood-vs-median caveat bench.py's
        # suite rows document.
        med_t = statistics.median(t_svc["turbo"])
        med_s = statistics.median(t_svc["scalar"])
        sp_svc = med_s / med_t
        print({"phase": "service",
               "n_requests": n_requests, "histories_per_request": n_hists,
               "n_ops": svc_ops, "client_concurrency": n_clients,
               "turbo_req_s": round(n_requests / med_t, 2),
               "scalar_req_s": round(n_requests / med_s, 2),
               "reps": {k: [round(t, 3) for t in v]
                        for k, v in t_svc.items()},
               "min_note": {k: round(min(v), 3)
                            for k, v in t_svc.items()},
               "speedup": round(sp_svc, 3),
               "acceptance_1_3x": sp_svc >= 1.3})
        overall_ok &= sp_svc >= 1.3

    print({"acceptance_all": overall_ok})


if __name__ == "__main__":
    main()

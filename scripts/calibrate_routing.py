#!/usr/bin/env python
"""One-shot calibration for the per-shape platform-routing gate.

`auto` routes a dense window group to the host mesh when its scanned-cell
count B×E is under PLATFORM_ROUTE_MIN_CELLS (checker/linearizable.py) —
a constant measured on one host+chip pair (doc/running.md "Measured
routing gates"). This script DERIVES the crossover on the current
hardware: it times the identical dense kernel launch on the default
backend and on the host CPU backend across a grid of batch shapes, finds
the largest shape where the host still wins, and prints the
JGRAFT_ROUTE_MIN_CELLS value to export.

Run it on a TPU-attached session (on a CPU-only host both "platforms"
are the same backend and the script says so). The shapes mirror the
suite's real spread: config-3-like tiny keys up through config-4-like
long histories.

Usage:
  python scripts/calibrate_routing.py            # full grid
  python scripts/calibrate_routing.py --quick    # 4 shapes, smoke test
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="4 shapes only (CI smoke)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed repetitions per shape (min is kept)")
    ap.add_argument("--unroll", action="store_true",
                    help="also sweep JGRAFT_SCAN_UNROLL in {1,2,4} per "
                         "shape on the default backend (round-5: unroll=2 "
                         "measured 1.49x on the CPU mesh at the config-4 "
                         "shape; the TPU default stays 1 until this sweep "
                         "runs on-chip)")
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from jepsen_jgroups_raft_tpu.history.packing import (encode_history,
                                                         pack_batch,
                                                         pad_batch_bucketed)
    from jepsen_jgroups_raft_tpu.history.synth import random_valid_history
    from jepsen_jgroups_raft_tpu.models.register import CasRegister
    from jepsen_jgroups_raft_tpu.ops.dense_scan import (
        dense_plan, make_dense_batch_checker)

    default = jax.default_backend()
    try:
        host = jax.devices("cpu")[0]
    except RuntimeError:
        print("cpu backend unavailable (JAX_PLATFORMS pinned exclusively); "
              "cannot calibrate", file=sys.stderr)
        return 2
    same = default == "cpu"
    if same:
        print("# default backend IS the host cpu — crossover is "
              "degenerate on this session; run on a TPU-attached host "
              "for a real gate", file=sys.stderr)

    # (histories, ops/history): config-3-like → config-4-like.
    shapes = [(600, 16), (600, 64), (128, 64), (128, 256),
              (64, 1000), (16, 1000), (16, 10_000)]
    if args.quick:
        shapes = [(64, 16), (64, 64), (8, 256), (4, 1000)]

    rng = random.Random(5)
    rows = []
    for n_hist, n_ops in shapes:
        encs = [encode_history(
            random_valid_history(rng, "register", n_ops=n_ops, n_procs=5,
                                 crash_p=0.05, max_crashes=3), CasRegister())
            for _ in range(n_hist)]
        plan = dense_plan(CasRegister(), encs)
        if plan is None:
            continue
        ev, (val_of,), B = pad_batch_bucketed(
            pack_batch(encs)["events"], (plan.val_of,))
        kernel = make_dense_batch_checker(CasRegister(), plan.kind,
                                          plan.n_slots, plan.n_states)
        cells = int(ev.shape[0]) * int(ev.shape[1])

        def timed(dev):
            e, v = ((jax.device_put(ev, dev), jax.device_put(val_of, dev))
                    if dev is not None else (ev, val_of))
            np.asarray(kernel(e, v)[0])  # warm (compile for this placement)
            best = float("inf")
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                np.asarray(kernel(e, v)[0])
                best = min(best, time.perf_counter() - t0)
            return best

        t_default = timed(None)
        t_host = t_default if same else timed(host)
        rows.append({"histories": n_hist, "ops": n_ops, "cells": cells,
                     "default_s": round(t_default, 4),
                     "host_s": round(t_host, 4),
                     "host_wins": bool(t_host < t_default)})
        if args.unroll:
            import os
            sweep = {}
            prior = os.environ.get("JGRAFT_SCAN_UNROLL")
            try:
                for u in (1, 2, 4):
                    os.environ["JGRAFT_SCAN_UNROLL"] = str(u)
                    # The kernel cache keys on scan_unroll(), so this
                    # builds (and compiles) a distinct kernel per value.
                    k_u = make_dense_batch_checker(
                        CasRegister(), plan.kind, plan.n_slots,
                        plan.n_states)
                    np.asarray(k_u(ev, val_of)[0])
                    best = float("inf")
                    for _ in range(args.repeats):
                        t0 = time.perf_counter()
                        np.asarray(k_u(ev, val_of)[0])
                        best = min(best, time.perf_counter() - t0)
                    sweep[f"unroll{u}"] = round(best, 4)
            finally:
                # Restore (not pop) so neither a mid-sweep failure nor
                # an operator-set value leaks a DIFFERENT unroll into
                # later shapes' default timings (mislabeled rows would
                # poison the derived gate).
                if prior is None:
                    os.environ.pop("JGRAFT_SCAN_UNROLL", None)
                else:
                    os.environ["JGRAFT_SCAN_UNROLL"] = prior
            rows[-1]["unroll_sweep"] = sweep
        print(json.dumps(rows[-1]), flush=True)

    # Derive the gate from the FIRST crossover in cell order, not the
    # largest host win: one noisy/stalled chip timing at a big shape
    # must not inflate the gate past every chip-winning shape below it
    # (a wedged-tunnel stall during calibration would otherwise print a
    # gate that routes chip-winning work to the host forever).
    by_cells = sorted(rows, key=lambda r: r["cells"])
    first_chip_win = next((r["cells"] for r in by_cells
                           if not r["host_wins"]), None)
    stray = [r["cells"] for r in by_cells
             if r["host_wins"] and first_chip_win is not None
             and r["cells"] > first_chip_win]
    if same:
        print("# no recommendation (single-backend session)")
    elif first_chip_win is None:
        print("# recommendation: the host won EVERY shape — the chip "
              "path looks unhealthy (tunnel stall?); re-run before "
              "trusting any gate")
    else:
        gate = first_chip_win
        print(f"# recommendation: export JGRAFT_ROUTE_MIN_CELLS={gate}")
        print("# (smallest chip-winning shape; update "
              "PLATFORM_ROUTE_MIN_CELLS + doc/running.md if this moves "
              "a headline row)")
        if stray:
            print(f"# WARNING: host also won at {stray} cells — "
                  "non-monotonic crossover, likely timing noise or a "
                  "tunnel stall; re-run before trusting the gate")
    return 0


if __name__ == "__main__":
    sys.exit(main())

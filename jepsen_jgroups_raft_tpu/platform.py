"""CPU-platform pinning for JAX, shared by every entry point.

The TPU plugin ('axon') is registered by sitecustomize at interpreter
start, which imports jax — so setting JAX_PLATFORMS in os.environ alone is
too late, and if the TPU tunnel is wedged, the first jax.devices() blocks
forever inside backend init (round-1 rc=124). Pinning must therefore
update jax.config directly, and XLA_FLAGS must be set before the CPU
backend itself initializes. Used by tests/conftest.py, bench.py, and
__graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Optional

_log = logging.getLogger(__name__)

#: Why this process is NOT running on the platform it was asked for (TPU
#: probe failed, tunnel dropped mid-flight, ...). None when the platform
#: in use is the intended one. Checker results carry this note so a run
#: that silently degraded to the host is distinguishable from an
#: intended-CPU run (the bench learned this distinction in round 5:
#: BENCH_r05.json's platform_note existed only in the bench JSON, never
#: in the checker's own result metadata).
_DEGRADED_NOTE: Optional[str] = None


def note_degraded(note: str) -> None:
    """Record that the platform silently degraded (first note wins: the
    root cause, not the retry cascade)."""
    global _DEGRADED_NOTE
    if _DEGRADED_NOTE is None:
        _DEGRADED_NOTE = note


def degraded_note() -> Optional[str]:
    """The degrade reason recorded by `note_degraded`, or None."""
    return _DEGRADED_NOTE


def env_int(name: str, default: int, minimum: Optional[int] = None) -> int:
    """Parse an integer env gate defensively: a non-integer value warns
    and falls back to the default instead of crashing at import time
    (`JGRAFT_ROUTE_MIN_CELLS=yes` used to kill every importer of
    checker/linearizable.py with a ValueError). `minimum` clamps with a
    warning — the gates this serves are counts/sizes where a negative
    or undersized value is always operator error, never intent."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = int(raw.strip())
    except ValueError:
        _log.warning("%s=%r is not an integer; using default %d",
                     name, raw, default)
        return default
    if minimum is not None and val < minimum:
        _log.warning("%s=%d below minimum %d; clamping",
                     name, val, minimum)
        return minimum
    return val


def env_float(name: str, default: float,
              minimum: Optional[float] = None) -> float:
    """`env_int`'s float twin (lease TTLs and skew margins are
    sub-second in tests): same defensive stance — garbage warns and
    keeps the default, sub-minimum clamps with a warning."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = float(raw.strip())
    except ValueError:
        _log.warning("%s=%r is not a number; using default %g",
                     name, raw, default)
        return default
    if minimum is not None and val < minimum:
        _log.warning("%s=%g below minimum %g; clamping",
                     name, val, minimum)
        return minimum
    return val


def env_str(name: str, default: str = "") -> str:
    """String twin of `env_int`/`env_float` for path/id knobs
    (JGRAFT_CLUSTER_DIR, JGRAFT_REPLICA_ID, ...): a missing OR
    blank/whitespace value falls back to the default, so
    `JGRAFT_CLUSTER_DIR=""` in a wrapper script means "unset", not "the
    current directory". Registered as a typed knob by the envknobs
    analyzer (lint/flow/envknobs.py), which is why string knobs should
    route through here rather than raw os.environ.get."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip()


def pin_cpu(n_devices: int = 8) -> None:
    """Force JAX onto a virtual `n_devices`-device CPU platform.

    Must run before the CPU backend initializes to control the device
    count (afterwards the pin still keeps the TPU backend from ever
    initializing, but the existing device count wins). An XLA_FLAGS count
    already present is raised to `n_devices` if smaller.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n_devices}"
        )
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")


def reset_backends() -> None:
    """Drop cached device backends so the next use re-initializes under
    the current ``jax_platforms`` pin.

    Needed by the mid-flight degrade path: a TPU backend that initialized
    successfully and THEN lost its tunnel (UNAVAILABLE during execution)
    stays cached, so a retry without this re-hits the dead backend even
    after pin_cpu(). Init-time failures never cache a backend, so the
    call is a no-op there (ADVICE r4).
    """
    import jax.extend.backend

    jax.extend.backend.clear_backends()


def is_backend_init_failure(e: BaseException) -> bool:
    """True for the failure flavors of an unusable accelerator backend:
    init refusal (plugin unregistered / unknown platform) and the
    tunnel-drop modes (UNAVAILABLE, DEADLINE_EXCEEDED, setup/compile
    errors). Shared by bench.py's CPU re-exec and the checker's in-
    process degrade so the two paths recognize the same world."""
    text = f"{type(e).__name__}: {e}"
    return ("Unable to initialize backend" in text
            or "backend setup/compile error" in text
            or "UNAVAILABLE" in text
            or "DEADLINE_EXCEEDED" in text)


def cpu_subprocess_env(base: dict | None = None) -> dict:
    """Environment for a CPU-only child interpreter, with the TPU-tunnel
    plugin registration DISARMED.

    `pin_cpu` protects the current process, but a child interpreter runs
    sitecustomize before any of our code, and with PALLAS_AXON_POOL_IPS
    set the axon `register()` call there contacts the tunnel relay — a
    wedged relay (observed 2026-07-30: 100% of interpreter starts hung
    >30 s) blocks the child BEFORE it can pin anything. Stripping the
    pool-IPs var makes sitecustomize skip registration entirely, so the
    child starts instantly and cannot reach the TPU — exactly right for
    CPU-bound children (soak workers, sanitizer runs, the bench's CPU
    re-exec). Children that WANT the TPU must keep the env and guard
    with a subprocess timeout instead."""
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env

"""CPU-platform pinning for JAX, shared by every entry point.

The TPU plugin ('axon') is registered by sitecustomize at interpreter
start, which imports jax — so setting JAX_PLATFORMS in os.environ alone is
too late, and if the TPU tunnel is wedged, the first jax.devices() blocks
forever inside backend init (round-1 rc=124). Pinning must therefore
update jax.config directly, and XLA_FLAGS must be set before the CPU
backend itself initializes. Used by tests/conftest.py, bench.py, and
__graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import os
import re


def pin_cpu(n_devices: int = 8) -> None:
    """Force JAX onto a virtual `n_devices`-device CPU platform.

    Must run before the CPU backend initializes to control the device
    count (afterwards the pin still keeps the TPU backend from ever
    initializing, but the existing device count wins). An XLA_FLAGS count
    already present is raised to `n_devices` if smaller.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n_devices}"
        )
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

"""Synthetic history generation.

A randomized generator with a built-in linearizability guarantee: ops take
effect atomically at a simulated linearization point between invocation and
completion, so the produced history IS linearizable by construction.
Crashed ops may linearize and then never report (→ info), reproducing the
ambiguous-completion semantics the reference's checker must handle
(reference workload/client.clj:52-63, doc/intro.md:35-41).

Used three ways (SURVEY.md §4 implications):
  * differential testing of the CPU and TPU checkers against each other,
  * adversarial tests via `corrupt` (perturb a completion, oracle decides),
  * bench.py workload synthesis (north-star configs, BASELINE.md).
"""

from __future__ import annotations

import random

from .ops import FAIL, INFO, INVOKE, OK, History, Op


def build_history(rows) -> History:
    """Build a history from (process, type, f, value) rows; indices/times
    are assigned from position."""
    h = History()
    for i, (process, typ, f, value) in enumerate(rows):
        h.append(Op(process=process, type=typ, f=f, value=value, time=i))
    return h


def random_valid_history(
    rng: random.Random,
    model_kind: str = "register",
    n_ops: int = 8,
    n_procs: int = 3,
    value_range: int = 3,
    crash_p: float = 0.2,
    max_crashes: int | None = None,
) -> History:
    """Generate a linearizable-by-construction history of n_ops ops.

    model_kind: "register" (read/write/cas), "counter"
    (read/add/add-and-get), "set" (add/read over the 32-wide
    membership), "queue" (ticket-FIFO enqueue/dequeue, completed
    enqueues observing their assigned ticket), or "list-append"
    (unique-element appends observing the resulting list, reads
    observing the whole list — ISSUE 19). crash_p biases how often
    a pending op crashes instead of completing (info ops are the
    checker-pressure knob).

    A crashed process is REPLACED by a fresh process id, the way jepsen's
    runner remaps crashed worker ids — so the history really reaches n_ops
    regardless of crashes. (Round-2 bug: crashed processes used to retire,
    so every "1000-op" benchmark history silently ended after the ~5th
    crash at a median of ~75 ops.) Every crashed op holds a concurrency-
    window slot forever, so `max_crashes` caps the total — the knob that
    keeps long histories inside a checkable window. The default (None)
    caps at n_procs: the concurrency window stays ≤ 2·n_procs no matter
    how long the history, and it matches the most crashes the pre-fix
    generator could ever produce. An uncapped run (windows in the
    hundreds, beyond every checker) must be asked for with
    max_crashes=n_ops."""

    if max_crashes is None:
        max_crashes = n_procs
    if model_kind == "register":
        state = None
    elif model_kind == "queue":
        state = (0, 0)  # (head, tail)
    elif model_kind == "list-append":
        state = []  # the append-only list itself
    else:
        state = 0  # counter value / set membership mask
    # list-append: unique elements 1..MAX_LEN (the packed int32 state
    # admits at most 6), then the generator degrades to reads
    next_elem = 1
    rows = []
    # pending: process -> dict(f, value, linearized?, result)
    pending: dict = {}
    done_ops = 0
    crashes = 0
    free = list(range(n_procs))
    next_pid = n_procs
    while done_ops < n_ops or pending:
        choices = []
        if done_ops < n_ops and free:
            choices.append("invoke")
        unlin = [p for p, d in pending.items() if not d["lin"]]
        lin = [p for p, d in pending.items() if d["lin"]]
        may_crash = crashes < max_crashes
        if unlin:
            choices.append("linearize")
            if may_crash and rng.random() < crash_p:
                choices.append("crash_unapplied")
        if lin:
            choices.append("complete")
            if may_crash and rng.random() < crash_p:
                choices.append("crash_applied")
        act = rng.choice(choices)
        if act == "invoke":
            p = free.pop(rng.randrange(len(free)))
            if model_kind == "register":
                f = rng.choice(["read", "write", "cas"])
                if f == "read":
                    value = None
                elif f == "write":
                    value = rng.randrange(value_range)
                else:
                    value = (rng.randrange(value_range), rng.randrange(value_range))
            elif model_kind == "set":
                f = rng.choice(["add", "add", "read"])
                value = rng.randrange(value_range) if f == "add" else None
            elif model_kind == "queue":
                f = rng.choice(["enqueue", "enqueue", "dequeue"])
                value = None
            elif model_kind == "list-append":
                if next_elem <= 6 and rng.random() < 0.5:
                    f, value = "append", next_elem
                    next_elem += 1
                else:
                    f, value = "read", None
            else:
                f = rng.choice(["read", "add", "add-and-get"])
                value = None if f == "read" else rng.randrange(1, value_range + 1)
            pending[p] = {"f": f, "value": value, "lin": False, "result": None}
            rows.append((p, INVOKE, f, value))
            done_ops += 1
        elif act == "linearize":
            p = rng.choice(unlin)
            d = pending[p]
            f, v = d["f"], d["value"]
            if model_kind == "register":
                if f == "read":
                    d["result"] = state
                elif f == "write":
                    state = v
                    d["result"] = None
                else:
                    frm, to = v
                    if state == frm:
                        state = to
                        d["result"] = True
                    else:
                        d["result"] = False
            elif model_kind == "set":
                if f == "add":
                    state |= 1 << v
                    d["result"] = None
                else:
                    d["result"] = [i for i in range(32)
                                   if (state >> i) & 1]
            elif model_kind == "queue":
                h, t = state
                if f == "enqueue":
                    state = (h, t + 1)
                    d["result"] = t  # the assigned ticket
                elif h == t:
                    d["result"] = None  # empty observation
                else:
                    state = (h + 1, t)
                    d["result"] = h
            elif model_kind == "list-append":
                if f == "append":
                    state = state + [v]
                d["result"] = list(state)  # the observed/resulting list
            else:
                if f == "read":
                    d["result"] = state
                elif f == "add":
                    state += v
                    d["result"] = None
                else:
                    state += v
                    d["result"] = (v, state)
            d["lin"] = True
        elif act == "complete":
            p = rng.choice(lin)
            d = pending.pop(p)
            f, r = d["f"], d["result"]
            if model_kind == "register" and f == "cas" and r is False:
                rows.append((p, FAIL, f, d["value"]))
            elif f == "read":
                rows.append((p, OK, f, r))
            elif f in ("add-and-get", "enqueue", "dequeue", "append"):
                rows.append((p, OK, f, r))  # observed result/ticket/list
            else:
                rows.append((p, OK, f, d["value"]))
            free.append(p)
        else:
            # Crash (applied or not): completion unknown. The op's slot
            # stays open forever; the worker comes back under a fresh
            # process id (jepsen's crashed-id remapping).
            p = rng.choice(lin if act == "crash_applied" else unlin)
            d = pending.pop(p)
            crashes += 1
            free.append(next_pid)
            next_pid += 1
            if rng.random() < 0.5:
                rows.append((p, INFO, d["f"], d["value"]))
            # else: no completion row at all — pair_ops treats the dangling
            # invocation as a crashed (info) op, same as jepsen.
    return build_history(rows)


def corrupt(rng: random.Random, hist: History) -> History:
    """Randomly perturb one completion (may or may not break
    linearizability — the oracle decides). Thin compat wrapper over the
    typed operator registry (`search/operators.py`, ISSUE 20), which
    fixed this function's two blind spots: the write arm used to be a
    silent no-op (completed writes echo the written value, so a sound
    perturbation must rewrite the invocation too) and list-append
    observed lists were never perturbed at all. Every model family now
    has at least one operator that can flip a seeded-valid history to
    invalid. Imported lazily: search composes on top of synth, not the
    other way around."""
    from ..search.operators import corrupt_once

    return corrupt_once(rng, hist)

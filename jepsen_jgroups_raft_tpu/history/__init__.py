"""Operation history: op records, pairing, and tensor packing.

Equivalent surface: jepsen's per-op history records
`{:process :type :f :value :time :index}` (reference
test/jepsen/jgroups/raft_test.clj:9-25 shows the shape), plus the
tensor-packing path that BASELINE.json's north star adds on top.
"""

from .ops import (  # noqa: F401
    INVOKE,
    OK,
    FAIL,
    INFO,
    NEMESIS,
    Op,
    History,
    invoke_op,
    pair_ops,
)
from .packing import (  # noqa: F401
    EV_PAD,
    EV_OPEN,
    EV_FORCE,
    NIL,
    EncodedHistory,
    encode_history,
    pack_batch,
)

"""Op records and history pairing.

An operation appears in a history twice: once as an invocation and once as a
completion. Completion types follow jepsen's taxonomy (reference
workload/client.clj:52-63 semantics):

  ``ok``    — op definitely applied, return value known
  ``fail``  — op definitely did NOT apply (definite error, or idempotent op)
  ``info``  — unknown: the op may or may not have applied (indefinite error).
              The checker must treat it as concurrent with everything after
              its invocation, forever.

Invocations that never complete by the end of the history are treated as
``info`` (crashed worker), matching jepsen/knossos behavior.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Iterable, Iterator, Optional, Union

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

#: process id used for nemesis ops in the history (jepsen convention).
NEMESIS = "nemesis"

#: int32 encoding of knossos' `nil` (e.g. the cas-register's initial
#: value). Lives here — the one leaf module both the models and the
#: packing layer import — so there is exactly one definition.
NIL = -(2**31)

_COMPLETIONS = (OK, FAIL, INFO)


@dataclass
class Op:
    """One history event.

    Fields mirror jepsen's op maps (reference raft_test.clj:9-25):
    process, type, f, value, time (ns since test start), index (position in
    the history). ``error`` carries the error keyword for fail/info ops.
    """

    process: Union[int, str]
    type: str
    f: str
    value: Any = None
    time: int = -1
    index: int = -1
    error: Optional[str] = None
    extra: dict = field(default_factory=dict)

    def is_invoke(self) -> bool:
        return self.type == INVOKE

    def is_completion(self) -> bool:
        return self.type in _COMPLETIONS

    def replace(self, **kw) -> "Op":
        return replace(self, **kw)

    def to_dict(self) -> dict:
        d = {
            "process": self.process,
            "type": self.type,
            "f": self.f,
            "value": self.value,
            "time": self.time,
            "index": self.index,
        }
        if self.error is not None:
            d["error"] = self.error
        if self.extra:
            d.update(self.extra)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Op":
        known = {"process", "type", "f", "value", "time", "index", "error"}
        return cls(
            process=d["process"],
            type=d["type"],
            f=d["f"],
            value=d.get("value"),
            time=d.get("time", -1),
            index=d.get("index", -1),
            error=d.get("error"),
            extra={k: v for k, v in d.items() if k not in known},
        )


def invoke_op(process, f, value=None, time=-1) -> Op:
    return Op(process=process, type=INVOKE, f=f, value=value, time=time)


@dataclass
class OpPair:
    """A matched invocation/completion.

    ``completion`` is None for crashed ops (treated as info). ``ctype`` is
    the effective completion type (crashes become ``info``).
    """

    invoke: Op
    completion: Optional[Op]

    @property
    def ctype(self) -> str:
        return self.completion.type if self.completion is not None else INFO

    @property
    def f(self) -> str:
        return self.invoke.f


class History:
    """An ordered sequence of ops with pairing helpers.

    The order of the underlying list *is* the real-time order the checker
    relies on (jepsen assigns dense indices; we use list position when
    ``index`` is unset).
    """

    def __init__(self, ops: Iterable[Union[Op, dict]] = ()):  # noqa: D401
        self.ops: list[Op] = [
            op if isinstance(op, Op) else Op.from_dict(op) for op in ops
        ]

    def append(self, op: Op) -> Op:
        if op.index < 0:
            op.index = len(self.ops)
        self.ops.append(op)
        return op

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __len__(self) -> int:
        return len(self.ops)

    def __getitem__(self, i):
        return self.ops[i]

    def client_ops(self) -> "History":
        return History(op for op in self.ops if op.process != NEMESIS)

    def nemesis_ops(self) -> "History":
        return History(op for op in self.ops if op.process == NEMESIS)

    def oks(self) -> list[Op]:
        return [op for op in self.ops if op.type == OK]

    def pairs(self) -> list[OpPair]:
        return pair_ops(self.ops)

    def to_dicts(self) -> list[dict]:
        return [op.to_dict() for op in self.ops]


def pair_ops(ops: Iterable[Op]) -> list[OpPair]:
    """Match invocations with their completions, per process.

    Jepsen guarantees a process has at most one outstanding op; a process
    that crashes (info) never invokes again under the same id. We mirror
    that: a completion matches the process's pending invocation; an
    unmatched completion raises; pending invocations at the end become
    crashed (info) pairs. Returned in invocation order.
    """
    return [OpPair(inv, comp) for _, _, inv, comp in pair_ops_indexed(ops)]


def pair_ops_indexed(ops: Iterable[Op]) -> list[tuple]:
    """`pair_ops` with positions: [(invoke_pos, completion_pos | -1,
    invoke, completion | None)], sorted by invocation position. One pass,
    no identity maps — this sits on the encode hot path (a 1000-history
    batch pairs a million ops; see the round-3 profile in the commit
    log)."""
    pending: dict = {}  # process -> (invoke position, invoke op)
    out: list = []
    for i, op in enumerate(ops):
        t = op.type
        if t == INVOKE:
            if op.process in pending:
                prev = pending[op.process][1]
                raise ValueError(
                    f"process {op.process} invoked twice without completing "
                    f"(indices {prev.index}, {op.index})"
                )
            pending[op.process] = (i, op)
        elif t in _COMPLETIONS:
            entry = pending.pop(op.process, None)
            if entry is None:
                raise ValueError(
                    f"completion without invocation: process {op.process} "
                    f"index {op.index}"
                )
            out.append((entry[0], i, entry[1], op))
        else:
            raise ValueError(f"unknown op type: {t!r}")
    for ipos, inv in pending.values():
        out.append((ipos, -1, inv, None))  # crashed: never completed
    out.sort(key=lambda e: e[0])
    return out

"""Pack an operation history into a fixed-shape int32 event tensor.

This is the "tensor-packing path" of the north star (BASELINE.json): the
bridge between jepsen-style histories and the on-device frontier search.

Key design decision (TPU-first): instead of shipping raw (invoke, complete)
interval pairs to the device, the host compiles the history into a compact
**event stream** the kernel can scan with fixed shapes:

  OPEN  slot f a b   — an op becomes available for linearization. The op is
                       assigned a *slot*: a position in a sliding window of
                       at most W concurrently-open ops. Slots of completed
                       (ok) ops are recycled; crashed (info) ops hold their
                       slot forever (they remain linearization candidates
                       until the end — reference doc/intro.md:35-41 names
                       exactly this as the checker-pressure problem).
  FORCE slot         — the op in `slot` completed ok: every surviving
                       search configuration must have linearized it by now.

A search configuration is then just (uint32 bitmask over W slots, int32
model state) — fixed width, dedupable by sort, vmappable. The algorithm is
the Wing&Gong/Lowe linear search reshaped for SIMD: closure-expansion of the
frontier needs to run only at FORCE events, because between two completions
every open op is mutually concurrent (no real-time edge can appear without a
completion), so deferring expansion to the next FORCE reaches the identical
configuration set.

`fail` completions are dropped before packing (the op never executed), and
idempotent info ops were dropped by the model encoding — mirroring the
reference's error taxonomy (workload/client.clj:52-63).

By default the kernels consume this stream MACRO-COMPACTED
(`macro_compact` / `pack_macro_batch`, ISSUE 4): each run of
consecutive OPENs coalesces into the FORCE step that ends it, so the
scan length drops to #FORCEs + spill. Since OPENs only latch registers
and closure was already deferred to FORCE events, the batched latch is
verdict-preserving bit for bit (doc/checker-design.md §1b);
JGRAFT_MACRO_EVENTS=0 restores the one-event-per-step stream.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from ..platform import env_int
from .ops import (NIL, History, Op, OpPair,  # noqa: F401  (NIL re-exported)
                  pair_ops, pair_ops_indexed)

# Event types.
EV_PAD = 0
EV_OPEN = 1
EV_FORCE = 2


def encode_vector_on() -> bool:
    """Whether encoding takes the vectorized columnar path (ISSUE 15
    tentpole (a)): `encode_history` routes through the per-model
    columnar twins + `_encode_history_columnar`, and the
    `IncrementalEncoder` settles suffixes columnar-ly.
    ``JGRAFT_ENCODE_VECTOR=0`` forces the per-pair Python loop — the
    differential ORACLE arm (byte-identical output, pinned by
    tests/test_fast_encode.py) and the A/B denominator
    (scripts/ab_hostpath.py). Parsed defensively via `env_int`:
    garbage warns and keeps the default (on)."""
    return env_int("JGRAFT_ENCODE_VECTOR", 1, minimum=0) != 0

#: Cap on opens carried by one macro-event row. Bounds the row width
#: (3 + 4·P int32 lanes) independently of the concurrency window — a
#: timeout-polluted sort-kernel history can hold ~100 slots open at
#: once, and an uncapped row would grow past 400 lanes for a run the
#: spill rule handles in ⌈run/16⌉ latch-only rows instead. Dense-kernel
#: runs (window ≤ 12) never spill at this cap.
MACRO_MAX_OPENS = 16


@dataclass
class EncodedHistory:
    """A packed history ready for the checker kernels.

    events:   [E, 5] int32 rows (etype, slot, f, a, b)
    op_index: [E]    int32 original history index of the op behind each
                     event (-1 for padding) — for counterexample reporting.
    n_slots:  width of the concurrency window actually used.
    n_ops:    number of encoded (non-dropped) ops.
    proc:     [E]    int32 dense process id of the op behind each event,
                     or None (hand-built encodings). Kernels never read
                     it — it exists for the weaker-consistency rung
                     relaxation (checker/consistency.py), which defers
                     FORCE events along per-process program order.
    """

    events: np.ndarray
    # counterexample attribution only; never read by a verdict path,
    # and derivable from the encode for identical event rows anyway
    op_index: np.ndarray  # lint: allow(fp-irrelevant)
    n_slots: int
    # recomputable from events (count of EV_OPEN rows): two histories
    # with identical hashed event bytes cannot differ in n_ops
    n_ops: int  # lint: allow(fp-irrelevant)
    proc: Optional[np.ndarray] = None

    @property
    def n_events(self) -> int:  # lint: allow(fp-irrelevant) derived: events.shape[0], and events is hashed
        return int(self.events.shape[0])


def encode_history(
    history: Union[History, Sequence[Op]],
    model,
    prune: bool = True,
) -> EncodedHistory:
    """Compile a history into the event-stream representation.

    The model provides per-pair encoding (opcode, args, forced?) via
    ``model.encode_pair``; this function owns slot assignment and event
    ordering. Real-time order is the order of ops in the history.
    `prune` enables the dead-crashed-op pre-pass (verdict-preserving;
    see `_prune_dead_crashed` — differential tests pin pruned vs
    unpruned encodings against the CPU oracle).

    Models exposing `encode_pairs_columnar` take the columnar fast path
    (`_encode_history_columnar`) — byte-identical output, ~7× less
    host time per op (the suite's end-to-end hist/s includes encode, so
    this is perf surface, not plumbing; round-4 work on VERDICT r3 #3).
    ``JGRAFT_ENCODE_VECTOR=0`` (`encode_vector_on`) pins the per-pair
    loop below instead — the differential oracle arm.
    """

    ops = list(history)
    pairs = pair_ops_indexed(ops)
    cols = (model.encode_pairs_columnar(pairs)
            if encode_vector_on() else None)
    if cols is not None:
        return _encode_history_columnar(ops, model, cols, prune)

    # Pair + encode in one pass over indexed pairs (no identity maps —
    # this is the batch-encode hot path; round-3 profile: ~85% of the
    # suite wall was host encode before this was flattened).
    opens: dict = {}  # invoke position -> (pair, encoded)
    forces: dict = {}  # completion position -> invoke position
    for ip, cp, inv, comp in pairs:
        pair = OpPair(inv, comp)
        enc = model.encode_pair(pair)
        if enc is None:
            continue
        opens[ip] = (pair, enc)
        if enc.forced:
            # A forced op must HAVE a completion (forced = "completed
            # ok, must linearize by then"); a model claiming forced for
            # a crashed pair is inconsistent and must fail loudly, not
            # silently drop the FORCE event (cp is -1 for crashed pairs
            # and would never be visited by the event loop).
            if cp < 0:
                raise ValueError(
                    f"model {type(model).__name__} encoded a pair with no "
                    f"completion as forced (invoke index {inv.index})")
            forces[cp] = ip
    if prune:
        _prune_dead_crashed(model, opens, forces)

    rows: List[tuple] = []
    op_idx: List[int] = []
    procs: List[int] = []
    pid_of: dict = {}
    free: List[int] = []  # min-heap of recyclable slots
    next_slot = 0
    slot_of: dict = {}  # invoke position -> slot
    for i, op in enumerate(ops):
        if i in opens:
            pair, enc = opens[i]
            if free:
                slot = heapq.heappop(free)
            else:
                slot = next_slot
                next_slot += 1
            slot_of[i] = slot
            rows.append((EV_OPEN, slot, enc.f, enc.a, enc.b))
            op_idx.append(op.index if op.index >= 0 else i)
            procs.append(pid_of.setdefault(op.process, len(pid_of)))
        elif i in forces:
            slot = slot_of[forces[i]]
            rows.append((EV_FORCE, slot, 0, 0, 0))
            op_idx.append(op.index if op.index >= 0 else i)
            procs.append(pid_of.setdefault(ops[forces[i]].process,
                                           len(pid_of)))
            heapq.heappush(free, slot)

    events = np.asarray(rows, dtype=np.int32).reshape(-1, 5)
    return EncodedHistory(
        events=events,
        op_index=np.asarray(op_idx, dtype=np.int32),
        n_slots=next_slot,
        n_ops=len(opens),
        proc=np.asarray(procs, dtype=np.int32),
    )


def _encode_history_columnar(ops, model, cols, prune: bool) -> EncodedHistory:
    """Columnar twin of the per-pair encode body: same prune fixpoint,
    same slot recycling, same event order — differential tests pin the
    output byte-identical. The per-op costs removed: OpPair + EncodedOp
    construction, per-field method calls, and the per-op observer list
    the prune used to build (now four numpy columns)."""
    fs, as_, bs, forced, ips, cps = cols
    n = len(fs)
    forced_a = np.asarray(forced, dtype=bool)
    cps_a = np.asarray(cps, dtype=np.int64) if n else \
        np.empty(0, dtype=np.int64)
    # Same contract as the per-pair path: forced ⇒ has a completion
    # (one vectorized check instead of a per-op loop).
    bad = forced_a & (cps_a < 0)
    if bad.any():
        k = int(np.flatnonzero(bad)[0])
        raise ValueError(
            f"model {type(model).__name__} encoded a pair with no "
            f"completion as forced (invoke index {ops[ips[k]].index})")
    if prune and not forced_a.all():
        keep = _prune_dead_crashed_columnar(model, fs, as_, bs, forced,
                                            ips, cps)
        if keep is not None and not keep.all():
            fs = np.asarray(fs, dtype=np.int64)[keep]
            as_ = np.asarray(as_, dtype=np.int64)[keep]
            bs = np.asarray(bs, dtype=np.int64)[keep]
            forced = forced_a[keep]
            ips = np.asarray(ips, dtype=np.int64)[keep]
            cps = cps_a[keep]
            n = len(fs)

    # Event stream = OPENs at invoke positions merged with FORCEs at the
    # completion positions of forced ops, ascending by history position
    # (positions are unique: one op per history row).
    forced_a = np.asarray(forced, dtype=bool)
    cps_a = np.asarray(cps, dtype=np.int64)
    force_ks = np.flatnonzero(forced_a)
    n_ev = n + len(force_ks)
    ev_pos = np.empty(n_ev, dtype=np.int64)
    ev_pos[:n] = ips
    ev_pos[n:] = cps_a[force_ks]
    ev_k = np.empty(n_ev, dtype=np.int64)
    ev_k[:n] = np.arange(n)
    ev_k[n:] = force_ks
    order = np.argsort(ev_pos, kind="stable")
    is_open = order < n
    which = ev_k[order]

    # Slot assignment must walk events in order (recycling is
    # history-order-dependent); lean int loop, arrays filled after.
    slot_of = [0] * n
    slots = [0] * n_ev
    free: List[int] = []
    next_slot = 0
    for j, (k, op_ev) in enumerate(zip(which.tolist(), is_open.tolist())):
        if op_ev:
            if free:
                s = heapq.heappop(free)
            else:
                s = next_slot
                next_slot += 1
            slot_of[k] = s
            slots[j] = s
        else:
            s = slot_of[k]
            slots[j] = s
            heapq.heappush(free, s)

    events = np.zeros((n_ev, 5), dtype=np.int32)
    events[:, 0] = np.where(is_open, EV_OPEN, EV_FORCE)
    events[:, 1] = slots
    fab = np.zeros((n, 3), dtype=np.int32)
    fab[:, 0] = fs
    fab[:, 1] = as_
    fab[:, 2] = bs
    events[is_open, 2:5] = fab[which[is_open]]

    # op_index: the op's history `index` field, or its position when unset.
    pos_l = ev_pos[order].tolist()
    op_idx = np.fromiter(
        ((ops[p].index if ops[p].index >= 0 else p) for p in pos_l),
        dtype=np.int32, count=n_ev)
    # Per-event dense process ids (a FORCE's completion op shares its
    # invoke's process, so indexing by history position is uniform).
    pid_of: dict = {}
    proc = np.fromiter(
        (pid_of.setdefault(ops[p].process, len(pid_of)) for p in pos_l),
        dtype=np.int32, count=n_ev)
    return EncodedHistory(events=events, op_index=op_idx,
                          n_slots=next_slot, n_ops=n, proc=proc)


def _prune_dead_crashed_columnar(model, fs, as_, bs, forced, ips, cps):
    """Vectorized twin of `_prune_dead_crashed` (same fixpoint, same
    verdict-preservation argument — see that docstring). Returns a keep
    mask over the kept-op columns, or None when the model's hooks
    disable pruning. Monotonicity makes the fixpoint order-independent:
    dropping an op only removes observers, which can only enable more
    drops, so iterating to stability reaches the same unique result as
    the per-op dict walk."""
    tabs = model.prune_observe_enable(fs, as_, bs)
    if tabs is None:
        return None
    enable_val, enable_has, observe_val, observe_has = tabs
    n = len(fs)
    forced_a = np.asarray(forced, dtype=bool)
    ip_a = np.asarray(ips, dtype=np.int64)
    # Force position per op; unforced ops never retire (+inf sentinel).
    fpos = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
    fpos[forced_a] = np.asarray(cps, dtype=np.int64)[forced_a]
    keep = np.ones(n, dtype=bool)
    candidates = np.flatnonzero(~forced_a)
    changed = True
    while changed:
        changed = False
        for c in candidates:
            if not keep[c]:
                continue
            if not enable_has[c]:
                # Empty enable set: the op provably never changes state,
                # and optional no-ops cannot constrain anything — drop.
                keep[c] = False
                changed = True
                continue
            observers = (keep & observe_has & (fpos > ip_a[c])
                         & (observe_val == enable_val[c]))
            observers[c] = False
            if not observers.any():
                keep[c] = False
                changed = True
    return keep


def _prune_dead_crashed(model, opens: dict, forces: dict) -> None:
    """Drop crashed (optional) ops that provably cannot change the
    verdict, BEFORE slot assignment — each drop frees a never-retiring
    slot, and kernel cost is exponential in the window (SURVEY §7.4.3;
    reference doc/intro.md:35-41 names crashed ops as the checker-
    pressure problem).

    Soundness: let c be an optional op and V = model.enable_values(c)
    the only state values linearizing c can newly expose. If no op that
    could linearize after c (= any op not FORCEd before c's invocation)
    observes any v ∈ V, then (⇐) a witness without c is a witness for
    both op sets, and (⇒) removing c from a witness keeps it legal: the
    op right after c cannot be one whose legality needs c's value (none
    observes it), so it is unconditionally legal (e.g. a register
    write) and the state trajectory re-converges — verdicts are equal.
    Iterated to fixpoint: each step preserves the verdict of the
    surviving set, so the composition does too. Models opt in via the
    enable/observe hooks; any None disables the pass (conservative)."""
    if all(enc.forced for _, enc in opens.values()):
        return  # no crashed candidates — skip building the observer list
    force_pos = {ip: cp for cp, ip in forces.items()}
    observers = []  # (invoke pos, force pos or None, frozenset(values))
    for ip, (pair, enc) in opens.items():
        ov = model.observe_values(enc)
        if ov is None:
            return
        observers.append((ip, force_pos.get(ip), frozenset(ov)))
    changed = True
    while changed:
        changed = False
        for ip, (pair, enc) in list(opens.items()):
            if enc.forced:
                continue
            ev = model.enable_values(enc)
            if ev is None or not set(ev):
                # No enable set known → keep; empty enable set → the op
                # exposes nothing, but optional no-ops cannot constrain
                # anything either, so drop it outright.
                if ev is not None:
                    del opens[ip]
                    observers = [o for o in observers if o[0] != ip]
                    changed = True
                continue
            observed = set()
            for oip, fpos, vals in observers:
                if oip == ip:
                    continue
                if fpos is None or fpos > ip:
                    observed |= vals
            if not (set(ev) & observed):
                del opens[ip]
                observers = [o for o in observers if o[0] != ip]
                changed = True


def pad_batch_bucketed(events: np.ndarray, tables=(), floor_b: int = 8,
                       floor_e: Optional[int] = 32, multiple_b: int = 1):
    """Pad a packed [B, E, 5] batch (and optional per-history [B, X]
    tables) to jit-cache-friendly shapes: B to the next bucket of the
    pow2+midpoint series ≥ floor_b (see `_bucket_pow2`; shapes like 12,
    48, 96 occur) then up to a multiple of multiple_b for mesh sharding;
    E likewise from floor_e (None keeps E exact). Pad rows are EV_PAD
    no-ops. Returns (events, tables_list, original_B) — the single home of
    the padding convention (checker and mesh both route through it)."""
    B, E = events.shape[0], events.shape[1]
    B2 = _bucket_pow2(B, floor_b)
    B2 = ((B2 + multiple_b - 1) // multiple_b) * multiple_b
    E2 = E if floor_e is None else _bucket_pow2(E, floor_e)
    if (B2, E2) != (B, E):
        padded = np.zeros((B2, E2) + events.shape[2:], dtype=events.dtype)
        padded[:B, :E] = events
        events = padded
    out_tables = []
    for t in tables:
        if t.shape[0] != B2:
            tp = np.zeros((B2,) + t.shape[1:], dtype=t.dtype)
            tp[:B] = t
            t = tp
        out_tables.append(t)
    return events, out_tables, B


def bucket_rows(n: int, floor: int = 8) -> int:
    """Public face of the pow2+midpoint bucket series for ROW counts —
    the chunked-scan scheduler (checker/schedule.py) recompacts a
    shrinking active set through these exact buckets so every
    recompaction hits a jit-cache entry the initial padding already
    compiled, instead of triggering a fresh XLA compile per eviction."""
    return _bucket_pow2(n, floor)


def _bucket_pow2(n: int, floor: int) -> int:
    """Next bucket ≥ n from the series floor·{1, 1.5, 2, 3, 4, 6, 8…}
    (powers of two plus their midpoints): padding waste is capped at
    ~33% instead of pow2's 2×, while the jit-cache shape count only
    doubles. The 1000-history north-star batch measured 1.34× padded
    rows under pure pow2 bucketing — real kernel time, not headroom."""
    b = floor
    while b < n:
        if b + b // 2 >= n:
            return b + b // 2
        b *= 2
    return b


def pack_batch(
    encoded: Iterable[EncodedHistory],
    n_events: Optional[int] = None,
) -> dict:
    """Pad a batch of encoded histories to a common event length.

    Returns numpy arrays: events [B, E, 5], op_index [B, E],
    n_events [B], n_slots [B]. Padding rows are EV_PAD (no-ops in the
    kernel scan), so histories of different lengths batch cleanly.
    """

    encs = list(encoded)
    if not encs:
        raise ValueError("empty batch")
    E = n_events or max(e.n_events for e in encs)
    if any(e.n_events > E for e in encs):
        raise ValueError("n_events smaller than longest history")
    B = len(encs)
    events = np.zeros((B, E, 5), dtype=np.int32)
    op_index = np.full((B, E), -1, dtype=np.int32)
    ne = np.zeros((B,), dtype=np.int32)
    ns = np.zeros((B,), dtype=np.int32)
    for i, e in enumerate(encs):
        events[i, : e.n_events] = e.events
        op_index[i, : e.n_events] = e.op_index
        ne[i] = e.n_events
        ns[i] = e.n_slots
    return {
        "events": events,
        "op_index": op_index,
        "n_events": ne,
        "n_slots": ns,
    }


def macro_events_on() -> bool:
    """Whether kernels consume the macro-compacted event stream (ISSUE-4
    tentpole; see `macro_compact`). ``JGRAFT_MACRO_EVENTS=0`` restores
    the legacy one-event-per-step stream — the differential/ablation
    path the macro≡legacy tests pin verdict-identical. Parsed
    defensively (`platform.env_int`): garbage warns and keeps the
    default (on)."""
    return env_int("JGRAFT_MACRO_EVENTS", 1, minimum=0) != 0


def bucket_opens(n: int, cap: int = MACRO_MAX_OPENS) -> int:
    """Macro payload width P for a group whose longest open run is `n`:
    the pow2+midpoint series (1, 2, 3, 4, 6, 8, 12, 16 — same shape
    discipline as rows/events) capped at MACRO_MAX_OPENS, so one
    compiled kernel serves a bucket of run lengths instead of a fresh
    XLA compile per batch. Runs longer than the cap spill into
    latch-only macro rows (`macro_compact`)."""
    return min(_bucket_pow2(max(int(n), 1), 1), cap)


def _macro_group_counts(events: np.ndarray):
    """Shared metadata pass behind the macro row math: (counts, nF,
    open_idx, force_idx, grp) where counts[i] = opens in macro group i
    (group i's opens precede force i; group nF is the trailing
    never-forced run). `max_open_run`, `macro_row_count`, and
    `macro_compact` all derive from these — one definition so the
    shard packers' cheap counting pass can never drift from the
    compaction itself."""
    events = np.asarray(events, dtype=np.int32)
    et = events[:, 0] if events.size else np.empty((0,), np.int32)
    open_idx = np.flatnonzero(et == EV_OPEN)
    force_idx = np.flatnonzero(et == EV_FORCE)
    grp = np.searchsorted(force_idx, open_idx, side="left")
    counts = np.bincount(grp, minlength=len(force_idx) + 1)
    return counts, len(force_idx), open_idx, force_idx, grp


def _macro_rows_from_counts(counts: np.ndarray, nF: int, macro_p: int) -> int:
    """Row-count half of the macro math given a history's (counts, nF)
    metadata: ⌈opens/P⌉ latch rows per group, minimum one row per
    FORCE."""
    n_rows = -(-counts // int(macro_p))
    n_rows[:nF] = np.maximum(n_rows[:nF], 1)
    return int(n_rows.sum())


def macro_row_count(events: np.ndarray, macro_p: int) -> int:
    """Macro rows `macro_compact(events, macro_p)` would produce,
    WITHOUT building them. The per-host packers size the batch-global
    macro row count E from this counting pass and then compact only
    their own shard."""
    counts, nF, _, _, _ = _macro_group_counts(events)
    return _macro_rows_from_counts(counts, nF, macro_p)


def max_open_run(events: np.ndarray) -> int:
    """Longest run of consecutive OPEN events (the quantity P buckets):
    opens are grouped by the number of FORCEs preceding them — the
    trailing group (crashed never-forced opens) counts too."""
    counts, _, open_idx, _, _ = _macro_group_counts(events)
    if not len(open_idx):
        return 0
    return int(counts.max())


def macro_compact(events: np.ndarray, macro_p: int) -> np.ndarray:
    """Compact a packed [E, 5] event stream into macro-event rows
    [E_mac, 3 + 4·P] int32 — the ISSUE-4 tentpole encoding.

    Each run of consecutive OPENs coalesces into the FORCE step that
    ends it: row = [mtype, force_slot, n_opens, (slot, f, a, b)·P].
    mtype is EV_FORCE for a macro ending in a FORCE, EV_OPEN for a
    latch-only macro (spill of a run longer than P, or the trailing
    run of crashed never-forced opens), EV_PAD for batch padding. The
    kernels latch all n_opens payloads at once (slots within a run are
    distinct — a slot is only recycled by a FORCE) and then run the
    single existing closure+FORCE, so the scan length drops to
    #FORCEs + spill rows while reaching the identical pre-FORCE
    register state as the one-event-per-step stream (closure is a
    reachability fixpoint over those registers — the soundness argument
    in doc/checker-design.md; macro≡legacy pinned bitwise by
    tests/test_macro_events.py)."""
    P = int(macro_p)
    events = np.asarray(events, dtype=np.int32)
    # Open group = number of FORCEs strictly before the open (group i's
    # opens precede force i; group nF is the trailing never-forced run).
    counts, nF, open_idx, force_idx, grp = _macro_group_counts(events)
    # Rows per group: ⌈opens/P⌉ latch rows, the last one carrying the
    # group's FORCE; a force with no fresh opens still needs its row.
    n_rows = -(-counts // P)
    n_rows[:nF] = np.maximum(n_rows[:nF], 1)
    row_base = np.concatenate([[0], np.cumsum(n_rows)])
    total = int(row_base[-1])
    rows = np.zeros((total, 3 + 4 * P), dtype=np.int32)
    if nF:
        frow = row_base[1:nF + 1] - 1
        rows[frow, 0] = EV_FORCE
        rows[frow, 1] = events[force_idx, 1]
    if len(open_idx):
        # rank of each open within its group
        starts = np.concatenate([[0], np.cumsum(counts)])
        j = np.arange(len(open_idx)) - starts[grp]
        mrow = row_base[grp] + j // P
        col = 3 + 4 * (j % P)
        for k in range(4):
            rows[mrow, col + k] = events[open_idx, 1 + k]
        rows[:, 2] = np.bincount(mrow, minlength=total)
    rows[rows[:, 0] == EV_PAD, 0] = EV_OPEN  # latch-only spill/trailing
    return rows


def pack_macro_batch(
    encoded: Iterable[EncodedHistory],
    n_events: Optional[int] = None,
    cap: int = MACRO_MAX_OPENS,
) -> dict:
    """Macro-stream twin of `pack_batch`: compact every history of a
    batch at one shared payload width P (`bucket_opens` of the batch's
    longest open run) and pad to a common macro-row count. Returns
    numpy arrays events [B, E_mac, 3+4·P], n_events [B] (MACRO row
    counts — the scheduler's exhaustion/span math runs on these),
    n_slots [B], plus the scalar "macro_p" the kernel builders key on
    and "legacy_events" (the batch's max one-event-per-step length):
    routing gates calibrated on legacy event counts — the host/TPU
    cell gate, the LONG-group exact-padding policy — must keep reading
    legacy lengths, or the ~2× compaction silently halves their
    thresholds. Padding rows are EV_PAD no-ops, exactly like
    `pack_batch`."""
    encs = list(encoded)
    if not encs:
        raise ValueError("empty batch")
    P = bucket_opens(max(max_open_run(e.events) for e in encs), cap)
    compacted = [macro_compact(e.events, P) for e in encs]
    E = n_events or max(max(c.shape[0] for c in compacted), 1)
    if any(c.shape[0] > E for c in compacted):
        raise ValueError("n_events smaller than longest macro stream")
    B = len(encs)
    events = np.zeros((B, E, 3 + 4 * P), dtype=np.int32)
    ne = np.zeros((B,), dtype=np.int32)
    ns = np.zeros((B,), dtype=np.int32)
    for i, (e, c) in enumerate(zip(encs, compacted)):
        events[i, : c.shape[0]] = c
        ne[i] = c.shape[0]
        ns[i] = e.n_slots
    return {
        "events": events,
        "n_events": ne,
        "n_slots": ns,
        "macro_p": P,
        "legacy_events": max(e.n_events for e in encs),
    }


def _shard_slice(n_encs: int, process_index: int, process_count: int,
                 n_rows: Optional[int]) -> tuple:
    """(lo, hi, n_rows) for a per-host pack: the shard's row range over
    the GLOBAL row count (≥ the batch size when the caller pre-pads for
    a global mesh; the extra rows are EV_PAD no-op histories assigned
    to the trailing shards)."""
    from ..parallel.distributed import shard_bounds

    n_rows = n_encs if n_rows is None else int(n_rows)
    if n_rows < n_encs:
        raise ValueError(f"n_rows {n_rows} smaller than batch {n_encs}")
    lo, hi = shard_bounds(n_rows, process_count, process_index)
    return lo, hi, n_rows


def pack_batch_shard(
    encoded: Sequence[EncodedHistory],
    process_index: int,
    process_count: int,
    n_rows: Optional[int] = None,
    n_events: Optional[int] = None,
) -> dict:
    """Per-host twin of `pack_batch` (ISSUE 7): pad/fill ONLY the row
    shard process `process_index` of `process_count` owns, at the
    batch-GLOBAL event length — so the shard tensors of all processes,
    concatenated in process order, equal `pack_batch` of the whole
    batch row for row (shard-local pack ≡ global pack then shard;
    doc/checker-design.md §10, pinned by tests/test_distributed.py).
    Each host therefore pays only its shard's share of the fill work,
    and the tensor is born on its shard. `n_rows` (≥ batch size) adds
    global EV_PAD padding rows for mesh-divisible launches. Extra keys:
    ``shard`` = (lo, hi) and ``n_rows_global``."""
    encs = list(encoded)
    if not encs:
        raise ValueError("empty batch")
    E = n_events or max(e.n_events for e in encs)
    if any(e.n_events > E for e in encs):
        raise ValueError("n_events smaller than longest history")
    lo, hi, n_rows = _shard_slice(len(encs), process_index, process_count,
                                  n_rows)
    B_local = hi - lo
    events = np.zeros((B_local, E, 5), dtype=np.int32)
    op_index = np.full((B_local, E), -1, dtype=np.int32)
    ne = np.zeros((B_local,), dtype=np.int32)
    ns = np.zeros((B_local,), dtype=np.int32)
    for j, e in enumerate(encs[lo:min(hi, len(encs))]):
        events[j, : e.n_events] = e.events
        op_index[j, : e.n_events] = e.op_index
        ne[j] = e.n_events
        ns[j] = e.n_slots
    return {
        "events": events,
        "op_index": op_index,
        "n_events": ne,
        "n_slots": ns,
        "shard": (lo, hi),
        "n_rows_global": n_rows,
    }


def pack_macro_batch_shard(
    encoded: Sequence[EncodedHistory],
    process_index: int,
    process_count: int,
    n_rows: Optional[int] = None,
    n_events: Optional[int] = None,
    cap: int = MACRO_MAX_OPENS,
) -> dict:
    """Per-host twin of `pack_macro_batch` (ISSUE 7 tentpole (b)). The
    batch-GLOBAL shapes — payload width P (longest open run anywhere in
    the batch) and macro row count E — are computed from every
    history's metadata via the cheap counting pass
    (`_macro_group_counts` / `macro_row_count`, no row assembly), then
    ONLY this process's row shard is actually compacted and filled. The
    concatenation of every process's output equals `pack_macro_batch`
    of the whole batch, row for row, so the per-host tensors feed the
    same compiled kernels at the same shapes (soundness:
    doc/checker-design.md §10; identity pinned by
    tests/test_distributed.py). This parallelizes the dominant
    host-side pack cost — `macro_compact` + array fill — across host
    CPUs (`scripts/ab_distributed.py` measures the win)."""
    encs = list(encoded)
    if not encs:
        raise ValueError("empty batch")
    # ONE metadata pass per history: (counts, nF) feeds both the
    # batch-global payload width P (longest run = counts.max()) and,
    # at that P, every history's macro row count — the batch-global
    # half of the pack cost every host pays, so it must not scan the
    # event arrays twice.
    metas = [_macro_group_counts(e.events)[:2] for e in encs]
    P = bucket_opens(max(int(c.max()) if c.size else 0 for c, _ in metas),
                     cap)
    row_counts = [_macro_rows_from_counts(c, nF, P) for c, nF in metas]
    E = n_events or max(max(row_counts), 1)
    if any(c > E for c in row_counts):
        raise ValueError("n_events smaller than longest macro stream")
    lo, hi, n_rows = _shard_slice(len(encs), process_index, process_count,
                                  n_rows)
    B_local = hi - lo
    events = np.zeros((B_local, E, 3 + 4 * P), dtype=np.int32)
    ne = np.zeros((B_local,), dtype=np.int32)
    ns = np.zeros((B_local,), dtype=np.int32)
    for j, e in enumerate(encs[lo:min(hi, len(encs))]):
        c = macro_compact(e.events, P)
        events[j, : c.shape[0]] = c
        ne[j] = c.shape[0]
        ns[j] = e.n_slots
    return {
        "events": events,
        "n_events": ne,
        "n_slots": ns,
        "macro_p": P,
        "legacy_events": max(e.n_events for e in encs),
        "shard": (lo, hi),
        "n_rows_global": n_rows,
    }


# ----------------------------------------------------- streaming encoder


class IncrementalEncoder:
    """Append-only twin of ``encode_history(..., prune=False)`` for
    streaming sessions (ISSUE 12): history rows arrive in real-time
    order across segment boundaries, and each ``feed`` emits the newly
    SETTLED suffix of the event stream — exactly the rows
    `encode_history` produces for the complete history, in the same
    order, so a kernel carry advanced on the suffixes reaches the same
    state as one uninterrupted scan (doc/checker-design.md §14).

    Settlement: an op's OPEN row content depends on its completion (an
    ok read encodes its observed value, a ``fail`` drops the op
    entirely), so the event at history position p can only be emitted
    once every invocation at position ≤ p has its completion RECORDED
    somewhere in the accumulated history. Jepsen's runner records an
    ``info`` row for crashed workers, so mid-run every invoke
    eventually settles; invokes still outstanding at ``feed(...,
    final=True)`` become crashed pairs — the same rule `pair_ops`
    applies to a finished history. Settled events are FINAL: appending
    rows appends events, never rewrites them (prefix stability — the
    differentials in tests/test_stream.py pin the emitted stream
    byte-identical to the one-shot encode at every cut).

    Pruning is off by design: `_prune_dead_crashed` keys on global
    observer structure that later appends can change. Pruning is
    verdict-preserving in both directions (its docstring), so streamed
    verdicts still match the pruned one-shot path.

    Memory: only the UNSETTLED tail of rows is retained (bounded by the
    live concurrency window in any real history); settled rows are
    dropped as their events are emitted.
    """

    def __init__(self, model):
        self.model = model
        #: columnar settle (ISSUE 15 tentpole (a)): the settled-suffix
        #: emit batch-encodes each settle's invokes through the model's
        #: columnar twin instead of per-op `encode_pair` calls. Fixed at
        #: construction (JGRAFT_ENCODE_VECTOR) and flipped off
        #: permanently if the model has no columnar hook — the two
        #: paths store different `_enc_of` payloads and must never mix
        #: mid-session. Emitted streams are byte-identical either way
        #: (tests/test_fast_encode.py pins random cuts).
        self._vector = encode_vector_on()
        self.consumed = 0   # history rows ingested
        self.cut = 0        # rows settled (events emitted)
        self.n_ops = 0      # encoded (kept) ops
        self.n_slots = 0    # window high-water (= reference next_slot)
        self.n_events = 0   # events emitted so far
        self._tail: list = []      # Op rows at positions [cut, consumed)
        self._pending: dict = {}   # process -> invoke position
        self._comp: dict = {}      # invoke position -> completion Op
        self._inv_of: dict = {}    # completion position -> invoke position
        self._enc_of: dict = {}    # invoke position -> EncodedOp | None
        self._free: list = []      # recyclable slots (min-heap)
        self._slot_of: dict = {}   # invoke position -> slot
        self._pid_of: dict = {}    # raw process -> dense id

    @property
    def unsettled(self) -> int:
        """Rows ingested but not yet settled (the resident tail)."""
        return self.consumed - self.cut

    def validate(self, ops) -> list:
        """Parse rows and check pairing against a scratch copy of the
        pending set WITHOUT mutating the encoder — the same errors
        `pair_ops_indexed` raises (double invoke, stray completion),
        raised atomically so a rejected segment leaves the session
        re-appendable. Returns the parsed Op rows."""
        ops = [op if isinstance(op, Op) else Op.from_dict(op)
               for op in ops]
        scratch = set(self._pending)
        for op in ops:
            t = op.type
            if t == "invoke":
                if op.process in scratch:
                    raise ValueError(
                        f"process {op.process} invoked twice without "
                        f"completing")
                scratch.add(op.process)
            elif op.is_completion():
                if op.process not in scratch:
                    raise ValueError(
                        f"completion without invocation: process "
                        f"{op.process}")
                scratch.discard(op.process)
            else:
                raise ValueError(f"unknown op type: {t!r}")
        return ops

    def feed(self, ops, final: bool = False):
        """Ingest history rows and emit the newly settled events.

        Returns (events [n,5] int32, op_index [n] int32, proc [n]
        int32) — empty arrays when nothing new settled. Raises
        ValueError on malformed rows (see `validate`) without mutating
        the encoder. ``final=True`` settles everything: outstanding
        invokes become crashed pairs (`pair_ops`' end-of-history
        rule)."""
        ops = self.validate(ops)
        for op in ops:
            pos = self.consumed
            self.consumed += 1
            self._tail.append(op)
            if op.type == "invoke":
                self._pending[op.process] = pos
            else:
                ipos = self._pending.pop(op.process)
                self._comp[ipos] = op
                self._inv_of[pos] = ipos
        if self._vector:
            return self._settle_vector(final)
        return self._settle(final)

    def _settle_vector(self, final: bool):
        """Columnar twin of `_settle` (ISSUE 15 tentpole (a)): the
        settled prefix's invoke rows batch-encode through the model's
        `encode_pairs_columnar` — one tight columnar pass instead of a
        per-op `encode_pair` call with OpPair/EncodedOp construction —
        then the slot/heap emission loop runs exactly like the scalar
        path, so the emitted stream is byte-identical (differential-
        pinned at random cuts). `_enc_of` stores the bare forced flag
        here (True/False, None for dropped ops) — the only field the
        completion branch reads — where the scalar path stores the
        EncodedOp; the per-session `_vector` latch keeps the two
        representations from ever mixing."""
        advance = 0
        for op in self._tail:
            pos = self.cut + advance
            if op.type == "invoke" and pos not in self._comp \
                    and not final:
                break  # completion not recorded yet: unsettled
            advance += 1
        empty = (np.empty((0, 5), dtype=np.int32),
                 np.empty(0, dtype=np.int32),
                 np.empty(0, dtype=np.int32))
        if advance == 0:
            return empty
        pairs = []
        # completion stream position per invoke position (the
        # encode_pairs_columnar contract wants the COMPLETION's
        # position in the pair tuple, like pair_ops_indexed emits —
        # _inv_of maps completion pos -> invoke pos, so invert it;
        # every recorded completion has an entry until the completion
        # row itself settles, which is after this pass)
        cpos_of = {ip: cp for cp, ip in self._inv_of.items()}
        for j in range(advance):
            op = self._tail[j]
            if op.type == "invoke":
                pos = self.cut + j
                comp = self._comp.get(pos)
                pairs.append((pos,
                              -1 if comp is None else cpos_of[pos],
                              op, comp))
        cols = self.model.encode_pairs_columnar(pairs)
        if cols is None:
            # model without a columnar twin: latch the scalar path for
            # the session's lifetime (nothing was stored vector-style
            # yet — the scalar settle re-walks the untouched tail)
            self._vector = False
            return self._settle(final)
        fs, as_, bs, forced, ips, _cps = cols
        kept = {ip: (int(f), int(a), int(b), bool(fo))
                for ip, f, a, b, fo in zip(ips, fs, as_, bs, forced)}

        rows: list = []
        op_idx: list = []
        procs: list = []
        for j in range(advance):
            op = self._tail[j]
            pos = self.cut + j
            if op.type == "invoke":
                ent = kept.get(pos)
                self._enc_of[pos] = ent if ent is None else ent[3]
                if ent is not None:
                    f, a, b, fo = ent
                    if fo and pos not in self._comp:
                        raise ValueError(
                            f"model {type(self.model).__name__} encoded "
                            f"a pair with no completion as forced "
                            f"(invoke index {op.index})")
                    if self._free:
                        slot = heapq.heappop(self._free)
                    else:
                        slot = self.n_slots
                        self.n_slots += 1
                    self._slot_of[pos] = slot
                    rows.append((EV_OPEN, slot, f, a, b))
                    op_idx.append(op.index if op.index >= 0 else pos)
                    procs.append(self._pid_of.setdefault(
                        op.process, len(self._pid_of)))
                    self.n_ops += 1
            else:
                ipos = self._inv_of.pop(pos)
                self._comp.pop(ipos, None)
                encF = self._enc_of.pop(ipos, None)
                if encF is True:
                    slot = self._slot_of.pop(ipos)
                    rows.append((EV_FORCE, slot, 0, 0, 0))
                    op_idx.append(op.index if op.index >= 0 else pos)
                    procs.append(self._pid_of.setdefault(
                        op.process, len(self._pid_of)))
                    heapq.heappush(self._free, slot)
                elif encF is False:
                    # optional (info) op: the slot never recycles
                    self._slot_of.pop(ipos, None)
        del self._tail[:advance]
        self.cut += advance
        self.n_events += len(rows)
        events = np.asarray(rows, dtype=np.int32).reshape(-1, 5)
        return (events,
                np.asarray(op_idx, dtype=np.int32),
                np.asarray(procs, dtype=np.int32))

    def _settle(self, final: bool):
        rows: list = []
        op_idx: list = []
        procs: list = []
        advanced = 0
        for op in self._tail:
            pos = self.cut + advanced
            if op.type == "invoke":
                if pos not in self._comp and not final:
                    break  # completion not recorded yet: unsettled
                comp = self._comp.get(pos)
                enc = self.model.encode_pair(OpPair(op, comp))
                self._enc_of[pos] = enc
                if enc is not None:
                    if enc.forced and comp is None:
                        raise ValueError(
                            f"model {type(self.model).__name__} encoded "
                            f"a pair with no completion as forced "
                            f"(invoke index {op.index})")
                    if self._free:
                        slot = heapq.heappop(self._free)
                    else:
                        slot = self.n_slots
                        self.n_slots += 1
                    self._slot_of[pos] = slot
                    rows.append((EV_OPEN, slot, enc.f, enc.a, enc.b))
                    op_idx.append(op.index if op.index >= 0 else pos)
                    procs.append(self._pid_of.setdefault(
                        op.process, len(self._pid_of)))
                    self.n_ops += 1
            else:
                ipos = self._inv_of.pop(pos)
                self._comp.pop(ipos, None)
                enc = self._enc_of.pop(ipos, None)
                if enc is not None and enc.forced:
                    slot = self._slot_of.pop(ipos)
                    rows.append((EV_FORCE, slot, 0, 0, 0))
                    op_idx.append(op.index if op.index >= 0 else pos)
                    procs.append(self._pid_of.setdefault(
                        op.process, len(self._pid_of)))
                    heapq.heappush(self._free, slot)
                elif enc is not None:
                    # optional (info) op: the slot never recycles — the
                    # op stays a linearization candidate forever.
                    self._slot_of.pop(ipos, None)
            advanced += 1
        del self._tail[:advanced]
        self.cut += advanced
        self.n_events += len(rows)
        events = np.asarray(rows, dtype=np.int32).reshape(-1, 5)
        return (events,
                np.asarray(op_idx, dtype=np.int32),
                np.asarray(procs, dtype=np.int32))

"""graftsearch — coverage-guided scenario search (ISSUE 20).

The checker fleet that finds its own bugs: a typed mutation-operator
registry over `history/synth.py` scenarios (`operators`), a
deterministic scenario genome + materializer (`scenario`), a fitness
function scored from signals every graftd verdict already carries
(`fitness`), a content-addressed minimized corpus under
``store/search/`` (`corpus`), the generation loop driving graftd's
batched admission (`driver`), and a seeded-violation recall harness
with a random-mutation ablation arm (`recall`).
"""

from .corpus import Corpus
from .driver import SearchConfig, SearchDriver
from .fitness import score_candidate
from .operators import REGISTRY, corrupt_once, family_of, operators_for
from .recall import RecallReport, plant_violations, run_recall
from .scenario import Scenario, materialize, scenario_fingerprint

__all__ = [
    "Corpus",
    "REGISTRY",
    "RecallReport",
    "Scenario",
    "SearchConfig",
    "SearchDriver",
    "corrupt_once",
    "family_of",
    "materialize",
    "operators_for",
    "plant_violations",
    "run_recall",
    "scenario_fingerprint",
    "score_candidate",
]

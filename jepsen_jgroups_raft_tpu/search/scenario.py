"""Scenario genomes: deterministic generation + edit replay (ISSUE 20).

A Scenario is a frozen genome — generation parameters plus an ordered
chain of (operator-name, edit-seed) history edits. `materialize` is a
pure function of the genome: the base history comes from
`history/synth.random_valid_history` under a seed derived from
(family, seed), nemesis params are folded in via
`nemesis/package.schedule_pressure`, and each edit replays under its
own derived RNG. Same genome ⇒ same bytes ⇒ same admission
fingerprint — that identity is what makes the corpus reproducible and
the ab_search determinism assertion meaningful.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field, replace
from typing import Optional, Tuple

from ..history.ops import INFO, INVOKE, OK, History
from ..history.synth import build_history, random_valid_history
from ..nemesis.package import schedule_pressure
from .operators import REGISTRY, Operator, apply_history_op

#: genome fields a "params" operator may rewrite
PARAM_FIELDS = ("n_ops", "n_procs", "value_range", "crash_p", "n_keys",
                "nemesis", "interval")


@dataclass(frozen=True)
class Scenario:
    family: str
    seed: int
    n_ops: int = 24
    n_procs: int = 3
    value_range: int = 3
    crash_p: float = 0.15
    n_keys: int = 1
    nemesis: str = "none"
    interval: float = 5.0
    #: ordered (operator-name, edit-seed) chain, replayed at materialize
    edits: Tuple[Tuple[str, int], ...] = field(default_factory=tuple)

    @property
    def region(self) -> Tuple[str, int]:
        """The (family, base-seed) pocket this genome explores — param
        and history edits stay inside the region."""
        return (self.family, self.seed)

    def to_dict(self) -> dict:
        return {
            "family": self.family, "seed": self.seed, "n_ops": self.n_ops,
            "n_procs": self.n_procs, "value_range": self.value_range,
            "crash_p": self.crash_p, "n_keys": self.n_keys,
            "nemesis": self.nemesis, "interval": self.interval,
            "edits": [list(e) for e in self.edits],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Scenario":
        d = dict(d)
        d["edits"] = tuple((str(n), int(s)) for n, s in d.get("edits", ()))
        return cls(**d)


def mutate(sc: Scenario, op: Operator, edit_seed: int) -> Scenario:
    """One mutation step: params operators rewrite the genome now;
    history operators append to the edit chain (replayed lazily)."""
    if op.target == "params":
        params = {f: getattr(sc, f) for f in PARAM_FIELDS}
        params = op.fn(random.Random(f"param:{op.name}:{edit_seed}"), params)
        return replace(sc, **params)
    return replace(sc, edits=sc.edits + ((op.name, edit_seed),))


def _multi_key_list_append(rng: random.Random, n_ops: int, n_procs: int,
                           n_keys: int, crash_p: float,
                           max_crashes: int) -> History:
    """Serial (valid-by-construction) multi-key list-append history with
    (key, value) tuples — the service's independent list-append workload
    splits it per key at admission; the anomaly rung reads the session
    order across keys. Crashed processes retire under fresh ids, same
    as the single-key generator."""
    keys = ["k%d" % i for i in range(max(1, n_keys))]
    state = {k: [] for k in keys}
    nxt = {k: 1 for k in keys}
    rows = []
    crashes = 0
    free = list(range(n_procs))
    next_pid = n_procs
    for _ in range(n_ops):
        p = free.pop(rng.randrange(len(free)))
        k = rng.choice(keys)
        if nxt[k] <= 6 and rng.random() < 0.6:
            f, elem = "append", nxt[k]
            nxt[k] += 1
            inv_val = (k, elem)
        else:
            f, inv_val = "read", (k, None)
        rows.append((p, INVOKE, f, inv_val))
        if f == "append":
            state[k] = state[k] + [elem]
        if crashes < max_crashes and rng.random() < crash_p:
            crashes += 1
            free.append(next_pid)
            next_pid += 1
            if rng.random() < 0.5:
                rows.append((p, INFO, f, inv_val))
        else:
            rows.append((p, OK, f, (k, list(state[k]))))
            free.append(p)
    return build_history(rows)


def materialize(sc: Scenario) -> History:
    """Genome → history, deterministically. Edits whose operator is
    inapplicable on the current base are deterministic no-ops (the
    genome still counts them — fingerprint dedup collapses the
    duplicates)."""
    pressure = schedule_pressure(sc.nemesis, sc.interval)
    crash_p = min(0.6, sc.crash_p + pressure["crash_bias"])
    max_crashes = sc.n_procs + pressure["crash_burst"]
    rng = random.Random(f"scenario:{sc.family}:{sc.seed}")
    if sc.family == "list-append" and sc.n_keys > 1:
        h = _multi_key_list_append(rng, sc.n_ops, sc.n_procs, sc.n_keys,
                                   crash_p, max_crashes)
    else:
        h = random_valid_history(rng, sc.family, n_ops=sc.n_ops,
                                 n_procs=sc.n_procs,
                                 value_range=sc.value_range,
                                 crash_p=crash_p, max_crashes=max_crashes)
    for name, edit_seed in sc.edits:
        op = REGISTRY[name]
        out = apply_history_op(
            op, random.Random(f"edit:{name}:{edit_seed}"), h)
        if out is not None:
            h = out
    return h


def scenario_workload(sc: Scenario) -> str:
    """Service workload name for this genome (family names match)."""
    return sc.family


def scenario_fingerprint(sc: Scenario,
                         consistency: str = "linearizable",
                         hist: Optional[History] = None) -> str:
    """The ADMISSION fingerprint of the materialized history — the same
    content hash graftd's result store dedupes on, so the search corpus
    and the service cache agree on candidate identity."""
    from ..history.packing import encode_history
    from ..service.request import build_units, fingerprint_encodings

    h = materialize(sc) if hist is None else hist
    model, units = build_units([h], scenario_workload(sc))
    encs = [encode_history(u, model) for _, u in units]
    return fingerprint_encodings(model, "auto", encs, consistency)

"""Seeded-violation recall harness (ISSUE 20 d).

Planting: each plant is a region — a (family, base-seed) pocket whose
base history is valid by construction — together with a PROOF that the
pocket contains a reachable violation: one (operator, edit-seed) pair
drawn from the same registry and the same bounded edit-seed space the
mutator searches, verified INVALID on the host checker at plant time.
The search driver never sees the proof; it only gets the bases. A
plant is FOUND when the corpus archives a re-verified violation in its
region.

Recall-per-CPU-minute uses `time.process_time`, which charges the
in-process graftd workers' checking threads to the run — the honest
denominator for the guided-vs-random comparison (wall time would
reward an arm that merely idles less in batch linger).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..checker.base import INVALID
from .driver import SearchConfig, SearchDriver
from .operators import operators_for
from .scenario import Scenario, materialize, mutate

_PLANT_ATTEMPTS_PER_SLOT = 12


def _scenario_invalid(sc: Scenario, consistency: str) -> bool:
    """Host-only verdict for a genome (no kernels: planting runs before
    any service exists)."""
    from ..checker.linearizable import check_histories
    from ..service.request import build_units

    hist = materialize(sc)
    model, units = build_units([hist], sc.family)
    for _, uh in units:
        res = check_histories([uh], model, algorithm="cpu",
                              consistency=consistency)[0]
        if res["valid?"] is INVALID:
            return True
    if sc.family == "list-append":
        from ..checker.anomaly import certify_submission

        if certify_submission([hist])["valid?"] is False:
            return True
    return False


@dataclass
class Plant:
    base: Scenario
    edit: tuple  # (operator-name, edit-seed) proven to invalidate

    @property
    def region(self):
        return self.base.region


@dataclass
class RecallReport:
    planted: int
    found: List[list]
    missed: List[list]
    recall: float
    cpu_s: float
    recall_per_cpu_min: float
    report: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "planted": self.planted, "found": self.found,
            "missed": self.missed, "recall": round(self.recall, 4),
            "cpu_s": round(self.cpu_s, 3),
            "recall_per_cpu_min": round(self.recall_per_cpu_min, 4),
            "report": self.report,
        }


def plant_violations(config: SearchConfig, k: int) -> List[Plant]:
    """Deterministically derive K plants across the config's families.
    Base seeds that admit no invalidating (operator, edit-seed) inside
    the mutator's edit space are skipped — every returned plant is
    PROVEN reachable, so recall misses are search failures, not
    planting failures."""
    plants: List[Plant] = []
    fams = list(config.families)
    slot = 0
    while len(plants) < k:
        fam = fams[len(plants) % len(fams)]
        plant = None
        for attempt in range(_PLANT_ATTEMPTS_PER_SLOT):
            seed = config.seed * 1000 + 101 * slot + 7 * attempt
            base = Scenario(
                family=fam, seed=seed, n_ops=config.n_ops,
                n_procs=config.n_procs, crash_p=config.crash_p,
                n_keys=config.n_keys if fam == "list-append" else 1)
            if _scenario_invalid(base, config.consistency):
                continue  # base must start valid
            ops = [op for op in operators_for(fam, "history")
                   if op.can_invalidate]
            hit = None
            for op in ops:
                for es in range(config.edit_space):
                    cand = mutate(base, op, es)
                    if _scenario_invalid(cand, config.consistency):
                        hit = (op.name, es)
                        break
                if hit:
                    break
            if hit:
                plant = Plant(base=base, edit=hit)
                break
        if plant is None:
            raise RuntimeError(
                f"could not derive a reachable plant for {fam!r} "
                f"(slot {slot}); widen JGRAFT_SEARCH_EDIT_SPACE")
        plants.append(plant)
        slot += 1
    return plants


def run_recall(config: SearchConfig, k: Optional[int] = None,
               plants: Optional[List[Plant]] = None,
               service=None) -> RecallReport:
    """Plant, search, score. The driver only receives the plant BASES;
    found = a re-verified violation archived in the plant's region."""
    if plants is None:
        plants = plant_violations(config, k or 20)
    t_cpu = time.process_time()
    driver = SearchDriver(config, service=service)
    rep = driver.run(seeds=[p.base for p in plants])
    cpu_s = max(1e-6, time.process_time() - t_cpu)
    regions = {tuple(e["region"]) for e in driver.corpus.entries()}
    found = [list(p.region) for p in plants if p.region in regions]
    missed = [list(p.region) for p in plants if p.region not in regions]
    recall = len(found) / max(1, len(plants))
    return RecallReport(
        planted=len(plants), found=found, missed=missed, recall=recall,
        cpu_s=cpu_s,
        recall_per_cpu_min=len(found) / (cpu_s / 60.0),
        report=rep)

"""Content-addressed search corpus (ISSUE 20 tentpole c).

Survivor violations live under ``<root>/corpus/<fp[:2]>/<fp>.json``,
keyed by the ADMISSION fingerprint (the same content hash the graftd
result store dedupes on), written temp + os.replace so a crashed search
never publishes a torn entry.

Every entry is MINIMIZED before archive (`checker/counterexample.py`):
the corpus is a regression suite the fleet replays forever, so each
entry should be the smallest witness of its violation, not the raw
mutant — a 6-op reproducer re-checks in microseconds on the cheap tier
and its failure mode is human-readable, where the 40-op original would
pay kernel admission on every replay and bury the witness. Archive
refuses entries whose minimized ops do not re-verify INVALID (that
would mean the minimizer returned a non-witness — a corpus poisoned
with passing entries is worse than no corpus).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Iterator, List, Optional

from ..checker.base import INVALID
from ..history.ops import History, Op


class Corpus:
    """Fingerprint-deduped violation archive."""

    def __init__(self, root: str):
        self.root = root
        self.dir = os.path.join(root, "corpus")
        os.makedirs(self.dir, exist_ok=True)
        self._fps = set()
        for sub in sorted(os.listdir(self.dir)):
            subdir = os.path.join(self.dir, sub)
            if os.path.isdir(subdir):
                for name in os.listdir(subdir):
                    if name.endswith(".json"):
                        self._fps.add(name[:-5])

    def __len__(self) -> int:
        return len(self._fps)

    def __contains__(self, fp: str) -> bool:
        return fp in self._fps

    def fingerprints(self) -> set:
        return set(self._fps)

    def _path(self, fp: str) -> str:
        return os.path.join(self.dir, fp[:2], fp + ".json")

    def add(self, entry: dict) -> bool:
        """Archive one entry keyed by entry['fingerprint']; False when a
        same-fingerprint entry already exists (dedup, not an error)."""
        fp = entry["fingerprint"]
        if fp in self._fps:
            return False
        path = self._path(fp)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".corpus-", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(entry, f, sort_keys=True, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic publish
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._fps.add(fp)
        return True

    def load(self, fp: str) -> dict:
        with open(self._path(fp)) as f:
            return json.load(f)

    def entries(self) -> Iterator[dict]:
        for fp in sorted(self._fps):
            yield self.load(fp)


def _history_from_views(views: List[dict]) -> History:
    """Rebuild a checkable unit history from archived op views
    (`counterexample._op_view` shape — values keep their in-memory
    types because the corpus never crosses a JSON tuple boundary at
    re-verify time: tuples arrive as lists and the models treat the
    add-and-get pair positionally)."""
    h = History()
    for v in views:
        val = v.get("value")
        if isinstance(val, list) and v.get("f") == "add-and-get":
            val = tuple(val)
        h.append(Op(process=v["process"], type=v["type"], f=v["f"],
                    value=val))
    return h


def reverify_entry(entry: dict) -> bool:
    """Re-check an archived entry's minimized ops: still INVALID?
    Runs the exact host checker (no kernels — corpus replay must work
    on a bare CPU box) at the entry's rung; transactional-overlay
    entries replay through the anomaly certifier instead."""
    from ..checker.linearizable import check_histories
    from ..service.request import service_workloads

    if entry.get("kind") == "txn":
        from ..checker.anomaly import certify_history

        h = _history_from_views(entry["txn-ops"])
        # archived tuples arrive as lists; the anomaly graph needs
        # (key, value) pairs back
        for op in h:
            if isinstance(op.value, list) and len(op.value) == 2 and \
                    isinstance(op.value[0], str):
                op.value = tuple(op.value)
        return certify_history(h, kernel=False)["valid?"] is False
    model_factory, _ = service_workloads()[entry["family"]]
    for unit in entry["units"]:
        h = _history_from_views(unit["ops"])
        res = check_histories([h], model_factory(), algorithm="cpu",
                              consistency=entry.get("consistency",
                                                    "linearizable"))[0]
        if res["valid?"] is INVALID:
            return True
    return False


def build_entry(sc, fingerprint: str, rows: List[dict],
                txn: Optional[dict], hist: History,
                generation: int, fitness: float,
                consistency: str = "linearizable") -> Optional[dict]:
    """Minimize an INVALID candidate and shape its corpus entry; None
    when nothing minimizes to a confirmed witness (the caller counts
    that as `unconfirmed`, it must never be archived)."""
    from ..checker.counterexample import attach_counterexample
    from ..service.request import build_units

    model, units = build_units([hist], sc.family)
    unit_views = []
    for (label, uh), row in zip(units, rows):
        if row.get("valid?") is not INVALID:
            continue
        r = dict(row)
        ce = r.get("counterexample") or {}
        if not ce.get("minimal-ops"):
            attach_counterexample(r, uh, model, consistency=consistency)
            ce = r.get("counterexample") or {}
        ops = ce.get("minimal-ops")
        minimized = ops is not None
        if ops is None:
            ops = [{"index": i, "process": o.process, "type": o.type,
                    "f": o.f, "value": o.value} for i, o in enumerate(uh)]
        unit_views.append({"label": label, "ops": ops,
                           "op-count": ce.get("minimal-op-count",
                                              r.get("op-count")),
                           "minimized": minimized})
    entry = {
        "fingerprint": fingerprint,
        "family": sc.family,
        "region": list(sc.region),
        "scenario": sc.to_dict(),
        "chain": [list(e) for e in sc.edits],
        "generation": generation,
        "fitness": round(fitness, 4),
        "consistency": consistency,
        "kind": "lin",
        "units": unit_views,
    }
    if not unit_views:
        if not (txn and txn.get("valid?") is False):
            return None
        # anomaly-overlay violation: every per-key unit passed its rung,
        # the cross-key txn graph is the witness — archive the full
        # (tupled) history for the certifier to replay
        entry["kind"] = "txn"
        entry["txn-ops"] = [{"index": i, "process": o.process,
                             "type": o.type, "f": o.f, "value": o.value}
                            for i, o in enumerate(hist)]
        entry["anomalies"] = sorted(
            k for per in txn.get("histories", [])
            for k, w in (per.get("anomalies") or {}).items()
            if w is not None)
    if not reverify_entry(entry):
        return None
    return entry

"""Generation loop over graftd's batched admission (ISSUE 20 c).

Each generation mutates survivors into a candidate population, submits
every unseen candidate through graftd (all submissions are in flight
before the first wait, so shape-bucket coalescing batches them for
free), scores fitness from the verdicts, archives minimized violations
into the content-addressed corpus, and selects the next survivor pool.

Guided vs random (the `JGRAFT_SEARCH_GUIDED=0` ablation) differ ONLY
in what feedback they read:

  * guided — survivors are the fittest candidates, parents are drawn
    fitness-weighted, operator choice is weighted by each operator's
    observed violation/fitness yield, and regions whose violation is
    already archived are retired so the budget concentrates on unfound
    pockets;
  * random — survivors, parents and operators are drawn uniformly and
    nothing is retired: pure blind mutation, same operators, same
    budget, same admission path.

Determinism: every stochastic choice flows from seeded Random chains
and candidate evaluation pins ``JGRAFT_AUTOTUNE=0`` for the duration
of the run — the measured per-bucket gates (lin fastpath, certify
batch) are host-mood state that would otherwise let tier attribution,
hence fitness, hence SELECTION, differ between two identical runs.
Same seed ⇒ identical corpus fingerprints, asserted by ab_search
before any timing.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from .. import platform as plat
from ..checker.base import INVALID
from .corpus import Corpus, build_entry
from .fitness import score_candidate
from .operators import Operator, operators_for
from .scenario import Scenario, materialize, mutate, scenario_fingerprint

#: workloads whose admission overlay needs raw ops — the binary lane
#: ships encodings only (service/request.py), so these submit as JSON
_TXN_WORKLOADS = ("list-append",)

_EVAL_TIMEOUT_S = 120.0


def search_config_from_env(**overrides) -> "SearchConfig":
    kw = dict(
        population=plat.env_int("JGRAFT_SEARCH_POP", 48, minimum=4),
        generations=plat.env_int("JGRAFT_SEARCH_GENERATIONS", 8, minimum=1),
        survivors=plat.env_int("JGRAFT_SEARCH_SURVIVORS", 12, minimum=2),
        edit_space=plat.env_int("JGRAFT_SEARCH_EDIT_SPACE", 24, minimum=2),
        seed=plat.env_int("JGRAFT_SEARCH_SEED", 0),
        guided=plat.env_int("JGRAFT_SEARCH_GUIDED", 1) != 0,
        corpus_dir=plat.env_str("JGRAFT_SEARCH_DIR", "store/search"),
    )
    kw.update(overrides)
    return SearchConfig(**kw)


@dataclass
class SearchConfig:
    families: Tuple[str, ...] = ("register", "set", "queue", "list-append")
    population: int = 48
    generations: int = 8
    survivors: int = 12
    edit_space: int = 24
    seed: int = 0
    guided: bool = True
    corpus_dir: str = "store/search"
    consistency: str = "linearizable"
    n_ops: int = 20
    n_procs: int = 3
    crash_p: float = 0.1
    n_keys: int = 2  # list-append bases are multi-key (txn tier)
    bases_per_family: int = 4
    service_url: Optional[str] = None
    max_inflight: int = 64


@dataclass
class _Candidate:
    sc: Scenario
    fingerprint: str
    fitness: float = 0.0
    invalid: bool = False
    rows: list = field(default_factory=list)
    txn: Optional[dict] = None


class SearchDriver:
    """One search run. Owns its CheckingService unless given a
    `service` (in-process) or a `SearchConfig.service_url` (a real
    graftd daemon over HTTP / unix socket, binary frames for the
    non-transactional workloads)."""

    def __init__(self, config: SearchConfig, service=None):
        self.config = config
        self.corpus = Corpus(config.corpus_dir)
        self._service = service
        self._client = None
        self._owns_service = service is None and config.service_url is None
        self.found_regions: set = set()
        self._anchors: List[_Candidate] = []
        self._rr = -1
        self.op_stats: dict = {}  # name -> [uses, invalids, fitness_sum]
        self.generation_stats: List[dict] = []
        self.unconfirmed = 0
        self.dedup_skips = 0
        self.candidates_evaluated = 0

    # ------------------------------------------------------------ seeds

    def base_scenarios(self) -> List[Scenario]:
        c = self.config
        out = []
        for fam in c.families:
            for i in range(c.bases_per_family):
                out.append(Scenario(
                    family=fam, seed=c.seed * 1000 + i, n_ops=c.n_ops,
                    n_procs=c.n_procs, crash_p=c.crash_p,
                    n_keys=c.n_keys if fam == "list-append" else 1))
        return out

    # ------------------------------------------------------- evaluation

    def _ensure_service(self):
        if self._service is None and self._owns_service:
            from ..service.daemon import CheckingService

            self._service = CheckingService(
                store_root=None,
                queue_capacity=max(256, 4 * self.config.population),
                batch_wait=0.02)
        if self._client is None and self.config.service_url:
            from ..service.client import ServiceClient

            self._client = ServiceClient(self.config.service_url)

    def close(self):
        if self._owns_service and self._service is not None:
            self._service.shutdown(wait=True)
            self._service = None
        if self._client is not None:
            self._client.close()
            self._client = None

    def _evaluate(self, cands: List[_Candidate]) -> None:
        """Submit every candidate, then wait — all in flight before the
        first wait so graftd's cross-request coalescing sees the whole
        population at once."""
        self._ensure_service()
        for chunk_start in range(0, len(cands), self.config.max_inflight):
            chunk = cands[chunk_start:chunk_start + self.config.max_inflight]
            if self._client is not None:
                self._eval_http(chunk)
            else:
                self._eval_inproc(chunk)
        for c in cands:
            self.candidates_evaluated += 1
            c.fitness = score_candidate(c.rows, c.txn)
            c.invalid = any(r.get("valid?") is INVALID for r in c.rows) or \
                bool(c.txn and c.txn.get("valid?") is INVALID)

    def _eval_inproc(self, chunk: List[_Candidate]) -> None:
        reqs = []
        for c in chunk:
            reqs.append(self._service.submit(
                [materialize(c.sc)], workload=c.sc.family,
                consistency=self.config.consistency))
        for c, req in zip(chunk, reqs):
            req.wait(_EVAL_TIMEOUT_S)
            c.rows = list(req.results or [])
            c.txn = req.txn_anomalies

    def _eval_http(self, chunk: List[_Candidate]) -> None:
        recs = []
        for c in chunk:
            binary = c.sc.family not in _TXN_WORKLOADS
            recs.append(self._client.submit(
                [materialize(c.sc)], workload=c.sc.family,
                consistency=self.config.consistency, binary=binary))
        deadline = time.monotonic() + _EVAL_TIMEOUT_S
        for c, rec in zip(chunk, recs):
            while rec["status"] not in ("done", "failed", "cancelled") \
                    and time.monotonic() < deadline:
                rec = self._client.result(rec["id"], wait_s=10.0)
            c.rows = list(rec.get("results") or [])
            c.txn = rec.get("txn-anomalies")

    # -------------------------------------------------------- selection

    def _pick_parent(self, rng: random.Random,
                     pool: List[_Candidate]) -> _Candidate:
        """Parent pool = base anchors (never evicted — every region
        stays reachable for the whole run) + the survivor pool.

        Guided splits its draws between coverage and exploitation:
        half round-robin over the anchors of regions with NO archived
        violation yet, half fitness-weighted over survivors in live
        regions. Random draws uniformly over the same structural pool
        and retires nothing — the ablation arm reads no feedback."""
        full = self._anchors + pool
        if not self.config.guided:
            return full[rng.randrange(len(full))]
        open_anchors = [c for c in self._anchors
                        if c.sc.region not in self.found_regions]
        live = [c for c in full if c.sc.region not in self.found_regions] \
            or full
        if open_anchors and (rng.random() < 0.7 or len(live) == 0):
            self._rr += 1
            return open_anchors[self._rr % len(open_anchors)]
        # fitness-weighted (shifted so zero-fitness pools stay uniform)
        weights = [0.25 + c.fitness for c in live]
        total = sum(weights)
        x = rng.random() * total
        for c, w in zip(live, weights):
            x -= w
            if x <= 0:
                return c
        return live[-1]

    def _pick_operator(self, rng: random.Random,
                       ops: Sequence[Operator]) -> Operator:
        if not self.config.guided:
            return ops[rng.randrange(len(ops))]
        weights = []
        for op in ops:
            uses, inv, gain = self.op_stats.get(op.name, (0, 0, 0.0))
            yield_w = (4.0 * inv + gain) / uses if uses else 0.0
            weights.append(0.5 + yield_w)
        total = sum(weights)
        x = rng.random() * total
        for op, w in zip(ops, weights):
            x -= w
            if x <= 0:
                return op
        return ops[-1]

    def _note_yield(self, op_name: str, child: _Candidate,
                    parent: _Candidate) -> None:
        uses, inv, gain = self.op_stats.get(op_name, (0, 0, 0.0))
        self.op_stats[op_name] = (
            uses + 1, inv + (1 if child.invalid else 0),
            gain + max(0.0, child.fitness - parent.fitness))

    # -------------------------------------------------------------- run

    def run(self, seeds: Optional[List[Scenario]] = None) -> dict:
        c = self.config
        arm = "guided" if c.guided else "random"
        rng = random.Random(f"search:{c.seed}:{arm}")
        t_wall = time.monotonic()
        t_cpu = time.process_time()
        saved_autotune = os.environ.get("JGRAFT_AUTOTUNE")
        os.environ["JGRAFT_AUTOTUNE"] = "0"  # deterministic tier routing
        try:
            return self._run(rng, seeds, t_wall, t_cpu)
        finally:
            if saved_autotune is None:
                os.environ.pop("JGRAFT_AUTOTUNE", None)
            else:
                os.environ["JGRAFT_AUTOTUNE"] = saved_autotune
            if self._owns_service:
                self.close()

    def _run(self, rng: random.Random, seeds: Optional[List[Scenario]],
             t_wall: float, t_cpu: float) -> dict:
        c = self.config
        bases = list(seeds) if seeds else self.base_scenarios()
        self._anchors = [_Candidate(sc, scenario_fingerprint(
            sc, c.consistency)) for sc in bases]
        self._rr = -1
        seen = {cand.fingerprint for cand in self._anchors}
        self._evaluate(self._anchors)
        self._archive(self._anchors, generation=0)
        pool: List[_Candidate] = []
        for gen in range(1, c.generations + 1):
            if c.guided and self._anchors and all(
                    a.sc.region in self.found_regions
                    for a in self._anchors):
                # coverage complete: every seeded region has an archived,
                # re-verified violation. Only the guided arm can know
                # this — stopping here is verdict feedback earning CPU,
                # exactly what the ablation measures.
                break
            children: List[_Candidate] = []
            attributions = []
            # exactly `population` mutation attempts per generation for
            # BOTH arms — duplicates burn their slot (dedup-skips), so
            # the ablation comparison is per-candidate-budget fair
            for _ in range(c.population):
                parent = self._pick_parent(rng, pool)
                ops = operators_for(parent.sc.family)
                op = self._pick_operator(rng, ops)
                child_sc = mutate(parent.sc, op, rng.randrange(c.edit_space))
                fp = scenario_fingerprint(child_sc, c.consistency)
                if fp in seen:
                    self.dedup_skips += 1
                    continue
                seen.add(fp)
                cand = _Candidate(child_sc, fp)
                children.append(cand)
                attributions.append((op.name, cand, parent))
            self._evaluate(children)
            for op_name, cand, parent in attributions:
                self._note_yield(op_name, cand, parent)
            found = self._archive(children, generation=gen)
            pool = self._select(rng, pool, children)
            fits = sorted(ch.fitness for ch in children) or [0.0]
            self.generation_stats.append({
                "generation": gen,
                "candidates": len(children),
                "invalid": sum(1 for ch in children if ch.invalid),
                "archived": found,
                "corpus": len(self.corpus),
                "fitness-mean": round(sum(fits) / len(fits), 4),
                "fitness-max": round(fits[-1], 4),
                "fitness-p50": round(fits[len(fits) // 2], 4),
            })
        return self._report(t_wall, t_cpu, bases)

    def _select(self, rng: random.Random, pool: List[_Candidate],
                children: List[_Candidate]) -> List[_Candidate]:
        c = self.config
        merged = pool + children
        if c.guided:
            merged.sort(key=lambda x: -x.fitness)  # stable: ties keep age
            return merged[:c.survivors]
        return [merged[rng.randrange(len(merged))]
                for _ in range(min(c.survivors, len(merged)))]

    def _archive(self, cands: List[_Candidate], generation: int) -> int:
        added = 0
        for cand in cands:
            if not cand.invalid:
                continue
            entry = build_entry(cand.sc, cand.fingerprint, cand.rows,
                                cand.txn, materialize(cand.sc), generation,
                                cand.fitness, self.config.consistency)
            if entry is None:
                self.unconfirmed += 1
                continue
            if self.corpus.add(entry):
                added += 1
            if self.config.guided:
                self.found_regions.add(cand.sc.region)
        return added

    def _report(self, t_wall: float, t_cpu: float,
                bases: List[Scenario]) -> dict:
        c = self.config
        fits = sorted(g["fitness-mean"] for g in self.generation_stats) \
            or [0.0]
        return {
            "arm": "guided" if c.guided else "random",
            "seed": c.seed,
            "families": list(c.families),
            "generations": len(self.generation_stats),
            "population": c.population,
            "candidates": self.candidates_evaluated,
            "dedup-skips": self.dedup_skips,
            "bases": len(bases),
            "corpus": len(self.corpus),
            "corpus-fingerprints": sorted(self.corpus.fingerprints()),
            "found-regions": sorted(map(list, self.found_regions)),
            "unconfirmed": self.unconfirmed,
            "fitness": {"mean": round(sum(fits) / len(fits), 4),
                        "max": round(fits[-1], 4),
                        "p50": round(fits[len(fits) // 2], 4)},
            "per-generation": self.generation_stats,
            "wall_s": round(time.monotonic() - t_wall, 3),
            "cpu_s": round(time.process_time() - t_cpu, 3),
        }

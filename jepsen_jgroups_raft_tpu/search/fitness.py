"""Fitness from signals every verdict already carries (ISSUE 20 b).

No new instrumentation: the score reads fields graftd's demux already
attaches to every result row. Mapping (doc/checker-design.md §22):

  signal                          reading                     weight
  ------------------------------  --------------------------  ------
  decided-tier                    distance from the cheap     0–1
                                  tiers (greedy 0, backtrack
                                  0.4, cycle 0.6, kernels/host
                                  1.0 — rows the certifier's
                                  step/abort budgets could not
                                  decide are near the boundary)
  valid? is INVALID               a found violation           +2.0
  valid? is UNKNOWN               undecidable inside budget   +1.5
  counterexample.minimal-op-count smaller minimized witness   +1/(1+n)
                                  = nearer the boundary
  sc-refuted                      cycle tier refuted the       +0.5
                                  stronger rung under a weak-
                                  rung pass
  cycle-skipped-size              txn graph past the node cap  +0.3
  decided-at-segment (stream)     later detection = deeper     +0.5·k/n
                                  pocket
  txn-anomalies overlay           anomaly classes witnessed    +1.0
                                  (+0.5 each extra class)

The kernel tiers (mask/dense/sort/host) collapse to one distance on
purpose: WHICH kernel family decides a row depends on the batch it
coalesced into (bucket shapes are sized to the batch's real maximum),
not on the row itself — scoring them apart would make fitness, and
therefore survivor selection, depend on admission timing and break the
corpus-determinism contract.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..checker.base import INVALID, UNKNOWN

TIER_DISTANCE = {
    "trivial": 0.0,
    "greedy": 0.0,
    "greedy@lin": 0.0,
    "backtrack": 0.4,
    "backtrack@lin": 0.4,
    "cycle": 0.6,
    "mask": 1.0,
    "dense": 1.0,
    "sort": 1.0,
    "host": 1.0,
    "remote-shard": 1.0,
}

INVALID_BONUS = 2.0
UNKNOWN_BONUS = 1.5


def score_result_row(row: dict) -> float:
    """Fitness contribution of one demuxed result row."""
    s = TIER_DISTANCE.get(row.get("decided-tier"), 1.0)
    v = row.get("valid?")
    if v is INVALID:
        ce = row.get("counterexample") or {}
        n = ce.get("minimal-op-count") or row.get("op-count") or 64
        s += INVALID_BONUS + 1.0 / (1.0 + n)
    elif v is UNKNOWN:
        s += UNKNOWN_BONUS
    if row.get("sc-refuted"):
        s += 0.5
    if row.get("cycle-skipped-size"):
        s += 0.3
    seg = row.get("decided-at-segment")
    segs = row.get("segments") or row.get("segment-count")
    if isinstance(seg, int) and isinstance(segs, int) and segs > 0:
        s += 0.5 * min(1.0, seg / segs)
    return s


def score_txn(txn: Optional[dict]) -> float:
    """Fitness contribution of the admission-time anomaly overlay."""
    if not txn:
        return 0.0
    s = 0.0
    classes = 0
    for per in txn.get("histories", []):
        found = per.get("anomalies") or {}
        classes += sum(1 for w in found.values() if w is not None)
        if per.get("cycle-skipped-size"):
            s += 0.3
    if txn.get("valid?") is INVALID or classes:
        s += 1.0 + 0.5 * max(0, classes - 1)
    return s


def score_candidate(rows: Sequence[dict], txn: Optional[dict] = None) -> float:
    """Candidate fitness: mean per-unit row score (mean, not sum, so a
    multi-key submission isn't fitter merely for having more keys) plus
    the transactional overlay."""
    if not rows:
        return 0.0
    return sum(score_result_row(r) for r in rows) / len(rows) + score_txn(txn)

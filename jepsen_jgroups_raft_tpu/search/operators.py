"""Typed mutation-operator registry (ISSUE 20 tentpole a).

Generalizes `history/synth.py corrupt()` — which silently no-op'd on
register writes and never touched list-append observed lists — into a
registry of named, per-family operators the search driver draws from
with a seed-stable RNG.

Soundness contract (doc/checker-design.md §22): every operator maps a
well-formed history to a well-formed history — the packing layer must
never reject a mutant, because a candidate that fails encode wastes an
admission slot and (worse) would make corpus replay seed-dependent on
the *error* path. Concretely:

  * value edits stay inside each model's packed domain (set/list
    elements ≤ 31, list length ≤ 6, queue tickets ≥ 0);
  * a completed append's observed list must end in its own element
    (models/listappend._prefix raises otherwise), so append edits only
    touch the prefix ``lst[:-1]``;
  * row moves keep every invocation strictly before its completion and
    never reorder one process's ops against each other;
  * crash injection (ok→info) rewrites the completion value back to the
    *invocation* value, matching the synth generator's info rows.

Operators return ``None`` when inapplicable (e.g. no cas rows to flip)
so the driver can treat the mutation as a deterministic no-op instead
of raising.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from ..history.ops import FAIL, INFO, INVOKE, OK, History
from ..history.synth import build_history

FAMILIES = ("register", "counter", "set", "queue", "list-append")

#: packed-domain bounds shared with models/gset.py and models/listappend.py
_MAX_ELEM = 31
_MAX_LIST_LEN = 6


def _rows(hist: History) -> List[list]:
    return [[o.process, o.type, o.f, o.value] for o in hist]


def _invoke_of(rows: Sequence[list], i: int) -> Optional[int]:
    """Index of the invocation row belonging to completion row i."""
    p = rows[i][0]
    for j in range(i - 1, -1, -1):
        if rows[j][0] == p:
            return j if rows[j][1] == INVOKE else None
    return None


def _completion_of(rows: Sequence[list], i: int) -> Optional[int]:
    """Index of the completion row belonging to invocation row i."""
    p = rows[i][0]
    for j in range(i + 1, len(rows)):
        if rows[j][0] == p:
            return j if rows[j][1] != INVOKE else None
    return None


def _kv(value, tupled: bool):
    """Unwrap a (key, payload) value for the transactional tier."""
    if tupled:
        return value[0], value[1]
    return None, value


def _wrap(key, payload, tupled: bool):
    return (key, payload) if tupled else payload


def _is_tupled(rows: Sequence[list]) -> bool:
    for r in rows:
        if r[1] == INVOKE:
            return isinstance(r[3], tuple)
    return False


# ---------------------------------------------------------------- value edits

def _perturb_read(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    idxs = [i for i, r in enumerate(rows)
            if r[1] == OK and r[2] == "read" and not isinstance(r[3], list)]
    if not idxs:
        return None
    i = rng.choice(idxs)
    v = rows[i][3]
    rows[i][3] = (v if isinstance(v, int) else 0) + rng.choice([1, -1])
    return rows


def _perturb_write(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    # the old corrupt() write arm was a silent no-op: completed writes
    # carry the written value, so flipping ONLY the completion would
    # desync it from the invocation. Rewrite both rows together.
    idxs = [i for i, r in enumerate(rows) if r[1] == OK and r[2] == "write"]
    if not idxs:
        return None
    i = rng.choice(idxs)
    j = _invoke_of(rows, i)
    if j is None:
        return None
    v = rows[i][3] if isinstance(rows[i][3], int) else 0
    nv = v + rng.choice([1, -1, 2])
    rows[i][3] = nv
    rows[j][3] = nv
    return rows


def _perturb_cas(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    idxs = [i for i, r in enumerate(rows)
            if r[1] in (OK, FAIL) and r[2] == "cas"]
    if not idxs:
        return None
    i = rng.choice(idxs)
    rows[i][1] = FAIL if rows[i][1] == OK else OK
    return rows


def _perturb_set_read(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    idxs = [i for i, r in enumerate(rows)
            if r[1] == OK and r[2] == "read" and isinstance(r[3], list)]
    if not idxs:
        return None
    i = rng.choice(idxs)
    v = list(rows[i][3])
    if v and rng.random() < 0.5:
        v.pop(rng.randrange(len(v)))  # drop an observed element
    else:
        absent = [e for e in range(_MAX_ELEM + 1) if e not in v]
        if not absent:
            return None
        v.append(absent[rng.randrange(len(absent))])  # claim one
        v.sort()
    rows[i][3] = v
    return rows


def _perturb_sum(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    idxs = [i for i, r in enumerate(rows)
            if r[1] == OK and r[2] == "add-and-get"
            and isinstance(r[3], tuple) and len(r[3]) == 2]
    if not idxs:
        return None
    i = rng.choice(idxs)
    v0, s = rows[i][3]
    rows[i][3] = (v0, s + rng.choice([1, -1]))
    return rows


def _perturb_ticket(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    idxs = [i for i, r in enumerate(rows)
            if r[1] == OK and r[2] in ("enqueue", "dequeue")]
    if not idxs:
        return None
    i = rng.choice(idxs)
    v = rows[i][3]
    if isinstance(v, int):
        rows[i][3] = max(0, v + rng.choice([1, -1])) if v else v + 1
    else:
        rows[i][3] = 0  # an empty dequeue claims a ticket
    return rows


def _perturb_observed_list(rng: random.Random,
                           rows: List[list]) -> Optional[List[list]]:
    tupled = _is_tupled(rows)
    cands = []
    for i, r in enumerate(rows):
        if r[1] != OK or r[2] not in ("read", "append"):
            continue
        _, payload = _kv(r[3], tupled)
        if not isinstance(payload, list):
            continue
        # appends may only edit the prefix (the list must keep ending in
        # the appended element or encode rejects the history)
        editable = len(payload) - 1 if r[2] == "append" else len(payload)
        if r[2] == "append" and editable < 1:
            continue
        cands.append((i, editable))
    if not cands:
        return None
    i, editable = cands[rng.randrange(len(cands))]
    key, payload = _kv(rows[i][3], tupled)
    lst = list(payload)
    tail = lst[editable:]
    head = lst[:editable]
    mode = rng.random()
    if head and mode < 0.45:
        head.pop(rng.randrange(len(head)))  # drop an observed element
    elif len(head) >= 2 and mode < 0.7:
        j = rng.randrange(len(head) - 1)
        head[j], head[j + 1] = head[j + 1], head[j]  # reorder observation
    else:
        absent = [e for e in range(1, _MAX_ELEM + 1) if e not in lst]
        if not absent or len(lst) >= _MAX_LIST_LEN:
            if not head:
                return None
            head.pop(rng.randrange(len(head)))
        else:
            head.insert(rng.randrange(len(head) + 1),
                        absent[rng.randrange(len(absent))])  # claim one
    rows[i][3] = _wrap(key, head + tail, tupled)
    return rows


# ----------------------------------------------------------- structural edits

#: ambiguity budget for crash-injecting operators: every crashed op
#: holds a concurrency-window slot forever (history/synth.py caps
#: max_crashes=n_procs for the same reason), and past a handful the
#: exact host check goes combinatorial — a mutant nobody can afford to
#: check is not a useful candidate, and its cost would swamp the
#: recall-per-CPU-minute metric with one pathological genome.
_MAX_CRASHED = 5


def _crashed_count(rows: Sequence[list]) -> int:
    """Crashed ops so far: info completions + silently dangling
    invocations (invokes minus completions)."""
    n_inv = sum(1 for r in rows if r[1] == INVOKE)
    n_done = sum(1 for r in rows if r[1] in (OK, FAIL))
    return n_inv - n_done  # info rows pair a dangling invoke


def _retire_process(rows: List[list], after: int, p) -> None:
    """Crashed-id remapping (history/synth.py): once an op's completion
    becomes unknown, its process can never act again — later rows of p
    move under a fresh worker id, or pair_ops rejects the history as
    invoked-twice-without-completing."""
    later = [j for j in range(after + 1, len(rows)) if rows[j][0] == p]
    if not later:
        return
    fresh = max((r[0] for r in rows if isinstance(r[0], int)),
                default=-1) + 1
    for j in later:
        rows[j][0] = fresh


def _drop_completion(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    if _crashed_count(rows) >= _MAX_CRASHED:
        return None
    idxs = [i for i, r in enumerate(rows) if r[1] == OK]
    if not idxs:
        return None
    i = rng.choice(idxs)
    p = rows[i][0]
    del rows[i]  # dangling invocation == crashed worker (pair_ops)
    _retire_process(rows, i - 1, p)
    return rows


def _crash_op(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    if _crashed_count(rows) >= _MAX_CRASHED:
        return None
    idxs = [i for i, r in enumerate(rows) if r[1] in (OK, FAIL)]
    if not idxs:
        return None
    i = rng.choice(idxs)
    j = _invoke_of(rows, i)
    if j is None:
        return None
    rows[i][1] = INFO
    rows[i][3] = rows[j][3]  # info rows carry the invocation value
    _retire_process(rows, i, rows[i][0])
    return rows


def _reorder_completion(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    idxs = []
    for i, r in enumerate(rows):
        if r[1] == INVOKE:
            continue
        j = _invoke_of(rows, i)
        if j is not None and i - j >= 2:
            idxs.append((i, j))
    if not idxs:
        return None
    i, j = idxs[rng.randrange(len(idxs))]
    dst = rng.randrange(j + 1, i)  # earlier, still after the invocation
    row = rows.pop(i)
    rows.insert(dst, row)
    return rows


def _reorder_invoke(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    idxs = []
    for i, r in enumerate(rows):
        if r[1] != INVOKE:
            continue
        c = _completion_of(rows, i)
        end = c if c is not None else len(rows)
        if end - i >= 2:
            idxs.append((i, end))
    if not idxs:
        return None
    i, end = idxs[rng.randrange(len(idxs))]
    dst = rng.randrange(i + 1, end)  # later, still before the completion
    row = rows.pop(i)
    rows.insert(dst, row)
    return rows


def _session_shuffle(rng: random.Random, rows: List[list]) -> Optional[List[list]]:
    """Swap two adjacent completed ops of one process that target
    DIFFERENT keys (transactional tier only). Each key's own op order
    is untouched — only the session (po) order flips, which is exactly
    the plane the anomaly certifier reads."""
    if not _is_tupled(rows):
        return None
    by_proc: dict = {}
    for i, r in enumerate(rows):
        if r[1] == INVOKE:
            c = _completion_of(rows, i)
            if c is not None and rows[c][1] == OK:
                by_proc.setdefault(r[0], []).append((i, c))
    cands = []
    for pairs in by_proc.values():
        for a, b in zip(pairs, pairs[1:]):
            ka = rows[a[0]][3][0]
            kb = rows[b[0]][3][0]
            if ka != kb:
                cands.append((a, b))
    if not cands:
        return None
    (ia, ca), (ib, cb) = cands[rng.randrange(len(cands))]
    rows[ia], rows[ib] = rows[ib], rows[ia]
    rows[ca], rows[cb] = rows[cb], rows[ca]
    return rows


# ------------------------------------------------------------- param mutation

def _mix_crash_rate(rng: random.Random, params: dict) -> dict:
    p = params["crash_p"]
    p = rng.choice([0.05, 0.15, 0.3]) if p <= 0 else p * rng.choice([0.5, 2.0])
    params["crash_p"] = min(0.6, round(p, 4))
    return params


def _mix_procs(rng: random.Random, params: dict) -> dict:
    params["n_procs"] = min(8, max(2, params["n_procs"] + rng.choice([-1, 1])))
    return params


def _mix_value_range(rng: random.Random, params: dict) -> dict:
    params["value_range"] = min(8, max(2, params["value_range"]
                                       + rng.choice([-1, 1])))
    return params


def _nemesis_interval(rng: random.Random, params: dict) -> dict:
    params["interval"] = min(20.0, max(0.5,
                                       params["interval"]
                                       * rng.choice([0.5, 2.0])))
    return params


def _nemesis_schedule(rng: random.Random, params: dict) -> dict:
    from ..nemesis.package import FAULTS, SCHEDULES

    specs = ("none",) + FAULTS + SCHEDULES + ("all",)
    cur = params["nemesis"]
    others = [s for s in specs if s != cur]
    params["nemesis"] = others[rng.randrange(len(others))]
    return params


# ------------------------------------------------------------------- registry

@dataclass(frozen=True)
class Operator:
    """A named, typed mutation.

    ``target`` is "history" (rewrites rows of a materialized history)
    or "params" (rewrites the scenario genome before generation).
    ``families`` scopes applicability; ``can_invalidate`` marks value
    edits that can flip a valid history to invalid — the compat
    `corrupt()` wrapper and the recall planter draw only from those.
    """

    name: str
    target: str
    families: Tuple[str, ...]
    can_invalidate: bool
    fn: Callable


_ALL = FAMILIES

_OPERATORS = (
    Operator("perturb-read", "history", ("register", "counter"), True,
             _perturb_read),
    Operator("perturb-write", "history", ("register",), True, _perturb_write),
    Operator("perturb-cas", "history", ("register",), True, _perturb_cas),
    Operator("perturb-set-read", "history", ("set",), True, _perturb_set_read),
    Operator("perturb-sum", "history", ("counter",), True, _perturb_sum),
    Operator("perturb-ticket", "history", ("queue",), True, _perturb_ticket),
    Operator("perturb-observed-list", "history", ("list-append",), True,
             _perturb_observed_list),
    Operator("drop-completion", "history", _ALL, False, _drop_completion),
    Operator("crash-op", "history", _ALL, False, _crash_op),
    Operator("reorder-completion", "history", _ALL, False,
             _reorder_completion),
    Operator("reorder-invoke", "history", _ALL, False, _reorder_invoke),
    Operator("session-shuffle", "history", ("list-append",), False,
             _session_shuffle),
    Operator("mix-crash-rate", "params", _ALL, False, _mix_crash_rate),
    Operator("mix-procs", "params", _ALL, False, _mix_procs),
    Operator("mix-value-range", "params",
             ("register", "counter", "set"), False, _mix_value_range),
    Operator("nemesis-interval", "params", _ALL, False, _nemesis_interval),
    Operator("nemesis-schedule", "params", _ALL, False, _nemesis_schedule),
)

REGISTRY = {op.name: op for op in _OPERATORS}


def operators_for(family: str, target: Optional[str] = None) -> List[Operator]:
    return [op for op in _OPERATORS
            if family in op.families
            and (target is None or op.target == target)]


def apply_history_op(op: Operator, rng: random.Random,
                     hist: History) -> Optional[History]:
    """Apply one history operator; None when inapplicable."""
    out = op.fn(rng, _rows(hist))
    return None if out is None else build_history(
        (r[0], r[1], r[2], r[3]) for r in out)


# ------------------------------------------------------------- compat surface

def family_of(hist: History) -> str:
    """Best-effort model family of a synth history (for the corrupt()
    compat wrapper, which historically dispatched on op shape)."""
    fs = {o.f for o in hist}
    if "append" in fs:
        return "list-append"
    if "enqueue" in fs or "dequeue" in fs:
        return "queue"
    if "cas" in fs or "write" in fs:
        return "register"
    if "add-and-get" in fs:
        return "counter"
    if "add" in fs:
        for o in hist:
            if o.type == OK and o.f == "read":
                return "set" if isinstance(o.value, list) else "counter"
        return "set"
    for o in hist:
        if o.type == OK and isinstance(o.value, list):
            return "set"
    return "register"


def corrupt_once(rng: random.Random, hist: History,
                 family: Optional[str] = None) -> History:
    """Single value-level corruption (the old `synth.corrupt` contract):
    perturb one completion so the oracle may or may not invalidate.
    Draws from the family's ``can_invalidate`` operators; returns the
    history unchanged when none applies (e.g. no completions at all)."""
    fam = family or family_of(hist)
    ops = [op for op in operators_for(fam, "history") if op.can_invalidate]
    order = list(range(len(ops)))
    rng.shuffle(order)
    for k in order:
        out = apply_history_op(ops[k], rng, hist)
        if out is not None:
            return out
    return hist

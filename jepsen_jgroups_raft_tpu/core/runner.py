"""The test interpreter.

Equivalent of jepsen.core/run! (reference L0; composed at raft.clj:54-92):

  1. set up the DB (SUT node lifecycle) on every node concurrently,
  2. spawn `concurrency` client worker threads (each bound round-robin to a
     node, each with its own client connection) plus one nemesis thread,
  3. drive them from the generator under a scheduler lock, recording every
     invocation/completion into the history with ns timestamps and dense
     indices,
  4. process-id bookkeeping: a worker whose op ends `info` (indefinite —
     the op may still execute server-side) retires its process id and
     continues as `process + concurrency` with a fresh client connection,
     exactly jepsen's crashed-process rule — this is what makes the
     history's forever-concurrent semantics true,
  5. tear down, run the composed checker over the history, persist to
     store/.

Wall-clock concurrency is host-side Python threading (the reference's
worker threads, SURVEY.md §2.4 row 1): these threads spend their lives
blocked on sockets, so the GIL is irrelevant; the compute-heavy part (the
checker) runs on TPU afterwards.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..client.errors import with_errors
from ..generator.base import NEMESIS_THREAD, PENDING, Generator, to_gen
from ..history.ops import INFO, INVOKE, NEMESIS, History, Op
from .store import prepare_dir, save_test

LOG = logging.getLogger("jgraft.core")

#: seconds between generator polls when PENDING.
POLL_INTERVAL = 0.002

#: default ops per live-stream segment (`live_stream` test key).
LIVE_STREAM_FLUSH_OPS = 64


class _LiveStreamFeeder:
    """Producer side of a streaming verdict session (ISSUE 12): a
    running test streams its client ops to graftd AS THEY COMPLETE, so
    the checker acts as a live monitor instead of a postmortem tool.

    `record()` is called under the history lock and must stay O(1): it
    buffers the op dict and hands full segments to a feeder thread,
    which appends them over HTTP with the client's idempotent
    per-segment retry. Every failure is absorbed (logged once, feeder
    disabled) — live streaming is an OBSERVER; it must never stall or
    kill the run it watches."""

    def __init__(self, cfg: dict):
        from ..service.client import ServiceClient, StreamSession

        self.flush_ops = int(cfg.get("flush_ops", LIVE_STREAM_FLUSH_OPS))
        client = ServiceClient(cfg["url"],
                               timeout=float(cfg.get("timeout_s", 30.0)))
        self.session = StreamSession(
            client, workload=cfg.get("workload", "register"),
            algorithm=cfg.get("algorithm", "auto"),
            binary=bool(cfg.get("binary", False)))
        self.session.open()
        self._buf: list = []
        self._q: list = []
        self._cond = threading.Condition()
        self._dead = False
        self._closing = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="live-stream")
        self._thread.start()

    def record(self, op) -> None:
        if self._dead or op.process == NEMESIS:
            return
        self._buf.append(op.to_dict())
        if len(self._buf) >= self.flush_ops:
            buf, self._buf = self._buf, []
            with self._cond:
                self._q.append(buf)
                self._cond.notify()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._closing:
                    self._cond.wait(0.2)
                if not self._q and self._closing:
                    return
                seg = self._q.pop(0)
            try:
                self.session.append(seg)
            except Exception:
                LOG.exception("live-stream append failed; streaming "
                              "disabled for this run")
                self._dead = True
                with self._cond:
                    self._q.clear()
                return

    def close(self) -> Optional[dict]:
        """Flush the tail, finish the session, return the final stream
        record (None when streaming died mid-run)."""
        if self._buf and not self._dead:
            with self._cond:
                self._q.append(self._buf)
        self._buf = []
        with self._cond:
            self._closing = True
            self._cond.notify()
        self._thread.join(60.0)
        if self._dead:
            return None
        try:
            return self.session.finish()
        except Exception:
            LOG.exception("live-stream finish failed")
            return None


def _open_client(proto, test: dict, node: str):
    """open + setup as ONE acquisition: when setup raises, the half-open
    connection is closed before the error propagates. Before this
    (graftcheck flow-resource-leak finding), every worker whose setup
    failed dropped an open socket on the floor — invisible per run,
    fd exhaustion across a long hell campaign."""
    client = proto.open(test, node)
    try:
        client.setup(test)
    except BaseException:
        try:
            client.close(test)
        except Exception:
            LOG.debug("close of half-open client failed", exc_info=True)
        raise
    return client


class Scheduler:
    """Serializes generator access across workers; owns test time."""

    def __init__(self, gen, test: dict):
        self.gen: Optional[Generator] = to_gen(gen)
        self.test = test
        self.lock = threading.Lock()
        self.t0 = time.monotonic_ns()
        self.busy = 0

    def now(self) -> int:
        return time.monotonic_ns() - self.t0

    def next_op(self, thread) -> Optional[dict]:
        """Block until an op is available for `thread`, or None when the
        generator is exhausted."""
        while True:
            with self.lock:
                if self.gen is None:
                    return None
                ctx = {"time": self.now(), "thread": thread, "busy": self.busy}
                r = self.gen.op(self.test, ctx)
                if r is None:
                    self.gen = None
                    return None
                op, g2 = r
                self.gen = g2
                if op != PENDING:
                    self.busy += 1
                    return op
            time.sleep(POLL_INTERVAL)

    def complete(self, event: Op) -> None:
        with self.lock:
            self.busy -= 1
            if self.gen is not None:
                ctx = {"time": self.now(), "thread": None, "busy": self.busy}
                self.gen = self.gen.update(self.test, ctx, event)


def run_test(test: dict) -> dict:
    """Run a test map; returns it with :history and :results filled in.

    Recognized keys (jepsen test-map equivalents, raft.clj:54-92):
      name, nodes, concurrency, client (Client), nemesis (Nemesis),
      generator, checker (Checker), db (DB), members (mutable set — the
      shared membership atom raft.clj:70), idempotent (op f's safe to fail
      on indefinite errors), store (bool).
    """

    test = dict(test)
    test.setdefault("name", "test")
    test.setdefault("nodes", [f"n{i}" for i in range(1, 6)])
    test.setdefault("concurrency", 5)
    test.setdefault("idempotent", set())
    if "members" not in test or test["members"] is None:
        test["members"] = set(test["nodes"])
    test.setdefault("start_time", time.time())

    history = History()
    hlock = threading.Lock()

    # Live streaming (ISSUE 12): `live_stream` is either a config dict
    # ({"url": "http://host:port" | "unix:/path.sock", "workload"?,
    # "flush_ops"?, "binary"?}) or a
    # ready feeder-like object with record/close. A feeder that fails
    # to OPEN degrades to no streaming — the run must not depend on the
    # monitor being up.
    feeder = None
    live_cfg = test.get("live_stream")
    if live_cfg is not None:
        try:
            feeder = (live_cfg if hasattr(live_cfg, "record")
                      else _LiveStreamFeeder(dict(live_cfg)))
        except Exception:
            LOG.exception("live-stream open failed; running without a "
                          "live monitor")
            feeder = None

    def record(op: Op) -> Op:
        with hlock:
            op.time = sched.now()
            history.append(op)  # assigns index
            if feeder is not None:
                feeder.record(op)
            return op

    db = test.get("db")
    if db is not None:
        LOG.info("setting up DB on %s", test["nodes"])
        with ThreadPoolExecutor(len(test["nodes"])) as ex:
            list(ex.map(lambda n: db.setup(test, n), test["nodes"]))

    sched = Scheduler(test.get("generator"), test)
    concurrency = int(test["concurrency"])

    def client_worker(i: int) -> None:
        process = i
        node = test["nodes"][i % len(test["nodes"])]
        proto = test.get("client")
        client = None
        if proto is not None:
            # A connect failure at startup must not kill the worker: the
            # loop below retries per-op and records :fail until it heals
            # (otherwise the generator never drains and run_test hangs).
            try:
                client = _open_client(proto, test, node)
            except Exception:
                LOG.exception("worker %d: initial open failed; will retry", i)
                client = None
        try:
            while True:
                opd = sched.next_op(i)
                if opd is None:
                    return
                inv = Op(process=process, type=INVOKE, f=opd["f"],
                         value=opd.get("value"))
                record(inv)
                if proto is not None and client is None:
                    # Previous reconnect failed; retry before invoking.
                    # (_open_client: a failed setup must leave client
                    # None AND closed, not a half-open object the next
                    # invoke would use.)
                    try:
                        client = _open_client(proto, test, node)
                    except Exception:
                        LOG.exception("worker %d: reconnect failed", i)
                        client = None
                if proto is None:
                    comp = inv.replace(type="ok")
                elif client is None:
                    comp = inv.replace(type="fail",
                                       error="connect: reconnect failed")
                else:
                    try:
                        comp = with_errors(
                            lambda t, o: client.invoke(t, o), test,
                            inv.replace(), test["idempotent"])
                    except Exception as e:
                        # Non-client exception (a bug in the client or
                        # workload): never kill the worker silently —
                        # record it as an indefinite crash, like jepsen.
                        LOG.exception("worker %d: invoke raised", i)
                        comp = inv.replace(type=INFO, error=repr(e))
                comp.process = process
                comp = comp.replace(index=-1)
                record(comp)
                sched.complete(comp)
                if comp.type == INFO:
                    # Crashed process: a fresh identity + connection
                    # (jepsen's thread->process remapping).
                    process += concurrency
                    if client is not None:
                        try:
                            client.close(test)
                        except Exception:
                            LOG.debug("worker %d: close after info op "
                                      "failed", i, exc_info=True)
                        try:
                            client = _open_client(proto, test, node)
                        except Exception:
                            LOG.exception(
                                "worker %d: reopen failed; will retry", i)
                            client = None
        finally:
            if client is not None:
                # teardown and close are SEPARATE obligations: a raising
                # teardown used to skip close entirely (graftcheck
                # flow-resource-leak finding), leaking the socket of
                # every worker whose workload teardown failed.
                try:
                    client.teardown(test)
                except Exception:
                    LOG.exception("client teardown failed (node %s)", node)
                finally:
                    try:
                        client.close(test)
                    except Exception:
                        LOG.debug("client close failed (node %s)", node,
                                  exc_info=True)

    def nemesis_worker() -> None:
        # Always run the nemesis loop: with no nemesis configured, a noop
        # one drains any nemesis-routed ops (otherwise the generator would
        # never exhaust and client workers would spin forever).
        from ..nemesis.base import NoopNemesis

        nem = test.get("nemesis") or NoopNemesis()
        try:
            nem = nem.setup(test) or nem
        except Exception:
            # A failed nemesis setup must not strand the run: keep draining
            # nemesis-routed ops with a noop (annotated) nemesis.
            LOG.exception("nemesis setup failed; continuing with noop")
            nem = NoopNemesis()
        try:
            while True:
                opd = sched.next_op(NEMESIS_THREAD)
                if opd is None:
                    return
                inv = Op(process=NEMESIS, type=INFO, f=opd["f"],
                         value=opd.get("value"))
                record(inv)
                try:
                    comp = nem.invoke(test, inv.replace())
                except Exception as e:
                    LOG.exception("nemesis op %s failed", opd["f"])
                    comp = inv.replace(error=repr(e))
                comp.process = NEMESIS
                comp.type = INFO
                comp = comp.replace(index=-1)
                record(comp)
                sched.complete(comp)
        finally:
            try:
                nem.teardown(test)
            except Exception:
                LOG.exception("nemesis teardown failed")

    threads = [
        threading.Thread(target=client_worker, args=(i,), daemon=True,
                         name=f"worker-{i}")
        for i in range(concurrency)
    ]
    threads.append(
        threading.Thread(target=nemesis_worker, daemon=True, name="nemesis"))
    LOG.info("running %s: %d workers + nemesis over %s",
             test["name"], concurrency, test["nodes"])
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    live_result = None
    if feeder is not None:
        try:
            live_result = feeder.close()
        except Exception:
            LOG.exception("live-stream close failed")

    # Prepare the run directory BEFORE log collection so DBs that download
    # node logs (ssh tier) can place them inside this run's store dir.
    if test.get("store", True) and "store_dir" not in test:
        test["store_dir"] = prepare_dir(test)

    if db is not None:
        logs = {}
        if hasattr(db, "log_files"):
            for n in test["nodes"]:
                try:
                    logs[n] = db.log_files(test, n)
                except Exception:
                    LOG.warning("log collection failed for %s", n,
                                exc_info=True)
        test["log_files"] = logs
        with ThreadPoolExecutor(len(test["nodes"])) as ex:
            list(ex.map(lambda n: db.teardown(test, n), test["nodes"]))

    test["history"] = history
    checker = test.get("checker")
    if checker is not None:
        from ..checker.perf import format_scan_stats, format_tier_stats
        from ..checker.schedule import stats_scope

        LOG.info("checking %d-op history", len(history))
        # Per-run scan-stats scope: this run's chunked-scan counters,
        # isolated from every other run this process executes. Stamped
        # AFTER the composed check completes — the perf sub-checker runs
        # before the workload checker inside the composition, so only
        # the runner sees the run's full counters.
        with stats_scope() as scan_scope:
            test["results"] = checker.check(test, history, {})
        scan = format_scan_stats(scan_scope)
        if scan is not None and isinstance(test["results"], dict):
            test["results"].setdefault("scan-stats", scan)
        # ISSUE 13: the run's per-tier decided counts ride beside the
        # scan counters (same scope, same authoritative-after-the-
        # composed-check stance).
        tiers = format_tier_stats(
            {k: {"rows": v[0], "wall_s": v[1]}
             for k, v in scan_scope.get("tiers", {}).items()})
        if tiers is not None and isinstance(test["results"], dict):
            test["results"].setdefault("decided-tiers", tiers)
    else:
        test["results"] = {"valid?": True, "note": "no checker"}
    if live_result is not None and isinstance(test["results"], dict):
        # the streamed verdict rides beside the local checker's (they
        # agree on valid? by the §14 identity; the stream record adds
        # mid-run detection metadata — decided-at-segment etc.)
        test["results"].setdefault("live-stream", live_result)

    if test.get("store", True):
        save_test(test, history, test["results"])
    LOG.info("run complete: valid? = %s", test["results"].get("valid?"))
    return test

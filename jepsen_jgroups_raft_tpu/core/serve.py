"""Results web server.

Equivalent of the reference's `lein run serve` (raft.clj:98-101 wiring
jepsen.cli's serve-cmd): browse the store/ directory of past runs — each
run's verdict, results.json, history, timeline HTML, and collected node
logs — over plain HTTP. No framework: stdlib http.server, read-only,
path-confined to the store root.
"""

from __future__ import annotations

import html
import json
from functools import partial
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path


def _run_dirs(root: Path):
    """store/<test-name>/<timestamp>/ dirs, newest first (the reference
    store layout, SURVEY.md §2.3 history & store)."""
    runs = []
    if not root.is_dir():
        return runs
    for test_dir in sorted(root.iterdir()):
        if not test_dir.is_dir():
            continue
        for run in sorted(test_dir.iterdir(), reverse=True):
            if run.is_dir() and not run.is_symlink():  # skip latest -> …
                runs.append(run)
    runs.sort(key=lambda p: p.name, reverse=True)
    return runs


def _verdict(run: Path):
    try:
        with open(run / "results.json") as f:
            return json.load(f).get("valid?")
    except (OSError, UnicodeDecodeError, json.JSONDecodeError):
        # Exactly the ways reading a verdict can fail: missing file, or
        # a results.json truncated/corrupted mid-write (including a cut
        # inside a multi-byte UTF-8 sequence, which raises
        # UnicodeDecodeError before the JSON parser even runs).
        # Anything else is a bug that must surface, not render as "?".
        return None


def _index_html(root: Path) -> str:
    rows = []
    for run in _run_dirs(root):
        rel = run.relative_to(root)
        v = _verdict(run)
        badge = {True: "&#9989; valid", False: "&#10060; INVALID"}.get(
            v, f"? {html.escape(str(v))}")  # e.g. "unknown" verdicts
        files = " | ".join(
            f'<a href="/{rel}/{f.name}">{html.escape(f.name)}</a>'
            for f in sorted(run.iterdir()) if f.is_file())
        rows.append(f"<tr><td><code>{html.escape(str(rel))}</code></td>"
                    f"<td>{badge}</td><td>{files}</td></tr>")
    body = ("<table border=1 cellpadding=6><tr><th>run</th><th>verdict</th>"
            "<th>files</th></tr>" + "".join(rows) + "</table>"
            if rows else "<p>no runs recorded yet</p>")
    return ("<!doctype html><title>test results</title>"
            "<h1>recorded runs</h1>" + body)


class _Handler(SimpleHTTPRequestHandler):
    def __init__(self, *a, store_root: Path, **kw):
        self.store_root = store_root
        super().__init__(*a, directory=str(store_root), **kw)

    def do_GET(self):
        if self.path in ("/", "/index.html"):
            page = _index_html(self.store_root).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(page)))
            self.end_headers()
            self.wfile.write(page)
            return
        super().do_GET()  # directory= confines paths to the store root

    def log_message(self, fmt, *args):
        pass  # quiet


def serve(store_root: str, host: str = "0.0.0.0", port: int = 8080) -> int:
    root = Path(store_root).resolve()
    httpd = ThreadingHTTPServer((host, port),
                                partial(_Handler, store_root=root))
    print(f"serving {root} on http://{host}:{port}/")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0

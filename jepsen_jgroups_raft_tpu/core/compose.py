"""Test composition: merge DB + workload + nemesis + checkers + the phased
generator into a runnable test map.

Equivalent of the reference's `raft-tests` (raft.clj:54-92):
  * workload by name from the registry (raft.clj:63, workload.clj:10-15),
  * nemesis package from the fault spec (raft.clj:62, nemesis.clj:48-58),
  * shared mutable membership set (raft.clj:70),
  * composed checker {perf, exceptions, stats, workload} (raft.clj:73-77),
  * the phased schedule (raft.clj:78-91):
      1. main phase: staggered client ops interleaved with the nemesis
         schedule (first nemesis op delayed `interval`), bounded by
         `time_limit`;
      2. heal log + 10 s quiesce;
      3. nemesis final generator (heal partitions / restart killed /
         re-grow membership);
      4. 10 s quiesce;
      5. workload final generator (slot exists; no stock workload defines
         one — same as the reference).
  * quorum_reads = not stale_reads (raft.clj:92).
"""

from __future__ import annotations

from typing import Optional

from ..checker.base import compose as compose_checkers
from ..checker.perf import PerfChecker
from ..checker.stats import StatsChecker, UnhandledExceptionsChecker
from ..generator.base import (
    Any,
    Clients,
    Delay,
    Log,
    NemesisGen,
    Phases,
    Seq,
    Sleep,
    Stagger,
    TimeLimit,
)
from ..nemesis.package import setup_nemesis
from ..workload import WORKLOADS

DEFAULTS = {
    # reference cli-opts (raft.clj:14-51)
    "rate": 10.0,              # ops/sec across the run's stagger
    "ops_per_key": 100,
    "workload": "single-register",
    "nemesis": None,
    "interval": 5.0,           # seconds between nemesis ops
    "operation_timeout": 10.0,
    "stale_reads": False,
    "time_limit": 60.0,
    "concurrency": 10,
    "quiesce": 10.0,
}


def compose_test(opts: dict, db=None, net=None,
                 seed: Optional[int] = None) -> dict:
    """Build a runnable test map from options (reference raft-tests)."""
    o = {**DEFAULTS, **opts}
    nodes = list(o.get("nodes") or [f"n{i}" for i in range(1, 6)])
    o["nodes"] = nodes
    workload_name = o["workload"]
    try:
        wl_ctor = WORKLOADS[workload_name]
    except KeyError:
        raise ValueError(
            f"unknown workload {workload_name!r}; valid: {sorted(WORKLOADS)}")
    wl = wl_ctor(o)

    db = db if db is not None else o.get("db")
    net = net if net is not None else o.get("net")
    if o.get("nemesis") is None and wl.get("suggested_nemesis"):
        # Paired fault schedule (ISSUE 10 satellite): a workload may name
        # the schedule that actually stresses it (set → membership churn
        # during the fill, queue → partition during the drain); an
        # explicit --nemesis (including "none") always wins.
        o["nemesis"] = wl["suggested_nemesis"]
    pkg = setup_nemesis(o, db, net, seed=seed)

    client_gen = Stagger(1.0 / float(o["rate"]), wl["generator"])
    # First nemesis op delayed by `interval` (raft.clj:81-84); spacing
    # between subsequent ops is owned by each package's Delay.
    main = Any(
        Clients(client_gen),
        NemesisGen(Seq([Sleep(float(o["interval"])), pkg.generator]))
        if pkg.generator is not None else None,
    )
    if o.get("time_limit"):
        main = TimeLimit(float(o["time_limit"]), main)

    quiesce = float(o["quiesce"])
    phases = [main, Log("healing cluster"), Sleep(quiesce)]
    if pkg.final_generator is not None:
        phases.append(NemesisGen(
            TimeLimit(60.0, pkg.final_generator)))
    phases.append(Sleep(quiesce))
    if wl.get("final_generator") is not None:
        phases.append(Clients(wl["final_generator"]))
    gen = Phases(*phases)

    checker = compose_checkers({
        "perf": PerfChecker(render=o.get("render_plots", True),
                            nemeses=pkg.perf),
        "exceptions": UnhandledExceptionsChecker(),
        "stats": StatsChecker(),
        "workload": wl["checker"],
    })

    return {
        "name": o.get("name", f"jgraft-{workload_name}"),
        "nodes": nodes,
        "concurrency": int(o["concurrency"]),
        "client": wl["client"],
        "nemesis": pkg.nemesis,
        "generator": gen,
        "checker": checker,
        "db": db,
        "members": set(nodes),        # the shared membership atom
        "idempotent": wl.get("idempotent", set()),
        "quorum_reads": not o.get("stale_reads", False),
        "store": o.get("store", True),
        "store_root": o.get("store_root", "store"),
        "opts": o,
    }

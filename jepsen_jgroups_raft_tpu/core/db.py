"""Node-lifecycle (DB) and network protocols.

Equivalent of the jepsen.db protocol family the reference's Server record
implements (reference src/jepsen/jgroups/server.clj:164-222): DB
setup/teardown, LogFiles, Primary (leader probe), Kill (crash/restart),
Pause (SIGSTOP/SIGCONT) — plus the network-manipulation boundary
(jepsen.net's role) used by the partition nemesis.

Implementations:
  * InMemoryDB / InMemoryNet — over sut/inmemory.InMemoryCluster fault
    hooks, for in-process tests (SURVEY.md §4 implication (b)).
  * the localhost/process tier (deploy/) drives real OS processes with
    signals; its DB speaks the same protocol.
"""

from __future__ import annotations

from typing import List


class DB:
    """Install/start the SUT on a node; reference db/DB."""

    def setup(self, test: dict, node: str) -> None:
        return None

    def teardown(self, test: dict, node: str) -> None:
        return None

    # LogFiles (reference server.clj:181-183)
    def log_files(self, test: dict, node: str) -> List[str]:
        return []

    # Primary (reference server.clj:188-196): every node's view of the
    # leader, deduped — may legitimately return 2+ during partitions.
    def primaries(self, test: dict) -> List[str]:
        return []

    # Kill (reference server.clj:198-218)
    def kill(self, test: dict, node: str) -> None:
        raise NotImplementedError

    def start(self, test: dict, node: str) -> None:
        raise NotImplementedError

    # Pause (reference server.clj:221-222)
    def pause(self, test: dict, node: str) -> None:
        raise NotImplementedError

    def resume(self, test: dict, node: str) -> None:
        raise NotImplementedError


class Net:
    """Network manipulation boundary (jepsen.net equivalent). A grudge is
    a map node -> set of nodes it cannot exchange packets with."""

    def partition(self, test: dict, grudge: dict) -> None:
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        raise NotImplementedError


class InMemoryDB(DB):
    """DB protocol over the in-process cluster's fault hooks."""

    def __init__(self, cluster):
        self.cluster = cluster

    def primaries(self, test):
        # Ask every node's local view, dedupe non-null — mirroring the
        # reference's probe-every-member strategy (server.clj:188-196).
        views = []
        for n in list(self.cluster.nodes):
            view = self.cluster.stale_views.get(n)
            leader = view[0] if view is not None else self.cluster.leader
            if leader is not None and leader not in views:
                views.append(leader)
        return views

    def kill(self, test, node):
        self.cluster.kill(node)

    def start(self, test, node):
        self.cluster.restart(node)

    def pause(self, test, node):
        self.cluster.pause(node)

    def resume(self, test, node):
        self.cluster.resume(node)

    # membership hooks (consensus add/remove in the native tier; direct
    # mutation here)
    def add_member(self, test, node):
        self.cluster.add_node(node)

    def remove_member(self, test, node):
        self.cluster.remove_node(node)


class InMemoryNet(Net):
    def __init__(self, cluster):
        self.cluster = cluster

    def partition(self, test, grudge):
        self.cluster.partition(grudge)

    def heal(self, test):
        self.cluster.heal()

"""Result persistence.

Equivalent of jepsen's store/ layer (SURVEY.md §2.3 "History & store"):
every run writes an immutable directory
``store/<test-name>/<timestamp>/`` containing the full history
(history.jsonl), the checker results (results.json), and the serializable
test parameters (test.json); ``store/<name>/latest`` symlinks the newest
run. The results web server (cli.py `serve`) browses this tree — the
reference's `lein run serve` (raft.clj:98-101).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Union

from ..history.ops import History, Op

DEFAULT_ROOT = "store"


def store_root(test: dict) -> Path:
    return Path(test.get("store_root", DEFAULT_ROOT))


def _jsonable(x):
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, set):
        return sorted(_jsonable(v) for v in x)
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    return repr(x)


def prepare_dir(test: dict) -> str:
    """Create the run directory up front (before checkers run) so
    artifact-producing checkers (timeline HTML, perf SVG) have somewhere
    to write."""
    ts = time.strftime("%Y%m%dT%H%M%S", time.localtime(test.get("start_time",
                                                                time.time())))
    d = store_root(test) / str(test.get("name", "test")) / ts
    n = 0
    while d.exists():  # same-second reruns
        n += 1
        d = d.with_name(f"{ts}-{n}")
    d.mkdir(parents=True)
    return str(d)


def save_test(test: dict, history: History, results: dict) -> str:
    d = Path(test.get("store_dir") or prepare_dir(test))

    with open(d / "history.jsonl", "w") as f:
        for op in history:
            f.write(json.dumps(_jsonable(op.to_dict())) + "\n")
    with open(d / "results.json", "w") as f:
        json.dump(_jsonable(results), f, indent=2)
    skip = {"history", "results", "client", "nemesis", "generator", "checker",
            "db", "store_dir"}
    with open(d / "test.json", "w") as f:
        json.dump({k: _jsonable(v) for k, v in test.items() if k not in skip},
                  f, indent=2)

    latest = d.parent / "latest"
    try:
        if latest.is_symlink() or latest.exists():
            latest.unlink()
        latest.symlink_to(d.name)
    except OSError:
        pass  # symlinks unavailable (exotic fs) — nonfatal
    return str(d)


def load_history(run_dir: Union[str, Path]) -> History:
    h = History()
    with open(Path(run_dir) / "history.jsonl") as f:
        for line in f:
            d = json.loads(line)
            if isinstance(d.get("value"), list):
                d["value"] = tuple(d["value"])
            h.append(Op.from_dict(d))
    return h

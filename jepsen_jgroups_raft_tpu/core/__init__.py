"""Test orchestration.

Equivalent surface: jepsen.core/run! and the interpreter that drives
worker threads + the nemesis thread from a generator, records the history,
runs the composed checker, and persists results (SURVEY.md §3.1).
"""

from .compose import compose_test  # noqa: F401
from .db import DB, InMemoryDB, InMemoryNet, Net  # noqa: F401
from .runner import run_test, Scheduler  # noqa: F401
from .store import save_test, store_root  # noqa: F401

"""graftlint core: findings, pragmas, file collection.

The suite is project-native on purpose (SURVEY.md §5.2 direction): generic
linters cannot know that recording ``fail`` for an indefinite error makes
the checker unsound (client/errors.py docstring), that an ``np.asarray``
inside a jitted body silently serializes a device→host round trip, or
that ``pending_`` belongs to ``mu_``. Each analyzer encodes one such
repo-level invariant and reports uniform :class:`Finding` rows.

Suppression: a line carrying ``lint: allow(<rule>)`` in a trailing comment
(``#`` in Python, ``//`` in C++) is exempt from that rule — the pragma is
the written record that a hop/handler is intentional. Analyzers decide
per-rule whether pragmas are honored (the jit-body rules are strict).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Sequence, Set

_PRAGMA_RE = re.compile(r"lint:\s*allow\(([\w\-,\s]+)\)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    """A file plus its per-line pragma index."""

    path: str
    text: str
    allows: Dict[int, Set[str]] = field(default_factory=dict)

    @classmethod
    def load(cls, path) -> "SourceFile":
        text = Path(path).read_text(encoding="utf-8", errors="replace")
        return cls.from_text(str(path), text)

    @classmethod
    def from_text(cls, path: str, text: str) -> "SourceFile":
        allows: Dict[int, Set[str]] = {}
        for i, line in enumerate(text.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                allows[i] = {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
        return cls(path, text, allows)

    def allowed(self, line: int, rule: str) -> bool:
        rules = self.allows.get(line)
        return bool(rules) and (rule in rules or "*" in rules)


def filter_allowed(src: SourceFile,
                   findings: Iterable[Finding]) -> List[Finding]:
    """Drop findings whose line carries a matching allow pragma."""
    return [f for f in findings if not src.allowed(f.line, f.rule)]


def collect_files(paths: Sequence[str], suffixes: Sequence[str]) -> List[Path]:
    """Expand files/directories into a sorted list of matching files."""
    out: Set[Path] = set()
    for p in paths:
        path = Path(p)
        if path.is_dir():
            for suf in suffixes:
                out.update(path.rglob(f"*{suf}"))
        elif path.suffix in suffixes:
            out.add(path)
    return sorted(out)


def rel(path, root) -> str:
    """Repo-relative display path (falls back to the input)."""
    try:
        return str(Path(path).resolve().relative_to(Path(root).resolve()))
    except ValueError:
        return str(path)

"""Taxonomy-soundness analyzer.

Enforces the load-bearing invariant of client/errors.py: a ``fail``
outcome asserts the op **certainly did not execute** — the checker drops
it. An exception path that records ``fail`` without routing through
``classify_error`` (or while catching an indefinite type on a
non-idempotent op) can therefore hide a write that later takes effect:
the checker would pass an unlinearizable history, i.e. become unsound.
The reverse mistake (recording ``info`` too often) only slows the search
(reference doc/intro.md:35-41), so ``info`` paths are never flagged.

Rules
-----
``taxonomy-bare-except-fail``
    An ``except Exception``/``except BaseException``/bare ``except``
    handler records a FAIL outcome without calling ``classify_error`` /
    ``with_errors``. A broad catch sees indefinite errors too.
``taxonomy-indefinite-fail``
    A handler catching a known-indefinite type (``ClientTimeout``,
    ``SocketBroken``, ``TimeoutError``, ``socket.timeout``,
    ``ConnectionResetError``) records FAIL with no visible idempotence
    guard (no name containing ``idempotent`` in the handler).
``taxonomy-silent-swallow``
    A broad handler whose body neither re-raises, classifies, logs, nor
    records any outcome — an invisible drop. In the client tier a
    swallowed indefinite error usually surfaces later as a mystery
    timeout; narrow the catch to the concrete types the ``try`` body can
    raise, or log it.

Scan set (when run via the CLI): ``client/``, ``workload/``,
``core/runner.py``, ``native/client.py``, ``deploy/``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .base import Finding, SourceFile, filter_allowed

#: Exception names treated as "catches everything".
BROAD = {"Exception", "BaseException"}

#: Exception names whose meaning is "the op may have executed" — the
#: taxonomy's own types plus every stdlib parent classify_error maps to
#: an indefinite kind (OSError/ConnectionError: `socket`; the timeout
#: family: `timeout`). Catching any of these and recording FAIL is the
#: indefinite-as-definite unsoundness, regardless of spelling.
INDEFINITE = {"ClientTimeout", "SocketBroken", "TimeoutError",
              "ConnectionResetError", "BrokenPipeError", "OSError",
              "ConnectionError", "timeout"}  # timeout = socket.timeout

#: Calls that prove the handler routes through the taxonomy.
CLASSIFIERS = {"classify_error", "with_errors"}

#: Logging attribute names that make a swallow visible.
LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical",
               "log"}

#: Default CLI scan set, relative to the package root. The service
#: tier (graftd, ISSUE-5) and both stdlib HTTP servers are covered: a
#: long-lived daemon is where a silently-swallowed broad except turns
#: into an unexplained wedge instead of a crashed run. The distributed
#: tier (ISSUE-7) rides along: its degrade paths (malformed cluster
#: env, failed init, unsupported collectives) are broad-except-shaped
#: by design and must stay VISIBLE — a silent swallow there is exactly
#: the r01–r05 silent-CPU pattern at cluster scale.
#: scripts/chaos_graftd.py rides along (ISSUE 8): a chaos harness that
#: silently swallows an exception reports invariants it never checked —
#: its handlers must be narrow or visible like the daemon's own.
#: The scenario tier (ISSUE 10) widens the net: generator/ rides along
#: (the set/queue workloads put stateful op generation there — a
#: swallowed error in a generator silently starves a phase), and the
#: scenario checkers (derived analyses + the consistency rung family)
#: are scanned like the service tier — a broad except around a verdict
#: path is exactly where an indefinite error could turn into a wrong
#: "valid".
#: search/ (ISSUE 20) is scanned like the service tier: a swallowed
#: error in evaluation or archive would silently count a candidate as
#: boring (fitness 0) or drop a violation — recall numbers that lie.
SCAN_PREFIXES = ("client/", "workload/", "deploy/", "service/",
                 "generator/", "search/")
SCAN_FILES = ("core/runner.py", "native/client.py", "core/serve.py",
              "parallel/distributed.py", "parallel/launch.py",
              "scripts/chaos_graftd.py", "checker/set_queue.py",
              "checker/consistency.py", "checker/counterexample.py")


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp.startswith(SCAN_PREFIXES) or rp in SCAN_FILES


def _names_of(type_expr: Optional[ast.expr]) -> List[str]:
    """Exception names in an except clause (handles tuples, dotted)."""
    if type_expr is None:
        return [""]  # bare except
    items = (type_expr.elts if isinstance(type_expr, ast.Tuple)
             else [type_expr])
    out = []
    for it in items:
        if isinstance(it, ast.Name):
            out.append(it.id)
        elif isinstance(it, ast.Attribute):
            out.append(it.attr)
    return out


def _is_fail_const(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and node.value == "fail":
        return True
    if isinstance(node, ast.Name) and node.id == "FAIL":
        return True
    if isinstance(node, ast.Attribute) and node.attr == "FAIL":
        return True
    return False


def _records_fail(handler: ast.ExceptHandler) -> Optional[int]:
    """Line of the first FAIL-outcome record in the handler body, if any."""
    for node in ast.walk(handler):
        # op.replace(type=FAIL) / Op(..., type="fail")
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "type" and _is_fail_const(kw.value):
                    return node.lineno
        # comp.type = FAIL / "fail"
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute) and tgt.attr == "type"
                        and _is_fail_const(node.value)):
                    return node.lineno
    return None


def _calls_classifier(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if name in CLASSIFIERS:
                return True
    return False


def _mentions_idempotent(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = node.id if isinstance(node, ast.Name) else node.attr
            if "idempotent" in name.lower():
                return True
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and "idempotent" in node.value.lower():
            return True
    return False


def _is_visible(handler: ast.ExceptHandler) -> bool:
    """Does the handler do ANYTHING observable with the error?"""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in LOG_METHODS:
                return True
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if name in CLASSIFIERS or name in ("print", "repr", "str"):
                return True
        # records any outcome at all (fail/info/ok)
        if isinstance(node, ast.keyword) and node.arg in ("type", "error"):
            return True
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr in ("type", "error"):
                    return True
    return False


def analyze_source(src: SourceFile) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        names = _names_of(node.type)
        broad = any(n in BROAD or n == "" for n in names)
        indefinite = [n for n in names if n in INDEFINITE]
        fail_line = _records_fail(node)
        if fail_line is not None and not _calls_classifier(node):
            if broad:
                findings.append(Finding(
                    src.path, fail_line, "taxonomy-bare-except-fail",
                    "broad except handler records a FAIL outcome without "
                    "classify_error — an indefinite error recorded as "
                    "definite makes the linearizability checker unsound "
                    "(client/errors.py)"))
            if indefinite and not _mentions_idempotent(node):
                findings.append(Finding(
                    src.path, fail_line, "taxonomy-indefinite-fail",
                    f"catching indefinite {'/'.join(indefinite)} but "
                    "recording FAIL with no idempotence guard — the op "
                    "may have executed; record INFO (or gate on the "
                    "workload's idempotent set)"))
        if broad and fail_line is None and not _is_visible(node):
            findings.append(Finding(
                src.path, node.lineno, "taxonomy-silent-swallow",
                f"broad `except {'/'.join(n or 'BaseException' for n in names)}`"
                " swallows the error invisibly — narrow it to the concrete "
                "types the try body raises, or log it"))
    return filter_allowed(src, findings)


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))

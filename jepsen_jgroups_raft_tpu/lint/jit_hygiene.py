"""JIT / trace hygiene analyzer for the device hot path.

Two failure modes this repo has paid for (BASELINE.md rounds 2-4):

* A host sync (``np.asarray``, ``.item()``, ``int()`` on a traced value,
  ``block_until_ready``) inside a jitted body silently serializes a
  device→host round trip per launch — behind a network-tunneled TPU that
  is the dominant cost (VERDICT r3 #3).
* Recompile hazards (unhashable static args, mutable defaults, Python
  branching on tracers) turn the jit cache into a per-call recompile
  storm, or fail at trace time deep inside a batch run.

Rules
-----
``jit-host-sync``
    Host-forcing call inside a traced body: any ``np.*`` call whose
    argument derives from a traced value, ``.item()``,
    ``.block_until_ready()``, or ``int()/float()/bool()`` on a traced
    value. (``jnp.*`` is device-side and fine; ``x.shape``/``x.dtype``
    are static and break the taint.)
``jit-python-branch``
    ``if``/``while``/``assert`` whose test involves a traced value — a
    trace-time ConcretizationError at best, silently baked-in control
    flow at worst. Use ``lax.cond``/``jnp.where``.
``jit-recompile-hazard``
    Mutable default argument (list/dict/set) on a traced function — the
    default is part of the trace cache key, so it is either unhashable
    (TypeError at call time) or a shared-mutation recompile hazard.
``host-sync``
    Outside traced bodies, in a *launch function* (one that builds a
    kernel via ``jax.jit`` / a ``make_*``/``_build_*``/``*_kernel``
    factory and then calls it): ``np.asarray``/``np.array`` on a
    non-parameter value, ``.item()``, or ``block_until_ready``. These
    block the async dispatch pipeline, so every one must be an
    *intentional, annotated* hop: suppress with ``# lint:
    allow(host-sync)`` on the line (the pattern in
    checker/linearizable.py).

Traced bodies are found structurally: ``@jax.jit`` decorators, and local
function names flowing (through local assignments) into ``jax.jit``,
``jax.vmap``, ``shard_map``, ``pl.pallas_call``, or a ``lax`` control-flow
combinator (``scan``/``cond``/``while_loop``/``fori_loop``/``map``/
``switch``). The pragma is honored for ``host-sync`` only; the in-trace
rules are strict (an intentional sync inside a jitted body is a
contradiction).

Scan set (CLI): ``ops/``, ``checker/``, ``parallel/``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, SourceFile

#: (callee-name, positional indexes holding traced callables).
TRACE_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,),
    "vmap": (0,),
    "pmap": (0,),
    "shard_map": (0,),
    "pallas_call": (0,),
    "scan": (0,),
    "map": (0,),          # lax.map only (attribute call, see below)
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": (),         # branch list is rarely resolvable statically
    "checkify": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
}

#: bare-name calls allowed to seed traces (plain `map` is a builtin).
BARE_WRAPPERS = {"jit", "shard_map", "pallas_call"}

SYNC_METHODS = {"item", "block_until_ready"}
HOST_CASTS = {"int", "float", "bool", "complex"}
TAINT_BREAKERS = {"shape", "dtype", "ndim", "size", "sharding"}

SCAN_PREFIXES = ("ops/", "checker/", "parallel/")


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp.startswith(SCAN_PREFIXES)


def _callee_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _is_np_call(call: ast.Call) -> Optional[str]:
    """'asarray' etc. when the call is np.<fn>(...); None otherwise."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id in ("np", "numpy"):
        return fn.attr
    return None


class _Scope:
    """One function (or module) body: local defs + assignment graph."""

    def __init__(self, node):
        self.node = node
        self.defs: Dict[str, ast.FunctionDef] = {}
        self.assigns: Dict[str, ast.expr] = {}
        body = node.body if hasattr(node, "body") else []
        for stmt in body:
            self._index(stmt)

    def _index(self, stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.defs[stmt.name] = stmt
            return  # nested defs get their own scope
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            self.assigns[stmt.targets[0].id] = stmt.value
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._index(child)

    def resolve_def(self, name: str, depth: int = 0) -> \
            Optional[ast.FunctionDef]:
        """Follow `x = jax.vmap(y)`-style chains to a local def."""
        if depth > 8:
            return None
        if name in self.defs:
            return self.defs[name]
        expr = self.assigns.get(name)
        if isinstance(expr, ast.Name):
            return self.resolve_def(expr.id, depth + 1)
        if isinstance(expr, ast.Call):
            cname = _callee_name(expr)
            idxs = TRACE_WRAPPERS.get(cname)
            if idxs:
                for i in idxs:
                    if i < len(expr.args) and \
                            isinstance(expr.args[i], ast.Name):
                        d = self.resolve_def(expr.args[i].id, depth + 1)
                        if d is not None:
                            return d
        return None


def _decorated_jit(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = ""
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute):
            name = target.attr
        if name == "jit":
            return True
        if name == "partial" and isinstance(dec, ast.Call) and dec.args:
            inner = dec.args[0]
            iname = inner.attr if isinstance(inner, ast.Attribute) else (
                inner.id if isinstance(inner, ast.Name) else "")
            if iname == "jit":
                return True
    return False


def _collect_traced(tree: ast.Module) -> Set[ast.FunctionDef]:
    """Every function def that is traced by jax (see module docstring)."""
    traced: Set[ast.FunctionDef] = set()
    # index scopes: module + every function
    scopes = [_Scope(tree)]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(_Scope(node))
            if _decorated_jit(node):
                traced.add(node)
    for scope in scopes:
        for node in ast.walk(scope.node):
            if not isinstance(node, ast.Call):
                continue
            cname = _callee_name(node)
            idxs = TRACE_WRAPPERS.get(cname)
            if idxs is None:
                continue
            if isinstance(node.func, ast.Name) and \
                    cname not in BARE_WRAPPERS:
                continue  # bare `map(...)`/`scan(...)` is not jax's
            for i in idxs:
                if i < len(node.args) and isinstance(node.args[i], ast.Name):
                    d = scope.resolve_def(node.args[i].id)
                    if d is not None:
                        traced.add(d)
    return traced


# --------------------------------------------------------------- taint walk


class _TraceChecker(ast.NodeVisitor):
    """Flag host syncs / tracer branching inside one traced body."""

    def __init__(self, src: SourceFile, fn: ast.FunctionDef):
        self.src = src
        self.fn = fn
        self.findings: List[Finding] = []
        args = fn.args
        self.tainted: Set[str] = {
            a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)}
        if args.vararg:
            self.tainted.add(args.vararg.arg)

    # -- taint -------------------------------------------------------------

    def _expr_tainted(self, node: Optional[ast.expr]) -> bool:
        """Any tainted name used outside a .shape/.dtype/... chain?"""
        if node is None:
            return False
        tainted = self.tainted

        class V(ast.NodeVisitor):
            hot = False

            def visit_Attribute(self, a):  # noqa: N802
                if a.attr in TAINT_BREAKERS:
                    return  # static metadata: do not descend
                self.generic_visit(a)

            def visit_Name(self, n):  # noqa: N802
                if n.id in tainted:
                    self.hot = True

        v = V()
        v.visit(node)
        return v.hot

    def _taint_assign(self, node: ast.Assign):
        if self._expr_tainted(node.value):
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        self.tainted.add(sub.id)

    # -- visitors ----------------------------------------------------------

    def visit_FunctionDef(self, node):  # noqa: N802
        if node is self.fn:
            self.generic_visit(node)
        # nested defs are visited via their own _TraceChecker if traced

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):  # noqa: N802
        self._taint_assign(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):  # noqa: N802
        if self._expr_tainted(node.value) and \
                isinstance(node.target, ast.Name):
            self.tainted.add(node.target.id)
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        np_fn = _is_np_call(node)
        if np_fn is not None and any(self._expr_tainted(a)
                                     for a in node.args):
            self.findings.append(Finding(
                self.src.path, node.lineno, "jit-host-sync",
                f"np.{np_fn}() on a traced value inside a jitted body — "
                "forces a device→host sync per launch; use jnp or move "
                "the conversion outside the trace"))
        cname = _callee_name(node)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in SYNC_METHODS:
            self.findings.append(Finding(
                self.src.path, node.lineno, "jit-host-sync",
                f".{node.func.attr}() inside a jitted body — host sync"))
        if isinstance(node.func, ast.Name) and cname in HOST_CASTS and \
                any(self._expr_tainted(a) for a in node.args):
            self.findings.append(Finding(
                self.src.path, node.lineno, "jit-host-sync",
                f"{cname}() on a traced value inside a jitted body — "
                "concretizes the tracer (host sync / trace error)"))
        self.generic_visit(node)

    def _branch(self, node, kind: str):
        if self._expr_tainted(node.test):
            self.findings.append(Finding(
                self.src.path, node.lineno, "jit-python-branch",
                f"Python `{kind}` on a traced value inside a jitted body "
                "— use lax.cond/jnp.where (trace-time concretization)"))

    def visit_If(self, node):  # noqa: N802
        self._branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):  # noqa: N802
        self._branch(node, "while")
        self.generic_visit(node)

    def visit_Assert(self, node):  # noqa: N802
        self._branch(node, "assert")
        self.generic_visit(node)


def _check_defaults(src: SourceFile, fn: ast.FunctionDef) -> List[Finding]:
    out = []
    defaults = list(fn.args.defaults) + [
        d for d in fn.args.kw_defaults if d is not None]
    for d in defaults:
        if isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            out.append(Finding(
                src.path, d.lineno, "jit-recompile-hazard",
                f"mutable default argument on traced `{fn.name}` — "
                "unhashable as a static arg and a recompile/aliasing "
                "hazard; use None or a tuple"))
    return out


# ------------------------------------------------------------ launch sites

_FACTORY_HINTS = ("kernel", "checker")


def _is_factory_call(expr: ast.expr) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    name = _callee_name(expr)
    if name == "jit":
        return True
    return (name.startswith(("make_", "_build_")) or
            name.endswith(_FACTORY_HINTS)) and any(
        h in name for h in _FACTORY_HINTS + ("call",))


def _launch_findings(src: SourceFile, fn: ast.FunctionDef,
                     traced: Set[ast.FunctionDef]) -> List[Finding]:
    """host-sync rule for non-traced launch functions (pragma-suppressible).

    Nested defs are separate scopes — only this function's own statements
    count (a deferred finalizer closure syncs by design).
    """
    own_nodes: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested function: its own scope
        own_nodes.append(node)
        stack.extend(ast.iter_child_nodes(node))

    kernels: Set[str] = set()
    for node in own_nodes:
        if isinstance(node, ast.Assign) and _is_factory_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    kernels.add(tgt.id)
    launches = any(
        isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
        and node.func.id in kernels for node in own_nodes)
    if not launches:
        return []

    params = {a.arg for a in fn.args.posonlyargs + fn.args.args +
              fn.args.kwonlyargs}
    out: List[Finding] = []
    for node in own_nodes:
        if not isinstance(node, ast.Call):
            continue
        np_fn = _is_np_call(node)
        if np_fn in ("asarray", "array") and node.args and not (
                isinstance(node.args[0], ast.Name) and
                node.args[0].id in params):
            out.append(Finding(
                src.path, node.lineno, "host-sync",
                f"np.{np_fn}() in kernel-launch function "
                f"`{fn.name}` blocks async dispatch (device→host); "
                "if intentional, annotate with "
                "`# lint: allow(host-sync)`"))
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in SYNC_METHODS:
            out.append(Finding(
                src.path, node.lineno, "host-sync",
                f".{node.func.attr}() in kernel-launch function "
                f"`{fn.name}` — annotate if intentional"))
    return out


def analyze_source(src: SourceFile) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    traced = _collect_traced(tree)
    findings: List[Finding] = []
    for fn in traced:
        checker = _TraceChecker(src, fn)
        checker.visit(fn)
        findings.extend(checker.findings)       # strict: no pragma
        findings.extend(_check_defaults(src, fn))
    host: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node not in traced:
            host.extend(_launch_findings(src, node, traced))
    findings.extend(f for f in host if not src.allowed(f.line, f.rule))
    return findings


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))

"""graftlint: project-native static analysis (see ISSUE/doc).

Six analyzers, one per repo-level invariant no generic linter knows —
three pattern-level (PR 1), three CFG/dataflow (the graftcheck tier,
:mod:`.flow`):

* :mod:`.taxonomy` — exception paths that record op outcomes must
  respect the definite/indefinite taxonomy (client/errors.py), or the
  linearizability checker is unsound.
* :mod:`.jit_hygiene` — no host syncs / Python tracer branching /
  recompile hazards inside jitted or Pallas-traced bodies; intentional
  device→host hops in launch functions carry ``# lint: allow(host-sync)``.
* :mod:`.lock_discipline` — ``// GUARDED_BY(mu)`` fields in
  ``native/src`` are only touched under their mutex (or in
  ``// REQUIRES(mu)`` helpers).
* :mod:`.flow.kernel_contract` — Pallas BlockSpec/grid/out_shape
  arithmetic verified statically under sampled contract bindings,
  with Mosaic tiling rules and a VMEM budget.
* :mod:`.flow.heal` — every nemesis fault-injection path heals,
  registers for teardown, or carries ``# lint: allow(unhealed)``.
* :mod:`.flow.resource` — acquire/release balance across exception
  paths in the deploy/runner tiers.

CLI: ``python -m jepsen_jgroups_raft_tpu.lint [paths]`` — with
``--format json`` (SARIF 2.1.0) and a regression baseline
(``--baseline`` / ``--update-baseline``, doc/running.md).
``scripts/lint.sh`` is the one-command gate (ruff → graftlint →
graftcheck → ``make -C native tidy``).
"""

from .base import Finding, SourceFile  # noqa: F401
from .cli import main, run  # noqa: F401

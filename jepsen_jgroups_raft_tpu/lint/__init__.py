"""graftlint: project-native static analysis (see ISSUE/doc).

Three analyzers, one per repo-level invariant no generic linter knows:

* :mod:`.taxonomy` — exception paths that record op outcomes must
  respect the definite/indefinite taxonomy (client/errors.py), or the
  linearizability checker is unsound.
* :mod:`.jit_hygiene` — no host syncs / Python tracer branching /
  recompile hazards inside jitted or Pallas-traced bodies; intentional
  device→host hops in launch functions carry ``# lint: allow(host-sync)``.
* :mod:`.lock_discipline` — ``// GUARDED_BY(mu)`` fields in
  ``native/src`` are only touched under their mutex (or in
  ``// REQUIRES(mu)`` helpers).

CLI: ``python -m jepsen_jgroups_raft_tpu.lint [paths]`` —
``scripts/lint.sh`` is the one-command gate (ruff → graftlint →
``make -C native tidy``).
"""

from .base import Finding, SourceFile  # noqa: F401
from .cli import main, run  # noqa: F401

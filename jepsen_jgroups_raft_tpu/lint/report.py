"""Finding output formats and the regression baseline.

SARIF-style JSON (``--format json``) makes graftlint findings machine-
readable for CI annotation (the 2.1.0 result/location shape GitHub code
scanning ingests). The baseline (``lint/baseline.json``) holds
fingerprints of accepted pre-existing findings so the gate fails only on
*regression*: new findings exit non-zero, baselined ones are reported as
suppressed.

Fingerprints are content-based, not line-based: sha1 over (path, rule,
stripped source line text) plus an occurrence counter for duplicates —
so findings survive unrelated edits that shift line numbers, and a
baseline never silently grows to cover a *new* instance of an old rule
on the same line text twice.
"""

from __future__ import annotations

import hashlib
import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .base import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _line_text(root: Path, finding: Finding,
               cache: Dict[str, List[str]]) -> str:
    lines = cache.get(finding.path)
    if lines is None:
        try:
            lines = (root / finding.path).read_text(
                encoding="utf-8", errors="replace").splitlines()
        except OSError:
            lines = []
        cache[finding.path] = lines
    if 1 <= finding.line <= len(lines):
        return lines[finding.line - 1].strip()
    return f"<line {finding.line}>"


def fingerprints(findings: Sequence[Finding],
                 root) -> List[Tuple[Finding, str]]:
    """[(finding, stable fingerprint)] in input order."""
    root = Path(root)
    cache: Dict[str, List[str]] = {}
    seen: Counter = Counter()
    out = []
    for f in findings:
        key = f"{f.path}|{f.rule}|{_line_text(root, f, cache)}"
        seq = seen[key]
        seen[key] += 1
        digest = hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]
        out.append((f, f"{digest}:{seq}"))
    return out


def load_baseline(path) -> set:
    p = Path(path)
    if not p.exists():
        return set()
    data = json.loads(p.read_text(encoding="utf-8"))
    return set(data.get("findings", []))


def save_baseline(path, fps: Sequence[str]) -> None:
    Path(path).write_text(json.dumps(
        {"version": 1,
         "comment": "accepted pre-existing graftlint findings; "
                    "regenerate with --update-baseline",
         "findings": sorted(fps)}, indent=2) + "\n", encoding="utf-8")


def to_sarif(findings: Sequence[Finding], baselined: Sequence[bool],
             rule_ids: Sequence[str],
             rule_help: Dict[str, str] = None) -> dict:
    """One-run SARIF log; `baselined[i]` marks finding i suppressed.
    `rule_help` maps rule ids to helpUri anchors (checker-design.md
    sections) so code-scanning UIs link each finding to the invariant
    it enforces."""
    results = []
    for f, sup in zip(findings, baselined):
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "suppressions": (
                [{"kind": "external",
                  "justification": "baselined in lint/baseline.json"}]
                if sup else []),
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "doc/checker-design.md#6-soundness-invariants",
                "rules": [
                    {"id": r, **({"helpUri": rule_help[r]}
                                 if rule_help and r in rule_help else {})}
                    for r in sorted(set(rule_ids))],
            }},
            "results": results,
        }],
    }

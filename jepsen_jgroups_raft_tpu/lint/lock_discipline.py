"""Native lock-discipline analyzer (header-annotation checker).

The C++ tier documents its concurrency contract in comments
(``// GUARDED_BY(mu_)`` on a field, ``// REQUIRES(mu_)`` on a helper
that is only called with the lock held — the ``*_locked`` convention
from raft.h made machine-checkable). This analyzer parses those
annotations out of ``native/src/*.h``/``*.cc`` and verifies, at
function granularity, that every use of a guarded field happens in a
function that either

* acquires the named mutex (``std::lock_guard<std::mutex> g(mu_);`` /
  ``std::unique_lock<std::mutex> g(mu_);`` anywhere in its body — block
  scoping inside the function is trusted, this is a lightweight checker
  in the clang-tidy lineage, not a flow analysis), or
* carries a ``// REQUIRES(mu_)`` annotation on/above its signature.

Constructors, destructors, and the declaration line itself are exempt
(members are initialized before any thread can see the object).
TSAN (tests/test_tsan.py) catches what this misses at runtime; this
catches what TSAN needs a lucky interleaving to see, at compile time.

Rules
-----
``lock-guarded-field``
    A guarded field is touched by a function that neither locks its
    mutex nor is annotated REQUIRES.
``lock-unknown-mutex``
    A GUARDED_BY/REQUIRES names a mutex that is not declared in the
    same class — a stale annotation is worse than none.

Suppress a deliberate unlocked access (e.g. an atomic pre-check) with
``// lint: allow(lock-guarded-field)`` on the line.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, SourceFile, filter_allowed

GUARDED_RE = re.compile(r"//\s*GUARDED_BY\((\w+)\)")
REQUIRES_RE = re.compile(r"//\s*REQUIRES\((\w+)\)")
ACQUIRE_RE = re.compile(
    # template args optional: C++17 CTAD allows `std::scoped_lock g(mu_)`
    r"\b(?:std::)?(?:lock_guard|unique_lock|scoped_lock)\s*(?:<[^>]*>)?\s*"
    r"\w+\s*\(\s*(\w+)")
MUTEX_DECL_RE = re.compile(r"\bstd::(?:recursive_)?mutex\s+(\w+)\s*;")
FIELD_DECL_RE = re.compile(
    # `type name;` / `type name = x;` / `type name{..};`, or a bare
    # `name;` continuation line of a wrapped declaration
    r"^\s*(?:[\w:<>,\s&*\[\]]+?[\s&*>])?(\w+)\s*(?:=[^;{]*|\{[^;]*\})?;")
CLASS_RE = re.compile(r"^\s*(?:class|struct)\s+(\w+)")
FUNC_RE = re.compile(
    r"^\s*(?:template\s*<[^>]*>\s*)?"
    r"(?:(?:static|virtual|inline|constexpr|explicit|friend|\[\[\w+\]\])\s+)*"
    r"(?:[\w:<>,\s&*~\[\]]+?[\s&*>])?"
    r"(~?\w+|operator\S+)\s*\(")


def _strip_code(line: str) -> Tuple[str, str]:
    """(code, comment) with string literals blanked out of code."""
    code = line
    # blank string/char literals (keeps length, avoids fake matches)
    code = re.sub(r'"(?:[^"\\]|\\.)*"', '""', code)
    code = re.sub(r"'(?:[^'\\]|\\.)*'", "''", code)
    idx = code.find("//")
    if idx >= 0:
        return code[:idx], code[idx:]
    return code, ""


@dataclass
class _Func:
    name: str
    cls: str
    start: int
    requires: Set[str] = field(default_factory=set)
    acquires: Set[str] = field(default_factory=set)
    #: (line, field, mutex) accesses recorded while inside the body
    accesses: List[Tuple[int, str, str]] = field(default_factory=list)


def analyze_source(src: SourceFile) -> List[Finding]:
    lines = src.text.splitlines()

    # Pass 1: class → {field: (mutex, decl line)} and declared mutexes.
    guarded: Dict[str, Dict[str, Tuple[str, int]]] = {}
    mutexes: Dict[str, Set[str]] = {}
    decl_lines: Set[int] = set()
    class_stack: List[Tuple[str, int]] = []  # (name, brace depth at entry)
    depth = 0
    pending_class: Optional[str] = None
    for i, raw in enumerate(lines, start=1):
        code, comment = _strip_code(raw)
        m = CLASS_RE.match(code)
        if m and ";" not in code.split("{")[0]:
            pending_class = m.group(1)
        cur = class_stack[-1][0] if class_stack else ""
        gm = GUARDED_RE.search(comment)
        if gm and cur:
            fm = FIELD_DECL_RE.match(code)
            if fm:
                guarded.setdefault(cur, {})[fm.group(1)] = (gm.group(1), i)
                decl_lines.add(i)
        mm = MUTEX_DECL_RE.search(code)
        if mm and cur:
            mutexes.setdefault(cur, set()).add(mm.group(1))
        for ch in code:
            if ch == "{":
                depth += 1
                if pending_class:
                    class_stack.append((pending_class, depth))
                    pending_class = None
            elif ch == "}":
                if class_stack and class_stack[-1][1] == depth:
                    class_stack.pop()
                depth -= 1

    findings: List[Finding] = []
    for cls, fields in guarded.items():
        declared = mutexes.get(cls, set())
        for fname, (mu, decl_line) in fields.items():
            if mu not in declared:
                findings.append(Finding(
                    src.path, decl_line, "lock-unknown-mutex",
                    f"{cls}.{fname} is GUARDED_BY({mu}) but {cls} "
                    f"declares no mutex `{mu}`"))
    if not guarded:
        return filter_allowed(src, findings)

    # Pass 2: walk function bodies, record acquisitions + field uses.
    # A REQUIRES annotation binds to a signature when it sits on the
    # signature line or on the line directly above it.
    def _requires_near(sig_line: int) -> Set[str]:
        out: Set[str] = set()
        for ln in (sig_line - 1, sig_line):
            if 1 <= ln <= len(lines):
                out |= set(REQUIRES_RE.findall(lines[ln - 1]))
        return out

    funcs: List[_Func] = []
    func_stack: List[_Func] = []
    class_stack = []
    depth = 0
    pending_class = None
    pending_func: Optional[_Func] = None
    for i, raw in enumerate(lines, start=1):
        code, comment = _strip_code(raw)
        m = CLASS_RE.match(code)
        if m and ";" not in code.split("{")[0]:
            pending_class = m.group(1)
        cur_cls = class_stack[-1][0] if class_stack else ""

        if pending_func is None and cur_cls and not func_stack:
            fm = FUNC_RE.match(code)
            if fm and "=" not in code.split("(")[0] and \
                    not code.strip().startswith(("return", "if", "for",
                                                 "while", "switch", "case",
                                                 "else", "do", "new",
                                                 "delete", "throw")):
                pending_func = _Func(name=fm.group(1), cls=cur_cls, start=i,
                                     requires=_requires_near(i))

        active = func_stack[-1] if func_stack else None
        if active is not None:
            am = ACQUIRE_RE.search(code)
            if am:
                active.acquires.add(am.group(1))
            fields = guarded.get(active.cls, {})
            if fields and i not in decl_lines:
                for fname, (mu, _) in fields.items():
                    if re.search(rf"\b{re.escape(fname)}\b", code):
                        active.accesses.append((i, fname, mu))

        for ch in code:
            if ch == "{":
                depth += 1
                if pending_class:
                    class_stack.append((pending_class, depth))
                    pending_class = None
                elif pending_func is not None:
                    pending_func.depth = depth  # type: ignore[attr-defined]
                    func_stack.append(pending_func)
                    funcs.append(pending_func)
                    pending_func = None
            elif ch == "}":
                if func_stack and \
                        getattr(func_stack[-1], "depth", -1) == depth:
                    func_stack.pop()
                if class_stack and class_stack[-1][1] == depth:
                    class_stack.pop()
                depth -= 1
        if pending_func is not None and ";" in code:
            pending_func = None  # declaration only, no body

    for fn in funcs:
        if fn.name == fn.cls or fn.name == f"~{fn.cls}":
            continue  # ctor/dtor: no concurrent access yet/anymore
        for line, fname, mu in fn.accesses:
            if mu in fn.acquires or mu in fn.requires:
                continue
            findings.append(Finding(
                src.path, line, "lock-guarded-field",
                f"`{fname}` is GUARDED_BY({mu}) but "
                f"`{fn.cls}::{fn.name}` neither locks {mu} nor is "
                f"annotated // REQUIRES({mu})"))
    return filter_allowed(src, findings)


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    return "native/src/" in rp and rp.endswith((".h", ".cc"))

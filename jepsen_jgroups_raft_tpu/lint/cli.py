"""graftlint CLI: run the project-native analyzers over the repo.

Usage::

    python -m jepsen_jgroups_raft_tpu.lint [paths...]
        [--rules taxonomy,jit,lock,kernel,heal,resource] [--list-rules]
        [--format text|json] [--baseline FILE] [--update-baseline]
        [--vmem-budget BYTES]

With no paths, lints the repo the package lives in (the self-hosting
default `scripts/lint.sh` runs). Each analyzer applies only to its scan
set when given a directory; an explicit single *file* argument is always
analyzed by every requested analyzer that understands its language —
that is what the seeded-violation tests (and quick one-file checks) use.

Two analyzer tiers: the pattern analyzers from PR 1 (taxonomy, jit,
lock) and the CFG/dataflow tier (kernel, heal, resource — see
``lint/flow/``). ``--format json`` emits a SARIF 2.1.0 log. A baseline
file (default ``lint/baseline.json`` when present) suppresses accepted
pre-existing findings so the gate fails only on regression;
``--update-baseline`` rewrites it from the current run.

Exit status: 0 clean (new findings only count), 1 new findings, 2 usage
error.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import List, Optional

from . import jit_hygiene, lock_discipline, report, taxonomy
from .base import Finding, collect_files, rel
from .flow import crashproto, degraded, envknobs, fingerprint, guarded, \
    heal, kernel_contract, knobclass, lockorder, lockstep, resource, \
    tierstamp
from .flow.kernel_contract import DEFAULT_VMEM_BUDGET

#: name → (module, suffixes)
ANALYZERS = {
    "taxonomy": (taxonomy, (".py",)),
    "jit": (jit_hygiene, (".py",)),
    "lock": (lock_discipline, (".h", ".cc")),
    "kernel": (kernel_contract, (".py",)),
    "heal": (heal, (".py",)),
    "resource": (resource, (".py",)),
    # graftsync tier (ISSUE 16): concurrency + crash-consistency
    "guarded": (guarded, (".py",)),
    "lockorder": (lockorder, (".py",)),
    "crashproto": (crashproto, (".py",)),
    "envknobs": (envknobs, (".py",)),
    # graftgate tier (ISSUE 17): verdict-integrity dataflow
    "fingerprint": (fingerprint, (".py",)),
    "degraded": (degraded, (".py",)),
    "knobclass": (knobclass, (".py",)),
    "tierstamp": (tierstamp, (".py",)),
    "lockstep": (lockstep, (".py",)),
}

RULES = {
    "taxonomy": ("taxonomy-bare-except-fail", "taxonomy-indefinite-fail",
                 "taxonomy-silent-swallow"),
    "jit": ("jit-host-sync", "jit-python-branch", "jit-recompile-hazard",
            "host-sync"),
    "lock": ("lock-guarded-field", "lock-unknown-mutex"),
    "kernel": ("kernel-block-divide", "kernel-grid-cover",
               "kernel-block-tile", "kernel-dtype", "kernel-vmem-budget",
               "kernel-unresolved"),
    "heal": ("flow-unhealed-fault",),
    "resource": ("flow-resource-leak",),
    "guarded": ("flow-unguarded-access",),
    "lockorder": ("flow-lock-cycle", "flow-lock-order",
                  "flow-lock-unranked"),
    "crashproto": ("flow-fsync-before-ack", "flow-inplace-publish",
                   "flow-nonatomic-publish"),
    "envknobs": ("flow-env-raw-parse", "flow-env-undocumented",
                 "flow-env-dup-default"),
    "fingerprint": ("flow-fp-unhashed", "flow-fp-rung-mismatch"),
    "degraded": ("flow-degraded-sink",),
    "knobclass": ("flow-knob-unclassified", "flow-knob-verdict"),
    "tierstamp": ("flow-tier-unstamped",),
    "lockstep": ("flow-lockstep-drift", "flow-lockstep-anchor"),
}

#: rule id → checker-design.md anchor for SARIF helpUri (§18 documents
#: the graftsync tier; the earlier tiers are §6/§7).
RULE_HELP = {
    **{r: "doc/checker-design.md#6-soundness-invariants"
       for a in ("taxonomy", "jit", "lock") for r in RULES[a]},
    **{r: "doc/checker-design.md#7-flow-invariants"
       for a in ("kernel", "heal", "resource") for r in RULES[a]},
    **{r: "doc/checker-design.md"
          "#18-concurrency--crash-consistency-analyzers-graftsync"
       for a in ("guarded", "lockorder", "crashproto", "envknobs")
       for r in RULES[a]},
    **{r: "doc/checker-design.md"
          "#19-verdict-integrity-dataflow-analyzers-graftgate"
       for a in ("fingerprint", "degraded", "knobclass", "tierstamp",
                 "lockstep")
       for r in RULES[a]},
}

DEFAULT_RULES = ("taxonomy,jit,lock,kernel,heal,resource,"
                 "guarded,lockorder,crashproto,envknobs,"
                 "fingerprint,degraded,knobclass,tierstamp,lockstep")


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def default_baseline() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def run(paths: List[str], rules: List[str],
        vmem_budget: int = DEFAULT_VMEM_BUDGET,
        timings: Optional[dict] = None) -> List[Finding]:
    root = repo_root()
    explicit = {Path(p).resolve() for p in paths if Path(p).is_file()}
    findings: List[Finding] = []
    for name in rules:
        t0 = time.perf_counter()
        mod, suffixes = ANALYZERS[name]
        for f in collect_files(paths, suffixes):
            relpath = rel(f, root)
            if not (Path(f).resolve() in explicit or
                    mod.applies_to(relpath)):
                continue
            if name == "kernel":
                found = mod.analyze_file(f, vmem_budget)
            else:
                found = mod.analyze_file(f)
            for finding in found:
                # honor the finding's own path when the analyzer looked
                # beyond the anchor file (lockorder loads the whole
                # service/ tier from daemon.py)
                findings.append(Finding(rel(finding.path, root),
                                        finding.line, finding.rule,
                                        finding.message))
        if timings is not None:
            timings[name] = timings.get(name, 0.0) + \
                (time.perf_counter() - t0)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m jepsen_jgroups_raft_tpu.lint",
        description="graftlint: checker-soundness, jit-hygiene, native "
                    "lock-discipline and CFG/dataflow (kernel-contract, "
                    "fault-heal, resource-leak) analysis")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the repo)")
    parser.add_argument("--rules", default=DEFAULT_RULES,
                        help="comma-separated analyzer subset")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text",
                        help="text (default) or SARIF 2.1.0 JSON")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="baseline file of accepted findings "
                             "(default: lint/baseline.json when present)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from this run's "
                             "findings and exit 0")
    parser.add_argument("--vmem-budget", type=int,
                        default=DEFAULT_VMEM_BUDGET, metavar="BYTES",
                        help="kernel-contract per-program VMEM budget")
    parser.add_argument("--knob-registry", default=None, metavar="FILE",
                        help="write the JGRAFT_* env-knob registry "
                             "harvested by the envknobs analyzer as "
                             "JSON to FILE")
    parser.add_argument("--timing", action="store_true",
                        help="emit per-analyzer wall seconds to stderr "
                             "(the lint.yml budget assert reads this)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for analyzer, rules in RULES.items():
            for r in rules:
                print(f"{analyzer}: {r}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    unknown = [r for r in rules if r not in ANALYZERS]
    if unknown:
        print(f"unknown analyzer(s): {', '.join(unknown)} "
              f"(have: {', '.join(ANALYZERS)})", file=sys.stderr)
        return 2

    # A typo'd path must be a loud usage error, not a silent clean pass.
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    paths = args.paths or [str(repo_root() / "jepsen_jgroups_raft_tpu"),
                           str(repo_root() / "native" / "src"),
                           # in-scope scripts (ISSUE 8): the chaos
                           # harness is gated like the service tier it
                           # exercises; absent on partial checkouts.
                           *(str(p) for p in
                             [repo_root() / "scripts" / "chaos_graftd.py"]
                             if p.exists())]
    timings: Optional[dict] = {} if args.timing else None
    findings = run(paths, rules, vmem_budget=args.vmem_budget,
                   timings=timings)

    # The knob registry is a whole-repo harvest (it also covers bench.py
    # and the scripts, which the per-file walk does not visit) — run it
    # on any default-path envknobs run, and whenever the artifact is
    # requested explicitly.
    if args.knob_registry or ("envknobs" in rules and not args.paths):
        registry, extra = envknobs.build_registry(repo_root())
        if "envknobs" in rules and not args.paths:
            findings = sorted(findings + extra,
                              key=lambda f: (f.path, f.line, f.rule))
        if args.knob_registry:
            Path(args.knob_registry).write_text(
                json.dumps(registry, indent=2, sort_keys=True) + "\n",
                encoding="utf-8")
            print(f"env-knob registry: {len(registry['knobs'])} knob(s) "
                  f"-> {args.knob_registry}", file=sys.stderr)

    if timings is not None:
        for name in sorted(timings, key=timings.get, reverse=True):
            print(f"lint-timing: {name} {timings[name]:.3f}s",
                  file=sys.stderr)
        print(f"lint-timing: total {sum(timings.values()):.3f}s",
              file=sys.stderr)

    fps = report.fingerprints(findings, repo_root())
    baseline_path: Optional[Path] = (
        Path(args.baseline) if args.baseline else default_baseline())
    if args.update_baseline:
        new_fps = {fp for _, fp in fps}
        # A partial run (analyzer subset or explicit paths) only SAW part
        # of the repo: rewriting from it would silently drop every
        # accepted fingerprint outside the run's scope, so merge instead.
        # Only the full default run is authoritative enough to prune.
        partial = bool(args.paths) or set(rules) != set(ANALYZERS)
        if partial:
            new_fps |= report.load_baseline(baseline_path)
        report.save_baseline(baseline_path, sorted(new_fps))
        print(f"baseline: wrote {len(new_fps)} finding(s) to "
              f"{baseline_path}"
              + (" (partial run: merged with existing)" if partial else ""),
              file=sys.stderr)
        return 0
    baseline = report.load_baseline(baseline_path)
    suppressed = [fp in baseline for _, fp in fps]
    new = [f for f, sup in zip(findings, suppressed) if not sup]

    if args.format == "json":
        rule_ids = [r for a in rules for r in RULES[a]]
        print(json.dumps(report.to_sarif(findings, suppressed, rule_ids,
                                         rule_help=RULE_HELP),
                         indent=2))
    else:
        for f in new:
            print(f.render())

    n_base = sum(suppressed)
    if new:
        print(f"graftlint: {len(new)} new finding(s)"
              + (f" ({n_base} baselined)" if n_base else ""),
              file=sys.stderr)
        return 1
    tail = f" — {n_base} baselined finding(s)" if n_base else ""
    print(f"graftlint: clean ({', '.join(rules)}){tail}",
          file=sys.stderr if args.format == "json" else sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())

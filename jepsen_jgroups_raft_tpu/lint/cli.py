"""graftlint CLI: run the project-native analyzers over the repo.

Usage::

    python -m jepsen_jgroups_raft_tpu.lint [paths...]
        [--rules taxonomy,jit,lock] [--list-rules]

With no paths, lints the repo the package lives in (the self-hosting
default `scripts/lint.sh` runs). Each analyzer applies only to its scan
set when given a directory; an explicit single *file* argument is always
analyzed by every requested analyzer that understands its language —
that is what the seeded-violation tests (and quick one-file checks) use.

Exit status: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List

from . import jit_hygiene, lock_discipline, taxonomy
from .base import Finding, collect_files, rel

#: name → (module, suffixes)
ANALYZERS = {
    "taxonomy": (taxonomy, (".py",)),
    "jit": (jit_hygiene, (".py",)),
    "lock": (lock_discipline, (".h", ".cc")),
}

RULES = {
    "taxonomy": ("taxonomy-bare-except-fail", "taxonomy-indefinite-fail",
                 "taxonomy-silent-swallow"),
    "jit": ("jit-host-sync", "jit-python-branch", "jit-recompile-hazard",
            "host-sync"),
    "lock": ("lock-guarded-field", "lock-unknown-mutex"),
}


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def run(paths: List[str], rules: List[str]) -> List[Finding]:
    root = repo_root()
    explicit = {Path(p).resolve() for p in paths if Path(p).is_file()}
    findings: List[Finding] = []
    for name in rules:
        mod, suffixes = ANALYZERS[name]
        for f in collect_files(paths, suffixes):
            relpath = rel(f, root)
            if not (Path(f).resolve() in explicit or
                    mod.applies_to(relpath)):
                continue
            for finding in mod.analyze_file(f):
                findings.append(Finding(relpath, finding.line,
                                        finding.rule, finding.message))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m jepsen_jgroups_raft_tpu.lint",
        description="graftlint: checker-soundness, jit-hygiene and "
                    "native lock-discipline analysis")
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the repo)")
    parser.add_argument("--rules", default="taxonomy,jit,lock",
                        help="comma-separated analyzer subset")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for analyzer, rules in RULES.items():
            for r in rules:
                print(f"{analyzer}: {r}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    unknown = [r for r in rules if r not in ANALYZERS]
    if unknown:
        print(f"unknown analyzer(s): {', '.join(unknown)} "
              f"(have: {', '.join(ANALYZERS)})", file=sys.stderr)
        return 2

    # A typo'd path must be a loud usage error, not a silent clean pass.
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    paths = args.paths or [str(repo_root() / "jepsen_jgroups_raft_tpu"),
                           str(repo_root() / "native" / "src")]
    findings = run(paths, rules)
    for f in findings:
        print(f.render())
    if findings:
        print(f"graftlint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"graftlint: clean ({', '.join(rules)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

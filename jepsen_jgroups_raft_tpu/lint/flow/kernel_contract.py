"""Kernel-contract analyzer: static Pallas/launch shape verification.

A mis-sized ``BlockSpec``, a grid that does not tile the output, or a
VMEM-oversized block is today a *runtime* failure — Mosaic rejects the
lowering or XLA OOMs — discovered only after burning (tunneled, paid)
TPU time. This analyzer evaluates the shape arithmetic around every
``pl.pallas_call`` **statically**: the enclosing scopes' assignments are
executed by the restricted interpreter (:mod:`.interp`) under sampled
symbol bindings drawn from the file's declared contract, and the
resulting concrete grids/blocks/shapes are checked against the Mosaic
and VMEM rules. Files with no symbols (test fixtures with literal
shapes) evaluate under the single empty binding.

Rules
-----
``kernel-block-divide``
    An out_spec block dim does not divide the declared ``out_shape`` dim.
``kernel-grid-cover``
    grid × block (via the evaluated ``index_map``) covers a different
    extent than the declared ``out_shape`` — the grid either misses part
    of the output or writes out of bounds.
``kernel-block-tile``
    Mosaic tiling: a block's lane dim must be a multiple of 128 and its
    sublane dim a multiple of 8, unless it spans the full (implied)
    array dim.
``kernel-dtype``
    A 64-bit ``out_shape`` dtype — does not propagate on TPU without
    x64 mode; the kernel would silently compute in 32 bits or fail.
``kernel-vmem-budget``
    Per-program resident block bytes (Σ in/out blocks) exceed the VMEM
    budget (default ~12 MiB of the ~16 MiB/core, CLI-configurable), or
    a contract's named budget invariant fails (e.g. ``tile_histories``
    must keep the lane-expanded event block inside
    ``_EVENTS_VMEM_BUDGET`` for every legal (S, E)).
``kernel-unresolved``
    The analyzer could not evaluate a shape it needed — a loud finding,
    never a silent pass, so adding symbols to a kernel without extending
    its contract fails the gate instead of going unchecked.

Scan set (CLI): ``ops/kernel_ir.py``, ``ops/pallas_scan.py``,
``ops/segment_scan.py``, ``ops/dense_scan.py``, ``ops/linear_scan.py``,
``parallel/mesh.py``, ``history/packing.py`` — the kernel IR carries
THE chunk-carry bindings for every family that chunks through it
(``_ir_chunk_budget``; the per-family duplicates are gone, PR 6), the
other non-Pallas files are covered for their declared cap/budget
constants (incl. the macro-event ``MACRO_MAX_OPENS`` payload cap, whose
67-lane rows the Pallas tile and chunk-slab bindings sample) and for
any ``pallas_call`` a future PR adds there.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from itertools import product
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..base import Finding, SourceFile, filter_allowed
from .interp import UNKNOWN, Closure, Dotted, Interp, _Abort, _Return

DEFAULT_VMEM_BUDGET = 12 << 20

#: dtypes that do not exist on TPU without jax x64 mode.
_BAD_DTYPES = {"float64", "int64", "uint64", "complex128"}

_DTYPE_BYTES = {"int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
                "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
                "int32": 4, "uint32": 4, "float32": 4}


@dataclass
class Contract:
    """Per-file symbol domains + named budget invariants."""

    #: symbol name -> candidate values (parameters of the functions
    #: enclosing the pallas_call); cross product, filtered by `where`.
    symbols: Dict[str, Tuple] = field(default_factory=dict)
    where: Optional[callable] = None
    #: (expr over module constants, max value, message) rows checked once.
    const_asserts: List[Tuple[str, int, str]] = field(default_factory=list)
    #: optional callable(interp) -> list of messages for file-specific
    #: budget invariants that need to *run* module functions. Each item
    #: is a bare message (emitted as kernel-vmem-budget) or an explicit
    #: (rule, message) pair — unresolved accounting must use
    #: ("kernel-unresolved", ...) so it stays loud under a
    #: kernel-vmem-budget baseline.
    custom: Optional[callable] = None


def _pallas_scan_tile_budget(interp: Interp) -> List[str]:
    """tile_histories(S, E, R) must keep the lane-expanded event block
    ([R·E, T·S] int32 = T·S·E·R·4 bytes) inside _EVENTS_VMEM_BUDGET for
    every legal (S, E, R) — the exact invariant its docstring claims.
    R samples both stream formats: 5 legacy fields and the widest
    macro-event row (3 + 4·MACRO_MAX_OPENS = 67 lanes; the macro cap is
    pinned by history/packing.py's own contract, so widening it fails
    the gate until these bindings are re-proven)."""
    out = []
    budget = interp.module_env.get("_EVENTS_VMEM_BUDGET")
    fn = interp.functions.get("tile_histories")
    if not isinstance(budget, int) or fn is None:
        return ["tile_histories/_EVENTS_VMEM_BUDGET not resolvable"]
    for S in (1, 2, 4, 8, 16):
        for E in (8, 64, 512, 4096, 131072):
            for R in (5, 35, 67):
                T = interp.exec_fn(fn, {"n_states": S, "n_events": E,
                                        "row_ints": R})
                if not isinstance(T, int):
                    out.append(
                        f"tile_histories({S}, {E}, {R}) not evaluable")
                    continue
                if T * S * E * R * 4 > budget and T > 1:
                    out.append(
                        f"tile_histories({S}, {E}, {R}) = {T}: event "
                        f"block {T * S * E * R * 4} B exceeds "
                        f"_EVENTS_VMEM_BUDGET {budget} B")
    return out


def _ir_chunk_budget(interp: Interp) -> List[str]:
    """THE chunk-carry contract bindings — proven once against the
    kernel IR (ops/kernel_ir.py) for every family that chunks through
    it, replacing the per-family dense/sort duplicates (PR 6
    satellite). The chunked entry points carry per-row scan state
    between kernel launches instead of rebuilding it — so the carry
    itself must fit the VMEM envelope at the eligibility caps, which
    live in the same module (a cap bump and an accounting change fail
    the gate together). Same loud-not-silent stance as the Pallas tile
    invariant: anything unresolvable is a kernel-unresolved finding."""
    out = []
    fn_d = interp.functions.get("dense_chunk_carry_bytes")
    caps_w = interp.module_env.get("DENSE_MAX_SLOTS")
    caps_s = interp.module_env.get("DENSE_MAX_STATES")
    mask_w = interp.module_env.get("MASK_DENSE_MAX_SLOTS")
    if fn_d is None or not all(isinstance(v, int)
                               for v in (caps_w, caps_s, mask_w)):
        out.append(("kernel-unresolved",
                    "dense_chunk_carry_bytes / dense caps not resolvable"))
    else:
        for W, S in ((1, 1), (caps_w, 1), (caps_w, caps_s), (mask_w, 1)):
            n = interp.exec_fn(fn_d, {"n_slots": W, "n_states": S})
            if not isinstance(n, int):
                out.append(("kernel-unresolved",
                            f"dense_chunk_carry_bytes({W}, {S}) "
                            "not evaluable"))
            elif n > 16 << 20:
                out.append(f"chunked dense carry at (W={W}, S={S}) = {n} "
                           "B exceeds usable per-core VMEM")
    fn_s = interp.functions.get("sort_chunk_carry_bytes")
    n_cfg = interp.module_env.get("SORT_DEFAULT_CONFIGS")
    n_slots = interp.module_env.get("SORT_MAX_SLOTS")
    if fn_s is None or not all(isinstance(v, int)
                               for v in (n_cfg, n_slots)):
        out.append(("kernel-unresolved",
                    "sort_chunk_carry_bytes / sort caps not resolvable"))
    else:
        for C, W in ((n_cfg, 1), (n_cfg, n_slots), (4 * n_cfg, n_slots)):
            n = interp.exec_fn(fn_s, {"n_configs": C, "n_slots": W})
            if not isinstance(n, int):
                out.append(("kernel-unresolved",
                            f"sort_chunk_carry_bytes({C}, {W}) "
                            "not evaluable"))
            elif n > 16 << 20:
                out.append(f"chunked sort carry at (C={C}, W={W}) = {n} B "
                           "exceeds usable per-core VMEM")
    # Macro-event rows (ISSUE-4): the widened chunk event slab must
    # still fit next to the carry at the caps. MACRO_MAX_OPENS comes
    # from history/packing.py via the sibling-constant merge; a cap
    # bump that outgrows the proven bindings surfaces here, loudly.
    # Cycle-closure adjacency slab (ISSUE 13): the batched transitive-
    # closure kernel keeps the int32 adjacency matrix and its squared
    # product resident per row — proven at the CYCLE_MAX_NODES cap so
    # a cap bump fails the gate until the accounting is re-proven.
    fn_cy = interp.functions.get("cycle_adjacency_bytes")
    cap_n = interp.module_env.get("CYCLE_MAX_NODES")
    if fn_cy is None or not isinstance(cap_n, int):
        out.append(("kernel-unresolved",
                    "cycle_adjacency_bytes / CYCLE_MAX_NODES "
                    "not resolvable"))
    else:
        for N in (2, cap_n):
            n = interp.exec_fn(fn_cy, {"n_nodes": N})
            if not isinstance(n, int):
                out.append(("kernel-unresolved",
                            f"cycle_adjacency_bytes({N}) not evaluable"))
            elif n > 16 << 20:
                out.append(f"cycle adjacency slab at N={N} = {n} B "
                           "exceeds usable per-core VMEM")
    # Blocked-closure tile slab (ISSUE 19): the tiled kernel keeps a
    # [T,N] row panel, a [T,N] col panel, one streamed [T,N] product
    # panel and the [T,T] pivot diagonal resident — the budget binding
    # moves to TILE granularity, so the proof samples the tiled cap at
    # the default tile, the minimum tile, and the first post-monolithic
    # bucket. A cap or tile bump fails here until re-proven.
    fn_ct = interp.functions.get("cycle_closure_tile_bytes")
    cap_tn = interp.module_env.get("CYCLE_MAX_NODES_TILED")
    tile_t = interp.module_env.get("CYCLE_TILE")
    if fn_ct is None or not all(isinstance(v, int)
                                for v in (cap_tn, tile_t)):
        out.append(("kernel-unresolved",
                    "cycle_closure_tile_bytes / CYCLE_MAX_NODES_TILED / "
                    "CYCLE_TILE not resolvable"))
    else:
        for N, T in ((cap_tn, tile_t), (cap_tn, 2), (1024, tile_t)):
            n = interp.exec_fn(fn_ct, {"n_nodes": N, "tile": T})
            if not isinstance(n, int):
                out.append(("kernel-unresolved",
                            f"cycle_closure_tile_bytes({N}, {T}) "
                            "not evaluable"))
            elif n > 16 << 20:
                out.append(f"blocked cycle-closure tile slab at (N={N}, "
                           f"T={T}) = {n} B exceeds usable per-core VMEM")
    fn_r = interp.functions.get("macro_row_ints")
    cap_p = interp.module_env.get("MACRO_MAX_OPENS")
    if fn_r is None or not isinstance(cap_p, int):
        out.append(("kernel-unresolved",
                    "macro_row_ints / MACRO_MAX_OPENS not resolvable"))
        return out
    r = interp.exec_fn(fn_r, {"macro_p": cap_p})
    if not isinstance(r, int):
        out.append(("kernel-unresolved",
                    f"macro_row_ints({cap_p}) not evaluable"))
        return out
    # Carry + slab only when the dense half resolved — its absence was
    # already reported above with the RIGHT cause; re-blaming
    # macro_row_ints here would point the maintainer at the wrong fn.
    if fn_d is not None and all(isinstance(v, int)
                                for v in (caps_w, caps_s)):
        carry = interp.exec_fn(fn_d, {"n_slots": caps_w,
                                      "n_states": caps_s})
        if isinstance(carry, int) and carry + 4096 * r * 4 > 16 << 20:
            out.append(f"chunked dense carry + macro event slab at the "
                       f"caps = {carry + 4096 * r * 4} B exceeds usable "
                       "per-core VMEM")
    return out


CONTRACTS: Dict[str, Contract] = {
    "ops/pallas_scan.py": Contract(
        symbols={"W": (5,), "S": (1, 4, 16), "E": (8, 64, 512),
                 "T": (1, 4, 32), "G": (1, 2, 8),
                 "R": (5, 35, 67), "interpret": (False,)},
        # the legal envelope tile_histories/make_pallas_batch_checker
        # guarantee: lane axis filled but never overfilled, E padded to
        # a multiple of 8 (Mosaic sublane rule — R is odd in both
        # stream formats, so E itself carries the rule), and for T > 1
        # the tile budget caps the lane-expanded event block at
        # _EVENTS_VMEM_BUDGET (T = 1 is the irreducible minimum tile).
        where=lambda b: (b["T"] * b["S"] <= 128 and b["E"] % 8 == 0
                         and (b["T"] == 1 or
                              b["T"] * b["S"] * b["E"] * b["R"] * 4
                              <= 6 << 20)),
        const_asserts=[
            # Pinned EXACTLY at the value the where-clause envelope
            # above samples (not just ≤ VMEM): raising the budget
            # would legalize bigger tiles that the envelope would then
            # silently stop sampling — fail here until both move
            # together.
            ("_EVENTS_VMEM_BUDGET", 6 << 20,
             "events VMEM budget outgrew the contract's sampled "
             "envelope (the where-clause bound); move both together"),
            ("_LANE_TARGET", 128, "lane target beyond the 128-lane VPU"),
        ],
        custom=_pallas_scan_tile_budget,
    ),
    "history/packing.py": Contract(const_asserts=[
        # The macro payload cap is load-bearing for every kernel
        # family's proven bindings: the Pallas tile budget and the
        # chunk-slab checks sample rows at 3 + 4·16 = 67 lanes, so a
        # cap bump must fail here until those bindings are re-proven.
        ("MACRO_MAX_OPENS", 16,
         "macro open cap outgrew the proven kernel-contract bindings "
         "(R = 67-lane rows); re-prove the Pallas tile and chunk-slab "
         "budgets before raising it"),
        ("3 + 4 * MACRO_MAX_OPENS", 67,
         "macro row width beyond the proven R samples"),
    ]),
    # The IR owns the family caps and the chunk-carry accounting; its
    # contract carries THE single set of chunk-carry bindings
    # (_ir_chunk_budget) plus the cap const-asserts that used to live
    # per family.
    "ops/kernel_ir.py": Contract(const_asserts=[
        ("(1 << DENSE_MAX_SLOTS) * DENSE_MAX_STATES * 4", 16 << 20,
         "dense frontier at the eligibility caps exceeds VMEM"),
        ("DENSE_MAX_CELLS * 4", 16 << 20,
         "dense cell cap exceeds VMEM"),
        ("(1 << MASK_DENSE_MAX_SLOTS) * 8", 16 << 20,
         "mask frontier + subset-sum lane at the cap exceeds VMEM"),
        # 4 mask words must keep a spare top bit for the all-ones
        # empty-entry sentinel (linear_scan docstring soundness
        # argument).
        ("SORT_MAX_SLOTS", 127,
         "window cap would consume the sentinel bit of the last word"),
        ("SORT_DEFAULT_CONFIGS * ((SORT_MAX_SLOTS // 32 + 1) * 4 + 4)",
         16 << 20,
         "sort frontier at the default capacity exceeds VMEM"),
        # ISSUE 13: the cycle-closure adjacency + product slab at the
        # node cap (the custom binding also executes the accounting fn).
        ("2 * CYCLE_MAX_NODES * CYCLE_MAX_NODES * 4", 16 << 20,
         "cycle adjacency slab at the node cap exceeds VMEM"),
        # ISSUE 19: the blocked-closure tile slab at the TILED cap —
        # the per-tile binding (3 [T,N] panels + the [T,T] diagonal)
        # that lets N grow past the monolithic 512 cap. The custom
        # binding also executes cycle_closure_tile_bytes at corners.
        ("(3 * CYCLE_TILE * CYCLE_MAX_NODES_TILED + "
         "CYCLE_TILE * CYCLE_TILE) * 4", 16 << 20,
         "blocked cycle-closure tile slab at the tiled cap exceeds "
         "VMEM; re-prove before raising CYCLE_MAX_NODES_TILED or "
         "CYCLE_TILE"),
    ], custom=_ir_chunk_budget),
    "ops/dense_scan.py": Contract(const_asserts=[
        # Re-assert the caps through dense_scan's own import site: the
        # sibling-constant merge resolves them from kernel_ir, so a
        # broken re-export chain is a loud unresolved finding here.
        ("(1 << DENSE_MAX_SLOTS) * DENSE_MAX_STATES * 4", 16 << 20,
         "dense frontier at the eligibility caps exceeds VMEM"),
    ]),
    "ops/linear_scan.py": Contract(const_asserts=[
        ("MAX_SLOTS", 127,
         "window cap would consume the sentinel bit of the last word"),
    ]),
    "ops/segment_scan.py": Contract(const_asserts=[
        ("MAX_BASIS * DENSE_MAX_CELLS * 4", 16 << 20,
         "segment seed-basis frontier at the caps exceeds VMEM"),
        ("DEFAULT_BLOCK_EVENTS * 5 * 4", 16 << 20,
         "segment event slab exceeds VMEM"),
    ]),
    "parallel/mesh.py": Contract(),
}

SCAN_FILES = tuple(CONTRACTS)


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp in SCAN_FILES


def _contract_for(path: str) -> Contract:
    rp = str(path).replace("\\", "/")
    for key, c in CONTRACTS.items():
        if rp.endswith(key):
            return c
    return Contract()


# ------------------------------------------------------------ extraction


def _leaf(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _enclosing_chain(tree: ast.Module) -> List[Tuple[ast.Call, list]]:
    """[(pallas_call node, [enclosing FunctionDefs outer→inner])]."""
    out = []

    def walk(node, chain):
        for child in ast.iter_child_nodes(node):
            nc = chain
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nc = chain + [child]
            if isinstance(child, ast.Call) and \
                    _leaf(child) == "pallas_call":
                out.append((child, list(nc)))
            walk(child, nc)

    walk(tree, [])
    return out


def _merge_sibling_consts(interp: Interp, tree: ast.Module,
                          path: str) -> None:
    """Resolve relative-import constants (`from .sibling import NAME`,
    `from ..pkg.mod import NAME`) so cross-module cap expressions stay
    checkable — segment_scan uses dense_scan's caps, and dense_scan's
    macro-row bindings use history/packing.py's MACRO_MAX_OPENS."""
    base = Path(path).parent
    for stmt in tree.body:
        if not (isinstance(stmt, ast.ImportFrom) and stmt.level >= 1
                and stmt.module):
            continue
        target = base
        for _ in range(stmt.level - 1):
            target = target.parent
        sib = target.joinpath(*stmt.module.split(".")).with_suffix(".py")
        if not sib.exists():
            continue
        try:
            sub = Interp(ast.parse(sib.read_text(encoding="utf-8",
                                                 errors="replace")))
        except SyntaxError:
            continue
        for alias in stmt.names:
            val = sub.module_env.get(alias.name, UNKNOWN)
            if val is not UNKNOWN:
                interp.module_env.setdefault(alias.asname or alias.name,
                                             val)


# -------------------------------------------------------------- checking


def _bindings(contract: Contract):
    if not contract.symbols:
        return [{}]
    names = sorted(contract.symbols)
    out = []
    for combo in product(*(contract.symbols[n] for n in names)):
        b = dict(zip(names, combo))
        if contract.where is None or contract.where(b):
            out.append(b)
    return out


def _eval_specs(interp: Interp, expr: Optional[ast.expr], env: dict):
    """BlockSpec list/single ast -> [(shape tuple, index_map Closure)]
    or None when unresolvable."""
    if expr is None:
        return []
    elts = expr.elts if isinstance(expr, (ast.List, ast.Tuple)) else [expr]
    specs = []
    for e in elts:
        if not (isinstance(e, ast.Call) and _leaf(e) == "BlockSpec"):
            return None
        shape_ast = e.args[0] if e.args else _kw(e, "block_shape")
        imap_ast = e.args[1] if len(e.args) > 1 else _kw(e, "index_map")
        shape = interp.eval(shape_ast, env) if shape_ast is not None \
            else None
        if not (isinstance(shape, tuple) and
                all(isinstance(d, int) and d > 0 for d in shape)):
            return None
        imap = interp.eval(imap_ast, env) if imap_ast is not None else None
        specs.append((shape, imap if isinstance(imap, Closure) else None))
    return specs


def _eval_out_shapes(interp: Interp, expr: Optional[ast.expr], env: dict):
    """out_shape ast -> [(shape tuple, dtype leaf str)] or None."""
    if expr is None:
        return None
    elts = expr.elts if isinstance(expr, (ast.List, ast.Tuple)) else [expr]
    out = []
    for e in elts:
        if not (isinstance(e, ast.Call) and
                _leaf(e) == "ShapeDtypeStruct" and len(e.args) >= 2):
            return None
        shape = interp.eval(e.args[0], env)
        dtype = interp.eval(e.args[1], env)
        if not (isinstance(shape, tuple) and
                all(isinstance(d, int) and d > 0 for d in shape)):
            return None
        out.append((shape, dtype.leaf if isinstance(dtype, Dotted)
                    else str(dtype)))
    return out


def _grid_points(grid: Tuple[int, ...]):
    total = 1
    for g in grid:
        total *= g
    if total <= 4096:
        return product(*(range(g) for g in grid))
    # corner sampling for huge grids: extremes bound the index maps the
    # repo writes (affine in program ids)
    return product(*(sorted({0, g - 1}) for g in grid))


def _implied_extent(shape, imap, grid):
    """(max block origin + 1) * block per dim, from evaluating the
    index map over the grid; None when the map is unresolvable."""
    if imap is None:
        return None
    maxo = [0] * len(shape)
    for point in _grid_points(grid):
        origins = imap.call(list(point))
        if not (isinstance(origins, tuple) and len(origins) == len(shape)
                and all(isinstance(o, int) and o >= 0 for o in origins)):
            return None
        for d, o in enumerate(origins):
            maxo[d] = max(maxo[d], o)
    return tuple((m + 1) * s for m, s in zip(maxo, shape))


def _tile_violations(shape, implied) -> List[str]:
    if len(shape) < 2:
        return []
    if implied is None:
        # no (resolvable) index_map: pallas defaults to a whole-array
        # block, which spans the full dims by definition — there is no
        # tile violation to assert, and claiming one would flag every
        # default BlockSpec.
        return []
    out = []
    lane, sub = shape[-1], shape[-2]
    full_lane = implied[-1]
    full_sub = implied[-2]
    if lane % 128 and lane != full_lane:
        out.append(f"lane dim {lane} is neither a multiple of 128 nor "
                   f"the full array dim ({full_lane})")
    if sub % 8 and sub != full_sub:
        out.append(f"sublane dim {sub} is neither a multiple of 8 nor "
                   f"the full array dim ({full_sub})")
    return out


def _check_call(call: ast.Call, chain: list, contract: Contract,
                interp: Interp, budget: int) -> List[Tuple[str, str]]:
    """One pallas_call over every contract binding -> [(rule, message)],
    deduped (first offending binding reported)."""
    seen = {}
    for binding in _bindings(contract):
        env = dict(binding)
        aborted = False
        for fn in chain:
            args = fn.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                env.setdefault(a.arg, UNKNOWN)
            try:
                interp.lenient = True
                interp.exec_body(fn.body, env)
            except _Return:
                pass
            except _Abort:
                # e.g. a loop past the interpreter's iteration ceiling:
                # the harvested env is partial and untrustworthy, so the
                # sample is reported unresolved below — a loud finding,
                # never a crashed lint run or a shape check against
                # half-evaluated values.
                aborted = True
            finally:
                interp.lenient = False

        def unresolved(what):
            seen.setdefault(("kernel-unresolved", what),
                            f"cannot statically evaluate {what} — extend "
                            "the file's contract in lint/flow/"
                            "kernel_contract.py or simplify the "
                            "expression")

        if aborted:
            unresolved("the enclosing scope (interpreter abort)")
            continue

        grid_ast = _kw(call, "grid")
        grid = interp.eval(grid_ast, env) if grid_ast is not None else ()
        if isinstance(grid, int):
            grid = (grid,)
        if not (isinstance(grid, tuple) and
                all(isinstance(g, int) and g > 0 for g in grid)):
            unresolved("grid")
            continue
        in_specs = _eval_specs(interp, _kw(call, "in_specs"), env)
        out_specs = _eval_specs(interp, _kw(call, "out_specs"), env)
        out_shapes = _eval_out_shapes(interp, _kw(call, "out_shape"), env)
        if in_specs is None:
            unresolved("in_specs")
            continue
        if out_specs is None or out_shapes is None:
            unresolved("out_specs/out_shape")
            continue

        blocks_bytes = 0
        for shape, imap in in_specs:
            implied = _implied_extent(shape, imap, grid)
            for v in _tile_violations(shape, implied):
                seen.setdefault(("kernel-block-tile", v),
                                f"in_spec block {shape} at {binding}: {v}")
            blocks_bytes += _prod(shape) * 4  # int32-dominated inputs

        for i, (shape, imap) in enumerate(out_specs):
            decl, dtype = out_shapes[i] if i < len(out_shapes) else \
                (None, "int32")
            if dtype in _BAD_DTYPES:
                seen.setdefault(("kernel-dtype", dtype),
                                f"out_shape dtype {dtype}: 64-bit dtypes "
                                "do not propagate on TPU (x64 off)")
            nbytes = _DTYPE_BYTES.get(dtype, 4)
            blocks_bytes += _prod(shape) * nbytes
            if decl is not None:
                if len(decl) != len(shape):
                    seen.setdefault(
                        ("kernel-block-divide", f"rank{i}"),
                        f"out_spec block {shape} rank differs from "
                        f"out_shape {decl}")
                    continue
                for d, (b, a) in enumerate(zip(shape, decl)):
                    if a % b:
                        seen.setdefault(
                            ("kernel-block-divide", f"{i}.{d}"),
                            f"out_spec block dim {b} does not divide "
                            f"out_shape dim {a} (axis {d}, at {binding})")
                implied = _implied_extent(shape, imap, grid)
                if implied is not None and implied != decl:
                    seen.setdefault(
                        ("kernel-grid-cover", str(i)),
                        f"grid {grid} × block {shape} covers {implied} "
                        f"but out_shape declares {decl} (at {binding})")
                for v in _tile_violations(shape, decl):
                    seen.setdefault(("kernel-block-tile", f"out:{v}"),
                                    f"out_spec block {shape}: {v}")

        if blocks_bytes > budget:
            seen.setdefault(
                ("kernel-vmem-budget", "blocks"),
                f"resident blocks ≈ {blocks_bytes} B exceed the VMEM "
                f"budget {budget} B (at {binding}; --vmem-budget to "
                "raise)")
    return [(rule, msg) for (rule, _detail), msg in seen.items()]


def _prod(shape) -> int:
    out = 1
    for d in shape:
        out *= d
    return out


# ------------------------------------------------------------- interface


def analyze_source(src: SourceFile,
                   vmem_budget: int = DEFAULT_VMEM_BUDGET) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    contract = _contract_for(src.path)
    interp = Interp(tree)
    _merge_sibling_consts(interp, tree, src.path)
    findings: List[Finding] = []

    for expr, limit, msg in contract.const_asserts:
        try:
            val = interp.eval(ast.parse(expr, mode="eval").body, {})
        except SyntaxError:
            val = UNKNOWN
        if not isinstance(val, int):
            findings.append(Finding(
                src.path, 1, "kernel-unresolved",
                f"budget expression {expr!r} not evaluable from module "
                "constants"))
        elif val > limit:
            findings.append(Finding(
                src.path, 1, "kernel-vmem-budget",
                f"{expr} = {val} > {limit}: {msg}"))

    if contract.custom is not None:
        # Custom analyzers yield either a bare message (a budget
        # violation) or an explicit (rule, message) pair — unresolved
        # accounting must surface under kernel-unresolved, the loud
        # could-not-evaluate rule, so baselining kernel-vmem-budget
        # can never swallow a vanished accounting fn.
        for item in contract.custom(interp):
            rule, msg = (item if isinstance(item, tuple)
                         else ("kernel-vmem-budget", item))
            findings.append(Finding(src.path, 1, rule, msg))

    for call, chain in _enclosing_chain(tree):
        for rule, msg in _check_call(call, chain, contract, interp,
                                     vmem_budget):
            findings.append(Finding(src.path, call.lineno, rule, msg))
    return filter_allowed(src, findings)


def analyze_file(path, vmem_budget: int = DEFAULT_VMEM_BUDGET
                 ) -> List[Finding]:
    return analyze_source(SourceFile.load(path), vmem_budget)

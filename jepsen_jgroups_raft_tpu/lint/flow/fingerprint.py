"""Fingerprint-completeness analyzer (graftgate rule (a), ISSUE 17).

The result cache, the shared store and the WAL all key verdicts on
``service/request.py:fingerprint_encodings``. A verdict is only safely
cacheable if it is a deterministic function of the hashed bytes — so
every :class:`EncodedHistory` field a verdict-deciding path reads must
be covered by the hash **at the rung that reads it**. PR 9 shipped the
counterexample this rule exists for: the weak-rung relaxation read
``proc`` while the fingerprint did not hash it, so two histories with
identical event rows but different per-process orders shared one cache
entry.

Cross-file, in three parses:

1. ``history/packing.py`` — the EncodedHistory field inventory
   (dataclass fields + properties). A field whose declaration line
   carries ``# lint: allow(fp-irrelevant)`` is exempt everywhere: the
   written record that it is derivable from hashed bytes (op_index /
   n_ops / n_events are recomputable from the events rows) and so
   cannot split a fingerprint.
2. ``service/request.py`` — per-field hash coverage inside
   ``fingerprint_encodings``: ``always`` when the field feeds the hash
   unconditionally, ``weak`` when only under a weak-rung guard (the
   ``weak = consistency != "linearizable"`` / ``if weak:`` idiom),
   absent otherwise.
3. the verdict surface (checker/linearizable, consistency, cycle,
   certify_batch, service/scheduler) — every attribute read of an
   inventory field:

   * coverage ``always`` → fine at any rung;
   * coverage ``weak``   → the read must be weak-context: intra-
     procedurally dominated by a weak-rung guard, or inside a function
     the :func:`taint.weak_functions` fixpoint proves is only ever
     called at weak rungs → else ``flow-fp-rung-mismatch``;
   * no coverage and not exempt → ``flow-fp-unhashed``.

Receivers are not typed: any attribute spelled like an inventory field
counts as a read. That is deliberately conservative where it matters —
always-hashed fields never fire, so lookalike attributes on other
types (``plan.n_slots``) cost nothing — and the unhashed/weak fields
(``proc``) have no lookalikes on the verdict surface. Pragma aliases:
``fp-irrelevant`` covers both rules at a read site too.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from ..base import Finding, SourceFile
from .cfg import build_cfg, functions_of, walk_own
from . import taint

RULE_UNHASHED = "flow-fp-unhashed"
RULE_RUNG = "flow-fp-rung-mismatch"
PRAGMA = "fp-irrelevant"

#: anchor file: the CLI walk triggers the whole-surface analysis once.
ANCHOR = "service/request.py"
PACKING = "history/packing.py"
HASH_FN = "fingerprint_encodings"
DATACLASS = "EncodedHistory"

#: the verdict-deciding surface (ISSUE 17 tentpole (a)).
SCAN = (
    "checker/linearizable.py",
    "checker/consistency.py",
    "checker/cycle.py",
    "checker/certify_batch.py",
    "service/scheduler.py",
)


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    return rp.split("jepsen_jgroups_raft_tpu/", 1)[-1] == ANCHOR


# ------------------------------------------------------ field inventory


def field_inventory(packing: SourceFile
                    ) -> Tuple[Set[str], Set[str], Optional[int]]:
    """(fields, exempt, class_line) from the EncodedHistory dataclass:
    annotated fields plus @property names; `exempt` holds the names
    whose declaration line carries the fp-irrelevant pragma."""
    tree = ast.parse(packing.text)
    fields: Set[str] = set()
    exempt: Set[str] = set()
    cls_line: Optional[int] = None
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and
                node.name == DATACLASS):
            continue
        cls_line = node.lineno
        for stmt in node.body:
            name = line = None
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                name, line = stmt.target.id, stmt.lineno
            elif isinstance(stmt, ast.FunctionDef) and any(
                    isinstance(d, ast.Name) and d.id == "property"
                    for d in stmt.decorator_list):
                name, line = stmt.name, stmt.lineno
            if name is None:
                continue
            fields.add(name)
            if packing.allowed(line, PRAGMA) or \
                    packing.allowed(line, RULE_UNHASHED):
                exempt.add(name)
    return fields, exempt, cls_line


# -------------------------------------------------------- hash coverage


def hash_coverage(request: SourceFile,
                  fields: Set[str]) -> Optional[Dict[str, str]]:
    """field -> "always" | "weak" from the fingerprint function; None
    when the function is missing (anchor drift must be loud)."""
    tree = ast.parse(request.text)
    fn = next((f for _c, f in functions_of(tree) if f.name == HASH_FN),
              None)
    if fn is None:
        return None
    wnames = taint.weak_assign_names(fn)
    cfg = build_cfg(fn)
    coverage: Dict[str, str] = {}
    for node in walk_own(fn):
        if not (isinstance(node, ast.Attribute) and
                isinstance(node.ctx, ast.Load) and
                node.attr in fields):
            continue
        weak_only = taint.dominated(cfg, node, wnames, taint.weak_edges)
        cov = "weak" if weak_only else "always"
        if coverage.get(node.attr) != "always":
            coverage[node.attr] = cov
    return coverage


# --------------------------------------------------------- read harvest


def _field_reads(fn: ast.AST, fields: Set[str]
                 ) -> List[ast.Attribute]:
    return [node for node in walk_own(fn)
            if isinstance(node, ast.Attribute) and
            isinstance(node.ctx, ast.Load) and node.attr in fields]


def analyze_sources(sources: Dict[str, SourceFile]) -> List[Finding]:
    """Whole-surface pass over {relpath: SourceFile}; must contain
    PACKING and ANCHOR, plus whichever SCAN modules are present."""
    packing = sources.get(PACKING)
    request = sources.get(ANCHOR)
    if packing is None or request is None:
        return []
    try:
        fields, exempt, cls_line = field_inventory(packing)
        if cls_line is None:
            return [Finding(packing.path, 1, RULE_UNHASHED,
                            f"{DATACLASS} dataclass not found in "
                            f"{PACKING} — the fingerprint-completeness "
                            "anchor moved; update lint/flow/"
                            "fingerprint.py")]
        coverage = hash_coverage(request, fields)
        if coverage is None:
            return [Finding(request.path, 1, RULE_UNHASHED,
                            f"{HASH_FN}() not found in {ANCHOR} — the "
                            "fingerprint-completeness anchor moved; "
                            "update lint/flow/fingerprint.py")]
    except SyntaxError as e:
        return [Finding(packing.path, e.lineno or 1, "parse-error",
                        str(e))]

    # per-module function tables for the interprocedural weak fixpoint
    functions: List[Tuple[str, ast.AST, object]] = []
    mods: List[Tuple[SourceFile, ast.AST]] = []
    for rel in SCAN:
        src = sources.get(rel)
        if src is None:
            continue
        try:
            tree = ast.parse(src.text)
        except SyntaxError as e:
            return [Finding(src.path, e.lineno or 1, "parse-error",
                            str(e))]
        mods.append((src, tree))
        for _cls, fn in functions_of(tree):
            functions.append((fn.name, fn, build_cfg(fn)))
    weak_fns = taint.weak_functions(functions)
    cfgs = {id(fn): cfg for _n, fn, cfg in functions}

    findings: List[Finding] = []
    for src, tree in mods:
        for _cls, fn in functions_of(tree):
            wnames = taint.weak_assign_names(fn)
            cfg = cfgs[id(fn)]
            for read in _field_reads(fn, fields):
                field, line = read.attr, read.lineno
                cov = coverage.get(field)
                if cov == "always" or field in exempt:
                    continue
                if src.allowed(line, PRAGMA) or \
                        src.allowed(line, RULE_UNHASHED) or \
                        src.allowed(line, RULE_RUNG):
                    continue
                if cov is None:
                    findings.append(Finding(
                        src.path, line, RULE_UNHASHED,
                        f"verdict path reads EncodedHistory.{field}, "
                        f"which {HASH_FN} never hashes — two "
                        "submissions differing only in this field "
                        "would share a cache entry (the PR-9 proc "
                        "bug class); hash it, or mark the field "
                        "declaration `# lint: allow(fp-irrelevant)` "
                        "with why it is derivable from hashed bytes"))
                    continue
                if fn.name in weak_fns or \
                        taint.dominated(cfg, read, wnames,
                                        taint.weak_edges):
                    continue
                findings.append(Finding(
                    src.path, line, RULE_RUNG,
                    f"EncodedHistory.{field} is hashed only at weak "
                    "rungs but this read is not proven weak-context "
                    "(no dominating weak-rung guard, and "
                    f"{fn.name}() has a non-weak call site) — a "
                    "linearizable-rung verdict would depend on "
                    "unhashed bytes; guard the read or extend the "
                    "hash to all rungs"))
    return findings


def _load_surface(anchor: Path) -> Dict[str, SourceFile]:
    pkg = anchor.resolve().parents[1]   # .../jepsen_jgroups_raft_tpu
    out: Dict[str, SourceFile] = {}
    for rel in (PACKING, ANCHOR) + SCAN:
        f = pkg / rel
        if f.exists():
            out[rel] = SourceFile.load(f)
    return out


def analyze_file(path) -> List[Finding]:
    return analyze_sources(_load_surface(Path(path)))

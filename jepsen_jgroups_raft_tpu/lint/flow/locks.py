"""Shared lock-region machinery for the concurrency analyzers.

The service tier's locking convention is uniform — every critical
section is a ``with <lock>:`` block over a ``threading.Lock`` /
``RLock`` / ``Condition`` — which makes lock *regions* a pure CFG
property: the nodes flooded from a ``with-enter`` up to the matching
``with-exit`` markers hold the lock, on every continuation the builder
modeled (normal fall-through, exception unwind, early return,
break/continue — the ``finally``-style duplication in cfg.py keeps each
one explicit). Both the lock-discipline analyzer (guarded.py) and the
lock-ordering analyzer (lockorder.py) consume the same region map, so
"held at this statement" means the same thing in both.

Annotation conventions recognized here (doc/checker-design.md §18):

* ``# guarded_by(lockname)`` — trailing comment on an attribute
  *declaration* (``self.x = ...`` in ``__init__``, or a class-level
  field): every read/write of that attribute must happen while the
  declaring object's ``lockname`` is held.
* ``# requires(lockname)`` — trailing comment on a ``def`` line: the
  method's *callers* hold ``self.lockname``; the body is analyzed as if
  the lock were held throughout (the Python twin of native/'s
  ``// REQUIRES(mu_)``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..base import SourceFile
from .cfg import CFG, EXC

_GUARDED_RE = re.compile(r"#\s*guarded_by\((\w+)\)")
_REQUIRES_RE = re.compile(r"#\s*requires\((\w+)\)")

#: dotted-name tail segments treated as locks when they appear as a
#: ``with`` context (``self._lock``, ``sess.lock``, ``self._gcond``,
#: module-level ``_DETAIL_STORE_LOCK`` ...).
_LOCKISH = ("lock", "cond", "mutex", "mu")


def dotted(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` for a pure Name/Attribute chain, else None (calls,
    subscripts and anything computed cannot name a stable lock)."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


def is_lockish(name: str) -> bool:
    tail = name.rsplit(".", 1)[-1].lower()
    return any(seg in tail for seg in _LOCKISH)


def node_locks(node) -> Set[str]:
    """Dotted lock names acquired at a ``with-enter`` node."""
    if node.label != "with-enter":
        return set()
    out = set()
    for item in node.stmt.items:
        d = dotted(item.context_expr)
        if d is not None and is_lockish(d):
            out.add(d)
    return out


def lock_regions(cfg: CFG) -> Dict[int, Set[str]]:
    """node idx → set of dotted lock names held *at* that node.

    Flood-fill from each lock-acquiring ``with-enter``'s non-exception
    successors (an ``__enter__`` that raised never took the lock),
    stopping at the ``with-exit`` markers of the same statement — the
    builder made one marker per escaping continuation, so exception and
    early-return paths end the region exactly where ``__exit__`` runs.
    """
    held: Dict[int, Set[str]] = {n.idx: set() for n in cfg.nodes}
    for enter in cfg.find("with-enter"):
        locks = node_locks(enter)
        if not locks:
            continue
        stmt = enter.stmt
        stack = [s for s, k in enter.succs if k != EXC]
        seen: Set[int] = set()
        while stack:
            n = stack.pop()
            if n.idx in seen:
                continue
            seen.add(n.idx)
            held[n.idx] |= locks
            if n.label == "with-exit" and n.stmt is stmt:
                continue  # lock released here — do not flood past it
            stack.extend(s for s, _k in n.succs)
    return held


def _stmt_comment_match(src: SourceFile, rx: re.Pattern, lo: int,
                        hi: int) -> Optional[str]:
    lines = src.text.splitlines()
    for i in range(lo, hi + 1):
        if 1 <= i <= len(lines):
            m = rx.search(lines[i - 1])
            if m:
                return m.group(1)
    return None


def guarded_decls(src: SourceFile,
                  tree: ast.AST) -> Dict[Tuple[str, str], str]:
    """``{(classname, attr): lockname}`` from ``# guarded_by(...)``
    comments on attribute declarations — ``self.attr = ...`` statements
    anywhere in the class body, plus class-level (dataclass-style)
    field declarations."""
    decls: Dict[Tuple[str, str], str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            hi = getattr(sub, "end_lineno", sub.lineno) or sub.lineno
            lock = _stmt_comment_match(src, _GUARDED_RE, sub.lineno, hi)
            if lock is None:
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    decls.setdefault((node.name, tgt.attr), lock)
                elif isinstance(tgt, ast.Name):
                    # class-level field (dataclass / class attribute)
                    decls.setdefault((node.name, tgt.id), lock)
    return decls


def fn_requires(src: SourceFile, fn: ast.FunctionDef) -> Set[str]:
    """Lock attribute names a ``# requires(...)`` comment on the def
    line (or a continuation line of a multi-line signature) declares as
    held by every caller."""
    hi = fn.body[0].lineno - 1 if fn.body else fn.lineno
    out: Set[str] = set()
    lines = src.text.splitlines()
    for i in range(fn.lineno, max(hi, fn.lineno) + 1):
        if 1 <= i <= len(lines):
            for m in _REQUIRES_RE.finditer(lines[i - 1]):
                out.add(m.group(1))
    return out


def walk_expr(root: ast.AST):
    """ast.walk over one evaluated expression/statement, not descending
    into lambdas or nested defs (their bodies run later, possibly on a
    different thread with different locks held)."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        if node is not root and isinstance(
                node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))

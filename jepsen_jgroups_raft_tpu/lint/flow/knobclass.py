"""Routing/verdict separation analyzer (graftgate rule (c), ISSUE 17).

PR 13/14 shipped fast paths behind JGRAFT_* gates under one contract:
a knob may choose *which engine* computes a verdict, *where* it is
persisted, or *how the fleet is operated* — it may never change the
verdict value itself. This analyzer makes that contract machine-checked
in two halves:

* **classification** (``flow-knob-unclassified``) — every knob the
  envknobs harvest finds must have a row in :data:`KNOB_CLASS`:
  ``routing`` (engine/tier selection, batching and fastpath gates,
  chunk/unroll/fanout shapes), ``durability`` (what is persisted and
  where), ``ops`` (fleet operation: workers, watchdogs, bench drivers,
  time budgets), or ``semantic`` (declared verdict-affecting — the
  class is deliberately EMPTY today; a future knob that genuinely
  changes verdict semantics must self-declare here and thereby exempt
  itself from the taint rule below, in writing).
* **taint** (``flow-knob-verdict``) — from every ``env_int`` /
  ``env_float`` / ``env_str`` / raw-environ call site of a ``routing``
  knob (unclassified knobs are treated as routing — conservative),
  values propagate through local assignments, module-level constants
  (cross-module by bare name: ``from mod import CONST`` re-binds the
  same name) and the return values of knob-*accessor* functions —
  functions whose return expression carries an env read or tainted
  constant directly, matched at bare-name call sites only (one level;
  transitive method-name matching conflates every ``get`` in the
  package). The sink is the verdict
  value itself: the value expression of a ``"valid?"`` key in a dict
  literal or a ``d["valid?"] = ...`` store. Control dependence is
  deliberately NOT tainted: ``if fastpath: <engine A> else: <engine
  B>`` is exactly what routing knobs are for — both engines must
  produce the same value, which the differential tests already pin.
  Data dependence is the violation: a verdict *computed from* a
  routing knob's value.

Pragma: ``# lint: allow(knob-verdict)`` on the sink line, with a
reason (none are needed on the shipped tree).

``verdict_taint(sources)`` additionally reports, for every knob of any
class, whether its value data-flows into a verdict expression — the
``verdict_reachable`` column of the ``--knob-registry`` artifact (all
false on the shipped tree; the CI assert keeps it that way).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from ..base import Finding, SourceFile
from .cfg import functions_of, walk_own
from .envknobs import _env_read, harvest

RULE_UNCLASS = "flow-knob-unclassified"
RULE_VERDICT = "flow-knob-verdict"
PRAGMA = "knob-verdict"

#: anchor file: the CLI walk triggers the whole-package analysis once
#: (platform.py defines the env_* helpers every knob read goes through).
ANCHOR = "platform.py"

ROUTING = "routing"
SEMANTIC = "semantic"
DURABILITY = "durability"
OPS = "ops"

#: every JGRAFT_* knob, classified (ISSUE 17 satellite 1). The table is
#: the contract: a new knob fails lint until a class is chosen for it,
#: and `semantic` membership is the only licence to influence a verdict.
KNOB_CLASS: Dict[str, str] = {
    # -- routing: which engine/tier computes the verdict --------------
    "JGRAFT_AUTOTUNE": ROUTING,
    "JGRAFT_AUTOTUNE_MIN_CELLS": ROUTING,
    "JGRAFT_AUTOTUNE_MIN_ROWS": ROUTING,
    "JGRAFT_AUTOTUNE_SAMPLES": ROUTING,
    "JGRAFT_AUTOTUNE_SAMPLE_ROWS": ROUTING,
    "JGRAFT_CERTIFY_BATCH": ROUTING,
    "JGRAFT_CERTIFY_BATCH_MIN": ROUTING,
    "JGRAFT_CERTIFY_BATCH_MIN_HIT": ROUTING,
    "JGRAFT_CERTIFY_BATCH_MIN_OBS": ROUTING,
    "JGRAFT_CYCLE_CONDENSE": ROUTING,
    "JGRAFT_CYCLE_KERNEL": ROUTING,
    "JGRAFT_CYCLE_MAX_OPS": ROUTING,
    "JGRAFT_CYCLE_TIER": ROUTING,
    "JGRAFT_CYCLE_TILE": ROUTING,
    "JGRAFT_DISTRIBUTED": ROUTING,
    "JGRAFT_DISTRIBUTED_AUTODETECT": ROUTING,
    "JGRAFT_DISTRIBUTED_VDEVS": ROUTING,
    "JGRAFT_ENCODE_VECTOR": ROUTING,
    "JGRAFT_GREEDY_BACKTRACK": ROUTING,
    "JGRAFT_GREEDY_CERTIFY": ROUTING,
    "JGRAFT_GROUP_DEVICES": ROUTING,
    "JGRAFT_HOIST": ROUTING,
    "JGRAFT_KERNEL": ROUTING,
    "JGRAFT_LIN_FASTPATH": ROUTING,
    "JGRAFT_LIN_FASTPATH_ABORT": ROUTING,
    "JGRAFT_LIN_FASTPATH_MIN_HIT": ROUTING,
    "JGRAFT_LIN_FASTPATH_MIN_OBS": ROUTING,
    # shared lin-fastpath gate dir (ISSUE 18): where gate records
    # replicate FROM decides which engine tries first — routing, like
    # the rest of the linfp family; verdicts never depend on it.
    "JGRAFT_LINFP_DIR": ROUTING,
    "JGRAFT_MACRO_EVENTS": ROUTING,
    "JGRAFT_MERGE_ALL": ROUTING,
    "JGRAFT_MERGE_LONG": ROUTING,
    "JGRAFT_PLATFORM_ROUTE": ROUTING,
    "JGRAFT_ROUTE_MIN_CELLS": ROUTING,
    "JGRAFT_SCAN_CHUNK": ROUTING,
    "JGRAFT_SCAN_UNROLL": ROUTING,
    # search-arm knobs route which CANDIDATES get generated/checked
    # (guided vs random parent/operator draw, mutation edit-seed
    # space); no knob touches how any candidate's verdict is computed
    "JGRAFT_SEARCH_EDIT_SPACE": ROUTING,
    "JGRAFT_SEARCH_GUIDED": ROUTING,
    "JGRAFT_SEGMENT": ROUTING,
    "JGRAFT_SERVICE_BATCH_WAIT_MS": ROUTING,
    "JGRAFT_SERVICE_MAX_BATCH_ROWS": ROUTING,
    "JGRAFT_STREAM_GREEDY_MAX_EVENTS": ROUTING,
    # -- durability: what is persisted, where, for how long -----------
    "JGRAFT_JOURNAL_GROUP_MS": DURABILITY,
    "JGRAFT_RESULT_STORE": DURABILITY,
    "JGRAFT_SERVICE_CLUSTER_DIR": DURABILITY,
    "JGRAFT_SERVICE_JOURNAL": DURABILITY,
    "JGRAFT_SERVICE_RETAIN": DURABILITY,
    # -- ops: fleet operation, bench drivers, budgets -----------------
    "JGRAFT_AUTOTUNE_STORE": OPS,
    "JGRAFT_BENCH_ALLOW_DEGRADED": OPS,
    "JGRAFT_BENCH_CONSISTENCY": OPS,
    "JGRAFT_BENCH_DEGRADED": OPS,
    "JGRAFT_BENCH_LIN_FASTPATH": OPS,
    "JGRAFT_BENCH_PLATFORM": OPS,
    "JGRAFT_BENCH_PROBE_RETRY_S": OPS,
    "JGRAFT_BENCH_PROBE_WINDOW_S": OPS,
    "JGRAFT_BENCH_REPS": OPS,
    "JGRAFT_BENCH_SAVE": OPS,
    "JGRAFT_BENCH_TARGET": OPS,
    "JGRAFT_BENCH_VDEVS": OPS,
    "JGRAFT_BENCH_WATCHDOG_S": OPS,
    "JGRAFT_CLIENT_KEEPALIVE": OPS,
    "JGRAFT_CLUSTER_SKEW_S": OPS,
    "JGRAFT_CLUSTER_TTL_S": OPS,
    "JGRAFT_DISTRIBUTED_TIMEOUT_MS": OPS,
    "JGRAFT_PROFILE_DIR": OPS,
    "JGRAFT_SEARCH_DIR": OPS,
    "JGRAFT_SEARCH_GENERATIONS": OPS,
    "JGRAFT_SEARCH_PLANTS": OPS,
    "JGRAFT_SEARCH_POP": OPS,
    "JGRAFT_SEARCH_SEED": OPS,
    "JGRAFT_SEARCH_SURVIVORS": OPS,
    "JGRAFT_SERVICE_ADVERTISE_URL": OPS,
    "JGRAFT_SERVICE_BENCH_CLIENTS": OPS,
    "JGRAFT_SERVICE_BENCH_FASTLANE": OPS,
    "JGRAFT_SERVICE_BENCH_GROUPAB": OPS,
    "JGRAFT_SERVICE_BENCH_HISTORIES": OPS,
    "JGRAFT_SERVICE_BENCH_INGESTAB": OPS,
    "JGRAFT_SERVICE_BENCH_OPS": OPS,
    "JGRAFT_SERVICE_BENCH_REQUESTS": OPS,
    "JGRAFT_SERVICE_CACHE": OPS,
    "JGRAFT_SERVICE_CRASH_CAP": OPS,
    "JGRAFT_SERVICE_QUEUE": OPS,
    "JGRAFT_SERVICE_REPLICA_ID": OPS,
    "JGRAFT_SERVICE_SHED_DEPTH": OPS,
    "JGRAFT_SERVICE_UDS": OPS,
    "JGRAFT_SERVICE_WATCHDOG_S": OPS,
    "JGRAFT_SERVICE_WORKERS": OPS,
    "JGRAFT_STREAM_BENCH_OPS": OPS,
    "JGRAFT_STREAM_BENCH_SEGMENTS": OPS,
    "JGRAFT_STREAM_BENCH_SESSIONS": OPS,
    "JGRAFT_STREAM_BYTES_PER_S": OPS,
    "JGRAFT_STREAM_IDLE_S": OPS,
    "JGRAFT_STREAM_RESIDENT_EVENTS": OPS,
    "JGRAFT_STREAM_SEGS_PER_S": OPS,
    "JGRAFT_STREAM_SESSIONS": OPS,
    "JGRAFT_SUITE_SCALE": OPS,
    # -- semantic: verdict-affecting by declaration (EMPTY: the PR-13/14
    # -- contract is that no knob changes verdict semantics) -----------
}

VERDICT_KEY = "valid?"


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    return rp.split("jepsen_jgroups_raft_tpu/", 1)[-1] == ANCHOR


def knob_class(name: str) -> str:
    return KNOB_CLASS.get(name, "unclassified")


# ----------------------------------------------------------- taint core


def _expr_knobs(expr: ast.AST, globals_t: Dict[str, Set[str]],
                locals_t: Dict[str, Set[str]],
                fns_t: Dict[str, Set[str]],
                tracked) -> Set[str]:
    """Knob names whose value data-flows into `expr`."""
    out: Set[str] = set()
    for sub in ast.walk(expr):
        r = _env_read(sub)
        if r is not None and tracked(r.name):
            out |= {r.name}
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            out |= locals_t.get(sub.id, set())
            out |= globals_t.get(sub.id, set())
        elif isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Name):
            # bare-name calls only: matching `x.get(...)` against every
            # method named `get` in the package conflates unrelated
            # definitions and poisons the whole call graph
            out |= fns_t.get(sub.func.id, set())
    return out


def _assign_targets(stmt: ast.AST) -> List[str]:
    tgts: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        tgts = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) and \
            stmt.value is not None:
        tgts = [stmt.target]
    out = []
    for t in tgts:
        for el in ast.walk(t):
            if isinstance(el, ast.Name):
                out.append(el.id)
    return out


def _fn_locals(fn: ast.AST, globals_t, fns_t, tracked
               ) -> Dict[str, Set[str]]:
    """Intra-function fixpoint of name -> tainting knob set."""
    locals_t: Dict[str, Set[str]] = {}
    for _ in range(8):  # assignment chains are short; bound the loop
        changed = False
        for stmt in walk_own(fn):
            if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                continue
            if stmt.value is None:
                continue
            knobs = _expr_knobs(stmt.value, globals_t, locals_t,
                                fns_t, tracked)
            if not knobs:
                continue
            for name in _assign_targets(stmt):
                if not knobs <= locals_t.get(name, set()):
                    locals_t[name] = locals_t.get(name, set()) | knobs
                    changed = True
        if not changed:
            break
    return locals_t


class _Surface:
    """Parsed whole-package view: module trees + the two cross-module
    taint maps (global constants and function return values)."""

    def __init__(self, sources: Dict[str, SourceFile], tracked):
        self.mods: List[Tuple[str, SourceFile, ast.AST]] = []
        self.globals_t: Dict[str, Set[str]] = {}
        self.fns_t: Dict[str, Set[str]] = {}
        self.errors: List[Finding] = []
        self.tracked = tracked
        for rel, src in sorted(sources.items()):
            try:
                tree = ast.parse(src.text)
            except SyntaxError as e:
                self.errors.append(Finding(src.path, e.lineno or 1,
                                           "parse-error", str(e)))
                continue
            self.mods.append((rel, src, tree))
        self._fixpoint()

    def _fixpoint(self) -> None:
        # pass 1 — module-level constants bound to knob reads, to a
        # cross-module fixpoint (a constant may re-export another).
        for _ in range(8):
            changed = False
            for _rel, _src, tree in self.mods:
                for stmt in tree.body:
                    if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                        continue
                    if getattr(stmt, "value", None) is None:
                        continue
                    knobs = _expr_knobs(stmt.value, self.globals_t, {},
                                        {}, self.tracked)
                    if not knobs:
                        continue
                    for name in _assign_targets(stmt):
                        if not knobs <= self.globals_t.get(name, set()):
                            self.globals_t[name] = \
                                self.globals_t.get(name, set()) | knobs
                            changed = True
            if not changed:
                break
        # pass 2 — knob-accessor functions: a return value carrying an
        # env read or tainted constant DIRECTLY (through locals, not
        # through further calls). Deliberately ONE level: transitive
        # call-taint over bare names conflates every `get`/`put`
        # method in the package and drowns the rule in false
        # positives, while the real conduits (`scan_chunk()`,
        # `lin_fastpath_on()`, `greedy_backtrack_budget()`) are all
        # direct accessors.
        for _rel, _src, tree in self.mods:
            for _cls, fn in functions_of(tree):
                locals_t = _fn_locals(fn, self.globals_t, {},
                                      self.tracked)
                ret: Set[str] = set()
                for stmt in walk_own(fn):
                    if isinstance(stmt, ast.Return) and \
                            stmt.value is not None:
                        ret |= _expr_knobs(stmt.value, self.globals_t,
                                           locals_t, {}, self.tracked)
                if ret:
                    self.fns_t[fn.name] = \
                        self.fns_t.get(fn.name, set()) | ret

    def verdict_sinks(self):
        """Yield (rel, src, line, value-expr, locals_t) for every
        verdict-constructing expression on the surface."""
        for rel, src, tree in self.mods:
            for _cls, fn in functions_of(tree):
                locals_t = _fn_locals(fn, self.globals_t, self.fns_t,
                                      self.tracked)
                for node in walk_own(fn):
                    if isinstance(node, ast.Dict):
                        for k, v in zip(node.keys, node.values):
                            if isinstance(k, ast.Constant) and \
                                    k.value == VERDICT_KEY:
                                yield rel, src, v.lineno, v, locals_t
                    elif isinstance(node, ast.Assign):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Subscript) and \
                                    isinstance(tgt.slice, ast.Constant) \
                                    and tgt.slice.value == VERDICT_KEY:
                                yield (rel, src, node.lineno,
                                       node.value, locals_t)


# --------------------------------------------------------------- driver


def verdict_taint(sources: Dict[str, SourceFile]) -> Dict[str, bool]:
    """knob -> does its value data-flow into any verdict expression?
    (all classes tracked; the --knob-registry verdict_reachable column)."""
    surface = _Surface(sources, tracked=lambda _n: True)
    reachable: Dict[str, bool] = {}
    for _rel, _src, _line, value, locals_t in surface.verdict_sinks():
        for knob in _expr_knobs(value, surface.globals_t, locals_t,
                                surface.fns_t, lambda _n: True):
            reachable[knob] = True
    return reachable


def analyze_sources(sources: Dict[str, SourceFile]) -> List[Finding]:
    findings: List[Finding] = []

    # half 1: every harvested knob is classified
    for rel, src in sorted(sources.items()):
        try:
            tree = ast.parse(src.text)
        except SyntaxError:
            continue  # _Surface reports the parse error below
        seen: Set[str] = set()
        for read in sorted(harvest(tree), key=lambda r: r.line):
            if read.name in seen or \
                    knob_class(read.name) != "unclassified":
                continue
            seen.add(read.name)
            if src.allowed(read.line, RULE_UNCLASS):
                continue
            findings.append(Finding(
                src.path, read.line, RULE_UNCLASS,
                f"{read.name} has no row in lint/flow/knobclass."
                "KNOB_CLASS — classify it as routing | semantic | "
                "durability | ops (semantic means verdict-affecting "
                "and exempts it from flow-knob-verdict, in writing)"))

    # half 2: routing-knob taint must never reach a verdict value
    def tracked(name: str) -> bool:
        return knob_class(name) in (ROUTING, "unclassified")

    surface = _Surface(sources, tracked=tracked)
    findings.extend(surface.errors)
    for _rel, src, line, value, locals_t in surface.verdict_sinks():
        knobs = _expr_knobs(value, surface.globals_t, locals_t,
                            surface.fns_t, tracked)
        if not knobs:
            continue
        if src.allowed(line, RULE_VERDICT) or src.allowed(line, PRAGMA):
            continue
        findings.append(Finding(
            src.path, line, RULE_VERDICT,
            "verdict value is computed from routing-class knob(s) "
            f"{', '.join(sorted(knobs))} — routing knobs choose which "
            "engine runs, never what it decides (PR-13/14 contract); "
            "reclassify the knob as `semantic` in KNOB_CLASS if the "
            "dependence is intended, otherwise derive the verdict "
            "from the history alone"))
    return findings


def _load_package(anchor: Path) -> Dict[str, SourceFile]:
    pkg = anchor.resolve().parent
    out: Dict[str, SourceFile] = {}
    for f in sorted(pkg.rglob("*.py")):
        out[str(f.relative_to(pkg))] = SourceFile.load(f)
    return out


def analyze_file(path) -> List[Finding]:
    return analyze_sources(_load_package(Path(path)))

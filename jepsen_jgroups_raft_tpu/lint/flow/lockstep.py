"""Cross-engine lock-step tripwire (graftgate satellite 2, ISSUE 17).

``consistency.certify_encoded`` (the one-shot greedy/backtrack
certifier) and ``consistency.StreamingCertifier`` (its resumable twin)
duplicate the PR-9 commit rules BY HAND — the one-shot stays a
hand-tuned closure loop because it is the measured hot path (see the
LOCK-STEP CONTRACT note on the class). Until now the only tripwire was
the differential test, which needs a history that happens to exercise
the drifted rule. This rule pins the duplicated structure statically:
edit one side's commit rules without the other and lint fails on every
run, witness or not.

Three pinned pairs, compared as normalized AST (``self._x`` reads
rewritten to bare ``x`` — the method side rebinds its attributes to
locals of exactly those names):

* **sweep** — the eager read-only commit test: every ``if`` test of
  the nested ``sweep`` closure vs ``StreamingCertifier._sweep``.
* **candidates** — the commit-option constants and value-guided
  ordering: every ``out.append(...)`` argument (the ``(-1, 0, 0, -1,
  None)`` direct-commit row and the ranked candidate row) plus the
  ``out.sort(key=...)`` ranking lambda, in order, of the nested
  ``candidates`` closure vs ``StreamingCertifier._candidates``.
* **scan** — the choice-point shape and helper wiring of the main
  loops (``certify_encoded`` body vs ``StreamingCertifier._scan``):
  every ``stack.append([...])`` snapshot row and every assignment
  whose value is a ``sweep(...)``/``candidates(...)`` call.

The guide-mask plumbing around those pins legitimately differs
(closure arrays vs instance state) and is deliberately NOT compared.
``flow-lockstep-anchor`` fires loudly if either side's function is
missing or a pair extracts nothing — a refactor that moves the code
must move this rule's anchors with it, not silently disable it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..base import Finding, SourceFile
from .cfg import functions_of, walk_own
from . import taint

RULE_DRIFT = "flow-lockstep-drift"
RULE_ANCHOR = "flow-lockstep-anchor"
PRAGMA = "lockstep"

ANCHOR = "checker/consistency.py"
ONESHOT = "certify_encoded"
TWIN = "StreamingCertifier"


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    return rp.split("jepsen_jgroups_raft_tpu/", 1)[-1] == ANCHOR


class _Normalize(ast.NodeTransformer):
    """``self._x`` / ``self.x`` -> ``x``: the streaming methods rebind
    their attributes to locals named exactly like the one-shot's."""

    def visit_Attribute(self, node: ast.Attribute):
        self.generic_visit(node)
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return ast.copy_location(
                ast.Name(id=node.attr.lstrip("_"), ctx=node.ctx), node)
        return node


def _sig(node: ast.AST) -> str:
    return ast.dump(_Normalize().visit(ast.parse(
        ast.unparse(node), mode="eval").body))


def _if_tests(fn: ast.AST) -> List[Tuple[int, str, str]]:
    # walk_own yields stack order, not document order — sort by line so
    # both sides' elements pair up positionally.
    return sorted((n.lineno, "if-test", _sig(n.test))
                  for n in walk_own(fn) if isinstance(n, ast.If))


def _append_sort(fn: ast.AST) -> List[Tuple[int, str, str]]:
    out = []
    for n in walk_own(fn):
        if not isinstance(n, ast.Call):
            continue
        name = taint.call_name(n)
        if name == "append" and n.args:
            out.append((n.lineno, "append", _sig(n.args[0])))
        elif name == "sort" and n.keywords:
            for kw in n.keywords:
                if kw.arg == "key":
                    out.append((n.lineno, "sort-key", _sig(kw.value)))
    return sorted(out)


def _scan_shape(fn: ast.AST) -> List[Tuple[int, str, str]]:
    out = []
    for n in walk_own(fn):
        if isinstance(n, ast.Call) and taint.call_name(n) == "append" \
                and n.args and isinstance(n.args[0], ast.List):
            out.append((n.lineno, "snapshot", _sig(n.args[0])))
        elif isinstance(n, ast.Assign) and \
                isinstance(n.value, ast.Call):
            callee = taint.call_name(n.value).lstrip("_")
            if callee in ("sweep", "candidates"):
                out.append((n.lineno, f"{callee}-call", _sig(n.value)))
    return sorted(out)


#: (pair name, one-shot function, twin method, extractor)
PAIRS = (
    ("sweep", "sweep", "_sweep", _if_tests),
    ("candidates", "candidates", "_candidates", _append_sort),
    ("scan", ONESHOT, "_scan", _scan_shape),
)


def _functions(tree: ast.AST) -> Dict[Tuple[Optional[str], str], ast.AST]:
    return {(cls.name if cls is not None else None, fn.name): fn
            for cls, fn in functions_of(tree)}


def analyze_source(src: SourceFile) -> List[Finding]:
    # The CLI analyzes explicit single-file arguments with EVERY
    # requested analyzer; this rule is anchored to one file's twin
    # functions, so stay quiet on anything that is neither the anchor
    # nor a fixture mentioning the twins (missing-anchor loudness would
    # otherwise fire on every `lint somefile.py` invocation).
    if not (str(src.path).replace("\\", "/").endswith(ANCHOR)
            or ONESHOT in src.text or TWIN in src.text):
        return []
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    fns = _functions(tree)
    findings: List[Finding] = []
    for pair, a_name, b_name, extract in PAIRS:
        a = fns.get((None, a_name))
        b = fns.get((TWIN, b_name))
        if a is None or b is None:
            missing = a_name if a is None else f"{TWIN}.{b_name}"
            findings.append(Finding(
                src.path, 1, RULE_ANCHOR,
                f"lock-step anchor {missing}() not found in {ANCHOR} — "
                "the duplicated-certifier contract moved; update "
                "lint/flow/lockstep.py's PAIRS with it"))
            continue
        sa, sb = extract(a), extract(b)
        if not sa or not sb:
            findings.append(Finding(
                src.path, min(a.lineno, b.lineno), RULE_ANCHOR,
                f"lock-step pair '{pair}' extracted no comparable "
                "structure — the commit-rule shape this rule pins "
                "changed; re-anchor lint/flow/lockstep.py"))
            continue
        if len(sa) != len(sb):
            line = sb[min(len(sa), len(sb)) - 1][0] if sb else b.lineno
            if not (src.allowed(line, RULE_DRIFT) or
                    src.allowed(line, PRAGMA)):
                findings.append(Finding(
                    src.path, line, RULE_DRIFT,
                    f"lock-step pair '{pair}': {a_name}() pins "
                    f"{len(sa)} commit-rule element(s) but "
                    f"{TWIN}.{b_name}() has {len(sb)} — the two "
                    "certifiers' commit rules are duplicated BY HAND "
                    "and must change together (PR-14 contract)"))
            continue
        for (la, ka, da), (lb, kb, db) in zip(sa, sb):
            if ka == kb and da == db:
                continue
            if src.allowed(lb, RULE_DRIFT) or src.allowed(lb, PRAGMA):
                continue
            findings.append(Finding(
                src.path, lb, RULE_DRIFT,
                f"lock-step pair '{pair}': the {kb} here drifted from "
                f"{a_name}()'s {ka} at line {la} — the one-shot and "
                "streaming certifiers duplicate the PR-9 commit rules "
                "BY HAND; mirror the edit in both (or re-anchor "
                "lint/flow/lockstep.py if the contract itself moved)"))
    return findings


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))

"""Degraded-result quarantine analyzer (graftgate rule (b), ISSUE 17).

The never-persist rule (doc/checker-design.md §16): a result stamped
``platform-degraded`` (the ISSUE-6 honesty stamp — the platform the
verdict ran on was not the platform the caller asked for) must never
reach a durable or shared surface, because every one of them replays
the stamp onto a healed platform: the LRU result cache, the
:class:`ResultStore` (``results/`` and ``detail/`` publishes), and WAL
terminal records. This analyzer walks every such sink in the service
and parallel tiers and demands a proof the value is clean:

* **guard dominance** — the sink is dominated by a degraded guard of
  clean polarity (``not any("platform-degraded" in r ...)`` on the
  TRUE arm, ``if is_degraded(x): return`` fall-through on the FALSE
  arm — :func:`taint.clean_edges`);
* **self-gating callee** — ``.put`` / ``.put_detail`` on a ``store``
  receiver is clean because ``ResultStore.put``/``put_detail``
  themselves refuse degraded input before ``_publish``. That gate is
  VERIFIED, not assumed: this analyzer re-proves the dominance inside
  store.py on every run, and if the gate is edited away, every call
  site that leaned on it fires along with the gate itself;
* **clean source** — a value read back from the store
  (``x = ...store.get(...)``) is clean by the store's own gate, so
  warming the LRU from it needs no local guard;
* **pragma** — ``# lint: allow(degraded)`` with a reason, for sinks
  whose cleanliness is structural but out of this analyzer's sight
  (daemon's journal-replay warm: WAL terminals never persist degraded
  results, so replayed results are clean by construction).

Sinks: ``<...cache...>.put(...)``, ``<...store...>.put/put_detail``,
``self._publish("results"|"detail", ...)`` (store.py's raw writer) and
``rec["results"] = ...`` in journal.py's record encoders (the WAL
terminal / stream-fin payload).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set

from ..base import Finding, SourceFile
from .cfg import build_cfg, functions_of
from . import taint

RULE = "flow-degraded-sink"
PRAGMA = "degraded"

#: anchor file: the CLI walk triggers the whole-tier analysis once.
ANCHOR = "service/daemon.py"

SCAN = (
    "service/daemon.py",
    "service/scheduler.py",
    "service/journal.py",
    "service/store.py",
    "service/cluster.py",
    "service/stream.py",
    "parallel/distributed.py",
)

STORE_FILE = "service/store.py"
JOURNAL_FILE = "service/journal.py"
_GATED_METHODS = ("put", "put_detail")


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    return rp.split("jepsen_jgroups_raft_tpu/", 1)[-1] == ANCHOR


# -------------------------------------------------------------- sinks


def _recv(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute):
        return taint.dotted(call.func.value) or ""
    return ""


def _is_cache_put(call: ast.Call) -> bool:
    return taint.call_name(call) == "put" and "cache" in _recv(call)


def _is_store_put(call: ast.Call) -> bool:
    return taint.call_name(call) in _GATED_METHODS and \
        "store" in _recv(call)


def _is_raw_publish(call: ast.Call) -> bool:
    if taint.call_name(call) != "_publish" or not call.args:
        return False
    kind = call.args[0]
    return isinstance(kind, ast.Constant) and \
        kind.value in ("results", "detail")


def _clean_source_names(fn: ast.AST) -> Set[str]:
    """Names assigned from a store read-back (`x = ...store.get(...)`):
    the store never holds degraded entries, so x is clean."""
    out: Set[str] = set()
    for node in taint.walk_own(fn):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call)):
            continue
        call = node.value
        if taint.call_name(call) == "get" and "store" in _recv(call):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _value_arg(call: ast.Call) -> Optional[ast.AST]:
    """The stored-value argument of a put-family call (last positional
    — put(key, value) / put_detail(key, value))."""
    return call.args[-1] if len(call.args) >= 2 else None


# ----------------------------------------------------- store self-gate


def _store_gate_ok(store_src: SourceFile) -> Dict[str, bool]:
    """method name -> is every path from entry to its _publish call
    dominated by a clean-polarity degraded guard?"""
    out = {m: False for m in _GATED_METHODS}
    try:
        tree = ast.parse(store_src.text)
    except SyntaxError:
        return out
    for _cls, fn in functions_of(tree):
        if fn.name not in _GATED_METHODS:
            continue
        cfg = build_cfg(fn)
        publishes = [n for n in taint.walk_own(fn)
                     if isinstance(n, ast.Call) and
                     taint.call_name(n) == "_publish"]
        ok = bool(publishes)
        for call in publishes:
            if not taint.dominated(cfg, call, set(),
                                   lambda t, _w: taint.clean_edges(t)):
                ok = False
        out[fn.name] = ok
    return out


# --------------------------------------------------------------- driver


def analyze_sources(sources: Dict[str, SourceFile]) -> List[Finding]:
    store_src = sources.get(STORE_FILE)
    gate_ok = _store_gate_ok(store_src) if store_src is not None \
        else {m: False for m in _GATED_METHODS}

    findings: List[Finding] = []
    for rel, src in sorted(sources.items()):
        try:
            tree = ast.parse(src.text)
        except SyntaxError as e:
            findings.append(Finding(src.path, e.lineno or 1,
                                    "parse-error", str(e)))
            continue
        is_store = rel.endswith("store.py")
        is_journal = rel.endswith("journal.py")
        for _cls, fn in functions_of(tree):
            cfg = build_cfg(fn)
            clean_names = _clean_source_names(fn)

            def guarded(node) -> bool:
                return taint.dominated(
                    cfg, node, set(),
                    lambda t, _w: taint.clean_edges(t))

            for node in taint.walk_own(fn):
                sink = kind = None
                if isinstance(node, ast.Call):
                    if _is_cache_put(node):
                        sink, kind = node, "LRU cache put"
                    elif _is_store_put(node) and not is_store:
                        if gate_ok.get(taint.call_name(node)):
                            continue  # verified self-gating callee
                        sink, kind = node, (
                            f"ResultStore.{taint.call_name(node)} "
                            "whose degraded self-gate is missing")
                    elif _is_raw_publish(node) and is_store:
                        sink, kind = node, "raw store publish"
                elif isinstance(node, ast.Assign) and is_journal:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript) and \
                                isinstance(tgt.slice, ast.Constant) \
                                and tgt.slice.value == "results":
                            sink, kind = node, "WAL record results field"
                if sink is None:
                    continue
                line = sink.lineno
                if src.allowed(line, RULE) or src.allowed(line, PRAGMA):
                    continue
                if isinstance(sink, ast.Call):
                    val = _value_arg(sink)
                    if isinstance(val, ast.Name) and \
                            val.id in clean_names:
                        continue  # store read-back: clean by the gate
                if guarded(sink):
                    continue
                findings.append(Finding(
                    src.path, line, RULE,
                    f"{kind} is reachable without a degraded-result "
                    "guard on the path — a platform-degraded verdict "
                    "would be persisted/shared and replayed onto a "
                    "healed platform (§16 never-persist rule); guard "
                    "with `not any(\"platform-degraded\" in r ...)` "
                    "/ is_degraded(), or record why the value is "
                    "structurally clean with `# lint: allow(degraded)`"))
    return findings


def _load_tier(anchor: Path) -> Dict[str, SourceFile]:
    pkg = anchor.resolve().parents[1]
    return {rel: SourceFile.load(pkg / rel)
            for rel in SCAN if (pkg / rel).exists()}


def analyze_file(path) -> List[Finding]:
    return analyze_sources(_load_tier(Path(path)))

"""Lock-discipline analyzer: guarded attributes accessed off-guard.

The service tier's thread-safety story is annotation + convention:
mutable shared state (daemon registries, scheduler queues, stream
session tables, journal group-commit state) is declared with a trailing
``# guarded_by(lockname)`` comment, and every access is supposed to sit
inside a ``with self.lockname:`` region (or in a method whose callers
hold it, declared ``# requires(lockname)``). The last five hardening
rounds each found a real violation of exactly this convention by review
— shutdown/submit races, stats read outside the daemon lock, a torn
inflight-table read. This analyzer makes the convention checkable: it
resolves lock regions on the CFG (locks.lock_regions — so try/finally,
early return and exception paths are all modeled) and flags any
read/write of a guarded attribute at a node where the declaring
object's lock is not held.

Model (biased against false positives, like resource.py):

* ``self.attr`` accesses are checked only inside the *declaring* class
  (another class's same-named attribute is a different field).
* ``obj.attr`` cross-object accesses are checked when ``attr`` is
  declared guarded by exactly one class in the file and ``obj`` is not
  a local born from a constructor call in the same function (a freshly
  constructed object is not yet shared).
* ``__init__`` bodies are exempt for ``self`` — construction happens
  before the object escapes to other threads.
* Reads via snapshot methods, deliberate racy fast-paths etc. carry
  ``# lint: allow(unguarded)`` with a reason comment.

Rule: ``flow-unguarded-access`` (pragma alias ``unguarded``). Scan set:
``service/``, ``parallel/``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..base import Finding, SourceFile
from .cfg import build_cfg, functions_of, own_exprs
from .locks import (dotted, fn_requires, guarded_decls, lock_regions,
                    walk_expr)

RULE = "flow-unguarded-access"

SCAN_PREFIXES = ("service/", "parallel/")


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp.startswith(SCAN_PREFIXES)


def _constructed_locals(fn: ast.FunctionDef) -> Set[str]:
    """Names assigned from a constructor-looking call (capitalized
    callee) in this function: the object is local-born, not shared."""
    out: Set[str] = set()
    for node in walk_expr(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = node.value.func
            name = callee.id if isinstance(callee, ast.Name) else (
                callee.attr if isinstance(callee, ast.Attribute) else "")
            if name[:1].isupper():
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def _analyze_function(src: SourceFile, clsname: str, fn: ast.FunctionDef,
                      decls: Dict[Tuple[str, str], str],
                      by_attr: Dict[str, List[Tuple[str, str]]]
                      ) -> List[Finding]:
    init = fn.name == "__init__"
    cfg = build_cfg(fn)
    held = lock_regions(cfg)
    required = fn_requires(src, fn)
    born = _constructed_locals(fn)
    findings: List[Finding] = []
    reported: Set[Tuple[int, str]] = set()
    for node in cfg.nodes:
        for expr in own_exprs(node):
            for sub in walk_expr(expr):
                if not isinstance(sub, ast.Attribute):
                    continue
                base = dotted(sub.value)
                if base is None:
                    continue
                attr = sub.attr
                if base == "self":
                    if init:
                        continue
                    lock = decls.get((clsname, attr))
                    if lock is None:
                        continue
                    if lock in required:
                        continue
                else:
                    owners = by_attr.get(attr, [])
                    if len(owners) != 1:
                        continue
                    if base.split(".", 1)[0] in born:
                        continue
                    lock = owners[0][1]
                if f"{base}.{lock}" in held[node.idx]:
                    continue
                line = getattr(sub, "lineno", node.line)
                key = (line, f"{base}.{attr}")
                if key in reported:
                    continue
                reported.add(key)
                if src.allowed(line, RULE) or src.allowed(line, "unguarded"):
                    continue
                findings.append(Finding(
                    src.path, line, RULE,
                    f"`{base}.{attr}` is guarded_by({lock}) but accessed "
                    f"without holding `{base}.{lock}` — wrap the access in "
                    f"`with {base}.{lock}:`, mark the method "
                    f"`# requires({lock})` if callers hold it, or record "
                    "the deliberate race with `# lint: allow(unguarded)` "
                    "+ a reason"))
    return findings


def analyze_source(src: SourceFile) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    decls = guarded_decls(src, tree)
    if not decls:
        return []
    by_attr: Dict[str, List[Tuple[str, str]]] = {}
    for (cls, attr), lock in decls.items():
        by_attr.setdefault(attr, []).append((cls, lock))
    findings: List[Finding] = []
    for cls, fn in functions_of(tree):
        findings.extend(_analyze_function(
            src, cls.name if cls is not None else "", fn, decls, by_attr))
    return findings


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))

"""Tier-stamp totality analyzer (graftgate rule (d), ISSUE 17).

PR 13's attribution contract: every terminal verdict records which
tier of the escalation ladder decided it (``decided-tier``), so the
fleet's tier counters, the bench's decided-tiers summary and the
incident playbook in doc/running.md stay trustworthy as new tiers
land. The invariant is *totality* — a construction site someone adds
next year must not silently ship unstamped rows.

Every dict literal carrying a ``"valid?"`` key on the verdict surface
(checker ladder, host ladder, fast lanes, stream mid-run/finish,
distributed demux) must satisfy one of:

* the literal itself carries a ``"decided-tier"`` key;
* the literal carries an ``"error"`` key — an undecided/error record:
  no tier decided anything, and stamping one would lie to the
  counters;
* the literal carries a ``"results"`` key — an aggregate envelope
  whose per-row results are stamped individually;
* the literal is bound to a local name and EVERY CFG path from the
  construction to the function's normal exit passes a
  ``name["decided-tier"] = ...`` / ``name.setdefault("decided-tier",
  ...)`` stamp (the post-assignment idiom; paths that end in a raise
  never return the dict and are exempt);
* a reasoned ``# lint: allow(no-tier)`` pragma.

Otherwise: ``flow-tier-unstamped``. The rule found a real one on the
shipped tree — the distributed demux stub (`_remote_result`) returned
wire-exact verdicts with no tier attribution, undercounting remote
rows in every fleet tier summary.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional

from ..base import Finding, SourceFile
from .cfg import build_cfg, functions_of, reach, walk_own
from . import taint

RULE = "flow-tier-unstamped"
PRAGMA = "no-tier"

VERDICT_KEY = "valid?"
TIER_KEY = "decided-tier"
#: keys whose presence in the same literal discharges the obligation.
EXEMPT_KEYS = ("error", "results")

#: anchor file: the CLI walk triggers the whole-surface analysis once.
ANCHOR = "checker/linearizable.py"

SCAN = (
    "checker/linearizable.py",
    "service/scheduler.py",
    "service/stream.py",
    "parallel/distributed.py",
)


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    return rp.split("jepsen_jgroups_raft_tpu/", 1)[-1] == ANCHOR


def _keys(d: ast.Dict) -> List[str]:
    return [k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)]


def _bound_name(fn: ast.AST, lit: ast.Dict) -> Optional[str]:
    """The local name the literal is directly assigned to, if any."""
    for stmt in walk_own(fn):
        if isinstance(stmt, ast.Assign) and stmt.value is lit:
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    return tgt.id
    return None


def _is_stamp(stmt: ast.AST, name: str) -> bool:
    """``name["decided-tier"] = ...`` or ``name.setdefault(
    "decided-tier", ...)``."""
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == name and \
                    isinstance(tgt.slice, ast.Constant) and \
                    tgt.slice.value == TIER_KEY:
                return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        call = stmt.value
        if taint.call_name(call) == "setdefault" and \
                isinstance(call.func, ast.Attribute) and \
                isinstance(call.func.value, ast.Name) and \
                call.func.value.id == name and call.args and \
                isinstance(call.args[0], ast.Constant) and \
                call.args[0].value == TIER_KEY:
            return True
    return False


def _stamped_on_all_paths(cfg, fn: ast.AST, lit: ast.Dict) -> bool:
    name = _bound_name(fn, lit)
    if name is None:
        return False
    starts = taint.nodes_containing(cfg, lit)
    if not starts:
        return False
    stamps = {n.idx for n in cfg.nodes
              if n.stmt is not None and _is_stamp(n.stmt, name)}

    def stop(node, _kind):
        if node.idx in stamps:
            return "kill"
        if node is cfg.exit:
            return "report"  # normal return with the stamp pending
        return None

    return not reach(cfg, starts, stop)


def analyze_sources(sources: Dict[str, SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for rel in SCAN:
        src = sources.get(rel)
        if src is None:
            continue
        try:
            tree = ast.parse(src.text)
        except SyntaxError as e:
            findings.append(Finding(src.path, e.lineno or 1,
                                    "parse-error", str(e)))
            continue
        for _cls, fn in functions_of(tree):
            cfg = None
            for node in walk_own(fn):
                if not isinstance(node, ast.Dict):
                    continue
                keys = _keys(node)
                if VERDICT_KEY not in keys:
                    continue
                if TIER_KEY in keys or any(k in keys
                                           for k in EXEMPT_KEYS):
                    continue
                line = node.lineno
                if src.allowed(line, RULE) or src.allowed(line, PRAGMA):
                    continue
                if cfg is None:
                    cfg = build_cfg(fn)
                if _stamped_on_all_paths(cfg, fn, node):
                    continue
                findings.append(Finding(
                    src.path, line, RULE,
                    "terminal result constructed without a "
                    "`decided-tier` stamp on some path to return — "
                    "PR-13 tier attribution must stay total (fleet "
                    "counters and the decided-tiers summary undercount "
                    "otherwise); stamp the literal, stamp the bound "
                    "name on every path, keep an `error` key on "
                    "undecided records, or justify with "
                    "`# lint: allow(no-tier)`"))
    return findings


def _load_surface(anchor: Path) -> Dict[str, SourceFile]:
    pkg = anchor.resolve().parents[1]
    return {rel: SourceFile.load(pkg / rel)
            for rel in SCAN if (pkg / rel).exists()}


def analyze_file(path) -> List[Finding]:
    return analyze_sources(_load_surface(Path(path)))

"""graftcheck: the CFG/dataflow tier of the lint suite (ISSUE 2 tentpole).

PR 1's graftlint analyzers are pattern-level — one AST shape, one
finding. The invariants this package polices are *path* properties that
pattern matching cannot express:

* ``kernel_contract`` — Pallas/launch shape arithmetic (BlockSpec, grid,
  out_shape, VMEM footprint) holds for every legal symbol binding, so a
  kernel misconfiguration is a lint error before it is a runtime XLA
  failure on (paid, tunneled) TPU time.
* ``heal`` — every nemesis path that injects a fault reaches the
  matching heal/restore (or registers the affliction for teardown) on
  *all* exits including exception edges; deliberate unhealed faults
  carry ``# lint: allow(unhealed)``.
* ``resource`` — acquire/release pairs (connections, popen handles,
  file handles, tempdirs) balance across exception paths in the deploy
  and runner tiers.

``cfg`` builds the statement-level control-flow graph (branches, loops,
try/except/finally, with, early returns, exception edges) that ``heal``
and ``resource`` run their path searches over; ``interp`` is the
restricted AST evaluator ``kernel_contract`` uses to execute shape
arithmetic symbolically over sampled bindings.
"""

from . import cfg, heal, interp, kernel_contract, resource  # noqa: F401

"""Crash-consistency protocol analyzer for the service tier.

checker-design.md §11/§13 promise three durability shapes, and graftd's
crash-recovery tests only exercise the crashes someone thought of. This
analyzer enforces the shapes statically, on the CFG, so a refactor that
quietly drops an fsync or converts an atomic publish into an in-place
write fails lint before it fails a power-cut:

* ``flow-fsync-before-ack`` — in any ``service/`` function, every
  non-exception path from a file-handle ``.write(...)`` to the
  function's return must pass ``os.fsync`` (§11: the WAL record is on
  disk before the caller can ack a 2xx). Handles are recognized
  structurally: locals born from builtin ``open(...)`` (including
  ``with open(...) as fh``), locals returned by a ``*handle*()``
  helper, and ``self._fh``-style attributes. A branch on a parameter
  whose name contains ``fsync`` is the caller explicitly opting out of
  durability for this record — its False arm is not a violation (the
  group-commit leader/member split in journal.py keeps the covering
  fsync on the leader's write path, which is the path with the write).
* ``flow-inplace-publish`` — any write-mode ``open()`` in ``service/``
  must be either append-mode (the WAL family: torn tails are handled
  by replay, §11) or a temp file whose name is later passed to
  ``os.replace``/``os.rename`` in the same function (§13: cross-process
  publishes are atomic; ownership claims are ``os.rename``). An
  in-place ``open(final_path, "w")`` is a torn-read window for every
  other process.
* ``flow-nonatomic-publish`` — ``shutil.move/copy*`` in ``service/``:
  neither atomic nor fsynced; publishes and claims must use the
  replace/rename idioms instead.

Deliberate exceptions (best-effort trace files, startup-time
migrations) carry ``# lint: allow(inplace-publish)`` /
``# lint: allow(nonatomic-publish)`` with a reason. Pragma alias
``fsync`` covers the first rule.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..base import Finding, SourceFile
from .cfg import EXC, FALSE, NORMAL, TRUE, build_cfg, functions_of, own_exprs, reach
from .locks import walk_expr

RULE_FSYNC = "flow-fsync-before-ack"
RULE_INPLACE = "flow-inplace-publish"
RULE_SHUTIL = "flow-nonatomic-publish"

SCAN_PREFIXES = ("service/",)

_SHUTIL_CALLS = {"move", "copy", "copy2", "copyfile", "copytree"}


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp.startswith(SCAN_PREFIXES)


# ------------------------------------------------------------ predicates


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _dotted(expr: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


def _is_file_born(value: ast.AST) -> bool:
    """Does this expression yield a real file handle? builtin open()
    or a *handle* helper (journal's `fh = self._handle()`)."""
    if not isinstance(value, ast.Call):
        return False
    name = _call_name(value)
    return name == "open" or "handle" in name.lower()


def _file_handles(fn: ast.FunctionDef) -> Set[str]:
    """Dotted names of file handles live in this function."""
    out: Set[str] = set()
    for node in walk_expr(fn):
        if isinstance(node, ast.Assign) and _is_file_born(node.value):
            for tgt in node.targets:
                d = _dotted(tgt)
                if d:
                    out.add(d)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None and \
                        _is_file_born(item.context_expr):
                    d = _dotted(item.optional_vars)
                    if d:
                        out.add(d)
    return out


def _writes_at(node, handles: Set[str]) -> List[int]:
    """Lines of handle.write(...) calls evaluated at this node."""
    out = []
    for expr in own_exprs(node):
        for sub in walk_expr(expr):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "write":
                recv = _dotted(sub.func.value)
                if recv is not None and (
                        recv in handles or recv.endswith("_fh")):
                    out.append(sub.lineno)
    return out


def _calls_fsync(node) -> bool:
    for expr in own_exprs(node):
        for sub in walk_expr(expr):
            if isinstance(sub, ast.Call) and _call_name(sub) == "fsync":
                return True
    return False


def _fsync_optout_guard(node) -> Optional[set]:
    """An `if <param-containing-fsync>:` branch: the False arm means
    the caller did not request durability for this record — only the
    True arm owes an fsync."""
    if node.label != "if":
        return None
    test = node.stmt.test
    names = {n.id for n in ast.walk(test) if isinstance(n, ast.Name)}
    if any("fsync" in n.lower() for n in names):
        return {TRUE}
    return None


# -------------------------------------------------- fsync-before-return


def _check_fsync(src: SourceFile, fn: ast.FunctionDef) -> List[Finding]:
    handles = _file_handles(fn)
    cfg = build_cfg(fn)
    findings: List[Finding] = []
    for node in cfg.nodes:
        for line in _writes_at(node, handles):
            if src.allowed(line, RULE_FSYNC) or src.allowed(line, "fsync"):
                continue

            def stop(n, kind_in):
                if _calls_fsync(n):
                    return "kill"
                if n is cfg.exit:
                    return "report"
                if n is cfg.raise_exit:
                    # exception escape = no ack to protect
                    return "kill"
                guard = _fsync_optout_guard(n)
                if guard is not None:
                    return guard
                # durability must hold on the SUCCESS path; exception
                # edges lead to error returns, which ack nothing
                return {NORMAL, TRUE, FALSE}

            starts = [s for s, k in node.succs if k != EXC]
            if reach(cfg, starts, stop):
                findings.append(Finding(
                    src.path, line, RULE_FSYNC,
                    "file write can reach the function's return without "
                    "an os.fsync on the same path — §11 requires the "
                    "record durable before the caller can ack; fsync "
                    "before returning (or route through the group-commit "
                    "path, whose leader fsyncs the batch)"))
    return findings


# ------------------------------------------------------ publish protocol


def _open_mode(call: ast.Call) -> str:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return "r"


def _replaced_names(fn: ast.FunctionDef) -> Set[str]:
    """Dotted names passed as the SOURCE of os.replace/os.rename in
    this function — i.e. temp files that get atomically published."""
    out: Set[str] = set()
    for node in walk_expr(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("replace", "rename") and node.args:
            base = _dotted(node.func.value)
            if base == "os":
                d = _dotted(node.args[0])
                if d:
                    out.add(d)
    return out


def _check_publish(src: SourceFile, fn: ast.FunctionDef) -> List[Finding]:
    findings: List[Finding] = []
    replaced = _replaced_names(fn)
    for node in walk_expr(fn):
        if isinstance(node, ast.Call) and _call_name(node) == "open" and \
                isinstance(node.func, ast.Name) and node.args:
            mode = _open_mode(node)
            writing = any(c in mode for c in "wx+")
            appending = "a" in mode and not writing
            if not writing or appending:
                continue  # reads and WAL-style appends are fine
            line = node.lineno
            if src.allowed(line, RULE_INPLACE) or \
                    src.allowed(line, "inplace-publish"):
                continue
            target = _dotted(node.args[0])
            if target is not None and target in replaced:
                continue  # temp-write + atomic replace/rename
            findings.append(Finding(
                src.path, line, RULE_INPLACE,
                f"write-mode open({mode!r}) is not a temp-write published "
                "via os.replace/os.rename in this function — §13 requires "
                "cross-process publishes to be atomic (write `<final>.tmp`,"
                " fsync, then os.replace) so readers never see a torn "
                "file; truly-local best-effort files need "
                "`# lint: allow(inplace-publish)` + a reason"))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SHUTIL_CALLS and \
                _dotted(node.func.value) == "shutil":
            line = node.lineno
            if src.allowed(line, RULE_SHUTIL) or \
                    src.allowed(line, "nonatomic-publish"):
                continue
            findings.append(Finding(
                src.path, line, RULE_SHUTIL,
                f"shutil.{node.func.attr} is neither atomic nor fsynced — "
                "publishes must be temp-write + os.replace, ownership "
                "claims os.rename (§13); startup-time migrations that "
                "predate concurrency need "
                "`# lint: allow(nonatomic-publish)` + a reason"))
    return findings


# --------------------------------------------------------------- driver


def analyze_source(src: SourceFile) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    findings: List[Finding] = []
    for _cls, fn in functions_of(tree):
        findings.extend(_check_fsync(src, fn))
        findings.extend(_check_publish(src, fn))
    return findings


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))

"""Shared taint lattice for the graftgate tier (ISSUE 17).

The four verdict-integrity analyzers (fingerprint, degraded, knobclass,
tierstamp) all reduce to the same two primitives over the §7 CFG:

* **guard polarity** — classify an ``if`` test's arms against a
  predicate family: which outgoing edge kind (TRUE/FALSE) *establishes*
  a fact about the guarded region. The weak-rung family proves
  ``consistency != "linearizable"`` on an arm (``!=`` / ``== <weak
  rung>`` conjuncts establish it on TRUE; ``== "linearizable"``
  disjuncts establish it on FALSE — an ``or`` arm is only sound on the
  all-false side, an ``and`` arm only on the all-true side). The
  degraded family proves "this value carries no platform-degraded
  stamp" the same way (``not <degraded-atom>`` conjuncts on TRUE, bare
  degraded atoms on FALSE).
* **guard dominance** — a node is dominated by a guard family iff it is
  unreachable from the CFG entry once every establishing edge is
  removed: each surviving path would be a path that reaches the node
  with the fact unproven. This is sound on the §7 graph because edge
  kinds are preserved through finally-instances and joins
  (``cfg._Builder.connect``).

Both are syntactic: a guard spelled through a helper this module does
not know (or a value laundered through a container) is reported, and
the fix is a ``# lint: allow(...)`` pragma with a reason — exactly the
written-record contract of the earlier tiers (doc/checker-design.md
§19).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .cfg import CFG, FALSE, TRUE, Node, walk_own

#: the strongest rung; everything else is "weak" (consistency.py's
#: CONSISTENCY_LEVELS — re-stated here so the lint package stays
#: import-free of the checker).
LIN = "linearizable"
WEAK_RUNGS = ("sequential", "session")

#: substrings marking a degraded-result atom: the stamp key itself and
#: the `is_degraded` / `stats.get("degraded")` helper idioms.
DEGRADED_MARKERS = ("platform-degraded", "degraded")


def call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def dotted(expr: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


def _conjuncts(test: ast.AST) -> List[ast.AST]:
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        return [c for t in test.values for c in _conjuncts(t)]
    return [test]


def _disjuncts(test: ast.AST) -> List[ast.AST]:
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        return [d for t in test.values for d in _disjuncts(t)]
    return [test]


def _str_consts(node: ast.AST) -> Set[str]:
    return {s.value for s in ast.walk(node)
            if isinstance(s, ast.Constant) and isinstance(s.value, str)}


# ------------------------------------------------------ weak-rung guards


def _weak_positive(expr: ast.AST, wnames: Set[str]) -> bool:
    """True when `expr` being true implies the rung is weak."""
    if isinstance(expr, ast.Name) and expr.id in wnames:
        return True
    if not (isinstance(expr, ast.Compare) and len(expr.ops) == 1):
        return False
    op = expr.ops[0]
    sides = {expr.left, expr.comparators[0]}
    consts = {s.value for s in sides if isinstance(s, ast.Constant)}
    if isinstance(op, ast.NotEq):
        return LIN in consts
    if isinstance(op, ast.Eq):
        return bool(consts & set(WEAK_RUNGS))
    if isinstance(op, ast.NotIn):
        return LIN in _str_consts(expr.comparators[0])
    return False


def _lin_positive(expr: ast.AST) -> bool:
    """True when `expr` being FALSE implies the rung is weak (i.e. the
    expression asserts linearizable — or something ⊇ it, which is
    still sound: all-disjuncts-false refutes this one too)."""
    if not (isinstance(expr, ast.Compare) and len(expr.ops) == 1):
        return False
    op = expr.ops[0]
    sides = {expr.left, expr.comparators[0]}
    consts = {s.value for s in sides if isinstance(s, ast.Constant)}
    if isinstance(op, ast.Eq):
        return LIN in consts
    if isinstance(op, ast.In):
        names = _str_consts(expr.comparators[0])
        # `in (None, "linearizable")`: false ⟹ not linearizable, as
        # long as no WEAK rung sits in the same tuple
        return LIN in names and not (names & set(WEAK_RUNGS))
    return False


def weak_assign_names(fn: ast.AST) -> Set[str]:
    """Local names bound to a weak-positive expression (the
    ``weak = consistency != "linearizable"`` idiom)."""
    out: Set[str] = set()
    for node in walk_own(fn):
        if isinstance(node, ast.Assign) and \
                _weak_positive(node.value, out | set()):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def weak_edges(test: ast.AST, wnames: Set[str]) -> Set[str]:
    """Edge kinds out of an ``if test:`` that establish a weak rung."""
    kinds: Set[str] = set()
    if any(_weak_positive(c, wnames) for c in _conjuncts(test)):
        kinds.add(TRUE)
    if any(_lin_positive(d) for d in _disjuncts(test)):
        kinds.add(FALSE)
    return kinds


# ------------------------------------------------------- degraded guards


def _degraded_atom(expr: ast.AST) -> bool:
    """Does evaluating `expr` test for a degrade stamp? Matches the
    repo idioms: ``"platform-degraded" in r`` (incl. inside any(...)),
    ``is_degraded(...)``, ``.stats.get("degraded")``."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Constant) and \
                sub.value in DEGRADED_MARKERS:
            return True
        if isinstance(sub, ast.Call) and \
                "degraded" in call_name(sub).lower():
            return True
    return False


def clean_edges(test: ast.AST) -> Set[str]:
    """Edge kinds out of an ``if test:`` that establish "the guarded
    value is NOT degraded"."""
    kinds: Set[str] = set()
    for c in _conjuncts(test):
        if isinstance(c, ast.UnaryOp) and isinstance(c.op, ast.Not) \
                and _degraded_atom(c.operand):
            kinds.add(TRUE)
            break
    for d in _disjuncts(test):
        if not (isinstance(d, ast.UnaryOp) and
                isinstance(d.op, ast.Not)) and _degraded_atom(d):
            kinds.add(FALSE)
            break
    return kinds


# ----------------------------------------------------- guard dominance


def reachable_without(cfg: CFG, blocked) -> Set[int]:
    """Node idxs reachable from entry along edges NOT classified as
    establishing: ``blocked(node)`` returns the establishing edge
    kinds out of `node` (empty set for most nodes)."""
    seen = {cfg.entry.idx}
    stack = [cfg.entry]
    while stack:
        n = stack.pop()
        cut = blocked(n)
        for succ, kind in n.succs:
            if kind in cut:
                continue
            if succ.idx not in seen:
                seen.add(succ.idx)
                stack.append(succ)
    return seen


def guard_blocked(wnames: Set[str], edges_of):
    """A ``blocked`` callback for :func:`reachable_without` that cuts
    the establishing arms of ``if`` guards, where `edges_of` is
    :func:`weak_edges`-shaped (test, wnames) -> kinds."""
    def blocked(node: Node) -> Set[str]:
        if node.label != "if":
            return set()
        return edges_of(node.stmt.test, wnames)
    return blocked


def nodes_containing(cfg: CFG, target: ast.AST) -> List[Node]:
    """CFG nodes whose evaluated expressions contain `target` (by
    identity)."""
    from .cfg import own_exprs

    out = []
    for node in cfg.nodes:
        for expr in own_exprs(node):
            if any(sub is target for sub in ast.walk(expr)):
                out.append(node)
                break
    return out


def dominated(cfg: CFG, target: ast.AST, wnames: Set[str],
              edges_of) -> bool:
    """Is every entry→target path forced through an establishing guard
    arm? False also when the target cannot be located in the graph
    (conservative: unlocated code is unguarded code)."""
    nodes = nodes_containing(cfg, target)
    if not nodes:
        return False
    alive = reachable_without(cfg, guard_blocked(wnames, edges_of))
    return all(n.idx not in alive for n in nodes)


# --------------------------------------------------------- call graphs


class CallSite:
    __slots__ = ("caller", "call", "name")

    def __init__(self, caller: ast.AST, call: ast.Call, name: str):
        self.caller = caller
        self.call = call
        self.name = name


def calls_of(fn: ast.AST) -> List[CallSite]:
    return [CallSite(fn, node, call_name(node))
            for node in walk_own(fn) if isinstance(node, ast.Call)]


def weak_functions(functions: Sequence[Tuple[str, ast.AST, CFG]]
                   ) -> Set[str]:
    """Greatest fixpoint of "only reachable at a weak rung" over a
    bare-name call graph: a function is weak iff it has at least one
    known call site and EVERY known call site is either intra-guarded
    by a weak-rung test or lives in a weak function. Entry points (no
    call sites in the scanned set) are never weak — they are exactly
    the rung-dispatching surface."""
    by_name: Dict[str, List[Tuple[str, ast.AST, CFG]]] = {}
    for name, fn, cfg in functions:
        by_name.setdefault(name, []).append((name, fn, cfg))
    sites: Dict[str, List[Tuple[str, bool]]] = {}
    for caller_name, fn, cfg in functions:
        wnames = weak_assign_names(fn)
        for cs in calls_of(fn):
            if cs.name not in by_name:
                continue
            guarded = dominated(cfg, cs.call, wnames, weak_edges)
            sites.setdefault(cs.name, []).append((caller_name, guarded))
    weak = {name for name in by_name if sites.get(name)}
    changed = True
    while changed:
        changed = False
        for name in sorted(weak):
            ok = all(guarded or caller in weak
                     for caller, guarded in sites[name])
            if not ok:
                weak.discard(name)
                changed = True
    return weak

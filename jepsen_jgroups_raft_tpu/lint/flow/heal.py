"""Fault↔heal pairing analyzer for the nemesis tier.

A nemesis that injects a fault and loses track of it silently
invalidates the run: a never-healed partition/pause turns the final
read phase into timeouts, and a never-restarted node makes "valid"
vacuous (the history just stops exercising the SUT). The runner
guarantees ``teardown`` runs (core/runner.py nemesis_worker's
``finally``), so the contract this rule enforces is *accountability*,
not inline healing:

    every path out of a function that performed a fault call —
    **including exception edges** — must either (a) complete the
    matching heal call, (b) register the affliction in instance state
    (``self.<set>.add(...)`` / ``self.<dict>[k] = …``) so teardown can
    undo it, or (c) be blanket-covered by a ``teardown`` in the class
    (or a same-module base class) that heals unconditionally — a heal
    call NOT inside a loop over instance state. A teardown that heals
    ``for n in self.afflicted`` only covers what was registered, so it
    deliberately does not discharge sites; that is what (b) is for.

Deliberate unhealed faults (crash workloads, members leaving the
cluster for good) carry ``# lint: allow(unhealed)`` on the fault line
with a comment saying why — the pragma inventory is the audit trail.

Coarseness, on purpose: which *node* a heal targets is not tracked (a
heal of any node discharges the path), and a method whose entire body
is a single delegating fault call (``KillNemesis._do``) is the
primitive itself, analyzed at its call sites, not flagged.

Rule: ``flow-unhealed-fault``. Scan set (CLI): ``nemesis/``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..base import Finding, SourceFile
from .cfg import EXC, build_cfg, functions_of, own_exprs, reach, walk_own

#: fault attribute-call name -> names whose successful completion heals
#: it. `_do` is the shared toggle-nemesis hook (its heal is `_undo`);
#: a removed member is regrown, so `add_member` pairs `remove_member`.
FAULT_HEALS: Dict[str, Set[str]] = {
    "partition": {"heal"},
    "kill": {"start", "restart"},
    "pause": {"resume"},
    "_do": {"_undo"},
    "remove_member": {"add_member"},
}

#: method names that count as registration containers regardless of the
#: attribute they are called on, provided the receiver hangs off `self`.
_REGISTER_CALLS = {"add", "append", "insert", "update"}

SCAN_PREFIXES = ("nemesis/",)

RULE = "flow-unhealed-fault"


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp.startswith(SCAN_PREFIXES)


# ------------------------------------------------------------ AST helpers


def _attr_calls(node: ast.AST):
    """(call, attr-name) for attribute calls in an expression subtree."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            yield sub, sub.func.attr


def _node_attr_calls(node):
    """Attribute calls evaluated AT a CFG node (header exprs only)."""
    for expr in own_exprs(node):
        yield from _attr_calls(expr)


def _touches_self(node: ast.AST) -> bool:
    return any(isinstance(s, ast.Name) and s.id == "self"
               for s in ast.walk(node))


def _is_registration(node) -> bool:
    """self.<container>.add/append/…(x) or self.<container>[k] = x."""
    for call, attr in _node_attr_calls(node):
        if attr in _REGISTER_CALLS and _touches_self(call.func):
            return True
    for expr in own_exprs(node):
        if isinstance(expr, ast.Assign):
            for tgt in expr.targets:
                if isinstance(tgt, ast.Subscript) and \
                        _touches_self(tgt.value):
                    return True
    return False


def _heal_at_node(node, heals: Set[str]) -> bool:
    return any(attr in heals for _, attr in _node_attr_calls(node))


def _fault_sites(fn: ast.FunctionDef):
    """Every fault attribute call in the function's own frame (nested
    defs get analyzed as their own functions)."""
    for sub in walk_own(fn):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr in FAULT_HEALS:
            yield sub, sub.func.attr


def _is_delegating_wrapper(fn: ast.FunctionDef) -> bool:
    """Body (minus docstring) is a single fault-call statement — the
    method IS the primitive; analyzed at its call sites."""
    body = [s for s in fn.body
            if not (isinstance(s, ast.Expr) and
                    isinstance(s.value, ast.Constant) and
                    isinstance(s.value.value, str))]
    return (len(body) == 1 and isinstance(body[0], ast.Expr) and
            isinstance(body[0].value, ast.Call) and
            any(attr in FAULT_HEALS
                for _, attr in _attr_calls(body[0])))


# ------------------------------------------------------- class-level pass


def _class_map(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    return {n.name: n for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef)}


def _mro_methods(cls: Optional[ast.ClassDef], classes, name: str):
    """`name` methods along the same-module single-inheritance chain."""
    seen = set()
    while cls is not None and cls.name not in seen:
        seen.add(cls.name)
        for stmt in cls.body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
                yield stmt
        nxt = None
        for base in cls.bases:
            if isinstance(base, ast.Name) and base.id in classes:
                nxt = classes[base.id]
                break
        cls = nxt


def _blanket_teardown(cls: Optional[ast.ClassDef], classes,
                      heals: Set[str]) -> bool:
    """Does teardown heal unconditionally (not just over a registry)?
    A heal inside a ``for`` iterating instance state only covers
    registered afflictions, so it does not blanket-discharge."""
    for td in _mro_methods(cls, classes, "teardown"):
        loops = [n for n in ast.walk(td)
                 if isinstance(n, ast.For) and _touches_self(n.iter)]
        in_loop = set()
        for lp in loops:
            for sub in ast.walk(lp):
                in_loop.add(id(sub))
        for call, attr in _attr_calls(td):
            if attr in heals and id(call) not in in_loop:
                return True
    return False


# --------------------------------------------------------------- analysis


def _analyze_function(src: SourceFile, cls, classes,
                      fn: ast.FunctionDef) -> List[Finding]:
    if _is_delegating_wrapper(fn):
        return []
    sites = [(call, kind) for call, kind in _fault_sites(fn)
             if not (src.allowed(call.lineno, RULE) or
                     src.allowed(call.lineno, "unhealed"))]
    if not sites:
        return []
    cfg = build_cfg(fn)
    findings: List[Finding] = []
    for call, kind in sites:
        heals = FAULT_HEALS[kind]
        if _blanket_teardown(cls, classes, heals):
            continue
        # the CFG node whose own (header) expressions contain this call
        site_nodes = [n for n in cfg.nodes
                      if any(sub is call for e in own_exprs(n)
                             for sub in ast.walk(e))]
        for node in site_nodes:
            # analysis starts at the fault's NORMAL completion: if the
            # fault call itself raised, the fault may not have landed.
            starts = [s for s, k in node.succs if k != EXC]

            def stop(n, kind_in, _heals=heals, _site=node):
                if _is_registration(n):
                    return "kill"
                if n is cfg.exit or n is cfg.raise_exit:
                    return "report"
                if n is not _site and _heal_at_node(n, _heals):
                    # completing the heal discharges; the heal call
                    # RAISING does not — keep walking its exc edge.
                    return {EXC}
                return None

            escapes = reach(cfg, starts, stop)
            if escapes:
                via = escapes[0]
                how = ("an exception path"
                       if via and via[-1] is cfg.raise_exit
                       else "a normal exit")
                findings.append(Finding(
                    src.path, call.lineno, RULE,
                    f"`{kind}` fault in `{fn.name}` can escape un-healed "
                    f"via {how}: no {'/'.join(sorted(heals))} completes "
                    "and the affliction is not registered in instance "
                    "state for teardown; heal it, register it, or "
                    "annotate `# lint: allow(unhealed)` with why"))
                break  # one finding per fault call site
    return findings


def analyze_source(src: SourceFile) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    classes = _class_map(tree)
    findings: List[Finding] = []
    for cls, fn in functions_of(tree):
        findings.extend(_analyze_function(src, cls, classes, fn))
    return findings


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))

"""Lock-ordering analyzer: the acquires-while-holding graph of service/.

graftd is one process holding half a dozen locks — the daemon registry
lock, per-shard queue conditions, stream session RLocks, the stream
manager table lock, the journal's append lock and group-commit
condition, the store publish lock. A deadlock needs only two of them
acquired in opposite orders on two threads, and no unit test reliably
produces that interleaving. This analyzer computes the
acquires-while-holding relation over the whole ``service/`` directory
(one analysis, not per-file — nesting crosses files via calls), fails
on cycles, and pins today's real acquisition order as an explicit
hierarchy so a contradicting edge fails review even before it closes a
cycle.

Lock identity is the *declaration*: ``self._lock = threading.Lock()``
in class C is the canonical lock ``C._lock`` (one lock class per
instance attribute — the standard lock-ordering abstraction; per-object
cycles within one lock class are caught by the reentrancy check
instead). Module-level ``X = threading.Lock()`` is ``module.X``.
Reentrant locks (``RLock``, argless ``Condition`` — its hidden lock is
an RLock) may self-nest; a self-edge on a non-reentrant lock is an
unconditional deadlock and reported as a cycle of length one.

Edges come from two sources, both computed on the CFG with
locks.lock_regions so try/finally and early-return paths are modeled:

* a ``with``-acquisition at a node where another lock is held;
* a *call* at such a node, resolved through a typed receiver map
  (param annotations, ``self.attr = ClassName(...)``, list/dict element
  types, locals) with a unique-method-name fallback for unannotated
  handles, into the callee's transitively-may-acquire set (fixpoint
  over the call graph).

Unresolvable receivers are skipped — under-approximation keeps the
reported edges real; the hierarchy check keeps the approximation
honest by requiring every *declared* lock to be ranked.

Rules: ``flow-lock-cycle`` (a cycle in the graph — deadlock),
``flow-lock-order`` (an edge contradicting the pinned hierarchy),
``flow-lock-unranked`` (a declared lock missing from the hierarchy —
update HIERARCHY + checker-design.md §18 together). Pragma alias for
all three: ``lock-order``.

CLI anchoring: the analyzer applies to ``service/daemon.py`` and, when
invoked on it, loads every sibling ``service/*.py`` — one whole-tier
analysis per run, attributed to the file each edge lives in.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..base import Finding, SourceFile
from .cfg import build_cfg, functions_of, own_exprs
from .locks import fn_requires, lock_regions, node_locks, walk_expr

RULE_CYCLE = "flow-lock-cycle"
RULE_ORDER = "flow-lock-order"
RULE_RANK = "flow-lock-unranked"

ANCHOR = "service/daemon.py"

#: Today's real acquisition order, outermost first (checker-design.md
#: §18 documents the same list with rationale). An edge from a lock to
#: one at the same or an earlier level fails flow-lock-order; a
#: declared lock absent from this list fails flow-lock-unranked so the
#: pinned order can never silently rot.
HIERARCHY: Tuple[str, ...] = (
    # stream tier: a session RLock is taken first (public entry points
    # lock the session, then journal/manager internals)
    "StreamSession.lock",
    "StreamManager._lock",
    # daemon tier: the registry lock wraps shard handoff
    "CheckingService._lock",
    "_ShardQueue._cond",
    "AdmissionQueue._cond",
    "BatchScheduler._seq_lock",
    "ShardLoads._lock",
    "ResultCache._lock",
    # request finish is leaf-before-journal (first-wins flag flip, then
    # durability outside the flag lock)
    "CheckRequest._finish_lock",
    # durability tier: group-commit membership, then the handle lock
    "AdmissionJournal._gcond",
    "AdmissionJournal._lock",
    # cross-process publish leaves: the detail-store singleton factory
    # holds the registry lock while constructing/loading the store
    "store._DETAIL_STORE_LOCK",
    "ResultStore._lock",
    # tenant-side leaf: keep-alive A/B counters (ISSUE 18) — bumped
    # with nothing else held, never wraps an acquisition
    "ServiceClient._counter_lock",
)

#: Method names too generic for unique-name call resolution (they exist
#: on builtins/stdlib types the typed layer does not track).
_GENERIC = {"get", "put", "pop", "append", "add", "remove", "clear",
            "update", "items", "keys", "values", "close", "stop",
            "start", "run", "join", "wait", "notify", "notify_all",
            "acquire", "release", "submit", "send", "recv", "read",
            "write", "flush", "set", "is_set", "cancel", "result",
            "copy", "sort", "index", "count", "setdefault", "extend",
            "strip", "split", "encode", "decode", "format", "mkdir",
            "exists", "unlink", "open"}


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp == ANCHOR


# ------------------------------------------------------------ harvesting


def _callee(call: ast.Call) -> Tuple[str, Optional[ast.AST]]:
    """(name, receiver-expr-or-None) of a call."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id, None
    if isinstance(fn, ast.Attribute):
        return fn.attr, fn.value
    return "", None


def _lock_ctor(value: ast.AST) -> Optional[bool]:
    """None if `value` is not a lock construction, else its reentrancy.

    Recognizes threading.Lock/RLock/Condition calls and the dataclass
    ``field(default_factory=threading.Lock)`` form."""
    if not isinstance(value, ast.Call):
        return None
    name, _recv = _callee(value)
    if name == "field":
        for kw in value.keywords:
            if kw.arg == "default_factory":
                fac = kw.value
                fname = (fac.attr if isinstance(fac, ast.Attribute)
                         else fac.id if isinstance(fac, ast.Name) else "")
                if fname in ("Lock", "RLock", "Condition"):
                    return fname != "Lock"
        return None
    if name == "Lock":
        return False
    if name == "RLock":
        return True
    if name == "Condition":
        # argless Condition wraps an RLock (reentrant); an explicit
        # Condition(threading.Lock()) is non-reentrant.
        if value.args:
            inner = _lock_ctor(value.args[0])
            return bool(inner)
        return True
    return None


class _World:
    """Cross-file harvest: locks, classes, methods, attribute types."""

    def __init__(self, srcs: Dict[str, SourceFile]):
        self.srcs = srcs
        self.trees: Dict[str, ast.AST] = {}
        self.parse_errors: List[Finding] = []
        #: canonical lock → (reentrant, filekey, line)
        self.locks: Dict[str, Tuple[bool, str, int]] = {}
        #: lock attr name → [classname] that declare it
        self.lock_owners: Dict[str, List[str]] = {}
        #: module-level lock Name → canonical (unique across files)
        self.module_locks: Dict[str, str] = {}
        self.classes: Set[str] = set()
        #: (classname, method) → (filekey, fn-node)
        self.methods: Dict[Tuple[str, str], Tuple[str, ast.FunctionDef]] = {}
        #: module function name → (filekey, fn-node); ambiguous → dropped
        self.modfuncs: Dict[str, Optional[Tuple[str, ast.FunctionDef]]] = {}
        #: method name → unique (classname, method) or None if ambiguous
        self.unique_methods: Dict[str, Optional[Tuple[str, str]]] = {}
        #: (classname, attr) → ClassName it holds
        self.attr_types: Dict[Tuple[str, str], str] = {}
        #: (classname, attr) → element ClassName (list/dict of)
        self.elem_types: Dict[Tuple[str, str], str] = {}
        for key, src in srcs.items():
            try:
                self.trees[key] = ast.parse(src.text)
            except SyntaxError as e:
                self.parse_errors.append(
                    Finding(src.path, e.lineno or 1, "parse-error", str(e)))
        for key, tree in self.trees.items():
            self._harvest_decls(key, tree)
        for key, tree in self.trees.items():
            self._harvest_types(key, tree)

    def _modbase(self, key: str) -> str:
        return Path(key).stem

    def _harvest_decls(self, key: str, tree: ast.AST) -> None:
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if isinstance(node, ast.Assign):
                re_ent = _lock_ctor(node.value)
                if re_ent is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            canon = f"{self._modbase(key)}.{tgt.id}"
                            self.locks[canon] = (re_ent, key, node.lineno)
                            if tgt.id in self.module_locks:
                                self.module_locks[tgt.id] = ""  # ambiguous
                            else:
                                self.module_locks[tgt.id] = canon
        for cls, fn in functions_of(tree):
            clsname = cls.name if cls is not None else None
            if clsname is None:
                prev = self.modfuncs.get(fn.name, "absent")
                self.modfuncs[fn.name] = ((key, fn) if prev == "absent"
                                          else None)
                continue
            self.classes.add(clsname)
            prev_m = self.methods.get((clsname, fn.name))
            if prev_m is None:
                self.methods[(clsname, fn.name)] = (key, fn)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self.classes.add(node.name)
                for sub in ast.walk(node):
                    self._class_lock_decl(key, node.name, sub)

    def _class_lock_decl(self, key: str, clsname: str, sub: ast.AST) -> None:
        if isinstance(sub, ast.Assign):
            targets, value = sub.targets, sub.value
        elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
            targets, value = [sub.target], sub.value
        else:
            return
        re_ent = _lock_ctor(value)
        if re_ent is None:
            return
        for tgt in targets:
            attr = None
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                attr = tgt.attr
            elif isinstance(tgt, ast.Name):
                attr = tgt.id
            if attr is not None:
                canon = f"{clsname}.{attr}"
                if canon not in self.locks:
                    self.locks[canon] = (re_ent, key, sub.lineno)
                    self.lock_owners.setdefault(attr, []).append(clsname)

    def _harvest_types(self, key: str, tree: ast.AST) -> None:
        for (clsname, _m), (k, fn) in list(self.methods.items()):
            if k != key:
                continue
            ann = {a.arg: self._ann_type(a.annotation)
                   for a in fn.args.args if a.annotation is not None}
            for node in walk_expr(fn):
                tgt_attr = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    t, value = node.target, node.value
                else:
                    continue
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    tgt_attr = t.attr
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Attribute) and \
                        isinstance(t.value.value, ast.Name) and \
                        t.value.value.id == "self":
                    # self.attr[k] = ClassName(...) → element type
                    elem = self._ctor_type(value)
                    if elem:
                        self.elem_types.setdefault(
                            (clsname, t.value.attr), elem)
                    continue
                if tgt_attr is None:
                    continue
                direct = self._ctor_type(value)
                if direct:
                    self.attr_types.setdefault((clsname, tgt_attr), direct)
                    continue
                elem = self._elem_ctor_type(value)
                if elem:
                    self.elem_types.setdefault((clsname, tgt_attr), elem)
                    continue
                if isinstance(value, ast.Name) and value.id in ann and ann[value.id]:
                    # self.journal = journal  (annotated param)
                    self.attr_types.setdefault(
                        (clsname, tgt_attr), ann[value.id])

    def _ann_type(self, ann: ast.AST) -> Optional[str]:
        if isinstance(ann, ast.Name) and ann.id in self.classes:
            return ann.id
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str) \
                and ann.value in self.classes:
            return ann.value
        if isinstance(ann, ast.Attribute) and ann.attr in self.classes:
            return ann.attr
        return None

    def _ctor_type(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            name, _recv = _callee(value)
            if name in self.classes:
                return name
        return None

    def _elem_ctor_type(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, (ast.List, ast.Tuple, ast.Set)):
            for e in value.elts:
                t = self._ctor_type(e)
                if t:
                    return t
        if isinstance(value, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._ctor_type(value.elt)
        if isinstance(value, ast.DictComp):
            return self._ctor_type(value.value)
        return None

    def finish(self) -> None:
        for (clsname, m) in self.methods:
            prev = self.unique_methods.get(m, "absent")
            self.unique_methods[m] = ((clsname, m) if prev == "absent"
                                      else None)


# -------------------------------------------------------------- analysis


class _MethodScan:
    """Per-method facts: local types, direct acquisitions, calls."""

    def __init__(self, world: _World, key: str, clsname: Optional[str],
                 fn: ast.FunctionDef):
        self.world = world
        self.key = key
        self.cls = clsname
        self.fn = fn
        self.cfg = build_cfg(fn)
        self.held_dotted = lock_regions(self.cfg)
        self.local_types = self._local_types()

    def _local_types(self) -> Dict[str, str]:
        w, out = self.world, {}
        for a in self.fn.args.args:
            if a.annotation is not None:
                t = w._ann_type(a.annotation)
                if t:
                    out[a.arg] = t
        for node in walk_expr(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                t = self._type_of(node.value, out)
                if t:
                    out[node.targets[0].id] = t
            if isinstance(node, (ast.For,)) and \
                    isinstance(node.target, ast.Name):
                t = self._iter_elem_type(node.iter, out)
                if t:
                    out[node.target.id] = t
        return out

    def _type_of(self, expr: ast.AST, env: Dict[str, str]) -> Optional[str]:
        w = self.world
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return self.cls
            return env.get(expr.id)
        if isinstance(expr, ast.Call):
            name, recv = _callee(expr)
            if name in w.classes:
                return name
            if name == "get" and recv is not None:
                base = self._type_of(recv, env)
                # dict-of-T lookup via typed attr
                if base is None and isinstance(recv, ast.Attribute):
                    owner = self._type_of(recv.value, env)
                    if owner:
                        return w.elem_types.get((owner, recv.attr))
            return None
        if isinstance(expr, ast.Attribute):
            owner = self._type_of(expr.value, env)
            if owner:
                return w.attr_types.get((owner, expr.attr))
            return None
        if isinstance(expr, ast.Subscript):
            if isinstance(expr.value, ast.Attribute):
                owner = self._type_of(expr.value.value, env)
                if owner:
                    return w.elem_types.get((owner, expr.value.attr))
            if isinstance(expr.value, ast.Name):
                return None
        return None

    def _iter_elem_type(self, it: ast.AST,
                        env: Dict[str, str]) -> Optional[str]:
        w = self.world
        if isinstance(it, ast.Attribute):
            owner = self._type_of(it.value, env)
            if owner:
                return w.elem_types.get((owner, it.attr))
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("values", "copy", "list"):
            return self._iter_elem_type(it.func.value, env)
        return None

    # -- canonicalization -------------------------------------------

    def canon_lock_expr(self, expr: ast.AST) -> Optional[str]:
        w = self.world
        if isinstance(expr, ast.Name):
            canon = w.module_locks.get(expr.id)
            return canon or None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            owner = self._type_of(expr.value, self.local_types)
            if owner and f"{owner}.{attr}" in w.locks:
                return f"{owner}.{attr}"
            owners = w.lock_owners.get(attr, [])
            if len(owners) == 1:
                return f"{owners[0]}.{attr}"
        return None

    def canon_dotted(self, dotted_name: str) -> Optional[str]:
        """Canonicalize a dotted lock name from lock_regions."""
        parts = dotted_name.split(".")
        if len(parts) == 1:
            return self.world.module_locks.get(parts[0]) or None
        env = self.local_types
        base: Optional[str]
        if parts[0] == "self":
            base = self.cls
        else:
            base = env.get(parts[0])
        for attr in parts[1:-1]:
            if base is None:
                break
            base = self.world.attr_types.get((base, attr))
        attr = parts[-1]
        if base and f"{base}.{attr}" in self.world.locks:
            return f"{base}.{attr}"
        owners = self.world.lock_owners.get(attr, [])
        if len(owners) == 1:
            return f"{owners[0]}.{attr}"
        return None

    def resolve_call(self, call: ast.Call
                     ) -> Optional[Tuple[Optional[str], str]]:
        """(classname-or-None, method) the call lands in, or None."""
        w = self.world
        name, recv = _callee(call)
        if not name:
            return None
        if recv is None:
            if name in w.classes and (name, "__init__") in w.methods:
                return (name, "__init__")
            mf = w.modfuncs.get(name)
            if mf:
                return (None, name)
            return None
        t = self._type_of(recv, self.local_types)
        if t is not None:
            if (t, name) in w.methods:
                return (t, name)
            return None  # typed receiver without such a method: not ours
        if name in _GENERIC:
            return None
        u = w.unique_methods.get(name)
        return u if u else None


def _method_key(cls: Optional[str], name: str, key: str):
    return (cls, name) if cls is not None else (f"mod:{key}", name)


def analyze_sources(srcs: Dict[str, SourceFile],
                    hierarchy: Optional[Sequence[str]] = HIERARCHY
                    ) -> List[Finding]:
    world = _World(srcs)
    world.finish()
    findings: List[Finding] = list(world.parse_errors)

    scans: Dict[Tuple, _MethodScan] = {}
    for (clsname, m), (key, fn) in world.methods.items():
        scans[_method_key(clsname, m, key)] = _MethodScan(
            world, key, clsname, fn)
    for name, entry in world.modfuncs.items():
        if entry:
            key, fn = entry
            scans[_method_key(None, name, key)] = _MethodScan(
                world, key, None, fn)

    # transitively-may-acquire fixpoint over the resolved call graph
    acq: Dict[Tuple, Set[str]] = {}
    calls: Dict[Tuple, List[Tuple]] = {}
    for mk, scan in scans.items():
        direct: Set[str] = set()
        callees: List[Tuple] = []
        for node in scan.cfg.nodes:
            for lock_expr_canon in (
                    scan.canon_lock_expr(it.context_expr)
                    for it in (node.stmt.items
                               if node.label == "with-enter" else [])):
                if lock_expr_canon:
                    direct.add(lock_expr_canon)
            for expr in own_exprs(node):
                for sub in walk_expr(expr):
                    if isinstance(sub, ast.Call):
                        r = scan.resolve_call(sub)
                        if r is not None:
                            cls_r, m_r = r
                            k = (world.methods[r][0] if cls_r is not None
                                 else world.modfuncs[m_r][0])
                            callees.append(_method_key(cls_r, m_r, k))
        acq[mk] = direct
        calls[mk] = callees
    changed = True
    while changed:
        changed = False
        for mk in scans:
            for callee in calls[mk]:
                extra = acq.get(callee, set()) - acq[mk]
                if extra:
                    acq[mk] |= extra
                    changed = True

    # edge collection: (src_lock, dst_lock) → (filekey, line, how)
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for mk, scan in scans.items():
        req: Set[str] = set()
        for name in fn_requires(srcs[scan.key], scan.fn):
            canon = scan.canon_dotted(f"self.{name}")
            if canon:
                req.add(canon)
        for node in scan.cfg.nodes:
            held = {c for c in (scan.canon_dotted(d)
                                for d in scan.held_dotted[node.idx]) if c}
            held |= req
            if not held:
                continue
            acquired_here: List[Tuple[str, str]] = []
            if node.label == "with-enter":
                for d in node_locks(node):
                    c = scan.canon_dotted(d)
                    if c:
                        acquired_here.append((c, "acquired directly"))
            for expr in own_exprs(node):
                for sub in walk_expr(expr):
                    if isinstance(sub, ast.Call):
                        r = scan.resolve_call(sub)
                        if r is None:
                            continue
                        cls_r, m_r = r
                        k = (world.methods[r][0] if cls_r is not None
                             else world.modfuncs[m_r][0])
                        label = (f"{cls_r}.{m_r}" if cls_r else m_r)
                        for c in acq.get(_method_key(cls_r, m_r, k), set()):
                            acquired_here.append(
                                (c, f"acquired via call to {label}()"))
            for c, how in acquired_here:
                for h in held:
                    if (h, c) not in edges:
                        edges[(h, c)] = (scan.key, node.line, how)

    # self-edges: reentrant locks may nest; others deadlock immediately
    graph: Dict[str, Set[str]] = {}
    for (a, b), (key, line, how) in sorted(edges.items()):
        if a == b:
            reentrant = world.locks.get(a, (False, "", 0))[0]
            if not reentrant:
                src = srcs[key]
                if not (src.allowed(line, RULE_CYCLE) or
                        src.allowed(line, "lock-order")):
                    findings.append(Finding(
                        src.path, line, RULE_CYCLE,
                        f"`{a}` is {how} while already held and is not "
                        "reentrant — this self-nesting deadlocks "
                        "unconditionally (move the inner acquisition "
                        "outside the region, or make the callee "
                        "# requires() the lock instead of taking it)"))
            continue
        graph.setdefault(a, set()).add(b)

    # cycle detection (iterative DFS, report each cycle once)
    color: Dict[str, int] = {}
    stack_path: List[str] = []
    reported_cycles: Set[frozenset] = set()

    def dfs(start: str) -> None:
        stack = [(start, iter(sorted(graph.get(start, ()))))]
        color[start] = 1
        stack_path.append(start)
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if color.get(nxt, 0) == 1:
                    cyc = stack_path[stack_path.index(nxt):] + [nxt]
                    key_c = frozenset(cyc)
                    if key_c not in reported_cycles:
                        reported_cycles.add(key_c)
                        fk, line, how = edges[(node, nxt)]
                        src = srcs[fk]
                        if not (src.allowed(line, RULE_CYCLE) or
                                src.allowed(line, "lock-order")):
                            findings.append(Finding(
                                src.path, line, RULE_CYCLE,
                                "lock-order cycle "
                                + " -> ".join(cyc)
                                + f" (closing edge here: `{nxt}` {how} "
                                  f"while `{node}` is held) — two threads "
                                  "taking these in opposite orders "
                                  "deadlock; restructure so acquisitions "
                                  "follow the §18 hierarchy"))
                elif color.get(nxt, 0) == 0:
                    color[nxt] = 1
                    stack_path.append(nxt)
                    stack.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
            if not advanced:
                color[node] = 2
                stack_path.pop()
                stack.pop()

    for start in sorted(graph):
        if color.get(start, 0) == 0:
            dfs(start)

    # hierarchy conformance
    if hierarchy is not None:
        rank = {name: i for i, name in enumerate(hierarchy)}
        unranked_seen: Set[str] = set()
        for lock, (_re, key, line) in sorted(world.locks.items()):
            if lock not in rank and lock not in unranked_seen:
                unranked_seen.add(lock)
                src = srcs[key]
                if not (src.allowed(line, RULE_RANK) or
                        src.allowed(line, "lock-order")):
                    findings.append(Finding(
                        src.path, line, RULE_RANK,
                        f"lock `{lock}` is not in the pinned hierarchy — "
                        "add it to lockorder.HIERARCHY and the §18 table "
                        "at the level its acquisitions demand"))
        for (a, b), (key, line, how) in sorted(edges.items()):
            if a == b or a not in rank or b not in rank:
                continue
            if rank[a] >= rank[b]:
                src = srcs[key]
                if not (src.allowed(line, RULE_ORDER) or
                        src.allowed(line, "lock-order")):
                    findings.append(Finding(
                        src.path, line, RULE_ORDER,
                        f"`{b}` {how} while `{a}` is held, but the pinned "
                        f"hierarchy orders `{b}` (level {rank[b]}) at or "
                        f"above `{a}` (level {rank[a]}) — either release "
                        "the outer lock first or re-pin the hierarchy in "
                        "lockorder.HIERARCHY + checker-design.md §18"))
    return findings


def analyze_source(src: SourceFile) -> List[Finding]:
    """Single-source entry (fixtures/mutation tests): the whole
    'package' is this one file."""
    return analyze_sources({Path(src.path).name or "mod.py": src})


def analyze_file(path) -> List[Finding]:
    p = Path(path)
    srcs = {f.name: SourceFile.load(f)
            for f in sorted(p.parent.glob("*.py"))}
    return analyze_sources(srcs)

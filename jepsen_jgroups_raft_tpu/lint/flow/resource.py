"""Resource-leak analyzer: acquire/release across exception paths.

The deploy and runner tiers hold real OS resources — client sockets
(NativeConn / Client.open), popen handles, log file handles, probe
sockets, tempdirs. A handle that leaks on an exception path is invisible
in a 10-op unit test and fatal in a 120-run hell campaign (fd
exhaustion mid-soak kills the harness, not the SUT — the verdict is
lost, not failed). This analyzer tracks each acquisition through the
function's CFG and reports any path — normal return, exception edge, or
a reassignment that drops the handle — on which the resource is neither
released nor transferred.

Model (deliberately coarse, biased against false positives):

* **acquire** — ``x = <acquire-call>()``: builtin ``open``, ``Popen``,
  ``NativeConn``, ``socket``/``create_connection``, tempfile makers,
  executors, and any callee with ``open`` as a snake-case segment
  (``proto.open``, ``_open_client``). ``with acquire() as x`` is
  release-by-construction and never tracked.
* **release** — ``x.close() / shutdown / terminate / kill / release /
  cleanup / stop``. An *attempted* release discharges even if it raises
  (the fd's fate is the callee's problem at that point).
* **transfer** — ownership leaves the function: ``return x`` (bare, or
  a tuple element), storing into an attribute/subscript, aliasing to
  another name, or adoption into a collection (``xs.append(x)``,
  ``d.setdefault(k, x)``…). Passing ``x`` as an argument to an ordinary
  call is **not** a transfer — ``Popen(stdout=log)`` does not own
  ``log``; that asymmetry is exactly what caught the start_node leak.
* **guards** — ``if x is None`` / ``is not None`` tests prune the branch
  on which the tracked value cannot be the live resource (the idiom the
  runner's close-in-finally uses).

Rule: ``flow-resource-leak`` (pragma alias ``resource-leak``). Scan set
(CLI): ``deploy/ssh.py``, ``deploy/local.py``, ``core/runner.py``,
``core/db.py``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..base import Finding, SourceFile
from .cfg import (EXC, FALSE, NORMAL, TRUE, build_cfg, functions_of,
                  own_exprs, reach)

RULE = "flow-resource-leak"

_ACQ_EXACT = {"open", "popen", "nativeconn", "socket", "create_connection",
              "mkdtemp", "mkstemp", "temporarydirectory",
              "namedtemporaryfile", "threadpoolexecutor", "sshclient",
              "connect"}

_RELEASE = {"close", "shutdown", "terminate", "kill", "release", "cleanup",
            "stop", "disconnect"}

#: collection-adoption callees: the receiver takes ownership.
_ADOPT = {"append", "add", "insert", "put", "register", "setdefault",
          "store"}

SCAN_FILES = ("deploy/ssh.py", "deploy/local.py", "core/runner.py",
              "core/db.py",
              # ISSUE-7 distributed tier: the multi-process launcher
              # holds subprocess handles + the coordinator port socket
              # across exception paths (a leaked child is a whole
              # wedged interpreter, not just an fd), and distributed.py
              # owns the cluster runtime handles.
              "parallel/distributed.py", "parallel/launch.py",
              # ISSUE-8 chaos harness: spawns daemon subprocesses and
              # sockets across kill/restart cycles — a leaked daemon
              # outlives the harness and squats its port/store.
              "scripts/chaos_graftd.py")

#: The service tier (ISSUE-5) is scanned wholesale: graftd holds queue
#: entries, per-call client sockets, trace file handles, and worker
#: threads across exception paths, and it is long-lived — a per-request
#: leak that a one-shot run never notices exhausts the daemon's fds.
#: workload/ rides along since the scenario tier (ISSUE 10): its
#: set/queue clients own real connections behind CAS retry loops — an
#: exception path that drops one mid-loop is the leak class this rule
#: exists for. search/ (ISSUE 20) rides along: the driver owns a whole
#: CheckingService (worker threads) plus corpus temp files — a search
#: that leaks its daemon on an exception path wedges the next run's
#: admission.
SCAN_PREFIXES = ("service/", "workload/", "search/")


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    rp = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return rp in SCAN_FILES or rp.startswith(SCAN_PREFIXES)


# ------------------------------------------------------------- predicates


def _callee_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _is_acquire_call(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    low = _callee_name(value).lower()
    return low in _ACQ_EXACT or "open" in low.split("_")


def _acquisitions(fn_cfg):
    """(node, varname) per tracked acquisition statement."""
    out = []
    for node in fn_cfg.nodes:
        for expr in own_exprs(node):
            if isinstance(expr, ast.Assign) and len(expr.targets) == 1 \
                    and isinstance(expr.targets[0], ast.Name) \
                    and _is_acquire_call(expr.value):
                out.append((node, expr.targets[0].id))
    return out


def _releases(node, var: str) -> bool:
    for expr in own_exprs(node):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _RELEASE and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id == var:
                return True
    return False


def _bare(expr: ast.expr, var: str) -> bool:
    return isinstance(expr, ast.Name) and expr.id == var


def _transfers(node, var: str) -> bool:
    for expr in own_exprs(node):
        if isinstance(expr, ast.Return) and expr.value is not None:
            v = expr.value
            if _bare(v, var) or (isinstance(v, ast.Tuple) and
                                 any(_bare(e, var) for e in v.elts)):
                return True
        if isinstance(expr, ast.Assign):
            # alias to another name, or escape into an attr/subscript
            if _bare(expr.value, var):
                return True
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _ADOPT:
                args = list(sub.args) + [k.value for k in sub.keywords]
                if any(_bare(a, var) for a in args):
                    return True
    return False


def _reassigns(node, var: str, site_stmt) -> bool:
    for expr in own_exprs(node):
        if expr is site_stmt:
            continue
        if isinstance(expr, (ast.Assign,)):
            for tgt in expr.targets:
                if _bare(tgt, var):
                    return True
        if isinstance(expr, ast.AugAssign) and _bare(expr.target, var):
            return True
    return False


def _none_guard(node, var: str) -> Optional[set]:
    """Edge kinds to follow through an `if` that tests the tracked var
    against None; None when the test says nothing about it."""
    if node.label != "if":
        return None
    tests = [node.stmt.test]
    if isinstance(node.stmt.test, ast.BoolOp) and \
            isinstance(node.stmt.test.op, ast.And):
        tests = list(node.stmt.test.values)
    for t in tests:
        if isinstance(t, ast.Compare) and _bare(t.left, var) and \
                len(t.ops) == 1 and \
                isinstance(t.comparators[0], ast.Constant) and \
                t.comparators[0].value is None:
            if isinstance(t.ops[0], ast.Is):
                # true arm ⇒ var is None ⇒ not the live resource
                return {FALSE, EXC}
            if isinstance(t.ops[0], ast.IsNot) and \
                    t is node.stmt.test:
                # (only sound for the whole test, not an And conjunct)
                return {TRUE, EXC}
    return None


# --------------------------------------------------------------- analysis


def _analyze_function(src: SourceFile, fn: ast.FunctionDef) -> List[Finding]:
    cfg = build_cfg(fn)
    findings: List[Finding] = []
    for site, var in _acquisitions(cfg):
        if src.allowed(site.line, RULE) or \
                src.allowed(site.line, "resource-leak"):
            continue
        site_stmt = site.stmt
        starts = [s for s, k in site.succs if k != EXC]

        def stop(n, kind_in, _var=var, _site=site, _stmt=site_stmt):
            if n is _site:
                return "kill"  # looped back: fresh acquisition re-tracks
            if _releases(n, _var) or _transfers(n, _var):
                return "kill"
            if _reassigns(n, _var, _stmt):
                return "report"
            if n is cfg.exit or n is cfg.raise_exit:
                return "report"
            guard = _none_guard(n, _var)
            if guard is not None:
                return guard | {NORMAL}
            return None

        escapes = reach(cfg, starts, stop)
        if escapes:
            end = escapes[0][-1]
            if end is cfg.raise_exit:
                how = "an exception path escapes the function"
            elif end is cfg.exit:
                how = "a return path completes"
            else:
                how = (f"line {end.line} reassigns `{var}` while it is "
                       "still open")
            findings.append(Finding(
                src.path, site.line, RULE,
                f"`{var}` acquired here is not released on every path: "
                f"{how} without close/transfer — release it in a "
                "finally, use `with`, or hand ownership off before the "
                "path splits"))
    return findings


def analyze_source(src: SourceFile) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    findings: List[Finding] = []
    for _cls, fn in functions_of(tree):
        findings.extend(_analyze_function(src, fn))
    return findings


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))

"""Restricted AST evaluator for static shape arithmetic.

``kernel_contract`` needs to *execute* the shape expressions around a
``pallas_call`` (``C = T * S``, ``grid=(G,)``, ``BlockSpec((E * 5, C),
lambda g: (g, 0))``) under concrete symbol bindings without importing
jax or running any real code. This module is that executor: a small
big-step interpreter over the integer/bool/tuple fragment of Python —
arithmetic, comparisons, conditionals, bounded loops, calls to
``max``/``min``/``len``/``range``/``int``/``abs``, and calls into other
functions of the same module (depth-bounded).

Anything outside the fragment evaluates to :data:`UNKNOWN`, which
propagates: an expression touching UNKNOWN is UNKNOWN, a branch on an
UNKNOWN test aborts the enclosing function evaluation (result UNKNOWN)
rather than guessing a path. The kernel-contract analyzer turns an
UNKNOWN where a shape was needed into a loud ``kernel-unresolved``
finding — silence is never vacuous.

Attribute chains resolve to an opaque :class:`Dotted` name (``jnp.int32``
→ ``Dotted("jnp.int32")``), which is how dtypes are read without
importing jax.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Optional


class _Unknown:
    def __repr__(self):
        return "UNKNOWN"


UNKNOWN = _Unknown()


class Dotted:
    """An unevaluated dotted name (``jnp.int32``); `.name` keeps the
    full spelling, `.leaf` the final attribute."""

    def __init__(self, name: str):
        self.name = name

    @property
    def leaf(self) -> str:
        return self.name.rsplit(".", 1)[-1]

    def __repr__(self):
        return f"Dotted({self.name})"

    def __eq__(self, other):
        return isinstance(other, Dotted) and other.name == self.name

    def __hash__(self):
        return hash(("Dotted", self.name))


class Closure:
    """A lambda/def captured with its defining environment."""

    def __init__(self, node, env: Dict[str, Any], interp: "Interp"):
        self.node = node
        self.env = env
        self.interp = interp

    def call(self, args):
        params = [a.arg for a in self.node.args.args]
        if len(args) != len(params):
            return UNKNOWN
        env = dict(self.env)
        env.update(zip(params, args))
        if isinstance(self.node, ast.Lambda):
            return self.interp.eval(self.node.body, env)
        return self.interp.exec_fn(self.node, env)


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Abort(Exception):
    """Evaluation left the supported fragment (unknown branch test,
    loop bound, iteration space…) — the whole function is UNKNOWN."""


_BUILTINS = {"max": max, "min": min, "len": len, "abs": abs, "int": int,
             "bool": bool, "sum": sum, "range": range, "sorted": sorted,
             "tuple": tuple, "list": list}

#: loop-iteration ceiling: shape arithmetic loops (pow2 bucketing etc.)
#: finish in tens of steps; anything longer is outside the fragment.
MAX_ITER = 100_000


class Interp:
    def __init__(self, module: Optional[ast.Module] = None,
                 max_depth: int = 6):
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.module_env: Dict[str, Any] = {}
        self.max_depth = max_depth
        self.depth = 0
        #: lenient mode (scope harvesting): an UNKNOWN branch test skips
        #: the construct instead of aborting — used when the goal is
        #: "collect every assignment we *can* evaluate", not a faithful
        #: single-path execution.
        self.lenient = False
        if module is not None:
            for stmt in module.body:
                if isinstance(stmt, ast.FunctionDef):
                    self.functions[stmt.name] = stmt
                elif isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name):
                    try:
                        v = self.eval(stmt.value, {})
                    except _Abort:
                        v = UNKNOWN
                    self.module_env[stmt.targets[0].id] = v

    # ------------------------------------------------------------ expr

    def eval(self, node: ast.AST, env: Dict[str, Any]) -> Any:
        try:
            return self._eval(node, env)
        except _Abort:
            raise
        except Exception:
            return UNKNOWN

    def _eval(self, node: ast.AST, env: Dict[str, Any]) -> Any:
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in self.module_env:
                return self.module_env[node.id]
            if node.id in self.functions:
                return Closure(self.functions[node.id], {}, self)
            if node.id in _BUILTINS:
                return _BUILTINS[node.id]
            return Dotted(node.id)
        if isinstance(node, ast.Attribute):
            base = self._eval(node.value, env)
            if isinstance(base, Dotted):
                return Dotted(f"{base.name}.{node.attr}")
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self._eval(e, env) for e in node.elts)
        if isinstance(node, ast.Lambda):
            return Closure(node, dict(env), self)
        if isinstance(node, ast.BinOp):
            a = self._eval(node.left, env)
            b = self._eval(node.right, env)
            if a is UNKNOWN or b is UNKNOWN:
                return UNKNOWN
            return _BINOPS[type(node.op)](a, b)
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, env)
            if v is UNKNOWN:
                return UNKNOWN
            return _UNOPS[type(node.op)](v)
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(v, env) for v in node.values]
            if any(v is UNKNOWN for v in vals):
                return UNKNOWN
            if isinstance(node.op, ast.And):
                out = True
                for v in vals:
                    out = out and v
                return out
            out = False
            for v in vals:
                out = out or v
            return out
        if isinstance(node, ast.Compare):
            left = self._eval(node.left, env)
            for op, comp in zip(node.ops, node.comparators):
                right = self._eval(comp, env)
                if left is UNKNOWN or right is UNKNOWN:
                    return UNKNOWN
                if not _CMPOPS[type(op)](left, right):
                    return False
                left = right
            return True
        if isinstance(node, ast.IfExp):
            test = self._eval(node.test, env)
            if test is UNKNOWN:
                return UNKNOWN
            return self._eval(node.body if test else node.orelse, env)
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env)
            idx = self._eval(node.slice, env)
            if base is UNKNOWN or idx is UNKNOWN:
                return UNKNOWN
            return base[idx]
        if isinstance(node, ast.Call):
            return self._call(node, env)
        return UNKNOWN

    def _call(self, node: ast.Call, env: Dict[str, Any]) -> Any:
        fn = self._eval(node.func, env)
        args = [self._eval(a, env) for a in node.args]
        if fn is UNKNOWN or isinstance(fn, Dotted):
            return UNKNOWN
        if any(a is UNKNOWN for a in args):
            return UNKNOWN
        if node.keywords:
            return UNKNOWN  # fragment: positional calls only
        if isinstance(fn, Closure):
            return fn.call(args)
        if callable(fn):
            return fn(*args)
        return UNKNOWN

    # ------------------------------------------------------------ stmts

    def exec_fn(self, fn: ast.FunctionDef, env: Dict[str, Any]) -> Any:
        """Run a def's body under `env`; returns its return value, or
        UNKNOWN when the body leaves the fragment."""
        if self.depth >= self.max_depth:
            return UNKNOWN
        self.depth += 1
        try:
            self.exec_body(fn.body, env)
            return None
        except _Return as r:
            return r.value
        except _Abort:
            return UNKNOWN
        finally:
            self.depth -= 1

    def exec_body(self, stmts, env: Dict[str, Any]) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt, env)

    def exec_stmt(self, stmt: ast.stmt, env: Dict[str, Any]) -> None:
        if isinstance(stmt, ast.Assign):
            val = self.eval(stmt.value, env)
            for tgt in stmt.targets:
                self._bind(tgt, val, env)
            return
        if isinstance(stmt, ast.AugAssign):
            if not isinstance(stmt.target, ast.Name):
                return
            cur = env.get(stmt.target.id, UNKNOWN)
            val = self.eval(stmt.value, env)
            if cur is UNKNOWN or val is UNKNOWN:
                env[stmt.target.id] = UNKNOWN
                return
            env[stmt.target.id] = _BINOPS[type(stmt.op)](cur, val)
            return
        if isinstance(stmt, ast.Return):
            raise _Return(self.eval(stmt.value, env)
                          if stmt.value is not None else None)
        if isinstance(stmt, ast.If):
            test = self.eval(stmt.test, env)
            if test is UNKNOWN:
                if self.lenient:
                    return
                raise _Abort
            self.exec_body(stmt.body if test else stmt.orelse, env)
            return
        if isinstance(stmt, ast.While):
            it = 0
            while True:
                test = self.eval(stmt.test, env)
                if test is UNKNOWN:
                    if self.lenient:
                        return
                    raise _Abort
                if not test:
                    return
                it += 1
                if it > MAX_ITER:
                    raise _Abort
                self.exec_body(stmt.body, env)
            return
        if isinstance(stmt, ast.For):
            seq = self.eval(stmt.iter, env)
            if seq is UNKNOWN or not isinstance(stmt.target, ast.Name):
                if self.lenient:
                    return
                raise _Abort
            it = 0
            for v in seq:
                it += 1
                if it > MAX_ITER:
                    raise _Abort
                env[stmt.target.id] = v
                self.exec_body(stmt.body, env)
            return
        if isinstance(stmt, ast.FunctionDef):
            env[stmt.name] = Closure(stmt, env, self)
            return
        if isinstance(stmt, (ast.Pass, ast.Expr, ast.Import,
                             ast.ImportFrom, ast.Assert)):
            return
        # anything else (try, with, class, del…) is outside the shape-
        # arithmetic fragment; its targets just become unresolvable.
        return

    def _bind(self, tgt: ast.expr, val: Any, env: Dict[str, Any]) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)) and \
                isinstance(val, tuple) and len(tgt.elts) == len(val):
            for t, v in zip(tgt.elts, val):
                self._bind(t, v, env)


_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Div: lambda a, b: a / b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
    ast.LShift: lambda a, b: a << b,
    ast.RShift: lambda a, b: a >> b,
    ast.BitAnd: lambda a, b: a & b,
    ast.BitOr: lambda a, b: a | b,
    ast.BitXor: lambda a, b: a ^ b,
}

_UNOPS = {
    ast.USub: lambda v: -v,
    ast.UAdd: lambda v: +v,
    ast.Not: lambda v: not v,
    ast.Invert: lambda v: ~v,
}

_CMPOPS = {
    ast.Eq: lambda a, b: a == b,
    ast.NotEq: lambda a, b: a != b,
    ast.Lt: lambda a, b: a < b,
    ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b,
    ast.GtE: lambda a, b: a >= b,
    ast.Is: lambda a, b: a is b,
    ast.IsNot: lambda a, b: a is not b,
    ast.In: lambda a, b: a in b,
    ast.NotIn: lambda a, b: a not in b,
}

"""Statement-level control-flow graphs over Python ``ast``.

One :class:`CFG` per function: nodes are statements (plus a few synthetic
markers), edges carry a kind — ``normal`` fall-through, ``true``/``false``
branch arms, and ``exc`` for the exception edge out of any statement whose
evaluation can raise. The builder models the control constructs the repo's
invariants live in: ``if``/``for``/``while`` (with ``break``/``continue``),
``try``/``except``/``else``/``finally``, ``with``, early ``return`` and
``raise``.

``finally`` semantics use instance duplication: each continuation kind
entering a ``try``/``finally`` (normal completion, exception propagation,
``return``, ``break``, ``continue``) gets its own copy of the ``finally``
body wired to that continuation's onward target. Duplication keeps every
path explicit — exactly what the heal/resource analyzers need, since "the
heal runs in the finally" must hold separately on the exception path and
the return path — at a node-count cost that is irrelevant at
function-sized graphs.

Exception dispatch is conservative: an ``exc`` edge from a statement goes
to the innermost ``except-dispatch`` node, which fans out to every
handler; unless some handler is a catch-all (bare / ``Exception`` /
``BaseException``), the dispatch also keeps a propagate edge outward
(through the enclosing ``finally`` chain). Which concrete exception type
flows where is not modeled — the analyzers' properties must hold on the
superset of paths.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Tuple

NORMAL = "normal"
TRUE = "true"
FALSE = "false"
EXC = "exc"

#: handler type names treated as catching every exception.
CATCH_ALL = {"Exception", "BaseException"}


class Node:
    """One CFG node: a statement, or a synthetic marker (entry/exit/
    join/except-dispatch/handler/finally/with-*)."""

    __slots__ = ("idx", "stmt", "label", "succs")

    def __init__(self, idx: int, stmt: Optional[ast.AST], label: str):
        self.idx = idx
        self.stmt = stmt
        self.label = label
        self.succs: List[Tuple["Node", str]] = []

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)

    def __repr__(self):
        return f"<{self.idx}:{self.label}@{self.line}>"


class CFG:
    """Graph for one function: ``entry``, statement nodes, ``exit``
    (normal return) and ``raise_exit`` (exception escapes the function)."""

    def __init__(self, name: str):
        self.name = name
        self.nodes: List[Node] = []
        self.entry = self.new(None, "entry")
        self.exit = self.new(None, "exit")
        self.raise_exit = self.new(None, "raise-exit")

    def new(self, stmt: Optional[ast.AST], label: str) -> Node:
        n = Node(len(self.nodes), stmt, label)
        self.nodes.append(n)
        return n

    def edge(self, src: Node, dst: Node, kind: str = NORMAL) -> None:
        src.succs.append((dst, kind))

    def stmt_nodes(self) -> List[Node]:
        return [n for n in self.nodes if n.stmt is not None]

    def find(self, label: str) -> List[Node]:
        return [n for n in self.nodes if n.label == label]


class _Ctx:
    """Continuation targets for the region being built. Entering a
    ``try``/``finally`` rebinds each target to that continuation's
    finally instance."""

    __slots__ = ("exc", "ret", "brk", "cont")

    def __init__(self, exc: Node, ret: Node,
                 brk: Optional[Node] = None, cont: Optional[Node] = None):
        self.exc = exc
        self.ret = ret
        self.brk = brk
        self.cont = cont

    def derive(self, **kw) -> "_Ctx":
        out = _Ctx(self.exc, self.ret, self.brk, self.cont)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


def _expr_raises(node: Optional[ast.AST]) -> bool:
    """Can evaluating this expression raise? Calls and subscripts are
    the raisers that matter for the invariants here (a KeyError out of
    ``test["members"]`` skips a heal exactly like a failed RPC does);
    attribute loads and arithmetic are treated as safe to keep the
    graph's exception fan-out meaningful."""
    if node is None:
        return False
    return any(isinstance(sub, (ast.Call, ast.Subscript, ast.Await))
               for sub in ast.walk(node))


def _stmt_raises(stmt: ast.stmt) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    return _expr_raises(stmt)


_SIMPLE = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr, ast.Delete,
           ast.Pass, ast.Import, ast.ImportFrom, ast.Global, ast.Nonlocal,
           ast.Assert, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)

Preds = List[Tuple[Node, str]]


class _Builder:
    def __init__(self, fn: ast.FunctionDef):
        self.cfg = CFG(fn.name)

    def build(self, fn: ast.FunctionDef) -> CFG:
        ctx = _Ctx(exc=self.cfg.raise_exit, ret=self.cfg.exit)
        out = self.body(fn.body, [(self.cfg.entry, NORMAL)], ctx)
        self.connect(out, self.cfg.exit)
        return self.cfg

    # ---------------------------------------------------------- plumbing

    def connect(self, preds: Preds, dst: Node) -> None:
        """Attach dangling edges to `dst`, PRESERVING each edge's own
        kind: a dangling if-FALSE arm stays a `false` edge even when it
        flows into a finally instance that resumes an exception —
        analyzers prune on the kind of the edge leaving its source node
        (guards, post-heal exception arms), not on what continuation the
        join serves."""
        for n, k in preds:
            self.cfg.edge(n, dst, k)

    def body(self, stmts: Sequence[ast.stmt], preds: Preds,
             ctx: _Ctx) -> Preds:
        for stmt in stmts:
            preds = self.stmt(stmt, preds, ctx)
        return preds

    def stmt(self, stmt: ast.stmt, preds: Preds, ctx: _Ctx) -> Preds:
        if isinstance(stmt, ast.If):
            return self._if(stmt, preds, ctx)
        if isinstance(stmt, ast.While):
            return self._while(stmt, preds, ctx)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._for(stmt, preds, ctx)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, preds, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, preds, ctx)
        if isinstance(stmt, ast.Return):
            n = self.cfg.new(stmt, "return")
            self.connect(preds, n)
            if _expr_raises(stmt.value):
                self.cfg.edge(n, ctx.exc, EXC)
            self.cfg.edge(n, ctx.ret, NORMAL)
            return []
        if isinstance(stmt, ast.Raise):
            n = self.cfg.new(stmt, "raise")
            self.connect(preds, n)
            self.cfg.edge(n, ctx.exc, EXC)
            return []
        if isinstance(stmt, ast.Break):
            n = self.cfg.new(stmt, "break")
            self.connect(preds, n)
            if ctx.brk is not None:
                self.cfg.edge(n, ctx.brk, NORMAL)
            return []
        if isinstance(stmt, ast.Continue):
            n = self.cfg.new(stmt, "continue")
            self.connect(preds, n)
            if ctx.cont is not None:
                self.cfg.edge(n, ctx.cont, NORMAL)
            return []
        # simple statement (incl. nested def/class: opaque, non-raising
        # at definition time beyond default-arg evaluation)
        n = self.cfg.new(stmt, "stmt")
        self.connect(preds, n)
        if isinstance(stmt, _SIMPLE) and _stmt_raises(stmt):
            self.cfg.edge(n, ctx.exc, EXC)
        return [(n, NORMAL)]

    # ------------------------------------------------------- structures

    def _if(self, stmt: ast.If, preds: Preds, ctx: _Ctx) -> Preds:
        cond = self.cfg.new(stmt, "if")
        self.connect(preds, cond)
        if _expr_raises(stmt.test):
            self.cfg.edge(cond, ctx.exc, EXC)
        out = self.body(stmt.body, [(cond, TRUE)], ctx)
        if stmt.orelse:
            out += self.body(stmt.orelse, [(cond, FALSE)], ctx)
        else:
            out += [(cond, FALSE)]
        return out

    def _while(self, stmt: ast.While, preds: Preds, ctx: _Ctx) -> Preds:
        cond = self.cfg.new(stmt, "while")
        loop_exit = self.cfg.new(stmt, "loop-exit")
        self.connect(preds, cond)
        if _expr_raises(stmt.test):
            self.cfg.edge(cond, ctx.exc, EXC)
        inner = ctx.derive(brk=loop_exit, cont=cond)
        back = self.body(stmt.body, [(cond, TRUE)], inner)
        self.connect(back, cond)
        forever = isinstance(stmt.test, ast.Constant) and \
            bool(stmt.test.value)
        if not forever:
            # `while True:` has no false arm — only `break` leaves.
            self.cfg.edge(cond, loop_exit, FALSE)
        out = [(loop_exit, NORMAL)]
        if stmt.orelse:
            out = self.body(stmt.orelse, out, ctx)
        return out

    def _for(self, stmt, preds: Preds, ctx: _Ctx) -> Preds:
        head = self.cfg.new(stmt, "for")
        loop_exit = self.cfg.new(stmt, "loop-exit")
        self.connect(preds, head)
        if _expr_raises(stmt.iter):
            self.cfg.edge(head, ctx.exc, EXC)
        inner = ctx.derive(brk=loop_exit, cont=head)
        back = self.body(stmt.body, [(head, TRUE)], inner)
        self.connect(back, head)
        self.cfg.edge(head, loop_exit, FALSE)
        out = [(loop_exit, NORMAL)]
        if stmt.orelse:
            out = self.body(stmt.orelse, out, ctx)
        return out

    def _finally_instance(self, finalbody: Sequence[ast.stmt], ctx: _Ctx,
                          onward: Node, kind: str) -> Node:
        """One copy of the finally body whose completion resumes the
        pending continuation via an edge of `kind` to `onward`.
        Exceptions raised *inside* the finally replace the continuation
        and propagate outward (ctx is the outer context)."""
        entry = self.cfg.new(None, "finally")
        out = self.body(list(finalbody), [(entry, NORMAL)], ctx)
        self.connect(out, onward)
        return entry

    def _try(self, stmt: ast.Try, preds: Preds, ctx: _Ctx) -> Preds:
        inner = ctx
        if stmt.finalbody:
            inner = ctx.derive(
                exc=self._finally_instance(stmt.finalbody, ctx,
                                           ctx.exc, EXC),
                ret=self._finally_instance(stmt.finalbody, ctx,
                                           ctx.ret, NORMAL))
            if ctx.brk is not None:
                inner.brk = self._finally_instance(stmt.finalbody, ctx,
                                                   ctx.brk, NORMAL)
            if ctx.cont is not None:
                inner.cont = self._finally_instance(stmt.finalbody, ctx,
                                                    ctx.cont, NORMAL)

        handler_out: Preds = []
        body_ctx = inner
        if stmt.handlers:
            dispatch = self.cfg.new(stmt, "except-dispatch")
            body_ctx = inner.derive(exc=dispatch)
            catch_all = False
            for h in stmt.handlers:
                entry = self.cfg.new(h, "handler")
                self.cfg.edge(dispatch, entry, EXC)
                names = _handler_names(h)
                if any(n in CATCH_ALL or n == "" for n in names):
                    catch_all = True
                # exceptions raised in a handler are not re-dispatched
                # here; they propagate (through any finally) outward
                handler_out += self.body(h.body, [(entry, NORMAL)], inner)
            if not catch_all:
                self.cfg.edge(dispatch, inner.exc, EXC)

        body_out = self.body(stmt.body, preds, body_ctx)
        if stmt.orelse:
            # else runs after an exception-free body; its exceptions are
            # NOT seen by this try's handlers
            body_out = self.body(stmt.orelse, body_out, inner)
        out = body_out + handler_out
        if stmt.finalbody:
            entry = self.cfg.new(None, "finally")
            fin_out = self.body(stmt.finalbody, [(entry, NORMAL)], ctx)
            self.connect(out, entry)
            return fin_out
        return out

    def _with(self, stmt, preds: Preds, ctx: _Ctx) -> Preds:
        enter = self.cfg.new(stmt, "with-enter")
        self.connect(preds, enter)
        if any(_expr_raises(it.context_expr) for it in stmt.items):
            self.cfg.edge(enter, ctx.exc, EXC)

        def exit_marker(onward: Node, kind: str) -> Node:
            m = self.cfg.new(stmt, "with-exit")
            self.cfg.edge(m, onward, kind)
            return m

        inner = ctx.derive(exc=exit_marker(ctx.exc, EXC),
                           ret=exit_marker(ctx.ret, NORMAL))
        if ctx.brk is not None:
            inner.brk = exit_marker(ctx.brk, NORMAL)
        if ctx.cont is not None:
            inner.cont = exit_marker(ctx.cont, NORMAL)
        out = self.body(stmt.body, [(enter, NORMAL)], inner)
        norm = self.cfg.new(stmt, "with-exit")
        self.connect(out, norm)
        return [(norm, NORMAL)]


def _handler_names(h: ast.ExceptHandler) -> List[str]:
    if h.type is None:
        return [""]
    items = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    out = []
    for it in items:
        if isinstance(it, ast.Name):
            out.append(it.id)
        elif isinstance(it, ast.Attribute):
            out.append(it.attr)
        else:
            out.append("?")
    return out


def build_cfg(fn: ast.FunctionDef) -> CFG:
    """CFG of one function body (nested defs are opaque single nodes —
    build theirs separately)."""
    return _Builder(fn).build(fn)


def own_exprs(node: Node) -> List[ast.AST]:
    """The AST actually *evaluated at* this node. Compound-statement
    nodes (if/while/for/with/try) carry the whole construct in `.stmt`
    for location info, but only their header expression executes there —
    matching against the full subtree would credit a node with calls
    that live in its body. Nested function/class defs execute nothing
    of their body at definition time."""
    s = node.stmt
    if s is None:
        return []
    if node.label in ("if", "while"):
        return [s.test]
    if node.label == "for":
        return [s.iter]
    if node.label == "with-enter":
        return [it.context_expr for it in s.items]
    if node.label in ("except-dispatch", "handler", "with-exit",
                      "loop-exit"):
        return []
    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return []
    return [s]


def walk_own(fn: ast.FunctionDef):
    """ast.walk over a function, not descending into nested defs or
    lambdas (their bodies run later, in their own frame)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def functions_of(tree: ast.AST):
    """Every function def in a module, with its enclosing class (or
    None): [(class_node, fn_node)]. Nested functions are included with
    the class of their outermost enclosing scope."""
    out = []

    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((cls, child))
                walk(child, cls)
            else:
                walk(child, cls)

    walk(tree, None)
    return out


def cfg_for(source: str, func: str) -> CFG:
    """Test helper: parse `source` and build the CFG of the (possibly
    nested / method) function named `func`."""
    tree = ast.parse(source)
    for _, fn in functions_of(tree):
        if fn.name == func:
            return build_cfg(fn)
    raise ValueError(f"no function {func!r} in source")


def reach(cfg: CFG, starts: Sequence[Node], stop) -> List[List[Node]]:
    """Depth-first path search used by the dataflow analyzers.

    `stop(node, kind_in)` classifies each visited node:
      * ``"kill"``   — path is discharged here, stop exploring it;
      * ``"report"`` — an escaping path ends here (exit reached while
        the property is still pending): record it;
      * a set/list of edge kinds — keep exploring, but only along edges
        whose kind is in the set;
      * ``None``     — keep exploring along every edge.

    Returns the recorded escape paths (each a node list, for messages).
    Cycles are cut with a visited set, so each node is expanded once —
    sound for pure reachability properties like these."""
    found: List[List[Node]] = []
    seen = set()
    stack = [(n, k, [n]) for n, k in ((s, NORMAL) for s in starts)]
    while stack:
        node, kind, path = stack.pop()
        if node.idx in seen:
            continue
        seen.add(node.idx)
        verdict = stop(node, kind)
        if verdict == "kill":
            continue
        if verdict == "report":
            found.append(path)
            continue
        for succ, k in node.succs:
            if verdict is not None and k not in verdict:
                continue
            stack.append((succ, k, path + [succ]))
    return found

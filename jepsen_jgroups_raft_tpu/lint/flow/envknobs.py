"""Env-knob registry analyzer: every ``JGRAFT_*`` read, accounted for.

Fifteen PRs of growth left ``JGRAFT_*`` knobs scattered across the
checker, service, parallel and bench tiers. Three failure modes keep
recurring: a raw ``int(os.environ.get(...))`` that crashes the importer
on a blank/garbage value (the PR 7 lesson platform.env_int exists to
prevent), two call sites parsing the same knob with *different
defaults* (the behavior silently depends on which module read it
first), and knobs that exist only in the source (doc/running.md's knob
tables drift). This analyzer harvests every read and enforces all
three, and ``build_registry`` emits the harvest as a JSON artifact so
CI (and doc reviews) can diff the actual knob surface.

Rules:

* ``flow-env-raw-parse`` (alias ``env-raw``) — ``int(...)``/
  ``float(...)`` directly wrapping an environment read of a
  ``JGRAFT_*`` name: must go through ``platform.env_int`` /
  ``env_float`` (``env_str`` for string knobs), whose blank/garbage
  handling warns and falls back instead of raising at import time.
* ``flow-env-undocumented`` (alias ``env-doc``) — a ``JGRAFT_*`` knob
  read in code but absent from ``doc/running.md`` (brace groups like
  ``JGRAFT_X_{A,B}`` in the doc are expanded before matching).
* ``flow-env-dup-default`` (alias ``env-dup``) — the same knob parsed
  at multiple sites with conflicting defaults/minimums/types
  (cross-file; reported by ``build_registry``, which the full-repo CLI
  run invokes).

Scan set: the whole package plus ``bench.py`` and the in-scope scripts
(the bench tier is where raw parses historically accumulate).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from ..base import Finding, SourceFile

RULE_RAW = "flow-env-raw-parse"
RULE_DOC = "flow-env-undocumented"
RULE_DUP = "flow-env-dup-default"

#: files outside the package covered by build_registry (and by the
#: per-file rules when the CLI full run invokes it).
EXTRA_FILES = ("bench.py", "scripts/chaos_graftd.py")

_KNOB_RE = re.compile(r"JGRAFT_[A-Z0-9_]+")
_BRACE_RE = re.compile(r"(JGRAFT_[A-Z0-9_]*)\{([A-Z0-9_,\s]+)\}")

_ENV_HELPERS = {"env_int": "int", "env_float": "float", "env_str": "str"}


def applies_to(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    stripped = rp.split("jepsen_jgroups_raft_tpu/", 1)[-1]
    return stripped.endswith(".py") or rp in EXTRA_FILES


# ------------------------------------------------------------ harvesting


class KnobRead:
    __slots__ = ("name", "via", "line", "default", "minimum")

    def __init__(self, name: str, via: str, line: int,
                 default=None, minimum=None):
        self.name = name
        self.via = via
        self.line = line
        self.default = default
        self.minimum = minimum


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal(node: Optional[ast.AST]):
    if node is None:
        return None
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return "<expr>"


def _dotted(expr: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


def _env_read(node: ast.AST) -> Optional[KnobRead]:
    """A JGRAFT_* environment read at this AST node, if any."""
    if isinstance(node, ast.Subscript) and \
            isinstance(node.ctx, ast.Load) and \
            _dotted(node.value) == "os.environ":
        name = _const_str(node.slice)
        if name and name.startswith("JGRAFT_"):
            return KnobRead(name, "environ", node.lineno)
        return None
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    callee = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    if callee == "get" and isinstance(fn, ast.Attribute) and \
            _dotted(fn.value) == "os.environ" and node.args:
        name = _const_str(node.args[0])
        if name and name.startswith("JGRAFT_"):
            return KnobRead(name, "environ", node.lineno,
                            default=_literal(node.args[1])
                            if len(node.args) > 1 else None)
    elif callee == "getenv" and node.args:
        name = _const_str(node.args[0])
        if name and name.startswith("JGRAFT_"):
            return KnobRead(name, "environ", node.lineno,
                            default=_literal(node.args[1])
                            if len(node.args) > 1 else None)
    elif callee in _ENV_HELPERS and node.args:
        name = _const_str(node.args[0])
        if name and name.startswith("JGRAFT_"):
            minimum = None
            for kw in node.keywords:
                if kw.arg == "minimum":
                    minimum = _literal(kw.value)
            if len(node.args) > 2 and minimum is None:
                minimum = _literal(node.args[2])
            return KnobRead(name, callee, node.lineno,
                            default=_literal(node.args[1])
                            if len(node.args) > 1 else None,
                            minimum=minimum)
    return None


def harvest(tree: ast.AST) -> List[KnobRead]:
    return [r for node in ast.walk(tree)
            for r in [_env_read(node)] if r is not None]


def _raw_parses(tree: ast.AST) -> List[Tuple[str, int]]:
    """(knob, line) for int()/float() directly wrapping an environ
    read of a JGRAFT_* name."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("int", "float"):
            for arg in node.args:
                for sub in ast.walk(arg):
                    r = _env_read(sub)
                    if r is not None and r.via == "environ":
                        out.append((r.name, node.lineno))
    return out


# --------------------------------------------------------- documentation


_DOC_CACHE: Dict[str, Optional[Set[str]]] = {}


def doc_knob_names(text: str) -> Set[str]:
    """Knob names mentioned in doc text, expanding ``JGRAFT_X_{A,B}``
    brace groups into JGRAFT_X_A, JGRAFT_X_B."""
    names = set(_KNOB_RE.findall(text))
    for m in _BRACE_RE.finditer(text):
        for part in m.group(2).split(","):
            part = part.strip()
            if part:
                names.add(m.group(1) + part)
    return names


def _find_doc(start: Path) -> Optional[Path]:
    for parent in [start] + list(start.parents):
        cand = parent / "doc" / "running.md"
        if cand.exists():
            return cand
    return None


def _doc_names_for(path_str: str) -> Optional[Set[str]]:
    doc = _find_doc(Path(path_str).resolve().parent)
    if doc is None:
        return None
    key = str(doc)
    if key not in _DOC_CACHE:
        _DOC_CACHE[key] = doc_knob_names(
            doc.read_text(encoding="utf-8", errors="replace"))
    return _DOC_CACHE[key]


# --------------------------------------------------------------- analysis


def analyze_source(src: SourceFile,
                   doc_names: Optional[Set[str]] = None) -> List[Finding]:
    try:
        tree = ast.parse(src.text)
    except SyntaxError as e:
        return [Finding(src.path, e.lineno or 1, "parse-error", str(e))]
    findings: List[Finding] = []
    for knob, line in _raw_parses(tree):
        if src.allowed(line, RULE_RAW) or src.allowed(line, "env-raw"):
            continue
        findings.append(Finding(
            src.path, line, RULE_RAW,
            f"raw int()/float() parse of {knob} — a blank or garbage "
            "value raises at import time; use platform.env_int/"
            "env_float, which warn and fall back to the default "
            "(PR 7 rule)"))
    if doc_names is None:
        doc_names = _doc_names_for(src.path)
    if doc_names is not None:
        seen: Set[str] = set()
        for read in sorted(harvest(tree), key=lambda r: r.line):
            if read.name in seen or read.name in doc_names:
                continue
            seen.add(read.name)
            if src.allowed(read.line, RULE_DOC) or \
                    src.allowed(read.line, "env-doc"):
                continue
            findings.append(Finding(
                src.path, read.line, RULE_DOC,
                f"{read.name} is read here but absent from "
                "doc/running.md's knob tables — add a row (or expand "
                "the brace group that should cover it)"))
    return findings


def analyze_file(path) -> List[Finding]:
    return analyze_source(SourceFile.load(path))


# --------------------------------------------------------------- registry


def build_registry(root) -> Tuple[dict, List[Finding]]:
    """Scan the package + EXTRA_FILES; return (registry-json-dict,
    findings): per-file findings for the EXTRA_FILES (the normal CLI
    walk does not visit them) plus cross-file dup-default findings."""
    root = Path(root)
    files: List[Path] = sorted(
        (root / "jepsen_jgroups_raft_tpu").rglob("*.py"))
    extras = [root / f for f in EXTRA_FILES if (root / f).exists()]
    doc = _doc_names_for(str(root / "jepsen_jgroups_raft_tpu"))
    knobs: Dict[str, List[Tuple[str, KnobRead]]] = {}
    findings: List[Finding] = []
    srcs: Dict[str, SourceFile] = {}
    for f in files + extras:
        src = SourceFile.load(f)
        relp = str(f.relative_to(root))
        srcs[relp] = src
        try:
            tree = ast.parse(src.text)
        except SyntaxError:
            continue  # the per-file pass reports parse errors
        for read in harvest(tree):
            knobs.setdefault(read.name, []).append((relp, read))
        if f in extras:
            for fnd in analyze_source(src, doc_names=doc):
                findings.append(Finding(relp, fnd.line, fnd.rule,
                                        fnd.message))
    # graftgate columns (ISSUE 17 satellite 1): classification from the
    # knobclass table, and whether the knob's value data-flows into any
    # verdict expression (imported lazily — knobclass imports this
    # module for the harvest helpers).
    from .knobclass import knob_class, verdict_taint

    reachable = verdict_taint({relp: s for relp, s in srcs.items()
                               if relp.endswith(".py")})
    registry: Dict[str, dict] = {}
    for name in sorted(knobs):
        sites = sorted(knobs[name], key=lambda s: (s[0], s[1].line))
        typed = [(relp, r) for relp, r in sites if r.via in _ENV_HELPERS]
        # conflicting parse configs for one knob: order-of-import decides
        # the behavior, which is exactly the bug class this rule kills
        distinct = {(r.via, repr(r.default), repr(r.minimum))
                    for _relp, r in typed}
        if len(distinct) > 1:
            first_relp, first = typed[0]
            for relp, r in typed[1:]:
                if (r.via, repr(r.default), repr(r.minimum)) == \
                        (first.via, repr(first.default), repr(first.minimum)):
                    continue
                if srcs[relp].allowed(r.line, RULE_DUP) or \
                        srcs[relp].allowed(r.line, "env-dup"):
                    continue
                findings.append(Finding(
                    relp, r.line, RULE_DUP,
                    f"{name} parsed as {r.via}(default={r.default!r}, "
                    f"minimum={r.minimum!r}) here but as "
                    f"{first.via}(default={first.default!r}, "
                    f"minimum={first.minimum!r}) at {first_relp}:"
                    f"{first.line} — one knob, one parse: hoist a shared "
                    "helper or align the defaults"))
        registry[name] = {
            "type": (typed[0][1].via.replace("env_", "")
                     if typed else "raw"),
            "class": knob_class(name),
            "verdict_reachable": bool(reachable.get(name, False)),
            "documented": (name in doc) if doc is not None else None,
            "sites": [{
                "path": relp, "line": r.line, "via": r.via,
                **({"default": r.default} if r.default is not None else {}),
                **({"minimum": r.minimum} if r.minimum is not None else {}),
            } for relp, r in sites],
        }
    reg = {"version": 2,
           "comment": "JGRAFT_* env-knob registry harvested by the "
                      "envknobs analyzer; regenerate with "
                      "python -m jepsen_jgroups_raft_tpu.lint "
                      "--rules envknobs --knob-registry FILE",
           "knobs": registry}
    return reg, findings

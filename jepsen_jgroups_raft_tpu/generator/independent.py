"""Multi-key decomposition: independent concurrent generator.

Equivalent of jepsen.independent/concurrent-generator + tuple values
(reference register.clj:112-117): client threads are partitioned into
groups of `n`; each group works one key at a time, running `gen_fn(key)`
until it exhausts, then moving to the next key from `keys`. Emitted op
values are wrapped as ``(key, value)`` tuples; the independent checker
(checker/independent.py) splits the history back per key — giving the
batch dimension the TPU checker vmaps over (SURVEY.md §2.4).

Stateful by design (group bookkeeping), safe because the interpreter calls
op() under the scheduler lock.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator

from .base import NEMESIS_THREAD, PENDING, Generator, to_gen


def tuple_value(key, value):
    """Wrap a value in the (key, value) independent tuple."""
    return (key, value)


class ConcurrentGenerator(Generator):
    def __init__(self, n: int, keys: Iterable, gen_fn: Callable):
        if n < 1:
            raise ValueError("need at least 1 thread per key")
        self.n = n
        self.keys: Iterator = iter(keys)
        self.gen_fn = gen_fn
        self.groups: dict = {}  # group id -> generator | None (exhausted)
        self.group_keys: dict = {}

    def _group_gen(self, gid: int):
        if gid not in self.groups:
            self._advance(gid)
        return self.groups[gid]

    def _advance(self, gid: int) -> None:
        try:
            key = next(self.keys)
        except StopIteration:
            self.groups[gid] = None
            self.group_keys[gid] = None
            return
        self.groups[gid] = to_gen(self.gen_fn(key))
        self.group_keys[gid] = key

    def op(self, test, ctx):
        thread = ctx.get("thread")
        if thread == NEMESIS_THREAD or thread is None:
            return PENDING, self
        gid = int(thread) // self.n
        while True:
            g = self._group_gen(gid)
            if g is None:
                # This group is out of keys. Only report global exhaustion
                # (None) when EVERY group is done — a lone None would tell
                # the scheduler the whole generator is finished (e.g. the
                # single-register workload keeps just group 0 busy; other
                # threads idle, reference register.clj:112-117 semantics).
                if all(gg is None for gg in self.groups.values()):
                    return None
                return PENDING, self
            r = g.op(test, ctx)
            if r is None:
                self._advance(gid)
                continue
            op, g2 = r
            self.groups[gid] = g2
            if op == PENDING:
                return PENDING, self
            key = self.group_keys[gid]
            out = dict(op)
            out["value"] = tuple_value(key, out.get("value"))
            return out, self

"""Core generator combinators (see package docstring for the protocol)."""

from __future__ import annotations

import logging
import random
from typing import Callable, Optional, Sequence

LOG = logging.getLogger("jgraft.generator")

#: "nothing for this thread right now" marker.
PENDING = "pending"

NEMESIS_THREAD = "nemesis"


class Generator:
    def op(self, test: dict, ctx: dict):
        raise NotImplementedError

    def update(self, test: dict, ctx: dict, event) -> "Generator":
        return self


def to_gen(x) -> Optional[Generator]:
    """Coerce: Generator | op-dict | callable(test, ctx)->op | list | None."""
    if x is None or isinstance(x, Generator):
        return x
    if isinstance(x, dict):
        return Seq([x])
    if callable(x):
        return OpFn(x)
    if isinstance(x, (list, tuple)):
        return Seq(list(x))
    raise TypeError(f"cannot make a generator from {x!r}")


class OpFn(Generator):
    """Infinite generator from a function (test, ctx) -> op dict."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def op(self, test, ctx):
        return dict(self.fn(test, ctx)), self


class Repeat(Generator):
    """Emit the same op template forever (or n times)."""

    def __init__(self, op_map: dict, n: Optional[int] = None):
        self.op_map = dict(op_map)
        self.n = n

    def op(self, test, ctx):
        if self.n is not None and self.n <= 0:
            return None
        nxt = Repeat(self.op_map, None if self.n is None else self.n - 1)
        return dict(self.op_map), nxt


class Seq(Generator):
    """Run children (generators or op maps) to exhaustion, in order."""

    def __init__(self, items: Sequence):
        self.items = list(items)

    def op(self, test, ctx):
        items = self.items
        while items:
            head = items[0]
            if isinstance(head, dict):
                return dict(head), Seq(items[1:])
            g = to_gen(head)
            r = g.op(test, ctx)
            if r is None:
                items = items[1:]
                continue
            op, g2 = r
            return op, Seq([g2] + items[1:])
        return None

    def update(self, test, ctx, event):
        if self.items and isinstance(self.items[0], Generator):
            return Seq([self.items[0].update(test, ctx, event)] + self.items[1:])
        return self


def Phases(*gens) -> Seq:
    """Sequential phases with a synchronization barrier between them
    (jepsen gen/phases): phase N+1 starts only after every phase-N op has
    *completed*, not merely been handed out — otherwise "final" reads run
    concurrently with unfinished earlier ops and the phase isolation the
    reference's schedule relies on (raft.clj:78-91) silently weakens."""
    items: list = []
    for g in gens:
        items.append(g)
        items.append(Synchronize())
    return Seq(items[:-1] if items else items)


class Mix(Generator):
    """Pick a random child for each emission (jepsen gen/mix). Children are
    op maps or op functions; exhausted children drop out."""

    def __init__(self, choices: Sequence, seed: Optional[int] = None):
        self.choices = list(choices)
        self.rng = random.Random(seed)

    def op(self, test, ctx):
        choices = self.choices
        while choices:
            i = self.rng.randrange(len(choices))
            g = to_gen(choices[i])
            r = g.op(test, ctx)
            if r is None:
                choices = choices[:i] + choices[i + 1:]
                continue
            op, g2 = r
            # __new__ clone: Mix() would reseed a fresh Random from OS
            # entropy on every emission, under the scheduler lock.
            nxt = Mix.__new__(Mix)
            nxt.rng = self.rng
            nxt.choices = choices[:i] + [g2] + choices[i + 1:]
            return op, nxt
        return None


class Stagger(Generator):
    """Space emissions ~dt seconds apart on average (uniform 0..2dt gaps),
    across all threads (jepsen gen/stagger — reference raft.clj:80)."""

    def __init__(self, dt: float, gen, _next_at: Optional[int] = None):
        self.dt = dt
        self.gen = to_gen(gen)
        self.next_at = _next_at  # ns timestamp of next allowed emission
        self.rng = random.Random()

    def _with(self, gen, next_at) -> "Stagger":
        # __new__ clone: Stagger() reseeds a Random from OS entropy; this
        # runs once per emitted op under the scheduler lock.
        nxt = Stagger.__new__(Stagger)
        nxt.dt = self.dt
        nxt.gen = gen
        nxt.next_at = next_at
        nxt.rng = self.rng
        return nxt

    def op(self, test, ctx):
        now = ctx["time"]
        next_at = self.next_at if self.next_at is not None else now
        if now < next_at:
            return PENDING, self
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        if r[0] == PENDING:
            return PENDING, self._with(r[1], next_at)
        op, g2 = r
        gap = int(self.rng.uniform(0, 2 * self.dt) * 1e9)
        # Clamp catch-up: if we fell far behind (idle workers), restart the
        # cadence from now instead of emitting a burst.
        base = next_at if next_at > now - 2 * gap else now
        return op, self._with(g2, base + gap)

    def update(self, test, ctx, event):
        return self._with(self.gen.update(test, ctx, event), self.next_at)


class Limit(Generator):
    """At most n emissions (jepsen gen/limit)."""

    def __init__(self, n: int, gen):
        self.n = n
        self.gen = to_gen(gen)

    def op(self, test, ctx):
        if self.n <= 0:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        op, g2 = r
        if op == PENDING:
            return PENDING, Limit(self.n, g2)
        return op, Limit(self.n - 1, g2)

    def update(self, test, ctx, event):
        return Limit(self.n, self.gen.update(test, ctx, event))


class TimeLimit(Generator):
    """Stop emitting after `secs` of test time (jepsen gen/time-limit)."""

    def __init__(self, secs: float, gen, _deadline: Optional[int] = None):
        self.secs = secs
        self.gen = to_gen(gen)
        self.deadline = _deadline

    def op(self, test, ctx):
        deadline = self.deadline
        if deadline is None:
            deadline = ctx["time"] + int(self.secs * 1e9)
        if ctx["time"] >= deadline:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        op, g2 = r
        return op, TimeLimit(self.secs, g2, deadline)

    def update(self, test, ctx, event):
        return TimeLimit(self.secs, self.gen.update(test, ctx, event),
                         self.deadline)


class Sleep(Generator):
    """Emit nothing for `secs`, then exhaust (jepsen gen/sleep)."""

    def __init__(self, secs: float, _until: Optional[int] = None):
        self.secs = secs
        self.until = _until

    def op(self, test, ctx):
        until = self.until
        if until is None:
            until = ctx["time"] + int(self.secs * 1e9)
            return PENDING, Sleep(self.secs, until)
        if ctx["time"] >= until:
            return None
        return PENDING, self


class Delay(Generator):
    """At least `dt` seconds between successive emissions (jepsen
    gen/delay — used by the membership flip-flop, membership.clj:110)."""

    def __init__(self, dt: float, gen, _next_at: int = 0):
        self.dt = dt
        self.gen = to_gen(gen)
        self.next_at = _next_at

    def op(self, test, ctx):
        if ctx["time"] < self.next_at:
            return PENDING, self
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        op, g2 = r
        if op == PENDING:
            return PENDING, Delay(self.dt, g2, self.next_at)
        return op, Delay(self.dt, g2, ctx["time"] + int(self.dt * 1e9))


class Log(Generator):
    """Log a message once, emit nothing (jepsen gen/log)."""

    def __init__(self, message: str, _done: bool = False):
        self.message = message
        self.done = _done

    def op(self, test, ctx):
        # Mutating under the scheduler lock; containers that re-poll
        # exhausted children (Any) must not re-log on every poll.
        if not self.done:
            self.done = True
            LOG.info(self.message)
        return None  # logging is a side effect; nothing to emit


class FlipFlop(Generator):
    """Alternate emissions between two generators (jepsen gen/flip-flop;
    reference membership.clj:105-111 alternates shrink/grow)."""

    def __init__(self, a, b, _turn: int = 0):
        self.gens = [to_gen(a), to_gen(b)]
        self.turn = _turn

    def op(self, test, ctx):
        g = self.gens[self.turn]
        if g is None:
            return None
        r = g.op(test, ctx)
        if r is None:
            return None
        op, g2 = r
        pair = list(self.gens)
        pair[self.turn] = g2
        if op == PENDING:
            return PENDING, FlipFlop(pair[0], pair[1], self.turn)
        return op, FlipFlop(pair[0], pair[1], 1 - self.turn)


class _Routed(Generator):
    """Restrict a child generator to a class of threads.

    Exhaustion is sticky and visible to ALL threads: once the child
    returns None (observable only on a matching thread's poll), the other
    thread class must see None too — otherwise an Any(clients, nemesis)
    pair deadlocks, each side reporting PENDING to the other forever.
    Mutating the flag is safe: generator calls run under the scheduler
    lock.
    """

    nemesis: bool

    def __init__(self, gen):
        self.gen = to_gen(gen)
        self.dead = self.gen is None

    def _mine(self, ctx) -> bool:
        is_nem = ctx.get("thread") == NEMESIS_THREAD
        return is_nem == self.nemesis

    def op(self, test, ctx):
        if self.dead:
            return None
        if not self._mine(ctx):
            return PENDING, self
        r = self.gen.op(test, ctx)
        if r is None:
            self.dead = True
            return None
        op, g2 = r
        if g2 is self.gen:
            return op, self
        return op, type(self)(g2)

    def update(self, test, ctx, event):
        return type(self)(self.gen.update(test, ctx, event))


class NemesisGen(_Routed):
    """Ops for the nemesis thread only (jepsen gen/nemesis)."""

    nemesis = True


class Clients(_Routed):
    """Ops for client threads only (jepsen gen/clients)."""

    nemesis = False


class Any(Generator):
    """Offer ops from whichever child has one for the asking thread
    (jepsen's implicit merge of client + nemesis streams). Exhausts when
    every child is exhausted."""

    def __init__(self, *gens):
        self.gens = [to_gen(g) for g in gens if g is not None]

    def op(self, test, ctx):
        # Exhausted children are dropped so they aren't re-polled forever.
        new = []
        found = None
        for g in self.gens:
            if found is not None:
                new.append(g)
                continue
            r = g.op(test, ctx)
            if r is None:
                continue
            op, g2 = r
            new.append(g2)
            if op != PENDING:
                found = op
        if not new:
            return None
        out = Any()
        out.gens = new
        return (found if found is not None else PENDING), out

    def update(self, test, ctx, event):
        out = Any()
        out.gens = [g.update(test, ctx, event) for g in self.gens]
        return out


class Synchronize(Generator):
    """Barrier: emit nothing until every worker is idle, then exhaust
    (jepsen gen/synchronize semantics, approximated via the interpreter's
    busy-thread count in ctx)."""

    def op(self, test, ctx):
        if ctx.get("busy", 0) > 0:
            return PENDING, self
        return None

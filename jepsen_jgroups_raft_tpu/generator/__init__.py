"""Generator algebra.

Equivalent surface: the jepsen.generator combinators the reference composes
its schedules from (SURVEY.md §2.3): phases, stagger, mix, limit,
time-limit, sleep, log, flip-flop, delay, nemesis/clients routing, plus the
`independent` concurrent-generator for multi-key workloads
(reference raft.clj:78-91, register.clj:112-117, membership.clj:105-111).

Design: generators are immutable-ish objects with
    op(test, ctx)   -> (op_dict, next_gen) | (PENDING, next_gen) | None
    update(test, ctx, event) -> next_gen
ctx carries {"time": ns_since_start, "thread": requesting thread id
("nemesis" or int)}. The interpreter (core/runner.py) polls each worker's
next op under a scheduler lock; PENDING means "nothing for you right now".
None means exhausted. Emitted ops are plain dicts {"f": ..., "value": ...}
— the interpreter assigns process ids, times, and history indices.
"""

from .base import (  # noqa: F401
    PENDING,
    Generator,
    to_gen,
    Any,
    Clients,
    Delay,
    FlipFlop,
    Limit,
    Log,
    Mix,
    NemesisGen,
    OpFn,
    Phases,
    Repeat,
    Seq,
    Sleep,
    Stagger,
    Synchronize,
    TimeLimit,
)
from .independent import ConcurrentGenerator, tuple_value  # noqa: F401

"""Native tier bindings: build orchestration for the C++ data plane.

The C++ tier (native/src) is the capability equivalent of the reference's
Java tier (SURVEY.md §2.2): raft_server daemon (Server.java), the three
state machines, libraftclient.so sync clients (SyncClient.java family), and
raft_member_cli (the jgroups-raft membership CLI the nemesis shells out to,
membership.clj:22-35). `ensure_built()` plays the role of the reference's
build-server! step (server.clj:48-58: uberjar built once on the control
node, gated so concurrent setups don't race).
"""

from __future__ import annotations

import os
import subprocess
import threading
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]
NATIVE_DIR = REPO_ROOT / "native"
BUILD_DIR = NATIVE_DIR / "build"

SERVER_BIN = BUILD_DIR / "raft_server"
CLIENT_LIB = BUILD_DIR / "libraftclient.so"
MEMBER_CLI = BUILD_DIR / "raft_member_cli"

_build_lock = threading.Lock()
_built = False

#: Sanitizer report markers per SAN= build, shared by every scanner
#: (tests/test_tsan.py, scripts/soak_hell.py --san) so they cannot
#: drift. No LeakSanitizer marker: every SUT exit under the harness is
#: SIGKILL, so LSAN's atexit check never runs — listing it would claim
#: coverage that doesn't exist.
SAN_MARKERS = {
    "tsan": ("WARNING: ThreadSanitizer",),
    "asan": ("ERROR: AddressSanitizer",),
}


def _sources_mtime() -> float:
    src = NATIVE_DIR / "src"
    times = [p.stat().st_mtime for p in src.glob("*")]
    times.append((NATIVE_DIR / "Makefile").stat().st_mtime)
    return max(times)


def ensure_built(san: str = "") -> None:
    """Build the native tier if binaries are missing or stale. Idempotent
    and serialized (build once per process, like build-server!'s
    primary-gated single build). Sanitizer builds (`san="tsan"|"asan"`)
    land in native/build-<san>/ without disturbing the normal binaries."""
    global _built
    with _build_lock:
        if _built and not san:
            return
        if san:
            build_dir = NATIVE_DIR / f"build-{san}"
            server = build_dir / "raft_server"
            stale = (not server.exists()
                     or _sources_mtime() > server.stat().st_mtime)
            if stale:
                proc = subprocess.run(
                    ["make", "-C", str(NATIVE_DIR), f"SAN={san}"],
                    capture_output=True, text=True)
                if proc.returncode != 0:
                    raise RuntimeError(f"native {san} build failed:\n"
                                       f"{proc.stdout}\n{proc.stderr}")
            return
        stale = not (SERVER_BIN.exists() and CLIENT_LIB.exists()
                     and MEMBER_CLI.exists())
        if not stale:
            stale = _sources_mtime() > min(
                SERVER_BIN.stat().st_mtime, CLIENT_LIB.stat().st_mtime,
                MEMBER_CLI.stat().st_mtime)
        if stale:
            proc = subprocess.run(["make", "-C", str(NATIVE_DIR)],
                                  env=dict(os.environ),
                                  capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"native build failed:\n{proc.stdout}\n{proc.stderr}")
        _built = True

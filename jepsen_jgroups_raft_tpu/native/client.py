"""ctypes bindings over libraftclient.so — the sync client family.

Python face of the C++ sync client (native/src/client_lib.cc), shaped like
the reference's Java clients that the Clojure harness loads in-process
(SURVEY.md §1 "key structural fact"; register.clj:14, counter.clj:13,
leader.clj:12):

  NativeRsmConn     ← SyncReplicatedStateMachineClient (put/get/cas)
  NativeCounterConn ← SyncReplicatedCounterClient (fixed counter name "mtc",
                      SyncReplicatedCounterClient.java:11)
  NativeLeaderConn  ← SyncLeaderInspectionClient (inspect → (leader, term))

Status codes map 1:1 onto the harness error taxonomy (client/errors.py →
reference workload/client.clj:6-44); CAS precondition failure returns False
rather than raising (register.clj:82-84 records it as :fail :cas-fail).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional, Tuple

from ..client.errors import (ClientTimeout, ConnectFailed, NotLeader,
                             SocketBroken)
from . import CLIENT_LIB, ensure_built

RC_OK = 0
RC_TIMEOUT = 1
RC_CONNECT = 2
RC_SOCKET = 3
RC_NOT_LEADER = 4
RC_SERVER = 5
RC_CAS_FAIL = 6


class ServerError(Exception):
    """Definite server-side rejection (crossed the wire as a failure
    Response — data/Response.java:42-67 semantics)."""


#: Everything a NativeConn call raises for node-side reasons: OSError
#: covers the whole indefinite family (ClientTimeout ⊂ TimeoutError,
#: ConnectFailed ⊂ ConnectionError, SocketBroken — all OSError
#: subclasses); NotLeader and ServerError are the definite rejections.
#: Callers that probe/clean up catch THIS, not Exception: a broad catch
#: would also swallow harness bugs, which the graftlint taxonomy rule
#: (taxonomy-silent-swallow) flags.
CONN_ERRORS = (OSError, NotLeader, ServerError)


_lib = None
_lib_lock = threading.Lock()

_SIGS = {
    "rc_create": ([ctypes.c_char_p, ctypes.c_int, ctypes.c_int],
                  ctypes.c_void_p),
    "rc_destroy": ([ctypes.c_void_p], None),
    "rc_last_error": ([ctypes.c_void_p], ctypes.c_char_p),
    "rc_map_put": ([ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64],
                   ctypes.c_int),
    "rc_map_get": ([ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int)], ctypes.c_int),
    "rc_map_cas": ([ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
                    ctypes.c_int64], ctypes.c_int),
    "rc_counter_get": ([ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                        ctypes.POINTER(ctypes.c_int64)], ctypes.c_int),
    "rc_counter_add": ([ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64],
                       ctypes.c_int),
    "rc_counter_add_get": ([ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_int64,
                            ctypes.POINTER(ctypes.c_int64)], ctypes.c_int),
    "rc_counter_cas": ([ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                        ctypes.c_int64], ctypes.c_int),
    "rc_inspect": ([ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                    ctypes.POINTER(ctypes.c_int64)], ctypes.c_int),
    "rc_admin_probe": ([ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                        ctypes.POINTER(ctypes.c_int64)], ctypes.c_int),
    "rc_admin_add": ([ctypes.c_void_p, ctypes.c_char_p], ctypes.c_int),
    "rc_admin_remove": ([ctypes.c_void_p, ctypes.c_char_p], ctypes.c_int),
    "rc_admin_block": ([ctypes.c_void_p, ctypes.c_char_p], ctypes.c_int),
    "rc_admin_unblock": ([ctypes.c_void_p], ctypes.c_int),
    "rc_admin_members": ([ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int],
                         ctypes.c_int),
}


def load_lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            ensure_built()
            lib = ctypes.CDLL(str(CLIENT_LIB))
            for name, (argtypes, restype) in _SIGS.items():
                fn = getattr(lib, name)
                fn.argtypes = argtypes
                fn.restype = restype
            _lib = lib
        return _lib


class NativeConn:
    """One blocking connection to one node's client port."""

    def __init__(self, host: str, port: int, timeout: float):
        self.lib = load_lib()
        self.handle = self.lib.rc_create(host.encode(), int(port),
                                         int(timeout * 1000))
        self._closed = False

    def _check(self, rc: int) -> int:
        if rc in (RC_OK, RC_CAS_FAIL):
            return rc
        msg = (self.lib.rc_last_error(self.handle) or b"").decode(
            "utf-8", "replace")
        if rc == RC_TIMEOUT:
            raise ClientTimeout(msg)
        if rc == RC_CONNECT:
            raise ConnectFailed(msg)
        if rc == RC_SOCKET:
            raise SocketBroken(msg)
        if rc == RC_NOT_LEADER:
            raise NotLeader(msg)
        raise ServerError(msg)

    def probe(self) -> Tuple[Optional[str], int]:
        """Local leader view — the JMX RAFT.leader probe analogue
        (server.clj:34-39)."""
        buf = ctypes.create_string_buffer(256)
        term = ctypes.c_int64()
        self._check(self.lib.rc_admin_probe(self.handle, buf, 256,
                                            ctypes.byref(term)))
        leader = buf.value.decode() or None
        return leader, int(term.value)

    def admin_add(self, member_spec: str) -> None:
        self._check(self.lib.rc_admin_add(self.handle, member_spec.encode()))

    def admin_remove(self, name: str) -> None:
        self._check(self.lib.rc_admin_remove(self.handle, name.encode()))

    def admin_block(self, peers) -> None:
        csv = ",".join(sorted(peers))
        self._check(self.lib.rc_admin_block(self.handle, csv.encode()))

    def admin_unblock(self) -> None:
        self._check(self.lib.rc_admin_unblock(self.handle))

    def admin_members(self) -> list:
        buf = ctypes.create_string_buffer(65536)
        self._check(self.lib.rc_admin_members(self.handle, buf, 65536))
        text = buf.value.decode()
        return [s for s in text.split(",") if s]

    def close(self) -> None:
        if not self._closed:
            self.lib.rc_destroy(self.handle)
            self._closed = True

    def __del__(self):
        try:
            self.close()
        except (OSError, AttributeError, TypeError):
            # interpreter-shutdown teardown: ctypes globals may already
            # be gone; anything else should surface
            pass


class NativeRsmConn(NativeConn):
    """Replicated-map connection (register workload)."""

    def put(self, key, value) -> None:
        self._check(self.lib.rc_map_put(self.handle, int(key), int(value)))

    def get(self, key, quorum: bool = True):
        val = ctypes.c_int64()
        found = ctypes.c_int()
        self._check(self.lib.rc_map_get(self.handle, int(key),
                                        1 if quorum else 0,
                                        ctypes.byref(val),
                                        ctypes.byref(found)))
        return int(val.value) if found.value else None

    def cas(self, key, frm, to) -> bool:
        rc = self._check(self.lib.rc_map_cas(self.handle, int(key),
                                             int(frm), int(to)))
        return rc == RC_OK


class NativeCounterConn(NativeConn):
    """Replicated-counter connection; counter name fixed to "mtc" like the
    reference client (SyncReplicatedCounterClient.java:11)."""

    NAME = b"mtc"

    def get(self, quorum: bool = True) -> int:
        val = ctypes.c_int64()
        self._check(self.lib.rc_counter_get(self.handle, self.NAME,
                                            1 if quorum else 0,
                                            ctypes.byref(val)))
        return int(val.value)

    def add(self, delta: int) -> None:
        self._check(self.lib.rc_counter_add(self.handle, self.NAME,
                                            int(delta)))

    def add_and_get(self, delta: int) -> int:
        val = ctypes.c_int64()
        self._check(self.lib.rc_counter_add_get(self.handle, self.NAME,
                                                int(delta),
                                                ctypes.byref(val)))
        return int(val.value)

    def cas(self, expect: int, update: int) -> bool:
        rc = self._check(self.lib.rc_counter_cas(self.handle, self.NAME,
                                                 int(expect), int(update)))
        return rc == RC_OK


class NativeLeaderConn(NativeConn):
    """Leader-inspection connection: inspect() → (leader, term) from the
    contacted node's local raft metadata (LeaderElection.java:35-44)."""

    def inspect(self) -> Tuple[Optional[str], int]:
        buf = ctypes.create_string_buffer(256)
        term = ctypes.c_int64()
        self._check(self.lib.rc_inspect(self.handle, buf, 256,
                                        ctypes.byref(term)))
        leader = buf.value.decode() or None
        return leader, int(term.value)


_KIND_CONN = {
    "register": NativeRsmConn,
    "counter": NativeCounterConn,
    "election": NativeLeaderConn,
}


def make_conn_factory(resolve):
    """Build the workloads' conn_factory over a node→(host, client_port)
    resolver. Mirrors how each workload opens its Java client against the
    node's port-9000 endpoint (register.clj:56-66)."""

    def factory(node: str, kind: str, timeout: float):
        host, port = resolve(node)
        return _KIND_CONN[kind](host, port, timeout)

    return factory

"""Compare-and-set register model.

Equivalent of knossos.model/cas-register as used by the reference's register
workload (reference workload/register.clj:106-111): ops are read / write /
cas over a single register whose initial value is nil.

Completion semantics mirror the reference client:
  * reads are idempotent, so indefinite failures were already turned into
    ``fail`` by the error taxonomy (register.clj:72) — an info read carries
    no constraint and is dropped here too;
  * a CAS that returned false is recorded ``fail`` ``:cas-fail``
    (register.clj:82-84) and dropped — it never mutated the register;
  * info writes/cas may or may not have applied: optional ops.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..history.ops import FAIL, INFO, OK, OpPair
from .base import NIL, EncodedOp, Model, _i32

READ = 0
WRITE = 1
CAS = 2

F_NAMES = {"read": READ, "write": WRITE, "cas": CAS}


class CasRegister(Model):
    name = "cas-register"
    n_fcodes = 3
    readonly_fcodes = (READ,)

    def __init__(self, initial: Optional[int] = None):
        self.initial = NIL if initial is None else _i32(initial)

    def init_state(self) -> int:
        return self.initial

    def step(self, state, f, a, b):
        if f == READ:
            return state, state == a
        if f == WRITE:
            return a, True
        if f == CAS:
            if state == a:
                return b, True
            return state, False
        raise ValueError(f"bad opcode {f}")

    def jax_step(self, state, f, a, b):
        is_write = f == WRITE
        is_cas = f == CAS
        match = state == a
        legal = is_write | match  # read/cas legal iff observed/from matches
        new_state = jnp.where(
            is_write, a, jnp.where(is_cas & match, b, state)
        )
        return new_state, legal

    def step_columnar(self, state, f, a, b):
        """Numpy batch twin of `step` (models/base.py contract): same
        select logic as `jax_step`, host-side."""
        import numpy as np

        is_write = f == WRITE
        is_cas = f == CAS
        match = state == a
        legal = is_write | match
        new_state = np.where(is_write, a,
                             np.where(is_cas & match, b, state))
        return new_state.astype(np.int32), legal

    def dense_domain(self, events):
        """Reachable register values: initial ∪ {a of writes} ∪ {b of cas}
        (a write sets a; a successful cas sets b; reads keep state). Read
        expectations outside this set simply never match — the config dies
        at that read's FORCE, which is the correct verdict."""
        import numpy as np

        from ..history.packing import EV_OPEN

        opens = events[events[:, 0] == EV_OPEN]
        vals = {int(self.initial)}
        vals.update(int(v) for v in opens[opens[:, 2] == WRITE][:, 3])
        vals.update(int(v) for v in opens[opens[:, 2] == CAS][:, 4])
        return [int(self.initial)] + sorted(vals - {int(self.initial)})

    def enable_values(self, enc: EncodedOp):
        """Linearizing a write exposes state a; a cas exposes its
        to-value b; a read exposes nothing."""
        if enc.f == WRITE:
            return (enc.a,)
        if enc.f == CAS:
            return (enc.b,)
        return ()

    def observe_values(self, enc: EncodedOp):
        """A read is legal iff the state equals its returned value; a
        cas iff the state equals its from-value; a write observes
        nothing (unconditionally legal)."""
        if enc.f == READ:
            return (enc.a,)
        if enc.f == CAS:
            return (enc.a,)
        return ()

    def rw_classify(self, f: int, a: int, b: int):
        """Cycle-tier roles (models/base.py contract — the register IS
        a last-writer-wins cell): READ observes a, WRITE exposes a, CAS
        observes a then exposes b. Every encoded register op
        classifies, so register histories always build a graph."""
        if f == READ:
            return ("r", a)
        if f == WRITE:
            return ("w", a)
        if f == CAS:
            return ("rw", a, b)
        return None

    def _encode(self, pair: OpPair) -> Optional[EncodedOp]:
        f = pair.f
        forced = pair.ctype == OK
        if f == "read":
            if not forced:
                return None  # unknown read constrains nothing
            value = pair.completion.value
            return EncodedOp(READ, _i32(value), 0, True)
        if f == "write":
            return EncodedOp(WRITE, _i32(pair.invoke.value), 0, forced)
        if f == "cas":
            frm, to = pair.invoke.value
            return EncodedOp(CAS, _i32(frm), _i32(to), forced)
        raise ValueError(f"cas-register: unknown op f={f!r}")

    def encode_pairs_columnar(self, pairs):
        """Tight-loop twin of `_encode` (see Model.encode_pairs_columnar;
        differential tests pin the two byte-identical)."""
        fs, as_, bs = [], [], []
        forced, ips, cps = [], [], []
        i32 = _i32
        for ip, cp, inv, comp in pairs:
            ctype = comp.type if comp is not None else INFO
            if ctype == FAIL:
                continue
            fo = ctype == OK
            f = inv.f
            if f == "read":
                if not fo:
                    continue  # unknown read constrains nothing
                fs.append(READ)
                as_.append(i32(comp.value))
                bs.append(0)
            elif f == "write":
                fs.append(WRITE)
                as_.append(i32(inv.value))
                bs.append(0)
            elif f == "cas":
                frm, to = inv.value
                fs.append(CAS)
                as_.append(i32(frm))
                bs.append(i32(to))
            else:
                raise ValueError(f"cas-register: unknown op f={f!r}")
            forced.append(fo)
            ips.append(ip)
            cps.append(cp)
        return fs, as_, bs, forced, ips, cps

    def prune_observe_enable(self, fs, as_, bs):
        """Columnar enable/observe (singletons): write enables a, cas
        enables b; read observes a, cas observes a (mirrors
        enable_values/observe_values exactly)."""
        import numpy as np

        f = np.asarray(fs, dtype=np.int32)
        a = np.asarray(as_, dtype=np.int32)
        b = np.asarray(bs, dtype=np.int32)
        enable_has = f != READ
        enable_val = np.where(f == CAS, b, a)
        observe_has = f != WRITE
        observe_val = a
        return enable_val, enable_has, observe_val, observe_has

"""Append-only list model packed into base-32 int32 digits (ISSUE 19).

Elle's bread-and-butter workload is list-append: per key, clients
append unique elements and read the whole list, and the OBSERVED
element order is the write order (a list never reorders or drops).
That recoverability is what the transactional anomaly rung
(checker/anomaly.py) feeds on; this model is the per-key
linearizability face of the same workload, so one history serves both
checkers.

State packing: a list [e₀, …, eₖ] with elements in 1..31 packs as the
base-32 integer ((e₀·32 + e₁)·32 + …) + eₖ — most recent element in
the LOWEST digit, so append is ``state·32 + e``. Element 0 is reserved
as "no digit", which makes the encoding prefix-free: MAX_LEN = 6
elements stay under 32⁶ = 2³⁰ < int32. The encoder rejects
out-of-range elements and over-long lists loudly (queue-model stance:
never wrap silently).

Ops (``f``, ``a``, ``b``):
  * ``READ a``        — completed read observed packed list ``a``:
                        legal iff state == a (the state IS the list).
  * ``APPEND a b``    — completed append of element ``b`` that
                        observed resulting list with packed prefix
                        ``a``: CAS-shaped — legal iff state == a;
                        state' = a·32 + b. The completion's recorded
                        result pins both the prefix and the element,
                        which is exactly the version-order evidence
                        the anomaly rung's ww edges ride.
  * ``APPEND_ANY a``  — crashed append of element ``a``: if it
                        linearizes it appends at whatever the state
                        is; legal iff the list has room; state' =
                        state·32 + a. Optional (info-op semantics).

`rw_classify` marks APPEND as the CAS it is — read a, write a·32+b —
so the exact cycle tier chains version order through completed
appends. APPEND_ANY classifies as a write of the NEGATIVE sentinel
−a−1: packed lists are non-negative, so the sentinel is never
observed, the crashed op is never pulled into the required graph, and
the tier stays sound without skipping the history.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..history.ops import FAIL, INFO, OK, OpPair
from .base import EncodedOp, Model

READ = 0
APPEND = 1
APPEND_ANY = 2

#: base-32 digits: elements in 1..31, 0 reserved as "no digit".
BASE = 32
MAX_ELEM = BASE - 1
#: 32^6 = 2^30 < int32; the packed-prefix bound is 32^(MAX_LEN-1).
MAX_LEN = 6
_PREFIX_MAX = BASE ** (MAX_LEN - 1)


def pack_list(lst) -> int:
    """Pack an element list (ints in 1..31, ≤ MAX_LEN long) into one
    int32; loud rejection outside the encodable domain."""
    if len(lst) > MAX_LEN:
        raise ValueError(
            f"list-append: {len(lst)} elements exceed MAX_LEN={MAX_LEN} "
            "(packed base-32 int32 state)")
    s = 0
    for e in lst:
        e = int(e)
        if not 1 <= e <= MAX_ELEM:
            raise ValueError(
                f"list-append: element {e} outside [1, {MAX_ELEM}]")
        s = s * BASE + e
    return s


def unpack_list(state: int) -> List[int]:
    """Inverse of pack_list (0 digits never occur, so unambiguous)."""
    out: List[int] = []
    s = int(state)
    while s > 0:
        out.append(s % BASE)
        s //= BASE
    out.reverse()
    return out


class ListAppend(Model):
    name = "list-append"
    n_fcodes = 3
    readonly_fcodes = (READ,)
    #: consumed by service-tier admission (service/request.admit): a
    #: history of this model is certifiable by checker/anomaly.py.
    txn_anomaly_capable = True

    def init_state(self) -> int:
        return 0

    def step(self, state, f, a, b):
        # _wrap32: legality bounds every APPLIED transition under
        # 32^MAX_LEN, but the differential contract with jax_step is
        # ELEMENTWISE — illegal transitions must wrap identically too
        if f == READ:
            return state, state == a
        if f == APPEND:
            return _wrap32(a * BASE + b), state == a
        if f == APPEND_ANY:
            return _wrap32(state * BASE + a), state < _PREFIX_MAX
        raise ValueError(f"bad opcode {f}")

    def jax_step(self, state, f, a, b):
        legal = (((f == READ) & (state == a))
                 | ((f == APPEND) & (state == a))
                 | ((f == APPEND_ANY) & (state < _PREFIX_MAX)))
        new_state = jnp.where(f == APPEND, a * BASE + b,
                              jnp.where(f == APPEND_ANY,
                                        state * BASE + a, state))
        return new_state, legal

    def step_columnar(self, state, f, a, b):
        """Numpy batch twin of `step` (models/base.py contract).
        Matches the scalar step elementwise — the arithmetic stays in
        int32 on both paths because legality bounds every applied
        transition under 32^MAX_LEN, and the kernels only take legal
        transitions."""
        import numpy as np

        legal = (((f == READ) & (state == a))
                 | ((f == APPEND) & (state == a))
                 | ((f == APPEND_ANY) & (state < _PREFIX_MAX)))
        a64 = a.astype(np.int64)
        s64 = state.astype(np.int64)
        # int64 math + int32 cast = two's-complement wrap, matching
        # the scalar step's _wrap32 and jax's int32 arithmetic
        new_state = np.where(f == APPEND, a64 * BASE + b,
                             np.where(f == APPEND_ANY,
                                      s64 * BASE + a,
                                      s64)).astype(np.int32)
        return new_state, legal

    def rw_classify(self, f: int, a: int, b: int):
        if f == READ:
            return ("r", int(a))
        if f == APPEND:
            return ("rw", int(a), int(a) * BASE + int(b))
        if f == APPEND_ANY:
            # negative sentinel: never observed, never pulled into the
            # required graph (module docstring)
            return ("w", -int(a) - 1)
        return None

    def _encode(self, pair: OpPair) -> Optional[EncodedOp]:
        f = pair.f
        forced = pair.ctype == OK
        if f == "append":
            e = _elem(pair.invoke.value)
            if not forced:
                return EncodedOp(APPEND_ANY, e, 0, False)
            return EncodedOp(APPEND, _prefix(pair.completion.value, e),
                             e, True)
        if f == "read":
            if not forced:
                # an unobserved read constrains nothing — drop it
                return None
            return EncodedOp(READ, pack_list(_lst(pair.completion.value)),
                             0, True)
        raise ValueError(f"list-append: unknown op f={f!r}")

    def encode_pairs_columnar(self, pairs):
        """Tight-loop twin of `_encode` (see Model.encode_pairs_columnar;
        differential tests pin the two byte-identical). No prune hooks —
        APPEND_ANY's enable set is state-dependent, so the conservative
        None default stands on both paths."""
        fs, as_, bs = [], [], []
        forced, ips, cps = [], [], []
        for ip, cp, inv, comp in pairs:
            ctype = comp.type if comp is not None else INFO
            if ctype == FAIL:
                continue
            fo = ctype == OK
            f = inv.f
            if f == "append":
                e = _elem(inv.value)
                if fo:
                    fs.append(APPEND)
                    as_.append(_prefix(comp.value, e))
                    bs.append(e)
                else:
                    fs.append(APPEND_ANY)
                    as_.append(e)
                    bs.append(0)
            elif f == "read":
                if not fo:
                    continue
                fs.append(READ)
                as_.append(pack_list(_lst(comp.value)))
                bs.append(0)
            else:
                raise ValueError(f"list-append: unknown op f={f!r}")
            forced.append(fo)
            ips.append(ip)
            cps.append(cp)
        return fs, as_, bs, forced, ips, cps


def _wrap32(x: int) -> int:
    """Two's-complement int32 wrap (what jnp int32 arithmetic does)."""
    return ((int(x) + (1 << 31)) % (1 << 32)) - (1 << 31)


def _elem(v) -> int:
    e = int(v)
    if not 1 <= e <= MAX_ELEM:
        raise ValueError(
            f"list-append: element {e} outside [1, {MAX_ELEM}]")
    return e


def _lst(v) -> list:
    if not isinstance(v, (list, tuple)):
        raise ValueError(f"list-append: read observed non-list {v!r}")
    return list(v)


def _prefix(completion_value, elem: int) -> int:
    """Packed prefix of a completed append's recorded result, which
    must be a list ending in the appended element."""
    lst = _lst(completion_value)
    if not lst or int(lst[-1]) != elem:
        raise ValueError(
            f"list-append: completed append of {elem} recorded result "
            f"{lst!r} not ending in it")
    return pack_list(lst[:-1])

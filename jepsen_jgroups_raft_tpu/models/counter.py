"""Replicated counter model.

Equivalent of the reference's hand-written CounterModel
(workload/counter.clj:100-127): ops are read ("get"), add (delta, including
negative deltas — the client maps decrement onto a negated add,
counter.clj:56-59), and add-and-get (delta plus the observed new value).

Semantics pinned by the reference's unit tests (raft_test.clj, SURVEY.md §4):
  * a completed add-and-get requires ``state + delta == observed``
    (counter.clj:113-127);
  * an ``info`` add/add-and-get may or may not have applied. The reference
    model "optimistically applies the delta" for info ops; in this framework
    the same semantics falls out of the search — info ops are *optional*
    linearization candidates, and an info add-and-get's return value is
    unconstrained, i.e. it degrades to a plain add.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..history.ops import FAIL, INFO, OK, OpPair
from .base import EncodedOp, Model, _i32

READ = 0
ADD = 1
ADD_AND_GET = 2


class Counter(Model):
    name = "counter"
    n_fcodes = 3
    readonly_fcodes = (READ,)

    def __init__(self, initial: int = 0):
        self.initial = _i32(initial)

    def init_state(self) -> int:
        return self.initial

    def step(self, state, f, a, b):
        if f == READ:
            return state, state == a
        if f == ADD:
            return _wrap32(state + a), True
        if f == ADD_AND_GET:
            new = _wrap32(state + a)
            return new, new == b
        raise ValueError(f"bad opcode {f}")

    def jax_step(self, state, f, a, b):
        added = state + a  # int32 wraparound matches _wrap32
        legal = (f == ADD) | ((f == READ) & (state == a)) | (
            (f == ADD_AND_GET) & (added == b)
        )
        new_state = jnp.where(f == READ, state, added)
        return new_state, legal

    def step_columnar(self, state, f, a, b):
        """Numpy batch twin of `step` (models/base.py contract): int32
        array addition wraps exactly like `_wrap32`."""
        import numpy as np

        added = (state + a).astype(np.int32)
        legal = (f == ADD) | ((f == READ) & (state == a)) | (
            (f == ADD_AND_GET) & (added == b)
        )
        new_state = np.where(f == READ, state, added).astype(np.int32)
        return new_state, legal

    # State after a set of linearized ops = initial + Σ deltas, regardless
    # of order — the property the mask-mode dense kernel exploits
    # (ops/dense_scan.py): the frontier needs no state dimension.
    mask_determined = True

    def mask_delta(self, f, a, b):
        return jnp.where(f == READ, 0, a)

    def _encode(self, pair: OpPair) -> Optional[EncodedOp]:
        f = pair.f
        forced = pair.ctype == OK
        # decrement ops are adds of the negated delta (counter.clj:56-59)
        sign = -1 if f in ("decr", "decr-and-get") else 1
        if f in ("read", "get"):
            if not forced:
                return None
            return EncodedOp(READ, _i32(pair.completion.value), 0, True)
        if f in ("add", "decr"):
            return EncodedOp(ADD, sign * _i32(pair.invoke.value), 0, forced)
        if f in ("add-and-get", "decr-and-get"):
            if forced:
                # completed value is [delta, new] (counter.clj:113-127)
                delta, new = pair.completion.value
                return EncodedOp(
                    ADD_AND_GET, sign * _i32(delta), _i32(new), True
                )
            # unknown result: constrains nothing beyond the delta
            return EncodedOp(ADD, sign * _i32(pair.invoke.value), 0, False)
        raise ValueError(f"counter: unknown op f={f!r}")

    def encode_pairs_columnar(self, pairs):
        """Tight-loop twin of `_encode` (see Model.encode_pairs_columnar).
        The counter model has no prune hooks (enable/observe inherit the
        conservative None), so `prune_observe_enable` stays None — prune
        is a no-op on both paths."""
        fs, as_, bs = [], [], []
        forced, ips, cps = [], [], []
        i32 = _i32
        for ip, cp, inv, comp in pairs:
            ctype = comp.type if comp is not None else INFO
            if ctype == FAIL:
                continue
            fo = ctype == OK
            f = inv.f
            sign = -1 if f in ("decr", "decr-and-get") else 1
            if f in ("read", "get"):
                if not fo:
                    continue
                fs.append(READ)
                as_.append(i32(comp.value))
                bs.append(0)
            elif f in ("add", "decr"):
                fs.append(ADD)
                as_.append(sign * i32(inv.value))
                bs.append(0)
            elif f in ("add-and-get", "decr-and-get"):
                if fo:
                    delta, new = comp.value
                    fs.append(ADD_AND_GET)
                    as_.append(sign * i32(delta))
                    bs.append(i32(new))
                else:
                    fs.append(ADD)
                    as_.append(sign * i32(inv.value))
                    bs.append(0)
            else:
                raise ValueError(f"counter: unknown op f={f!r}")
            forced.append(fo)
            ips.append(ip)
            cps.append(cp)
        return fs, as_, bs, forced, ips, cps


def _wrap32(x: int) -> int:
    """Two's-complement int32 wraparound, matching jnp.int32 arithmetic."""
    x &= 0xFFFFFFFF
    return x - (1 << 32) if x >= (1 << 31) else x

"""Model protocol for the linearizability checker.

A model is a sequential state machine. The checker asks one question: "is
this operation, with this observed result, legal in this state — and what is
the state afterwards?" (knossos.model/Model semantics, reference L0).

To run on TPU, models are constrained to:
  * int32 state (one scalar; richer models pack their state into 32 bits),
  * a small integer op code ``f`` plus two int32 arguments ``a``/``b``,
  * a branch-free vectorized JAX step (pure jnp where-math, no data-dependent
    control flow) so the kernel can evaluate every (configuration, candidate
    op) pair in one shot on the VPU.

``encode_pair`` is the bridge from history op pairs to kernel ops. It also
owns the completion-type semantics (reference workload/client.clj:52-63 and
counter.clj:113-127):
  * ``fail``  completions are dropped — the op never happened.
  * ``ok``    completions are *forced* — they must linearize before their
              completion event.
  * ``info``  completions (and crashed invokes) are *optional* — they may
              linearize at any point from invocation onward, or never.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from ..history.ops import FAIL, NIL, OpPair

INT32_MIN = -(2**31)
INT32_MAX = 2**31 - 1


def _i32(x) -> int:
    """Clamp a python int into int32 range (values outside are out of model
    range anyway; clamping keeps packing total)."""
    if x is None:
        return NIL
    x = int(x)
    return max(INT32_MIN, min(INT32_MAX, x))


@dataclass(frozen=True)
class EncodedOp:
    """A kernel-ready op: opcode + two int32 args + whether its completion
    forces linearization (ok) or leaves it optional forever (info)."""

    f: int
    a: int
    b: int
    forced: bool


class Model:
    """Base class; subclasses define opcodes, steps, and history encoding."""

    name: str = "abstract"

    def init_state(self) -> int:
        raise NotImplementedError

    def cache_key(self) -> tuple:
        """Hashable identity of this model's compiled-kernel semantics.
        Every kernel cache (ops/dense_scan, ops/pallas_scan,
        ops/linear_scan, parallel/mesh) keys on it. The default assumes a
        model is fully determined by its class + initial state; a subclass
        whose `jax_step`/`mask_delta` depends on extra constructor
        parameters MUST extend the tuple, or equivalent-looking models
        would silently share one stale compiled kernel."""
        return (type(self), int(self.init_state()))

    def step(self, state: int, f: int, a: int, b: int) -> Tuple[int, bool]:
        """Pure python step: (state, op) -> (state', legal). Must agree
        exactly with `jax_step` — the differential tests pin this."""
        raise NotImplementedError

    def jax_step(self, state, f, a, b):
        """Vectorized step on jnp arrays (broadcasting), -> (state', legal).

        Must be branch-free: called inside the frontier-expansion kernel on
        a [n_configs, n_slots] grid.
        """
        raise NotImplementedError

    #: Columnar host twin of `step` (ISSUE 15): numpy int32 arrays over
    #: a batch axis, -> (state' int32 array, legal bool array). The
    #: batched certifier core (checker/certify_batch.py) evaluates one
    #: op per row across a whole batch of histories with it, so it MUST
    #: agree with the scalar `step` ELEMENTWISE — including int32
    #: wraparound and packed-field masking — or batched verdicts drift
    #: from the scalar engine (the differential tests pin this next to
    #: the step↔jax_step pin). None (the default) routes every row
    #: through the scalar certifier.
    step_columnar = None

    def encode_pair(self, pair: OpPair) -> Optional[EncodedOp]:
        """Encode one invocation/completion pair, or None to drop it."""
        if pair.ctype == FAIL:
            return None
        return self._encode(pair)

    def encode_pairs_columnar(self, pairs):
        """Batch-encode indexed pairs ([(invoke_pos, completion_pos|-1,
        invoke, completion|None)], the `pair_ops_indexed` output) into
        parallel lists (fs, as_, bs, forced, invoke_pos, completion_pos)
        of KEPT ops, or None to use the per-pair path.

        This is the encode hot path (~85% of suite wall time was host
        encode before round 3; round 4 removed the remaining per-op
        dataclass+method-call overhead — ~7 µs/op → ~1 µs/op). A model
        implementing it MUST produce exactly what a `encode_pair` loop
        would (differential tests pin this), and must also define
        `prune_observe_enable` consistently with its enable/observe
        hooks: None there ⇔ the hooks disable pruning for this model.
        """
        return None

    def prune_observe_enable(self, fs, as_, bs):
        """Columnar twin of enable_values/observe_values for the fast
        prune: (enable_val, enable_has, observe_val, observe_has) int32/
        bool numpy arrays over the kept ops — valid only for models
        whose enable/observe sets are at most singletons — or None when
        the model's hooks disable pruning (the conservative default)."""
        return None

    def dense_domain(self, events) -> Optional[list]:
        """Enumerate the reachable state-value domain of a packed history
        (events [E,5] int32, initial state FIRST), or None when the domain
        is not small/enumerable. Models that can answer (e.g. a register:
        initial ∪ written ∪ cas-to values) unlock the dense-bitset kernel
        (ops/dense_scan.py); the default keeps the general sort kernel."""
        return None

    #: True when the state after linearizing a SET of ops is independent
    #: of their order (e.g. a counter: state = initial + Σ deltas). Such
    #: models need no state dimension at all in the dense kernel — the
    #: frontier is a bare bitset over window masks, with per-mask states
    #: derived from `mask_delta` subset sums (ops/dense_scan.py mask mode).
    mask_determined = False

    #: Opcodes whose step never mutates state (pure observations). The
    #: weaker-consistency rung family (checker/consistency.py) uses this
    #: to place session-rung precedence edges: an op only has to
    #: linearize before the same process's next *read*. Empty = the
    #: session rung degrades to end-of-stream forces for that model.
    readonly_fcodes: tuple = ()

    def mask_eligible(self, events) -> bool:
        """Per-HISTORY mask-mode eligibility (consulted by the dense
        router alongside the class-level `mask_determined`). The mask
        kernel derives per-config states as initial + subset SUMS of
        `mask_delta`; a model whose state combine is order-independent
        but not additive in general (e.g. a set: OR of element bits)
        can still ride the mask kernel for the histories where sum and
        combine coincide — this hook is that proof, checked against the
        packed events. Default: the class-level claim."""
        return self.mask_determined

    def mask_delta(self, f, a, b):
        """Vectorized: the state delta op (f, a, b) contributes when
        linearized (0 for pure reads). Only consulted when
        `mask_determined` is True."""
        raise NotImplementedError

    # -- crashed-op pruning hooks (SURVEY §7.4.3: crashed ops never
    # retire and double the search frontier; these let the encoder prove
    # some of them irrelevant and drop them before slot assignment) ----

    def enable_values(self, enc: EncodedOp):
        """EVERY state value that linearizing this op can set the state
        to (e.g. a register write's value) — not merely the "new" ones:
        an empty set is a load-bearing assertion that the op NEVER
        changes state (the prune drops crashed ops with empty enable
        sets outright, so an op that rewrites the current/initial value
        must still list it). Return None when the model cannot answer —
        None disables pruning for this op. (Round-3 advisor finding:
        the earlier "newly expose" wording permitted a sound-looking
        implementation that made the prune unsound.)"""
        return None

    def observe_values(self, enc: EncodedOp):
        """State values this op's legality depends on observing (e.g. a
        register read's expected value, a CAS's from-value), or None
        when the model cannot answer — None disables pruning for the
        whole history (every op's observations must be known for the
        'nobody observes v downstream' proof to hold)."""
        return None

    def rw_classify(self, f: int, a: int, b: int):
        """Dependency-graph role of op (f, a, b) for the exact cycle
        tier (checker/cycle.py): ``("r", v)`` reads value v, ``("w",
        v)`` writes value v, ``("rw", rv, wv)`` reads rv then writes wv
        (a CAS), or None — the model cannot classify this op and the
        whole history skips the cycle tier (conservative: the tier only
        ever refutes, so skipping is always sound).

        Contract: only meaningful for last-writer-wins models whose
        state IS the most recently written value (a read of v is legal
        iff the latest preceding write wrote v). The cycle tier's
        writes-before / anti-dependency edge derivations assume exactly
        that; a model violating it must return None."""
        return None

    def _encode(self, pair: OpPair) -> Optional[EncodedOp]:
        raise NotImplementedError

    # -- conveniences -----------------------------------------------------

    def run_sequential(self, encoded_ops) -> bool:
        """Apply ops in order; True iff every step is legal. (Test helper &
        sequential-consistency fast path.)"""
        state = self.init_state()
        for e in encoded_ops:
            state, legal = self.step(state, e.f, e.a, e.b)
            if not legal:
                return False
        return True

"""Grow-only set model over a 32-wide membership bitmask.

The scenario-tier twin of Jepsen's bread-and-butter set workload: clients
``add`` small integer elements and ``read`` the full membership; the
checker asks whether some linearization of the adds explains every
observed membership. State is one int32 — bit ``e`` set ⇔ element ``e``
is a member — so the model rides the branch-free kernel substrate
unchanged (models/base.py int32 constraint).

Op encoding (``f``, ``a``, ``b``):
  * ``ADD e``      — state' = state | (1 << e); always legal.
  * ``READ mask``  — legal iff state == mask (an exact membership
                     observation: the reference set workloads read the
                     whole set, so a read pins every bit, which is what
                     makes stale/phantom elements *linearizability*
                     violations here, not just derived-analysis ones).

Completion semantics follow the taxonomy (models/base.py): ``fail`` adds
are dropped, ``info`` adds are optional forever (they may have applied),
``info`` reads constrain nothing and are dropped.

Kernel routing: a grow-only set's combine (OR) is order-independent but
NOT additive, so the class-level ``mask_determined`` stays False; the
per-history ``mask_eligible`` hook proves the additive special case —
every add in the history targets a distinct element absent from the
initial mask — under which subset SUMS of single-bit deltas equal the
OR, and the history rides the cheap mask kernel. Histories that re-add
elements fall back to the domain kernel (small distinct-add counts) or
the sort ladder, both exact.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..history.ops import FAIL, INFO, OK, OpPair
from .base import EncodedOp, Model, _i32

ADD = 0
READ = 1

#: Membership width: elements live in [0, 32) so the mask fits int32.
SET_WIDTH = 32


def element_mask(value) -> int:
    """Element collection (or pre-packed int mask) → int32 bitmask."""
    if value is None:
        return 0
    if isinstance(value, int):
        if value >> SET_WIDTH:
            raise ValueError(f"set mask {value:#x} exceeds {SET_WIDTH} bits")
        return _i32(value & 0xFFFFFFFF)
    mask = 0
    for e in value:
        e = int(e)
        if not 0 <= e < SET_WIDTH:
            raise ValueError(f"set element {e} outside [0, {SET_WIDTH})")
        mask |= 1 << e
    return _i32(mask)


class GSet(Model):
    name = "set"
    n_fcodes = 2
    readonly_fcodes = (READ,)

    def __init__(self, initial: int = 0):
        self.initial = element_mask(initial)

    def init_state(self) -> int:
        return self.initial

    def step(self, state, f, a, b):
        if f == ADD:
            return _or32(state, a), True
        if f == READ:
            return state, state == a
        raise ValueError(f"bad opcode {f}")

    def jax_step(self, state, f, a, b):
        is_add = f == ADD
        legal = is_add | (state == a)
        new_state = jnp.where(is_add, state | a, state)
        return new_state, legal

    def step_columnar(self, state, f, a, b):
        """Numpy batch twin of `step` (models/base.py contract): int32
        bitwise OR matches `_or32` bit for bit."""
        import numpy as np

        is_add = f == ADD
        legal = is_add | (state == a)
        new_state = np.where(is_add, state | a, state).astype(np.int32)
        return new_state, legal

    def mask_delta(self, f, a, b):
        # Valid ONLY under mask_eligible's distinct-bit proof: each
        # add's single-bit delta sums without carries, so Σ == OR.
        return jnp.where(f == ADD, a, 0)

    def mask_eligible(self, events) -> bool:
        """Additive special case: every ADD in the history carries a
        distinct element bit not present in the initial mask (then
        subset sums of the deltas equal the OR the step computes, with
        no carries). READ deltas are 0, so only ADDs matter."""
        import numpy as np

        from ..history.packing import EV_OPEN

        ev = np.asarray(events)
        opens = ev[(ev[:, 0] == EV_OPEN) & (ev[:, 2] == ADD)]
        adds = opens[:, 3].astype(np.int64) & 0xFFFFFFFF
        if adds.size == 0:
            return True
        combined = np.bitwise_or.reduce(adds)
        if combined & (np.int64(self.initial) & 0xFFFFFFFF):
            return False
        # distinct single bits ⇔ popcount(OR) == count and each is 1-bit
        one_bit = np.all(adds & (adds - 1) == 0) and np.all(adds != 0)
        return bool(one_bit and
                    int(combined).bit_count() == int(adds.size))

    def dense_domain(self, events) -> Optional[list]:
        """Reachable states = initial ∪ {initial | OR(S)} over subsets S
        of the distinct add masks — enumerable when few distinct adds
        occur (e.g. short sub-histories); None hands bigger histories to
        the mask kernel / sort ladder."""
        import numpy as np

        from ..history.packing import EV_OPEN

        ev = np.asarray(events)
        opens = ev[(ev[:, 0] == EV_OPEN) & (ev[:, 2] == ADD)]
        distinct = sorted({int(a) & 0xFFFFFFFF for a in opens[:, 3]})
        if len(distinct) > 4:  # 2^k states; DENSE_MAX_STATES is 16
            return None
        base = int(self.initial) & 0xFFFFFFFF
        states = {base}
        for m in distinct:
            states |= {s | m for s in states}
        return [_u2i(base)] + sorted(_u2i(s) for s in states - {base})

    def _encode(self, pair: OpPair) -> Optional[EncodedOp]:
        f = pair.f
        forced = pair.ctype == OK
        if f == "add":
            elem = pair.invoke.value
            elem = int(elem)
            if not 0 <= elem < SET_WIDTH:
                raise ValueError(
                    f"set: element {elem} outside [0, {SET_WIDTH})")
            return EncodedOp(ADD, _i32(1 << elem), 0, forced)
        if f == "read":
            if not forced:
                return None  # unknown read constrains nothing
            return EncodedOp(READ, element_mask(pair.completion.value),
                             0, True)
        raise ValueError(f"set: unknown op f={f!r}")

    def encode_pairs_columnar(self, pairs):
        """Tight-loop twin of `_encode` (see Model.encode_pairs_columnar;
        differential tests pin the two byte-identical). No prune hooks:
        an add's enable set depends on the current state (OR), so the
        conservative None default stands on both paths."""
        fs, as_, bs = [], [], []
        forced, ips, cps = [], [], []
        for ip, cp, inv, comp in pairs:
            ctype = comp.type if comp is not None else INFO
            if ctype == FAIL:
                continue
            fo = ctype == OK
            f = inv.f
            if f == "add":
                elem = int(inv.value)
                if not 0 <= elem < SET_WIDTH:
                    raise ValueError(
                        f"set: element {elem} outside [0, {SET_WIDTH})")
                fs.append(ADD)
                as_.append(_i32(1 << elem))
                bs.append(0)
            elif f == "read":
                if not fo:
                    continue
                fs.append(READ)
                as_.append(element_mask(comp.value))
                bs.append(0)
            else:
                raise ValueError(f"set: unknown op f={f!r}")
            forced.append(fo)
            ips.append(ip)
            cps.append(cp)
        return fs, as_, bs, forced, ips, cps


def _or32(state: int, mask: int) -> int:
    """int32 OR matching jnp.int32 semantics (negative masks = high bit)."""
    v = (state & 0xFFFFFFFF) | (mask & 0xFFFFFFFF)
    return v - (1 << 32) if v >= (1 << 31) else v


def _u2i(v: int) -> int:
    return v - (1 << 32) if v >= (1 << 31) else v

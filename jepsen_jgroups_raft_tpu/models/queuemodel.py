"""Ticket-FIFO queue model packed into an int32 head/tail sequence state.

Jepsen's other bread-and-butter workload is the queue: unique elements
enqueued once, dequeued at most once, FIFO. A general FIFO's contents
cannot fit one int32 — but a *log-backed* queue's can: the SUT assigns
each enqueued element a dense ticket (its sequence index — exactly what
a raft log does for appended entries), dequeues pop tickets in order,
and the whole queue state collapses to the pair (head, tail):

    state = head | (tail << 15)        # 15-bit fields, int32-positive
    queue contents ≡ the ticket interval [head, tail)

Ops (``f``, ``a``):
  * ``ENQ t``      — completed enqueue observed ticket ``t``: legal iff
                     ``t == tail`` (tickets are handed out in
                     linearization order); tail += 1.
  * ``ENQ_ANY``    — crashed enqueue (ticket unknown): if it linearizes
                     it takes whatever the tail is; always legal;
                     tail += 1. This is the info-op handling: the op is
                     *optional* (models/base.py), so "maybe applied with
                     some ticket" is exactly optional ENQ_ANY.
  * ``DEQ t``      — completed dequeue observed ticket ``t``: legal iff
                     the queue is non-empty and ``t == head``; head += 1.
                     A wrong-order or double dequeue dies here — the
                     FIFO property IS this legality check.
  * ``DEQ_EMPTY``  — dequeue observed an empty queue: legal iff
                     head == tail.
  * ``DEQ_ANY``    — crashed dequeue: if it linearizes it consumed the
                     head; legal iff non-empty; head += 1. Optional.

The state combine is ADDITIVE (every mutating op contributes a fixed
delta: +1 head-units or +1<<15 tail-units) regardless of order, so the
model is `mask_determined` and rides the cheapest dense kernel (mask
mode, ops/dense_scan.py) — legality stays exact because the mask kernel
evaluates `jax_step` legality at each subset-sum state during closure.
Field width bounds histories to < 2^15 enqueues/dequeues; the encoder
rejects longer ones loudly rather than wrapping silently.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..history.ops import FAIL, INFO, OK, OpPair
from .base import EncodedOp, Model

ENQ = 0
ENQ_ANY = 1
DEQ = 2
DEQ_EMPTY = 3
DEQ_ANY = 4

#: head/tail field width; tickets live in [0, 2^15).
TICKET_BITS = 15
TICKET_MAX = (1 << TICKET_BITS) - 1


def pack_state(head: int, tail: int) -> int:
    return (head & TICKET_MAX) | ((tail & TICKET_MAX) << TICKET_BITS)


def unpack_state(state: int):
    return state & TICKET_MAX, (state >> TICKET_BITS) & TICKET_MAX


class TicketQueue(Model):
    name = "queue"
    n_fcodes = 5
    readonly_fcodes = (DEQ_EMPTY,)
    mask_determined = True

    def init_state(self) -> int:
        return 0

    def step(self, state, f, a, b):
        h, t = unpack_state(state)
        if f in (ENQ, ENQ_ANY):
            legal = True if f == ENQ_ANY else a == t
            return pack_state(h, t + 1), legal
        if f in (DEQ, DEQ_ANY):
            legal = h < t if f == DEQ_ANY else (h < t and a == h)
            return pack_state(h + 1, t), legal
        if f == DEQ_EMPTY:
            return state, h == t
        raise ValueError(f"bad opcode {f}")

    def jax_step(self, state, f, a, b):
        h = state & TICKET_MAX
        t = (state >> TICKET_BITS) & TICKET_MAX
        enq = (f == ENQ) | (f == ENQ_ANY)
        deq = (f == DEQ) | (f == DEQ_ANY)
        nonempty = h < t
        legal = ((f == ENQ_ANY)
                 | ((f == ENQ) & (a == t))
                 | ((f == DEQ_ANY) & nonempty)
                 | ((f == DEQ) & nonempty & (a == h))
                 | ((f == DEQ_EMPTY) & (h == t)))
        new_state = state + jnp.where(deq, 1, 0) \
            + jnp.where(enq, 1 << TICKET_BITS, 0)
        return new_state, legal

    def step_columnar(self, state, f, a, b):
        """Numpy batch twin of `step` (models/base.py contract) —
        mirrors the SCALAR `step` exactly, including `pack_state`'s
        per-field masking at the 2^15 boundary (where `jax_step`'s
        additive form would carry across fields; the encoder rejects
        histories long enough to reach it, so the two only differ
        outside the encodable domain)."""
        import numpy as np

        h = state & TICKET_MAX
        t = (state >> TICKET_BITS) & TICKET_MAX
        enq = (f == ENQ) | (f == ENQ_ANY)
        deq = (f == DEQ) | (f == DEQ_ANY)
        nonempty = h < t
        legal = ((f == ENQ_ANY)
                 | ((f == ENQ) & (a == t))
                 | ((f == DEQ_ANY) & nonempty)
                 | ((f == DEQ) & nonempty & (a == h))
                 | ((f == DEQ_EMPTY) & (h == t)))
        nh = np.where(deq, (h + 1) & TICKET_MAX, h)
        nt = np.where(enq, (t + 1) & TICKET_MAX, t)
        new_state = np.where(enq | deq, nh | (nt << TICKET_BITS),
                             state).astype(np.int32)
        return new_state, legal

    def mask_delta(self, f, a, b):
        enq = (f == ENQ) | (f == ENQ_ANY)
        deq = (f == DEQ) | (f == DEQ_ANY)
        return jnp.where(enq, 1 << TICKET_BITS, jnp.where(deq, 1, 0))

    def _encode(self, pair: OpPair) -> Optional[EncodedOp]:
        f = pair.f
        forced = pair.ctype == OK
        if f == "enqueue":
            if not forced:
                return EncodedOp(ENQ_ANY, 0, 0, False)
            return EncodedOp(ENQ, _ticket(pair.completion.value), 0, True)
        if f == "dequeue":
            if not forced:
                return EncodedOp(DEQ_ANY, 0, 0, False)
            v = pair.completion.value
            if v is None:
                return EncodedOp(DEQ_EMPTY, 0, 0, True)
            return EncodedOp(DEQ, _ticket(v), 0, True)
        raise ValueError(f"queue: unknown op f={f!r}")

    def encode_pairs_columnar(self, pairs):
        """Tight-loop twin of `_encode` (see Model.encode_pairs_columnar;
        differential tests pin the two byte-identical). No prune hooks —
        an optional enqueue's enable set is state-dependent, so the
        conservative None default stands on both paths."""
        fs, as_, bs = [], [], []
        forced, ips, cps = [], [], []
        for ip, cp, inv, comp in pairs:
            ctype = comp.type if comp is not None else INFO
            if ctype == FAIL:
                continue
            fo = ctype == OK
            f = inv.f
            if f == "enqueue":
                if fo:
                    fs.append(ENQ)
                    as_.append(_ticket(comp.value))
                else:
                    fs.append(ENQ_ANY)
                    as_.append(0)
            elif f == "dequeue":
                if not fo:
                    fs.append(DEQ_ANY)
                    as_.append(0)
                elif comp.value is None:
                    fs.append(DEQ_EMPTY)
                    as_.append(0)
                else:
                    fs.append(DEQ)
                    as_.append(_ticket(comp.value))
            else:
                raise ValueError(f"queue: unknown op f={f!r}")
            bs.append(0)
            forced.append(fo)
            ips.append(ip)
            cps.append(cp)
        # Loud field-overflow rejection for UN-ticketed ops too: _ticket
        # bounds every observed ticket, but a history of >2^15 crashed
        # enqueues/dequeues would let the kernels wrap the packed
        # head/tail fields silently (ENQ_ANY carries no ticket to
        # validate). Counting here covers the production encode path.
        n_enq = sum(1 for f in fs if f in (ENQ, ENQ_ANY))
        n_deq = sum(1 for f in fs if f in (DEQ, DEQ_ANY))
        if n_enq > TICKET_MAX or n_deq > TICKET_MAX:
            raise ValueError(
                f"queue: {max(n_enq, n_deq)} enqueue/dequeue ops exceed "
                f"the packed head/tail field (2^{TICKET_BITS} - 1)")
        return fs, as_, bs, forced, ips, cps


def _ticket(v) -> int:
    t = int(v)
    if not 0 <= t <= TICKET_MAX:
        raise ValueError(
            f"queue: ticket {t} outside [0, {TICKET_MAX}] — histories "
            f"longer than 2^{TICKET_BITS} enqueues exceed the packed "
            "head/tail state")
    return t

"""Consistency models.

Equivalent surface: knossos.model (reference L0 dep) plus the two
hand-written models in the reference — CounterModel
(workload/counter.clj:100-127) and LeaderModel (workload/leader.clj:63-75).

A model here is a deterministic state machine over int32 state with a
vectorized JAX step, so the linearizability frontier search can run it
on-device for thousands of configurations at once (SURVEY.md §7.2 step 2).
"""

from .base import Model, NIL  # noqa: F401
from .register import CasRegister  # noqa: F401
from .counter import Counter  # noqa: F401
from .leader import LeaderModel  # noqa: F401
from .setmodel import GSet  # noqa: F401
from .queuemodel import TicketQueue  # noqa: F401
from .listappend import ListAppend  # noqa: F401

#: name → constructor, used by workloads and the CLI.
MODELS = {
    "cas-register": CasRegister,
    "counter": Counter,
    "leader": LeaderModel,
    "set": GSet,
    "queue": TicketQueue,
    "list-append": ListAppend,
}

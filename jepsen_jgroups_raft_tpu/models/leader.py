"""Election-safety model.

Equivalent of the reference's LeaderModel (workload/leader.clj:63-75): each
``inspect`` op observes a ``(leader, term)`` tuple; the invariant is that no
term ever has two different leaders ("election safety"). Like the reference
(comment at leader.clj:58-62) it does NOT check majority agreement.

This invariant is order-independent — no linearization search is needed —
so it gets a direct vectorized check rather than the frontier kernel:
sort observations by term and compare adjacent same-term leaders.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..history.ops import INVOKE, OK, History


class LeaderModel:
    """Checks election safety over inspect observations."""

    name = "leader"

    def observations(self, history: History) -> np.ndarray:
        """Extract [(term, leader_id)] int32 pairs from ok inspect ops.

        Leaders are interned to dense int ids (node names are strings).
        """
        self._leaders: dict = {}
        rows = []
        for op in history:
            if op.type == OK and op.f == "inspect":
                leader, term = op.value
                if leader is None:
                    continue  # no leader known at inspection time
                lid = self._leaders.setdefault(leader, len(self._leaders))
                rows.append((int(term), lid))
        return np.asarray(rows, dtype=np.int32).reshape(-1, 2)

    def check(self, history: History) -> dict:
        obs = self.observations(history)
        valid, bad_term = check_election_safety_np(obs)
        result = {"valid?": bool(valid), "observation-count": int(len(obs))}
        if not valid:
            by_id = {v: k for k, v in self._leaders.items()}
            leaders = sorted(
                {by_id[int(l)] for t, l in obs if int(t) == bad_term}
            )
            result["error"] = (
                f"two leaders observed for term {bad_term}: {leaders}"
            )
            result["term"] = int(bad_term)
        return result


class MajorityLeaderModel(LeaderModel):
    """Opt-in strengthening past the reference's parity point.

    The reference deliberately does NOT check cross-node agreement
    (leader.clj:58-62: a partitioned node can legitimately still think X
    is leader — stale views are not errors). But this build's DB probes
    EVERY node's local view (deploy/local.py primaries), so stronger —
    still sound — invariants are checkable from `views` observations
    (ops with f="views", value = [(node, leader, term), ...]):

      1. POOLED election safety: one leader per term across every
         node's view, not just the connected node's. Two same-term
         majorities with different leaders must share a node (majorities
         intersect), and that node's two reports collide here — so a
         genuine dual-majority view fails while a stale minority view
         (old leader at an OLD term) passes.
      2. Per-node term monotonicity: a node's reported term never goes
         backward. Lagging forever is fine; regressing is not (Raft
         terms are monotone per server: currentTerm only grows).
    """

    name = "leader-majority"

    def check(self, history: History) -> dict:
        result = super().check(history)  # inspect-op safety (parity)
        pooled = []  # (term, leader_id) across inspect + views
        # node -> [(invoke_idx, ok_idx, term)] — both endpoints kept
        # because concurrent views ops have no order: monotonicity may
        # only be asserted between snapshots where one op COMPLETED
        # before the other was INVOKED (a later-invoked op overlapping
        # an earlier one can legitimately land first in the history).
        by_node: dict = {}
        pending: dict = {}  # process -> invoke idx of its open views op
        interned = dict(self._leaders)
        for idx, op in enumerate(history):
            if op.f == "views" and op.type == INVOKE:
                pending[op.process] = idx
            if op.type != OK:
                continue
            if op.f == "inspect":
                leader, term = op.value
                if leader is not None:
                    lid = interned.setdefault(leader, len(interned))
                    pooled.append((int(term), lid))
            elif op.f == "views":
                inv = pending.pop(op.process, idx)
                for node, leader, term in op.value or ():
                    if leader is None:
                        continue
                    lid = interned.setdefault(leader, len(interned))
                    pooled.append((int(term), lid))
                    by_node.setdefault(node, []).append(
                        (inv, idx, int(term)))
        obs = np.asarray(pooled, dtype=np.int32).reshape(-1, 2)
        ok, bad_term = check_election_safety_np(obs)
        if not ok:
            by_id = {v: k for k, v in interned.items()}
            leaders = sorted({by_id[int(l)] for t, l in obs
                              if int(t) == bad_term})
            result["valid?"] = False
            result["error"] = ("cross-node election safety: two leaders "
                              f"for term {bad_term}: {leaders}")
            result["term"] = int(bad_term)
        for node, snaps in sorted(by_node.items()):
            # Compare each snapshot only against the max term of
            # snapshots that happened-before it (completed before its
            # invocation). Two-pointer sweep — snapshots in invocation
            # order, a completion-ordered cursor carrying the running
            # max — keeps this O(n log n); the naive per-snapshot
            # rescan was O(n^2) and, now that this model is the
            # DEFAULT, sat on every election run's checking path
            # (round-5 review finding).
            done = sorted(snaps, key=lambda s: s[1])
            k = 0
            run_max = None
            for inv_j, _, term_j in sorted(snaps):
                while k < len(done) and done[k][1] < inv_j:
                    t = done[k][2]
                    run_max = t if run_max is None else max(run_max, t)
                    k += 1
                if run_max is not None and term_j < run_max:
                    result["valid?"] = False
                    result["error"] = (
                        f"node {node} term went backward: {run_max} "
                        f"-> {term_j} across non-overlapping snapshots")
                    return result
        result["view-count"] = int(sum(len(t) for t in by_node.values()))
        return result


def check_election_safety_np(obs: np.ndarray) -> Tuple[bool, Optional[int]]:
    """(valid?, first offending term). obs: [N,2] int32 (term, leader)."""
    if len(obs) == 0:
        return True, None
    order = np.lexsort((obs[:, 1], obs[:, 0]))
    s = obs[order]
    same_term = s[1:, 0] == s[:-1, 0]
    diff_leader = s[1:, 1] != s[:-1, 1]
    bad = same_term & diff_leader
    if bad.any():
        return False, int(s[1:][bad][0, 0])
    return True, None


def check_election_safety_jax(obs):
    """Batched/jittable variant: obs [N,2] int32 (padded rows = -1 term).

    Returns a bool scalar. Sorts by (term, leader) and checks adjacency;
    padding terms of -1 are allowed to repeat by also padding leader = -1.
    """
    import jax.numpy as jnp
    from jax import lax

    term, leader = obs[:, 0], obs[:, 1]
    ts, ls = lax.sort((term, leader), num_keys=2)
    same_term = ts[1:] == ts[:-1]
    diff_leader = ls[1:] != ls[:-1]
    real = ts[1:] >= 0
    return ~jnp.any(same_term & diff_leader & real)

"""Election-safety model.

Equivalent of the reference's LeaderModel (workload/leader.clj:63-75): each
``inspect`` op observes a ``(leader, term)`` tuple; the invariant is that no
term ever has two different leaders ("election safety"). Like the reference
(comment at leader.clj:58-62) it does NOT check majority agreement.

This invariant is order-independent — no linearization search is needed —
so it gets a direct vectorized check rather than the frontier kernel:
sort observations by term and compare adjacent same-term leaders.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import numpy as np

from ..history.ops import OK, History, Op


class LeaderModel:
    """Checks election safety over inspect observations."""

    name = "leader"

    def observations(self, history: History) -> np.ndarray:
        """Extract [(term, leader_id)] int32 pairs from ok inspect ops.

        Leaders are interned to dense int ids (node names are strings).
        """
        self._leaders: dict = {}
        rows = []
        for op in history:
            if op.type == OK and op.f == "inspect":
                leader, term = op.value
                if leader is None:
                    continue  # no leader known at inspection time
                lid = self._leaders.setdefault(leader, len(self._leaders))
                rows.append((int(term), lid))
        return np.asarray(rows, dtype=np.int32).reshape(-1, 2)

    def check(self, history: History) -> dict:
        obs = self.observations(history)
        valid, bad_term = check_election_safety_np(obs)
        result = {"valid?": bool(valid), "observation-count": int(len(obs))}
        if not valid:
            by_id = {v: k for k, v in self._leaders.items()}
            leaders = sorted(
                {by_id[int(l)] for t, l in obs if int(t) == bad_term}
            )
            result["error"] = (
                f"two leaders observed for term {bad_term}: {leaders}"
            )
            result["term"] = int(bad_term)
        return result


def check_election_safety_np(obs: np.ndarray) -> Tuple[bool, Optional[int]]:
    """(valid?, first offending term). obs: [N,2] int32 (term, leader)."""
    if len(obs) == 0:
        return True, None
    order = np.lexsort((obs[:, 1], obs[:, 0]))
    s = obs[order]
    same_term = s[1:, 0] == s[:-1, 0]
    diff_leader = s[1:, 1] != s[:-1, 1]
    bad = same_term & diff_leader
    if bad.any():
        return False, int(s[1:][bad][0, 0])
    return True, None


def check_election_safety_jax(obs):
    """Batched/jittable variant: obs [N,2] int32 (padded rows = -1 term).

    Returns a bool scalar. Sorts by (term, leader) and checks adjacency;
    padding terms of -1 are allowed to repeat by also padding leader = -1.
    """
    import jax.numpy as jnp
    from jax import lax

    term, leader = obs[:, 0], obs[:, 1]
    ts, ls = lax.sort((term, leader), num_keys=2)
    same_term = ts[1:] == ts[:-1]
    diff_leader = ls[1:] != ls[:-1]
    real = ts[1:] >= 0
    return ~jnp.any(same_term & diff_leader & real)

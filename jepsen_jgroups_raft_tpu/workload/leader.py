"""Leader-election workload: inspect leadership, check election safety.

Equivalent of the reference's election workload (workload/leader.clj):
a single `inspect` op (leader.clj:14-17) observing (leader, term) tuples,
checked for election safety — no two leaders in one term (leader.clj:63-75;
like the reference, majority agreement is NOT checked).
"""

from __future__ import annotations

from ..checker.base import Checker, compose
from ..checker.stats import StatsChecker
from ..checker.timeline import TimelineChecker
from ..client.base import Client
from ..generator.base import Limit, Mix
from ..history.ops import History, OK, Op
from ..models.leader import LeaderModel


def inspect(test, ctx):
    return {"f": "inspect", "value": None}


class LeaderInspectionClient(Client):
    def __init__(self, conn_factory, timeout: float = 10.0):
        self.conn_factory = conn_factory
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        c = LeaderInspectionClient(self.conn_factory, self.timeout)
        c.conn = self.conn_factory(node, "election", self.timeout)
        return c

    def invoke(self, test, op: Op) -> Op:
        if op.f != "inspect":
            raise ValueError(f"election: unknown op {op.f!r}")
        leader, term = self.conn.inspect()
        return op.replace(type=OK, value=(leader, term))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


class ElectionSafetyChecker(Checker):
    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        return LeaderModel().check(history.client_ops())


def leader_workload(opts: dict) -> dict:
    total_ops = opts.get("total_ops")
    gen = Mix([inspect])
    if total_ops:
        gen = Limit(total_ops, gen)
    return {
        "client": LeaderInspectionClient(
            opts["conn_factory"], opts.get("operation_timeout", 10.0)),
        "checker": compose({
            "timeline": TimelineChecker(),
            "stats": StatsChecker(),
            "linear": ElectionSafetyChecker(),
        }),
        "generator": gen,
        "idempotent": {"inspect"},  # leader.clj:39
        "model": LeaderModel,
    }

"""Leader-election workload: inspect leadership, check election safety.

Equivalent of the reference's election workload (workload/leader.clj):
a single `inspect` op (leader.clj:14-17) observing (leader, term) tuples,
checked for election safety — no two leaders in one term (leader.clj:63-75).
Unlike the reference (which deliberately skips cross-node agreement,
leader.clj:58-62), the DEFAULT checker here is the cross-node majority
model (pooled per-term safety + per-node term monotonicity) fed by an
every-node `views` probe; pass ``weak_election`` for reference parity.
"""

from __future__ import annotations

from ..checker.base import Checker, compose
from ..checker.stats import StatsChecker
from ..checker.timeline import TimelineChecker
from ..client.base import Client
from ..generator.base import Limit, Mix
from ..history.ops import History, OK, Op
from ..models.leader import LeaderModel, MajorityLeaderModel


def inspect(test, ctx):
    return {"f": "inspect", "value": None}


def views(test, ctx):
    return {"f": "views", "value": None}


class LeaderInspectionClient(Client):
    def __init__(self, conn_factory, timeout: float = 10.0,
                 views_probe=None):
        self.conn_factory = conn_factory
        self.timeout = timeout
        self.views_probe = views_probe
        self.conn = None

    def open(self, test, node):
        c = LeaderInspectionClient(self.conn_factory, self.timeout,
                                   self.views_probe)
        c.conn = self.conn_factory(node, "election", self.timeout)
        return c

    def invoke(self, test, op: Op) -> Op:
        if op.f == "views":
            # Every node's local (leader, term) — the primaries-probe
            # data the majority checker consumes. Unreachable nodes are
            # simply absent (their staleness is the tolerated case).
            return op.replace(type=OK, value=self.views_probe())
        if op.f != "inspect":
            raise ValueError(f"election: unknown op {op.f!r}")
        leader, term = self.conn.inspect()
        return op.replace(type=OK, value=(leader, term))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


class ElectionSafetyChecker(Checker):
    def __init__(self, majority: bool = False):
        self.majority = majority

    def check(self, test, history, opts=None) -> dict:
        if not isinstance(history, History):
            history = History(history)
        model = MajorityLeaderModel() if self.majority else LeaderModel()
        return model.check(history.client_ops())


def leader_workload(opts: dict) -> dict:
    total_ops = opts.get("total_ops")
    weak = bool(opts.get("weak_election"))
    views_probe = None if weak else opts.get("views_probe")
    # Default-on strengthening (VERDICT r4 #5): with a views probe wired
    # (every local/ssh deployment has one), every 4th op snapshots all
    # nodes' views and the checker runs the cross-node majority model —
    # pooled per-term safety + per-node term monotonicity — on top of
    # the parity check. `weak_election` is the escape hatch back to the
    # reference-parity single-client model (leader.clj:58-62 checks no
    # cross-node agreement at all).
    gen = Mix([inspect, inspect, inspect, views] if views_probe
              else [inspect])
    if total_ops:
        gen = Limit(total_ops, gen)
    return {
        "client": LeaderInspectionClient(
            opts["conn_factory"], opts.get("operation_timeout", 10.0),
            views_probe=views_probe),
        "checker": compose({
            "timeline": TimelineChecker(),
            "stats": StatsChecker(),
            "linear": ElectionSafetyChecker(majority=not weak),
        }),
        "generator": gen,
        "idempotent": {"inspect", "views"},  # leader.clj:39
        "model": LeaderModel,
    }

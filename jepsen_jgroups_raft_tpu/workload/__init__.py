"""Workloads.

Equivalent of the reference's workload registry (workload/workload.clj:7-15):
name → constructor; each constructor takes the options map and returns a
dict with client / checker / generator / idempotent keys (the
`{:client :checker :generator}` shape of register.clj:100-117).
"""

from .register import register_workload
from .counter import counter_workload
from .leader import leader_workload


def single_register(opts):
    return register_workload({**opts, "keys": range(1)})


def multi_register(opts):
    import itertools

    return register_workload({**opts, "keys": itertools.count()})


#: name → constructor (reference workload.clj:10-15).
WORKLOADS = {
    "single-register": single_register,
    "multi-register": multi_register,
    "counter": counter_workload,
    "election": leader_workload,
}

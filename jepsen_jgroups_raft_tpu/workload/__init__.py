"""Workloads.

Equivalent of the reference's workload registry (workload/workload.clj:7-15):
name → constructor; each constructor takes the options map and returns a
dict with client / checker / generator / idempotent keys (the
`{:client :checker :generator}` shape of register.clj:100-117).
"""

from .register import register_workload
from .counter import counter_workload
from .leader import leader_workload
from .set import set_workload
from .queue import queue_workload
from .listappend import listappend_workload


def single_register(opts):
    return register_workload({**opts, "keys": range(1)})


def multi_register(opts):
    """Independent multi-key registers (generator/independent.py
    concurrent generator; checker: one cross-key batched kernel launch
    via checker/independent.check_keyed)."""
    import itertools

    return register_workload({**opts, "keys": itertools.count()})


#: name → constructor (reference workload.clj:10-15; set/queue are the
#: ISSUE-10 scenario tier).
WORKLOADS = {
    "single-register": single_register,
    "multi-register": multi_register,
    "counter": counter_workload,
    "election": leader_workload,
    "set": set_workload,
    "queue": queue_workload,
    "list-append": listappend_workload,
}

"""List-append workload: Elle's transactional shape over the map (ISSUE 19).

Per key, clients append unique elements to an append-only list and
read the whole list back; SESSIONS deliberately hop across keys, so
the recorded history carries the cross-key program-order edges the
transactional anomaly rung (checker/anomaly.py) needs — the per-key
relaxation rungs literally cannot see a cross-key cycle (independent
decomposition throws the po edges away), which is the whole point of
running this workload beside them.

Substrate: each key's list lives as a base-32 packed int
(models/listappend.py) in one register-conn key ``la-<k>``, mutated by
the CAS retry loop every scenario workload uses — so it runs on every
deployment tier serving the register conn. A completed append records
the RESULTING list (the CAS's to-value, unpacked): that observation is
the version-order evidence both checkers feed on. Timeouts are
honestly indefinite (the CAS may have landed).

Checker stack: per-key linearizability over the ListAppend frontier
model (one cross-key batched launch, checker/independent.py) PLUS the
multi-key TxnAnomalyChecker on the undecomposed history — G0 / G1c /
G-single certification via the cycle tier's condensation + blocked
closure arms.
"""

from __future__ import annotations

import random

from ..checker.anomaly import TxnAnomalyChecker
from ..checker.base import compose
from ..checker.independent import IndependentLinearizable
from ..checker.stats import StatsChecker
from ..checker.timeline import TimelineChecker
from ..client.base import Client
from ..generator.base import Limit
from ..history.ops import FAIL, OK, Op
from ..models.listappend import (MAX_ELEM, MAX_LEN, ListAppend, pack_list,
                                 unpack_list)

#: register-conn key prefix; one packed list per workload key.
KEY_PREFIX = "la-"

#: CAS rounds before an append reports definite contention failure
#: (the loop never mutated anything, so FAIL is sound).
MAX_CAS_ROUNDS = 64


class ListAppendClient(Client):
    """Append-only lists over the register conn (get/cas retry)."""

    def __init__(self, conn_factory, timeout: float = 10.0):
        self.conn_factory = conn_factory
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        c = ListAppendClient(self.conn_factory, self.timeout)
        c.conn = self.conn_factory(node, "register", self.timeout)
        return c

    def invoke(self, test, op: Op) -> Op:
        key, v = op.value
        store_key = f"{KEY_PREFIX}{key}"
        if op.f == "read":
            cur = self.conn.get(store_key,
                                quorum=test.get("quorum_reads", True))
            return op.replace(type=OK, value=(key, unpack_list(int(cur or 0))))
        if op.f == "append":
            e = int(v)
            for _ in range(MAX_CAS_ROUNDS):
                cur = int(self.conn.get(store_key, quorum=True) or 0)
                lst = unpack_list(cur)
                if len(lst) >= MAX_LEN:
                    # definite: the list is full, the append never ran
                    return op.replace(type=FAIL, error="list-full")
                if self.conn.cas(store_key, cur or None, pack_list(lst + [e])):
                    return op.replace(type=OK, value=(key, lst + [e]))
            return op.replace(type=FAIL, error="cas-contention")
        raise ValueError(f"list-append: unknown op {op.f!r}")

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def _keyhop_generator(n_keys: int, seed=None):
    """Op generator hopping keys WITHIN each session (the cross-key po
    edges live or die here): every op picks a random key; appends drain
    a per-key unique-element budget (1..MAX_ELEM, at most MAX_LEN per
    key so lists stay packable), reads keep flowing after the budget is
    spent."""
    rng = random.Random(seed)
    remaining = {k: list(range(1, min(MAX_LEN, MAX_ELEM) + 1))
                 for k in range(n_keys)}

    def gen(test, ctx):
        k = rng.randrange(n_keys)
        budget = remaining[k]
        if budget and rng.random() < 0.6:
            return {"f": "append", "value": (k, budget.pop(0))}
        return {"f": "read", "value": (k, None)}

    return gen


def listappend_workload(opts: dict) -> dict:
    n_keys = int(opts.get("listappend_keys", 4))
    n_ops = int(opts.get("listappend_ops", n_keys * 2 * MAX_LEN))
    return {
        "client": ListAppendClient(opts["conn_factory"],
                                   opts.get("operation_timeout", 10.0)),
        "checker": compose({
            "timeline": TimelineChecker(),
            "stats": StatsChecker(),
            # the undecomposed multi-key history — cross-key anomalies
            "txn": TxnAnomalyChecker(),
            "linear": IndependentLinearizable(
                ListAppend,
                algorithm=opts.get("algorithm", "auto"),
                consistency=opts.get("consistency", "linearizable")),
        }),
        "generator": Limit(n_ops, _keyhop_generator(n_keys,
                                                    opts.get("seed"))),
        "idempotent": {"read"},
        "model": ListAppend,
    }

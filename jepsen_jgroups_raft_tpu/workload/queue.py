"""Queue workload: ticket-FIFO enqueue/dequeue over the replicated map.

The scenario-tier twin of Jepsen's queue workload, shaped for the
raft-log substrate: a log-backed FIFO hands each enqueued element a
dense *ticket* (its sequence index — what a raft log does for appended
entries), and dequeues pop tickets in order. The whole queue state is
the (head, tail) pair, packed into one register of the replicated map
and mutated by CAS retry loops — so the workload runs on every
deployment tier serving the register conn, and the recorded history
checks against the TicketQueue frontier model (models/queuemodel.py)
plus the order-free conservation analysis (checker/set_queue.py).

Schedule shape: the main phase FILLS (enqueue-heavy mix), then DRAINS
(dequeue-only) — both inside the nemesis window, so the paired
`suggested_nemesis` "queue-drain" (nemesis/package.py) partitions the
cluster WHILE the drain is running: the schedule that actually loses or
double-delivers elements on a buggy SUT. A short post-heal drain rides
the workload final generator.

Op/value conventions (service + store wire format): ``enqueue`` invokes
with value None and completes ok with the assigned ticket; ``dequeue``
completes ok with the popped ticket, or ok with value None when the
queue was empty (a real observation — legal only against an empty
queue); timeouts are honestly indefinite (the CAS may have landed).
"""

from __future__ import annotations

from ..checker.base import compose
from ..checker.linearizable import LinearizableChecker
from ..checker.set_queue import QueueConservation
from ..checker.stats import StatsChecker
from ..checker.timeline import TimelineChecker
from ..client.base import Client
from ..generator.base import Limit, Mix, Seq
from ..history.ops import FAIL, OK, Op
from ..models.queuemodel import TicketQueue, pack_state, unpack_state

#: The one replicated-map key holding the packed (head, tail) state.
QUEUE_KEY = "fifo"

#: CAS rounds before an op reports definite contention failure (the
#: loop never mutated anything, so FAIL is sound — same stance as the
#: set workload's budget).
MAX_CAS_ROUNDS = 64


def _unpack(cur) -> tuple:
    # The MODEL's bit layout (models/queuemodel.py) is the single
    # source of truth — the client only adds the None-is-empty rule.
    return unpack_state(int(cur or 0))


_pack = pack_state


class QueueClient(Client):
    """Ticket FIFO over the register conn (get/cas retry loops)."""

    def __init__(self, conn_factory, timeout: float = 10.0):
        self.conn_factory = conn_factory
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        c = QueueClient(self.conn_factory, self.timeout)
        c.conn = self.conn_factory(node, "register", self.timeout)
        return c

    def invoke(self, test, op: Op) -> Op:
        if op.f == "enqueue":
            for _ in range(MAX_CAS_ROUNDS):
                cur = self.conn.get(QUEUE_KEY, quorum=True)
                h, t = _unpack(cur)
                if self.conn.cas(QUEUE_KEY, cur, _pack(h, t + 1)):
                    return op.replace(type=OK, value=t)  # ticket = t
            return op.replace(type=FAIL, error="cas-contention")
        if op.f == "dequeue":
            for _ in range(MAX_CAS_ROUNDS):
                cur = self.conn.get(QUEUE_KEY, quorum=True)
                h, t = _unpack(cur)
                if h == t:
                    # Empty observation: the get is the linearization
                    # point (legal only against head == tail).
                    return op.replace(type=OK, value=None)
                if self.conn.cas(QUEUE_KEY, cur, _pack(h + 1, t)):
                    return op.replace(type=OK, value=h)
            return op.replace(type=FAIL, error="cas-contention")
        raise ValueError(f"queue: unknown op {op.f!r}")

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def queue_workload(opts: dict) -> dict:
    def enq(test, ctx):
        return {"f": "enqueue", "value": None}

    def deq(test, ctx):
        return {"f": "dequeue", "value": None}

    fill = int(opts.get("queue_fill", 120))
    drain = int(opts.get("queue_drain", 120))
    gen = Seq([
        Limit(fill, Mix([enq, enq, enq, deq])),  # fill-heavy
        Limit(drain, Mix([deq])),                # drain under faults
    ])
    consistency = opts.get("consistency", "linearizable")
    return {
        "client": QueueClient(opts["conn_factory"],
                              opts.get("operation_timeout", 10.0)),
        "checker": compose({
            "timeline": TimelineChecker(),
            "stats": StatsChecker(),
            "queue": QueueConservation(),
            "linear": LinearizableChecker(
                TicketQueue(), algorithm=opts.get("algorithm", "auto"),
                consistency=consistency),
        }),
        "generator": gen,
        # Post-heal drain: pull whatever survived the faults so the
        # conservation analysis sees the delivered tail.
        "final_generator": Limit(drain, Mix([deq])),
        "idempotent": set(),  # even "empty" dequeues observe state
        "model": TicketQueue,
        "suggested_nemesis": "queue-drain",
    }

"""Counter workload: concurrent add/decr/read over a replicated counter.

Equivalent of the reference's counter workload (workload/counter.clj):
ops get'/add/add-and-get/decr/decr-and-get (counter.clj:15-38), a client
over the counter connection API (decrements negate the delta at the
client, counter.clj:56-59), and a {timeline, linear} checker over the
Counter model (counter.clj:129-138). Generation is a plain mix — no key
independence, matching the reference.
"""

from __future__ import annotations

import random

from ..checker.base import compose
from ..checker.counter_bounds import CounterChecker
from ..checker.linearizable import LinearizableChecker
from ..checker.stats import StatsChecker
from ..checker.timeline import TimelineChecker
from ..client.base import Client
from ..generator.base import Limit, Mix
from ..history.ops import OK, Op
from ..models.counter import Counter

_RNG = random.Random()


def get_(test, ctx):
    return {"f": "read", "value": None}


def add(test, ctx):
    return {"f": "add", "value": _RNG.randrange(1, 6)}


def add_and_get(test, ctx):
    return {"f": "add-and-get", "value": _RNG.randrange(1, 6)}


def decr(test, ctx):
    return {"f": "decr", "value": _RNG.randrange(1, 6)}


def decr_and_get(test, ctx):
    return {"f": "decr-and-get", "value": _RNG.randrange(1, 6)}


class CounterClient(Client):
    def __init__(self, conn_factory, timeout: float = 10.0):
        self.conn_factory = conn_factory
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        c = CounterClient(self.conn_factory, self.timeout)
        c.conn = self.conn_factory(node, "counter", self.timeout)
        return c

    def invoke(self, test, op: Op) -> Op:
        f, v = op.f, op.value
        if f == "read":
            return op.replace(type=OK, value=self.conn.get())
        if f == "add":
            self.conn.add(v)
            return op.replace(type=OK)
        if f == "decr":
            self.conn.add(-v)  # negated add (counter.clj:56-59)
            return op.replace(type=OK)
        if f == "add-and-get":
            new = self.conn.add_and_get(v)
            return op.replace(type=OK, value=(v, new))
        if f == "decr-and-get":
            new = self.conn.add_and_get(-v)
            return op.replace(type=OK, value=(v, new))
        raise ValueError(f"counter: unknown op {f!r}")

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def counter_workload(opts: dict) -> dict:
    total_ops = opts.get("total_ops")
    mix = Mix([get_, add, add_and_get, decr, decr_and_get])
    gen = Limit(total_ops, mix) if total_ops else mix
    return {
        "client": CounterClient(opts["conn_factory"],
                                opts.get("operation_timeout", 10.0)),
        "checker": compose({
            "timeline": TimelineChecker(),
            "stats": StatsChecker(),
            # Exact linearizability (the reference's CounterModel
            # semantics) with the jepsen checker/counter interval tier
            # deciding what the exact engines cannot budget — canonical-
            # envelope runs (concurrency 100 hell) pile up thousands of
            # crashed adds and blow the window past every engine.
            "linear": CounterChecker(LinearizableChecker(
                Counter(0), algorithm=opts.get("algorithm", "auto"),
                consistency=opts.get("consistency", "linearizable"))),
        }),
        "generator": gen,
        "idempotent": {"read"},  # counter.clj:80
        "model": Counter,
    }

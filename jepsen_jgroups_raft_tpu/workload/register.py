"""Register workload: concurrent read/write/cas over independent keys.

Equivalent of the reference's register workload (workload/register.clj):
op generators r/w/cas with values in [0,5) (register.clj:21-34), a client
speaking the RSM connection API, per-key independent decomposition with
`min(2n, concurrency)` threads per key (register.clj:112-117), and a
composed {timeline, linear} checker over the cas-register model
(register.clj:106-111) — with the linear checker batching all keys into
one TPU kernel launch.

Divergence from the reference, on purpose: the reference's ops-per-key cap
is inert (`maybe-limit` compares two literal keywords, register.clj:91-97 —
noted in SURVEY.md §2.1 C3); here `ops_per_key` actually limits, honoring
the CLI flag's documented intent (raft.clj:24-27).
"""

from __future__ import annotations

import random

from ..checker.base import compose
from ..checker.independent import IndependentLinearizable
from ..checker.stats import StatsChecker
from ..checker.timeline import TimelineChecker
from ..client.base import Client
from ..generator.base import Limit, Mix
from ..generator.independent import ConcurrentGenerator
from ..history.ops import FAIL, OK, Op
from ..models.register import CasRegister

_RNG = random.Random()


def r(test, ctx):
    return {"f": "read", "value": None}


def w(test, ctx):
    return {"f": "write", "value": _RNG.randrange(5)}


def cas(test, ctx):
    return {"f": "cas", "value": (_RNG.randrange(5), _RNG.randrange(5))}


class RegisterClient(Client):
    """Client over an RSM connection (the reference's
    ReplicatedStateMachineClient, register.clj:53-89). Values are
    independent (key, v) tuples; reads honor quorum_reads
    (register.clj:36-41 / raft.clj:92)."""

    def __init__(self, conn_factory, timeout: float = 10.0):
        self.conn_factory = conn_factory
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        c = RegisterClient(self.conn_factory, self.timeout)
        c.conn = self.conn_factory(node, "register", self.timeout)
        return c

    def invoke(self, test, op: Op) -> Op:
        key, v = op.value
        if op.f == "read":
            out = self.conn.get(key, quorum=test.get("quorum_reads", True))
            return op.replace(type=OK, value=(key, out))
        if op.f == "write":
            self.conn.put(key, v)
            return op.replace(type=OK)
        if op.f == "cas":
            frm, to = v
            ok = self.conn.cas(key, frm, to)
            if ok:
                return op.replace(type=OK)
            # definite: the CAS executed and returned false
            # (register.clj:82-84's :fail :cas-fail)
            return op.replace(type=FAIL, error="cas-fail")
        raise ValueError(f"register: unknown op {op.f!r}")

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def register_workload(opts: dict) -> dict:
    n = len(opts.get("nodes", [])) or 5
    concurrency = int(opts.get("concurrency", 5))
    threads_per_key = max(1, min(2 * n, concurrency))
    ops_per_key = int(opts.get("ops_per_key", 100))
    keys = opts.get("keys", range(1))
    gen = ConcurrentGenerator(
        threads_per_key, keys,
        lambda k: Limit(ops_per_key, Mix([r, w, cas])))
    return {
        "client": RegisterClient(opts["conn_factory"],
                                 opts.get("operation_timeout", 10.0)),
        "checker": compose({
            "timeline": TimelineChecker(),
            "stats": StatsChecker(),
            "linear": IndependentLinearizable(
                CasRegister,
                algorithm=opts.get("algorithm", "auto"),
                consistency=opts.get("consistency", "linearizable")),
        }),
        "generator": gen,
        "idempotent": {"read"},  # register.clj:72
        "model": CasRegister,
    }

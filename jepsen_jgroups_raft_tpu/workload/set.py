"""Set workload: concurrent element adds + membership reads over a
replicated grow-only set.

The scenario-tier twin of Jepsen's set workload: clients add small
integer elements (a global sequence modulo the 32-element width, so
churn-induced retries and duplicates occur naturally) and occasionally
read the full membership; a final whole-set read closes the run. The
checker composes the cheap derived analysis (lost/stale elements —
checker/set_queue.py) with the exact frontier check over the GSet model
(models/setmodel.py), both over the SAME history.

SUT mapping: the set lives in one register of the replicated map as a
32-bit membership mask, mutated by CAS retry loops — so the workload
runs unchanged on every deployment tier that serves the register conn
(inmemory fake, local native cluster, ssh). The linearization point of
an add is its winning CAS (or the read that proved the element already
present); a timeout mid-loop is honestly indefinite (the CAS may have
landed), while a loop that exhausts its CAS budget never mutated
anything — a definite fail.

Paired nemesis (ISSUE 10 satellite): membership churn during the fill —
`suggested_nemesis` "set-churn" (nemesis/package.py) shrinks and
re-grows the cluster at twice the default fault rate while adds are in
flight, the schedule that actually loses acknowledged elements on a
buggy SUT.
"""

from __future__ import annotations

import itertools

from ..checker.base import compose
from ..checker.linearizable import LinearizableChecker
from ..checker.set_queue import SetAnalysis
from ..checker.stats import StatsChecker
from ..checker.timeline import TimelineChecker
from ..client.base import Client
from ..generator.base import Limit, Mix, Seq
from ..history.ops import FAIL, OK, Op
from ..models.setmodel import SET_WIDTH, GSet

#: The one replicated-map key holding the membership mask.
SET_KEY = "gset"

#: CAS rounds before an add reports definite contention failure: the
#: loop never mutated anything, so FAIL ("did not apply") is sound.
MAX_CAS_ROUNDS = 64


class SetClient(Client):
    """Grow-only set over the register conn (put/get/cas)."""

    def __init__(self, conn_factory, timeout: float = 10.0):
        self.conn_factory = conn_factory
        self.timeout = timeout
        self.conn = None

    def open(self, test, node):
        c = SetClient(self.conn_factory, self.timeout)
        c.conn = self.conn_factory(node, "register", self.timeout)
        return c

    def invoke(self, test, op: Op) -> Op:
        if op.f == "add":
            e = int(op.value)
            for _ in range(MAX_CAS_ROUNDS):
                cur = self.conn.get(SET_KEY, quorum=True)
                mask = int(cur or 0)
                if (mask >> e) & 1:
                    # Already present: the get IS the linearization
                    # point (adding an existing element is a no-op).
                    return op.replace(type=OK)
                if self.conn.cas(SET_KEY, cur, mask | (1 << e)):
                    return op.replace(type=OK)
            return op.replace(type=FAIL, error="cas-contention")
        if op.f == "read":
            cur = self.conn.get(SET_KEY,
                                quorum=test.get("quorum_reads", True))
            mask = int(cur or 0)
            return op.replace(
                type=OK,
                value=[i for i in range(SET_WIDTH) if (mask >> i) & 1])
        raise ValueError(f"set: unknown op {op.f!r}")

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def set_workload(opts: dict) -> dict:
    n_elements = min(SET_WIDTH, int(opts.get("set_elements", SET_WIDTH)))
    counter = itertools.count()

    # Stateful by design (the element sequence); safe because the
    # interpreter calls op() under the scheduler lock — the same stance
    # as generator/independent.py's group bookkeeping.
    def add(test, ctx):
        return {"f": "add", "value": next(counter) % n_elements}

    def read(test, ctx):
        return {"f": "read", "value": None}

    total_ops = opts.get("total_ops")
    mix = Mix([add, add, add, add, read])  # fill-heavy, reads keep it honest
    gen = Limit(int(total_ops), mix) if total_ops else mix
    consistency = opts.get("consistency", "linearizable")
    return {
        "client": SetClient(opts["conn_factory"],
                            opts.get("operation_timeout", 10.0)),
        "checker": compose({
            "timeline": TimelineChecker(),
            "stats": StatsChecker(),
            "set": SetAnalysis(),
            "linear": LinearizableChecker(
                GSet(), algorithm=opts.get("algorithm", "auto"),
                consistency=consistency),
        }),
        "generator": gen,
        # Final whole-set read AFTER the heal phases: the read the
        # lost-element analysis anchors on.
        "final_generator": Seq([{"f": "read", "value": None}]),
        "idempotent": {"read"},
        "model": GSet,
        "suggested_nemesis": "set-churn",
    }

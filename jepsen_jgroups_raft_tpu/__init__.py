"""TPU-native distributed-systems testing framework.

A brand-new framework with the capabilities of jabolina/jepsen-jgroups-raft
(reference mounted at /root/reference): deploy a Raft-replicated state machine
onto a cluster, drive concurrent client operations under fault injection,
record a timestamped operation history with a definite/indefinite error
taxonomy, and verify the history for linearizability.

The defining difference from the reference: history verification — the
Knossos WGL/linear search (reference L0 dependency, SURVEY.md §3.4) — runs on
TPU. Histories are packed into int32 event tensors
(`history.packing`), the search runs as a fixed-shape frontier scan under
`jax.lax.scan`/`while_loop` (`ops.linear_scan`), and independent histories are
vmapped/sharded over a device mesh and verified as one batch (`parallel`).

Package layout (mirrors the reference layer map, SURVEY.md §1):
  history/   op records, error taxonomy, tensor packing      (jepsen.history)
  models/    cas-register, counter, leader models            (knossos.model)
  checker/   linearizable / compose / stats / perf / ...     (jepsen.checker)
  ops/       the TPU frontier-search kernels                 (knossos search)
  generator/ generator algebra                               (jepsen.generator)
  client/    client protocol + error taxonomy                (jepsen.client)
  nemesis/   fault injection packages                        (jepsen.nemesis)
  control/   remote/local execution, daemon lifecycle        (jepsen.control)
  workload/  register, counter, election workloads           (src/jepsen/jgroups/workload)
  core/      test orchestration (run!)                       (jepsen.core)
  parallel/  device mesh sharding of batched verification    (new, TPU-first)
  utils/     timeouts, logging, misc                         (jepsen.util)
"""

__version__ = "0.1.0"

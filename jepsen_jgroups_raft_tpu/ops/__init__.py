"""On-device kernels: the TPU execution backend for history verification.

This package is the equivalent of knossos' search engine (the reference's
L0 "compute kernel", SURVEY.md §3.4), re-designed for XLA/TPU. The
kernel families share one step-parts substrate and sit behind one
routing layer (doc/checker-design.md):

* `kernel_ir`   — the shared IR (PR 6): event-row decode, macro latch,
  FORCE dispatch, chunk-carry schema, monolithic + chunked drivers,
  eligibility caps and the chunk-carry contract bindings. Families
  instantiate it with their state lowering.
* `dense_scan`  — dense-bitset frontiers for small enumerable domains
  (register) and order-independent models (counter, mask mode); exact,
  overflow-free.
* `linear_scan` — the general sort-dedup frontier scan (windows ≤127).
* `pallas_scan` — the dense scan as a Pallas kernel, frontier in VMEM
  (opt-in via JGRAFT_KERNEL=pallas).
"""

from .dense_scan import (  # noqa: F401
    DensePlan,
    dense_plan,
    dense_plans_grouped,
    make_dense_batch_checker,
)
from .linear_scan import (  # noqa: F401
    make_batch_checker,
    make_history_checker,
    DEFAULT_N_CONFIGS,
    MAX_SLOTS,
)

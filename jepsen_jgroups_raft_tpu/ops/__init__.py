"""On-device kernels: the TPU execution backend for history verification.

This package is the equivalent of knossos' search engine (the reference's
L0 "compute kernel", SURVEY.md §3.4), re-designed for XLA/TPU: fixed-shape
frontier expansion under lax.scan/while_loop, sort-based deduplication,
vmap over batches of independent histories.
"""

from .linear_scan import (  # noqa: F401
    make_batch_checker,
    make_history_checker,
    DEFAULT_N_CONFIGS,
    MAX_SLOTS,
)

"""Pallas TPU kernel for the dense-bitset linearizability scan.

The BASELINE.json north star names this shape explicitly: "the Knossos
WGL/linear search … becomes a Pallas kernel operating on int32-encoded op
histories resident in HBM, with the visited-configuration cache kept as
an on-device bitset". This module is that kernel: the domain-mode dense
frontier (ops/dense_scan.py) re-expressed as a `pl.pallas_call` with the
frontier pinned in VMEM — no HBM round-trip of the scan carry between
events, which is what the XLA `lax.scan` formulation pays.

Round-5 redesign (VERDICT r4 #2 — "batch-parallel the grid"): the
round-3 kernel ran ONE history per grid program, and TPU grid programs
execute sequentially — so a [2^W, S] frontier (256×4 cells at the
north-star shape) left the 8×128-lane VPU ~97% idle per step while the
vmapped XLA kernel batched histories. Each grid program now carries a
TILE of T histories with the frontier laid out **F[2^W, T·S]** — lanes
carry (history, state) pairs, T sized so T·S fills the 128-lane axis
(T=32 at S=4) under a VMEM events budget.

Lane-row layout (the first on-chip session's Mosaic lesson): the
original tile rewrite bridged per-history planes to lane rows with
`(T, S) → (1, T·S)` / `(T, S) → (T·S, 1)` reshapes, and Mosaic rejects
exactly that shape cast ("infer-vector-layout: unsupported shape cast",
`tpu.reshape vector<16x4xi32> -> vector<1x64xi32>`;
bench_runs/certify_20260731T005939/pallas_hw_test.log). So nothing in
this kernel ever holds a (T, S) plane:

  * per-event fields are pre-expanded to lane rows OUTSIDE the kernel —
    event e's five int32 fields become five `[1, C]` rows (C = T·S)
    with each history's scalar replicated across its S lanes, and
    `val_of` is pre-flattened to `[1, C]` per tile. The expansion runs
    as plain XLA ops inside the jitted call (the compact `[B, E, 5]`
    array is what crosses the tunneled host↔device link; see
    `_expand_lane_rows`), so Mosaic never sees a reshape.
  * per-slot carries live as `[W, C]` lane-row stacks (static row
    slices feed each transition), not `[T, W]` planes.
  * the only row→column move the math needs (the transition matrix
    wants next-state as a `[C, 1]` column) is an identity-mask
    reduction: `sum(I ⊙ row, axis=1)` — elementwise multiply plus a
    lane reduction, both native Mosaic ops, no transpose, no reshape.

Per event the expansion (slot w, uniform across the tile) is ONE
`[M, C] @ [C, C]` matmul against a block-diagonal transition matrix
(zero across history blocks — built rank-2 from a same-history iota
mask) followed by the static row-shift butterfly; FORCE kills are
column-masked kill+shift variants reduced per history block via a
`[1, C] @ [C, C]` block-mask matmul, so `ok` stays a lane-replicated
row. Closure runs when ANY tile member forces with a dirty frontier;
members mid-OPEN just re-close — idempotent (closure is a reachability
fixpoint; expanding at an OPEN computes the same configs the deferred
fixpoint would), so early closure is a work-only cost, never a
semantic one.

Status: opt-in (`JGRAFT_KERNEL=pallas` routes eligible register batches
here; see checker/linearizable.py) and validated against the XLA dense
kernel and the CPU oracle by differential tests in interpret mode plus
the hardware (Mosaic) test on real TPU; the compete-or-retire
measurement lives in BASELINE.md's engine-ablation row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..history.packing import EV_FORCE, EV_OPEN
from .kernel_ir import macro_row_ints

#: Lane budget: T·S targets the 128-lane vector axis.
_LANE_TARGET = 128

#: VMEM budget for one program's event block (bytes). Conservative slice
#: of ~16 MiB usable VMEM: events dominate ([R·E, C] int32 after the
#: host's lane expansion — C ≤ 128 lanes and R = 5 legacy lanes or
#: 3 + 4·P macro lanes); the frontier itself is ≤ 2^10 × 128 × 4 B =
#: 512 KiB.
_EVENTS_VMEM_BUDGET = 6 << 20


def tile_histories(n_states: int, n_events: int,
                   row_ints: int = 5) -> int:
    """Histories per grid program: fill the lane axis, stay inside the
    events VMEM budget, power of two for stable compile shapes. The
    lane-expanded event block is [R·E, T·S] int32 (R = `row_ints`: 5
    legacy fields, or `macro_row_ints(P)` macro lanes), so VMEM charges
    T·S·E·R·4 bytes — n_states scales the block too (each history's
    fields are replicated across its S lanes)."""
    by_lanes = max(1, _LANE_TARGET // max(1, int(n_states)))
    by_vmem = max(1, _EVENTS_VMEM_BUDGET
                  // max(1, int(n_events) * int(row_ints) * 4
                        * int(n_states)))
    t = 1
    while t * 2 <= min(by_lanes, by_vmem):
        t *= 2
    return t


def _build_kernel(model, W: int, S: int, E: int, T: int,
                  macro_p=None):
    """Kernel body over one T-history tile, closed over static shapes.

    Refs: events_ref [R·E, C] (row R·e+k = field k of event e as a lane
    row, this tile's block; R = 5 legacy fields or 3 + 4·P macro
    lanes), val_ref / out_ref [G, C] (FULL arrays, constant index map —
    Mosaic's block rule demands sublane dims be multiples of 8 or
    whole-array, and these are a few rows; each program touches only
    its program_id row). C = T·S; history t owns lanes [t·S, (t+1)·S);
    every per-history scalar is replicated across its block's lanes.

    `macro_p`: consume macro-event rows (history/packing.py
    macro_compact) — a static-P-unrolled multi-slot latch, then the
    identical closure+FORCE; the payload lanes arrive pre-expanded by
    `_expand_lane_rows` exactly like the legacy fields, so Mosaic
    never sees a new reshape."""
    M = 1 << W
    C = T * S
    R = 5 if macro_p is None else macro_row_ints(macro_p)

    def kernel(events_ref, val_ref, out_ref):
        val_row = val_ref[pl.ds(pl.program_id(0), 1), :]  # [1, C]
        mask_ids = lax.broadcasted_iota(jnp.int32, (M, 1), 0)
        lane_c0 = lax.broadcasted_iota(jnp.int32, (C, C), 0)
        lane_c1 = lax.broadcasted_iota(jnp.int32, (C, C), 1)
        same_t = lane_c0 // S == lane_c1 // S
        blockmask = same_t.astype(jnp.float32)  # [C, C] block-sum matmul
        ident = (lane_c0 == lane_c1).astype(jnp.int32)
        lane_s = lax.broadcasted_iota(jnp.int32, (1, C), 1) % S
        w_iota = lax.broadcasted_iota(jnp.int32, (W, C), 0)

        def to_col(row):
            """[1, C] lane row → [C, 1] column without transpose/reshape:
            identity-mask then reduce along lanes (row broadcasts down
            the sublane axis; exactly one survivor per output row)."""
            return jnp.sum(ident * row, axis=1, keepdims=True)

        def transition(w, slot_f, slot_a, slot_b, slot_open):
            """Block-diagonal T_w[C, C]: history t's [S, S] transition
            for its slot-w registers, zero across blocks."""
            ns, legal = model.jax_step(val_row, slot_f[w:w + 1],
                                       slot_a[w:w + 1],
                                       slot_b[w:w + 1])   # [1, C] each
            legal = legal & (slot_open[w:w + 1] > 0)
            ns_col = to_col(ns)
            legal_col = to_col(legal.astype(jnp.int32))
            return ((ns_col == val_row) & (legal_col > 0) &
                    same_t).astype(jnp.float32)

        def event_step(e, carry):
            F, slot_f, slot_a, slot_b, slot_open, ok_row, dirty_row = carry
            ev = events_ref[pl.ds(e * R, R), :]           # [R, C]
            if macro_p is None:
                etype_row, slot_row = ev[0:1, :], ev[1:2, :]
                f_row, a_row, b_row = ev[2:3, :], ev[3:4, :], ev[4:5, :]
                is_open = (etype_row == EV_OPEN).astype(jnp.int32)
                is_force = (etype_row == EV_FORCE).astype(jnp.int32)

                upd = ((w_iota == slot_row).astype(jnp.int32) *
                       is_open)                           # [W, C]
                slot_f = slot_f * (1 - upd) + f_row * upd
                slot_a = slot_a * (1 - upd) + a_row * upd
                slot_b = slot_b * (1 - upd) + b_row * upd
                slot_open = jnp.maximum(slot_open, upd)
                dirty_row = jnp.maximum(dirty_row, is_open)
            else:
                # Macro row: [mtype, force_slot, n_opens] + P payloads.
                # Static-P-unrolled multi-slot latch (slots within a
                # macro are distinct, so payload order is immaterial).
                mtype_row, slot_row = ev[0:1, :], ev[1:2, :]
                n_row = ev[2:3, :]
                is_force = (mtype_row == EV_FORCE).astype(jnp.int32)
                for j in range(macro_p):
                    pj = ev[3 + 4 * j:7 + 4 * j, :]       # [4, C]
                    valid_j = (n_row > j).astype(jnp.int32)
                    upd = ((w_iota == pj[0:1, :]).astype(jnp.int32) *
                           valid_j)                       # [W, C]
                    slot_f = slot_f * (1 - upd) + pj[1:2, :] * upd
                    slot_a = slot_a * (1 - upd) + pj[2:3, :] * upd
                    slot_b = slot_b * (1 - upd) + pj[3:4, :] * upd
                    slot_open = jnp.maximum(slot_open, upd)
                dirty_row = jnp.maximum(dirty_row,
                                        (n_row > 0).astype(jnp.int32))

            Ts = [transition(w, slot_f, slot_a, slot_b, slot_open)
                  for w in range(W)]

            def sweep(F):
                for w in range(W):
                    d = 1 << w
                    no_row = 1 - ((mask_ids >> w) & 1)    # [M, 1]
                    stepped = (jnp.dot(
                        F.astype(jnp.float32), Ts[w],
                        preferred_element_type=jnp.float32) > 0.5
                    ).astype(jnp.int32)
                    src = stepped * no_row
                    shifted = jnp.concatenate(
                        [jnp.zeros((d, C), jnp.int32), src[:M - d]],
                        axis=0)
                    F = jnp.maximum(F, shifted)
                return F

            def closure_cond(c):
                return c[0]

            def closure_body(c):
                _, it, F = c
                F0 = F
                F = sweep(F)
                changed = jnp.sum(jnp.abs(F - F0)) > 0
                return (changed & (it < W), it + 1, F)

            need = jnp.sum(is_force * dirty_row) > 0
            _, _, F = lax.while_loop(closure_cond, closure_body,
                                     (need, jnp.int32(0), F))
            dirty_row = dirty_row * (1 - is_force)

            # FORCE: per-history slot → column-selected kill+shift.
            Fk_sel = jnp.zeros((M, C), jnp.int32)
            moved_sel = jnp.zeros((M, C), jnp.int32)
            for w in range(W):
                d = 1 << w
                has_row = (mask_ids >> w) & 1
                cm = (slot_row == w).astype(jnp.int32) * is_force  # [1, C]
                Fk = F * has_row
                moved = jnp.concatenate(
                    [Fk[d:], jnp.zeros((d, C), jnp.int32)],
                    axis=0) * (1 - has_row)
                Fk_sel = Fk_sel + Fk * cm
                moved_sel = moved_sel + moved * cm
            F = F * (1 - is_force) + moved_sel

            colsum = jnp.sum(Fk_sel, axis=0,
                             keepdims=True).astype(jnp.float32)  # [1, C]
            blocksum = jnp.dot(colsum, blockmask,
                               preferred_element_type=jnp.float32)
            alive_row = (blocksum > 0.5).astype(jnp.int32)
            ok_row = ok_row * jnp.where((is_force > 0) & (alive_row == 0),
                                        0, 1)
            slot_open = slot_open * (
                1 - (w_iota == slot_row).astype(jnp.int32) * is_force)
            return (F, slot_f, slot_a, slot_b, slot_open, ok_row,
                    dirty_row)

        # Initial config per history block: empty mask, state id 0.
        seed = ((mask_ids == 0) & (lane_s == 0)).astype(jnp.int32)
        carry = (seed,
                 jnp.zeros((W, C), jnp.int32), jnp.zeros((W, C), jnp.int32),
                 jnp.zeros((W, C), jnp.int32), jnp.zeros((W, C), jnp.int32),
                 jnp.ones((1, C), jnp.int32), jnp.zeros((1, C), jnp.int32))
        carry = lax.fori_loop(0, E, event_step, carry)
        out_ref[pl.ds(pl.program_id(0), 1), :] = carry[5]  # [1, C]

    return kernel


def _expand_lane_rows(events, T: int, S: int):
    """[Bp, E, R] int32 → [G·R·E, C] lane rows (G = Bp/T, C = T·S):
    tile g's row R·e+k holds field k of event e, history t's scalar
    replicated across lanes [t·S, (t+1)·S). R is whatever the stream
    carries — 5 legacy fields or 3 + 4·P macro lanes; the macro
    payload rows grow the SAME pre-expansion, so Mosaic sees no new
    reshape. Runs as jnp INSIDE the jitted call — the compact
    [Bp, E, R] array crosses the (tunneled) host↔device link and XLA
    expands on device; Mosaic's no-reshape rule only binds inside the
    pallas kernel."""
    Bp, E, R = events.shape
    G = Bp // T
    # (G, T, E, R) → (G, E, R, T) → repeat S on lanes → (G·R·E, T·S)
    lanes = jnp.repeat(
        events.reshape(G, T, E, R).transpose(0, 2, 3, 1), S, axis=3)
    return lanes.reshape(G * E * R, T * S)


_CALL_CACHE: dict = {}


def _build_call(model, W: int, S: int, E: int, T: int, G: int,
                R: int, interpret: bool, macro_p):
    key = (*model.cache_key(), W, S, E, T, G, R, interpret, macro_p)
    cached = _CALL_CACHE.get(key)
    if cached is not None:
        return cached
    kernel = _build_kernel(model, W, S, E, T, macro_p)
    C = T * S

    def call(events, val_rows):
        ev_rows = _expand_lane_rows(events, T, S)
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                pl.BlockSpec((E * R, C), lambda g: (g, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((G, C), lambda g: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((G, C), lambda g: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((G, C), jnp.int32),
            interpret=interpret,
        )(ev_rows, val_rows)

    jitted = jax.jit(call)
    _CALL_CACHE[key] = jitted
    return jitted


def make_pallas_batch_checker(model, n_slots: int, n_states: int,
                              n_events: int, interpret: bool = False,
                              macro_p=None):
    """fn(events [B,E,5] int32, val_of [B,S] int32) -> (valid[B] bool,
    overflow[B] bool) — the dense-domain check as one Pallas launch, one
    grid program per T-history tile. Like the dense kernel, overflow is
    structurally impossible. `interpret` runs the Pallas interpreter
    (CPU-correctness mode, used by the differential tests). `macro_p`
    consumes macro-event batches ([B, E_mac, 3+4·P] from
    `pack_macro_batch`) instead — the tile budget charges the wider
    rows, everything else is unchanged."""
    W, S, E = int(n_slots), int(n_states), int(n_events)
    R = 5 if macro_p is None else macro_row_ints(macro_p)
    T_cap = tile_histories(S, E, R)

    def check(events, val_of):
        events = np.asarray(events, np.int32)
        val_of = np.asarray(val_of, np.int32)
        B = events.shape[0]
        E = events.shape[1]
        if E % 8:
            # Mosaic block rule: the event block's sublane dim (R·E)
            # must divide by 8 when the grid has >1 tile; R is odd in
            # both formats, so E itself must. EV_PAD rows are no-ops,
            # so round E up (the kernel cache keys on E).
            E8 = ((E + 7) // 8) * 8
            events = np.concatenate(
                [events, np.zeros((B, E8 - E, R), np.int32)], axis=1)
            E = E8
        # Clamp the tile to the batch: a 2-history long-event group must
        # not pay a 32-lane tile of per-event matmul work (the kernel
        # cache already keys on T).
        T = 1
        while T * 2 <= T_cap and T < B:
            T *= 2
        Bp = ((B + T - 1) // T) * T
        if Bp != B:
            # Tile padding: EV_PAD streams are no-ops, pad verdicts are
            # discarded below.
            events = np.concatenate(
                [events, np.zeros((Bp - B, E, R), np.int32)])
            val_of = np.concatenate(
                [val_of, np.zeros((Bp - B, S), np.int32)])
        G = Bp // T
        val_rows = np.ascontiguousarray(val_of.reshape(G, T * S))
        call = _build_call(model, W, S, E, T, G, R, bool(interpret),
                           macro_p)
        ok_rows = call(jnp.asarray(events), jnp.asarray(val_rows))
        # History t's verdict is lane t·S of its tile row (block-
        # replicated; any lane would do). Stays a LAZY device array —
        # callers launch several window groups and block once, and a
        # host sync here would serialize a tunnel round trip per group.
        ok = ok_rows.reshape(Bp, S)[:B, 0] > 0
        return ok, jnp.zeros_like(ok)

    return check

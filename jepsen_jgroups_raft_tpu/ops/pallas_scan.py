"""Pallas TPU kernel for the dense-bitset linearizability scan.

The BASELINE.json north star names this shape explicitly: "the Knossos
WGL/linear search … becomes a Pallas kernel operating on int32-encoded op
histories resident in HBM, with the visited-configuration cache kept as
an on-device bitset". This module is that kernel: the domain-mode dense
frontier (ops/dense_scan.py) re-expressed as a `pl.pallas_call` with the
frontier pinned in VMEM — no HBM round-trip of the scan carry between
events, which is what the XLA `lax.scan` formulation pays.

Round-5 redesign (VERDICT r4 #2 — "batch-parallel the grid"): the
round-3 kernel ran ONE history per grid program, and TPU grid programs
execute sequentially — so a [2^W, S] frontier (256×4 cells at the
north-star shape) left the 8×128-lane VPU ~97% idle per step while the
vmapped XLA kernel batched histories. Each grid program now carries a
TILE of T histories with the frontier laid out **F[2^W, T·S]** — lanes
carry (history, state) pairs, T sized so T·S fills the 128-lane axis
(T=32 at S=4) under a VMEM events budget:

  * expansion (slot w, uniform across the tile): ONE [M, T·S] @
    [T·S, T·S] matmul against a BLOCK-DIAGONAL transition matrix (zero
    across history blocks — built rank-2 from a same-history iota mask),
    then the same static row-shift butterfly as before. Per-history
    open/legal gating lives inside the block diagonal.
  * FORCE (slot differs per history): W kill+shift variants are
    computed (cheap [M, T·S] elementwise) and column-selected per
    history block by lane masks; survivors' liveness reduces per block
    via a [1, T·S] @ [T·S, T·S] block-mask matmul, so `ok` stays a
    lane-replicated row — no reshape/transpose of per-history scalars.
  * closure runs when ANY tile member forces with a dirty frontier;
    members mid-OPEN just re-close — idempotent (closure is a
    reachability fixpoint; expanding at an OPEN computes the same
    configs the deferred fixpoint would), so early closure is a
    work-only cost, never a semantic one.

Everything stays rank-2 for Mosaic. The two layout bridges —
(T, S) → (1, T·S) and (T, S) → (T·S, 1) collapses — are the only
reshape patterns used; both touch trailing dims only.

Status: opt-in (`JGRAFT_KERNEL=pallas` routes eligible register batches
here; see checker/linearizable.py) and validated against the XLA dense
kernel and the CPU oracle by differential tests in interpret mode —
hardware (Mosaic) validation + the compete-or-retire measurement run on
the first TPU-attached session via tests/test_pallas_scan.py and
BASELINE.md's engine-ablation row.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..history.packing import EV_FORCE, EV_OPEN

#: Lane budget: T·S targets the 128-lane vector axis.
_LANE_TARGET = 128

#: VMEM budget for one program's event block (bytes). Conservative slice
#: of ~16 MiB usable VMEM: events dominate ([T, E, 5] int32); the
#: frontier itself is ≤ 2^10 × 128 × 4 B = 512 KiB.
_EVENTS_VMEM_BUDGET = 6 << 20


def tile_histories(n_states: int, n_events: int) -> int:
    """Histories per grid program: fill the lane axis, stay inside the
    events VMEM budget, power of two for stable compile shapes."""
    by_lanes = max(1, _LANE_TARGET // max(1, int(n_states)))
    by_vmem = max(1, _EVENTS_VMEM_BUDGET // max(1, int(n_events) * 5 * 4))
    t = 1
    while t * 2 <= min(by_lanes, by_vmem):
        t *= 2
    return t


def _build_kernel(model, W: int, S: int, E: int, T: int):
    """Kernel body over one T-history tile, closed over static shapes."""
    M = 1 << W
    C = T * S

    def kernel(events_ref, val_ref, out_ref):
        val = val_ref[...]                      # [T, S]
        val_row = val.reshape(1, C)             # history-major lanes
        mask_ids = jax.lax.broadcasted_iota(jnp.int32, (M, 1), 0)
        same_t = (jax.lax.broadcasted_iota(jnp.int32, (C, C), 0) // S ==
                  jax.lax.broadcasted_iota(jnp.int32, (C, C), 1) // S)
        blockmask = same_t.astype(jnp.float32)  # [C, C] block-sum matmul
        lane_s = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1) % S

        def flat(x_t1):
            """[T, 1] per-history scalar → [1, C] lane-replicated row."""
            return jnp.broadcast_to(x_t1, (T, S)).reshape(1, C)

        def transition(w, slot_f, slot_a, slot_b, slot_open):
            """Block-diagonal T_w[C, C]: history t's [S, S] transition
            for its slot-w registers, zero across blocks."""
            ns, legal = model.jax_step(val, slot_f[:, w:w + 1],
                                       slot_a[:, w:w + 1],
                                       slot_b[:, w:w + 1])      # [T, S]
            legal = legal & (slot_open[:, w:w + 1] > 0)
            ns_col = ns.reshape(C, 1)
            legal_col = legal.reshape(C, 1)
            return ((ns_col == val_row) & legal_col &
                    same_t).astype(jnp.float32)

        def event_step(e, carry):
            F, slot_f, slot_a, slot_b, slot_open, ok_col, dirty_col = carry
            ev = events_ref[:, pl.ds(e, 1), :][:, 0, :]          # [T, 5]
            etype, slot = ev[:, 0:1], ev[:, 1:2]
            f, a, b = ev[:, 2:3], ev[:, 3:4], ev[:, 4:5]
            is_open = etype == EV_OPEN
            is_force = etype == EV_FORCE

            lane_w = jax.lax.broadcasted_iota(jnp.int32, (T, W), 1)
            upd = ((lane_w == slot) & is_open).astype(jnp.int32)
            slot_f = slot_f * (1 - upd) + f * upd
            slot_a = slot_a * (1 - upd) + a * upd
            slot_b = slot_b * (1 - upd) + b * upd
            slot_open = jnp.maximum(slot_open, upd)

            open_col = flat(is_open.astype(jnp.int32))
            force_col = flat(is_force.astype(jnp.int32))
            slot_col = flat(slot)
            dirty_col = jnp.maximum(dirty_col, open_col)

            Ts = [transition(w, slot_f, slot_a, slot_b, slot_open)
                  for w in range(W)]

            def sweep(F):
                for w in range(W):
                    d = 1 << w
                    no_row = 1 - ((mask_ids >> w) & 1)           # [M, 1]
                    stepped = (jnp.dot(
                        F.astype(jnp.float32), Ts[w],
                        preferred_element_type=jnp.float32) > 0.5
                    ).astype(jnp.int32)
                    src = stepped * no_row
                    shifted = jnp.concatenate(
                        [jnp.zeros((d, C), jnp.int32), src[:M - d]],
                        axis=0)
                    F = jnp.maximum(F, shifted)
                return F

            def closure_cond(c):
                return c[0]

            def closure_body(c):
                _, it, F = c
                F0 = F
                F = sweep(F)
                changed = jnp.sum(jnp.abs(F - F0)) > 0
                return (changed & (it < W), it + 1, F)

            need = jnp.sum(force_col * dirty_col) > 0
            _, _, F = lax.while_loop(closure_cond, closure_body,
                                     (need, jnp.int32(0), F))
            dirty_col = dirty_col * (1 - force_col)

            # FORCE: per-history slot → column-selected kill+shift.
            Fk_sel = jnp.zeros((M, C), jnp.int32)
            moved_sel = jnp.zeros((M, C), jnp.int32)
            for w in range(W):
                d = 1 << w
                has_row = (mask_ids >> w) & 1
                cm = ((slot_col == w) & (force_col > 0)).astype(jnp.int32)
                Fk = F * has_row
                moved = jnp.concatenate(
                    [Fk[d:], jnp.zeros((d, C), jnp.int32)],
                    axis=0) * (1 - has_row)
                Fk_sel = Fk_sel + Fk * cm
                moved_sel = moved_sel + moved * cm
            F = F * (1 - force_col) + moved_sel

            colsum = jnp.sum(Fk_sel, axis=0,
                             keepdims=True).astype(jnp.float32)  # [1, C]
            blocksum = jnp.dot(colsum, blockmask,
                               preferred_element_type=jnp.float32)
            alive_col = (blocksum > 0.5).astype(jnp.int32)
            ok_col = ok_col * jnp.where((force_col > 0) & (alive_col == 0),
                                        0, 1)
            slot_open = slot_open * (
                1 - ((lane_w == slot) & is_force).astype(jnp.int32))
            return (F, slot_f, slot_a, slot_b, slot_open, ok_col,
                    dirty_col)

        # Initial config per history block: empty mask, state id 0.
        seed = ((mask_ids == 0) & (lane_s == 0)).astype(jnp.int32)
        carry = (seed,
                 jnp.zeros((T, W), jnp.int32), jnp.zeros((T, W), jnp.int32),
                 jnp.zeros((T, W), jnp.int32), jnp.zeros((T, W), jnp.int32),
                 jnp.ones((1, C), jnp.int32), jnp.zeros((1, C), jnp.int32))
        carry = lax.fori_loop(0, E, event_step, carry)
        ok_col = carry[5]
        # Scalar verdicts through SMEM (Mosaic rejects scalar VMEM
        # stores); the TPU grid is sequential so per-row stores race-free.
        for t in range(T):
            out_ref[pl.program_id(0) * T + t, 0] = ok_col[0, t * S]

    return kernel


_CALL_CACHE: dict = {}


def _build_call(model, W: int, S: int, E: int, T: int, Bp: int,
                interpret: bool):
    key = (*model.cache_key(), W, S, E, T, Bp, interpret)
    cached = _CALL_CACHE.get(key)
    if cached is not None:
        return cached
    kernel = _build_kernel(model, W, S, E, T)

    def call(events, val_of):
        return pl.pallas_call(
            kernel,
            grid=(Bp // T,),
            in_specs=[
                pl.BlockSpec((T, E, 5), lambda g: (g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((T, S), lambda g: (g, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((Bp, 1), lambda g: (0, 0),
                                   memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((Bp, 1), jnp.int32),
            interpret=interpret,
        )(events, val_of)

    jitted = jax.jit(call)
    _CALL_CACHE[key] = jitted
    return jitted


def make_pallas_batch_checker(model, n_slots: int, n_states: int,
                              n_events: int, interpret: bool = False):
    """fn(events [B,E,5] int32, val_of [B,S] int32) -> (valid[B] bool,
    overflow[B] bool) — the dense-domain check as one Pallas launch, one
    grid program per T-history tile. Like the dense kernel, overflow is
    structurally impossible. `interpret` runs the Pallas interpreter
    (CPU-correctness mode, used by the differential tests)."""
    W, S, E = int(n_slots), int(n_states), int(n_events)
    T_cap = tile_histories(S, E)

    def check(events, val_of):
        events = np.asarray(events, np.int32)
        val_of = np.asarray(val_of, np.int32)
        B = events.shape[0]
        # Clamp the tile to the batch: a 2-history long-event group must
        # not pay a 32-lane tile of per-event matmul work (the kernel
        # cache already keys on T).
        T = 1
        while T * 2 <= T_cap and T < B:
            T *= 2
        Bp = ((B + T - 1) // T) * T
        if Bp != B:
            # Tile padding: EV_PAD streams are no-ops, pad verdicts are
            # discarded below.
            events = np.concatenate(
                [events, np.zeros((Bp - B, E, 5), np.int32)])
            val_of = np.concatenate(
                [val_of, np.zeros((Bp - B, S), np.int32)])
        call = _build_call(model, W, S, E, T, Bp, bool(interpret))
        ok = call(jnp.asarray(events), jnp.asarray(val_of))[:B, 0] > 0
        return ok, jnp.zeros_like(ok)

    return check

"""Pallas TPU kernel for the dense-bitset linearizability scan.

The BASELINE.json north star names this shape explicitly: "the Knossos
WGL/linear search … becomes a Pallas kernel operating on int32-encoded op
histories resident in HBM, with the visited-configuration cache kept as
an on-device bitset". This module is that kernel: the domain-mode dense
frontier (ops/dense_scan.py) re-expressed as a `pl.pallas_call` where one
grid program scans one history end-to-end with the frontier pinned in
VMEM — no HBM round-trip of the scan carry between events, which is what
the XLA `lax.scan` formulation pays.

Mosaic-friendliness drives the formulation (everything is rank-2):

  * The frontier F[2^W, S] lives as int32 0/1; OR is `maximum`, AND is
    `*` — no bool arrays.
  * The butterfly "configs without bit w flow to mask|bit_w" is a static
    slice + concatenate SHIFT of the mask axis by 2^w rows, masked by
    precomputed [M, 1] bit-column constants — no 4D reshapes, no
    scatter/gather, no transposes.
  * The per-slot transition matrix T[s, s'] = legal(s)·(step(s) == v_s')
    needs the domain both as a column and as a row; both layouts are
    passed from the host ([B, S, 1] and [B, 1, S] inputs) so the kernel
    never transposes.
  * Events are read per iteration with `pl.ds` dynamic row slices from
    the program's [E, 5] VMEM block.

Status: opt-in (`JGRAFT_KERNEL=pallas` routes eligible register batches
here; see checker/linearizable.py) and validated against the XLA dense
kernel and the CPU oracle by differential tests in interpret mode —
hardware (Mosaic) validation runs on the first TPU-attached session via
tests/test_pallas_scan.py::test_pallas_on_tpu_if_available.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..history.packing import EV_FORCE, EV_OPEN


def _build_kernel(model, W: int, S: int, E: int):
    """The kernel body, closed over static shapes and the model step."""
    M = 1 << W

    # Pallas kernels may not capture array constants, so the per-slot
    # bit-column masks are derived in-kernel from an iota over mask ids.
    def _bit_cols(w):
        mask_ids = jax.lax.broadcasted_iota(jnp.int32, (M, 1), 0)
        has = (mask_ids >> w) & 1
        return has, 1 - has

    def expand_w(w, F, Ts):
        """Configs without bit w linearize op w: transition every row
        through T_w, keep rows with bit w clear, shift them onto their
        mask|bit_w partner rows (m + 2^w), and OR in."""
        d = 1 << w
        _, no_col = _bit_cols(w)
        stepped = jnp.dot(F.astype(jnp.float32), Ts[w],
                          preferred_element_type=jnp.float32)
        src = (stepped > 0.5).astype(jnp.int32) * no_col
        shifted = jnp.concatenate(
            [jnp.zeros((d, S), jnp.int32), src[:M - d]], axis=0)
        return jnp.maximum(F, shifted)

    def force_branch(w, F):
        """Kill configs missing bit w, recycle the bit (shift back)."""
        d = 1 << w
        has_col, no_col = _bit_cols(w)
        Fk = F * has_col
        alive = jnp.sum(Fk) > 0
        moved = jnp.concatenate(
            [Fk[d:], jnp.zeros((d, S), jnp.int32)], axis=0) * no_col
        return moved, alive

    def kernel(events_ref, val_col_ref, val_row_ref, out_ref):
        val_col = val_col_ref[0]  # [S, 1]
        val_row = val_row_ref[0]  # [1, S]

        def transition(w, slot_f, slot_a, slot_b, slot_open):
            ns, legal = model.jax_step(val_col, slot_f[0, w], slot_a[0, w],
                                       slot_b[0, w])  # [S, 1]
            T = ((ns == val_row) & legal &
                 (slot_open[0, w] > 0)).astype(jnp.float32)  # [S, S]
            return T

        def event_step(e, carry):
            F, slot_f, slot_a, slot_b, slot_open, ok, dirty = carry
            ev = events_ref[0, pl.ds(e, 1), :]  # [1, 5]
            etype, slot = ev[0, 0], ev[0, 1]
            f, a, b = ev[0, 2], ev[0, 3], ev[0, 4]
            is_open = (etype == EV_OPEN).astype(jnp.int32)
            is_force = (etype == EV_FORCE).astype(jnp.int32)

            lane = jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
            upd = ((lane == slot) & (is_open > 0)).astype(jnp.int32)
            slot_f = slot_f * (1 - upd) + f * upd
            slot_a = slot_a * (1 - upd) + a * upd
            slot_b = slot_b * (1 - upd) + b * upd
            slot_open = jnp.maximum(slot_open, upd)
            dirty = jnp.maximum(dirty, is_open)

            Ts = [transition(w, slot_f, slot_a, slot_b, slot_open)
                  for w in range(W)]

            def sweep(F):
                for w in range(W):
                    F = expand_w(w, F, Ts)
                return F

            def closure_cond(c):
                return c[0]

            def closure_body(c):
                _, it, F = c
                F0 = F
                F = sweep(F)
                changed = jnp.sum(jnp.abs(F - F0)) > 0
                return (changed & (it < W), it + 1, F)

            _, _, F = lax.while_loop(
                closure_cond, closure_body,
                ((is_force * dirty) > 0, jnp.int32(0), F))
            dirty = dirty * (1 - is_force)

            slot_w = jnp.clip(slot, 0, W - 1)
            F_forced, alive = lax.switch(
                slot_w, [functools.partial(force_branch, w)
                         for w in range(W)], F)
            F = jnp.where(is_force > 0, F_forced, F)
            ok = ok * jnp.where((is_force > 0) & ~alive, 0, 1)
            slot_open = slot_open * (1 - ((lane == slot) & (is_force > 0))
                                     .astype(jnp.int32))
            return (F, slot_f, slot_a, slot_b, slot_open, ok, dirty)

        F0 = jnp.zeros((M, S), jnp.int32)
        # Initial config: empty mask, state id 0 (the initial value).
        seed = ((jax.lax.broadcasted_iota(jnp.int32, (M, S), 0) == 0) &
                (jax.lax.broadcasted_iota(jnp.int32, (M, S), 1) == 0)
                ).astype(jnp.int32)
        carry = (jnp.maximum(F0, seed),
                 jnp.zeros((1, W), jnp.int32), jnp.zeros((1, W), jnp.int32),
                 jnp.zeros((1, W), jnp.int32), jnp.zeros((1, W), jnp.int32),
                 jnp.int32(1), jnp.int32(0))
        carry = lax.fori_loop(0, E, event_step, carry)
        # Scalar verdict goes out through SMEM: Mosaic rejects scalar
        # stores to VMEM, and this jax version applies the "block tiles to
        # (8, 128) or spans the array" rule to every memory space — so the
        # SMEM block spans the whole [B, 1] array and each grid program
        # scalar-stores its own row (the TPU grid is sequential: no race).
        out_ref[pl.program_id(0), 0] = carry[5]

    return kernel


_CALL_CACHE: dict = {}


def _build_call(model, W: int, S: int, E: int, interpret: bool):
    # Same keying as the other kernel caches (Model.cache_key): equivalent
    # model instances share one Mosaic compile.
    key = (*model.cache_key(), W, S, E, interpret)
    cached = _CALL_CACHE.get(key)
    if cached is not None:
        return cached
    kernel = _build_kernel(model, W, S, E)

    def call(events, val_col, val_row):
        B = events.shape[0]
        return pl.pallas_call(
            kernel,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, E, 5), lambda b: (b, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, S, 1), lambda b: (b, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, S), lambda b: (b, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((B, 1), lambda b: (0, 0),
                                   memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((B, 1), jnp.int32),
            interpret=interpret,
        )(events, val_col, val_row)

    jitted = jax.jit(call)
    _CALL_CACHE[key] = jitted
    return jitted


def make_pallas_batch_checker(model, n_slots: int, n_states: int,
                              n_events: int, interpret: bool = False):
    """fn(events [B,E,5] int32, val_of [B,S] int32) -> (valid[B] bool,
    overflow[B] bool) — the dense-domain check as one Pallas launch, one
    grid program per history. Like the dense kernel, overflow is
    structurally impossible. `interpret` runs the Pallas interpreter
    (CPU-correctness mode, used by the differential tests)."""
    call = _build_call(model, int(n_slots), int(n_states), int(n_events),
                       bool(interpret))

    def check(events, val_of):
        events = jnp.asarray(events, jnp.int32)
        val_col = jnp.asarray(val_of, jnp.int32)[:, :, None]
        val_row = jnp.asarray(val_of, jnp.int32)[:, None, :]
        ok = call(events, val_col, val_row)[:, 0] > 0
        return ok, jnp.zeros_like(ok)

    return check

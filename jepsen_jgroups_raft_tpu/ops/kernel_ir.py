"""Unified kernel IR: the one step-parts substrate every scan family
instantiates.

Before this module existed, every performance feature grew 4× by hand:
chunking (ISSUE 3) and macro-event compaction (ISSUE 4) were each ported
separately into the dense/mask kernels (ops/dense_scan.py), the sort
ladder (ops/linear_scan.py), the segment kernel and the Pallas tile
kernel — four copies of the event-row decode, the macro-latch
application, the arithmetic FORCE dispatch, the chunk-carry schema and
the decided/exhausted flag semantics. This module is the single home of
that shared machinery; each family now keeps ONLY its state-
representation lowering (how a frontier is stored and swept) and plugs
it into the IR through three hooks.

The IR's contract — what a family must supply (doc/checker-design.md §9):

  ``latch(carry, slot, f, a, b, is_open, upd) -> carry``
      Latch ONE op's registers (legacy one-event-per-step stream).
      ``upd`` is the precomputed per-slot write mask
      ``(slot_ids == slot) & is_open``.
  ``macro_latch(carry, pslot, pf, pa, pb, valid, n, eq, upd) -> carry``
      Latch ≤P opens at once (macro stream, history/packing.py
      macro_compact). ``eq``/``upd`` come from :func:`macro_select`;
      slots within a macro are distinct (packing only recycles a slot
      at its FORCE), so at most one payload matches per slot.
  ``force_tail(carry, is_force, slot) -> carry``
      The closure + FORCE phase. Identical for both streams — this is
      the whole macro soundness argument: the latch phases reach the
      same pre-FORCE register state, then run THIS same code, and
      closure is a reachability fixpoint over exactly those registers,
      so verdicts are bitwise-identical (pinned by
      tests/test_macro_events.py and tests/test_kernel_ir.py).

:func:`make_stream_step` assembles the hooks into the per-event
``scan_step``; :class:`KernelParts` bundles (init, scan_step, verdict);
:func:`monolithic_check` and :func:`batch_chunk_checker` are the two
drivers (one step body, two drivers — the chunked wavefront of
checker/schedule.py can never diverge semantically from the reference
scan). The chunk-carry schema ({"inner", "left"}) and the
decided/exhausted eviction flags are defined here ONCE; their soundness
argument (``ok`` is monotone, a dead frontier stays dead, an exhausted
row only has EV_PAD no-ops left) is restated at :func:`chunk_step_fns`.

The eligibility caps and the chunk-carry byte accounting live here too:
the graftcheck kernel-contract analyzer (lint/flow/kernel_contract.py)
proves the VMEM budgets ONCE against this module instead of per family.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..history.packing import EV_FORCE, EV_OPEN, MACRO_MAX_OPENS

# --------------------------------------------------------------- caps
# Family eligibility caps (moved here from the per-family modules so the
# kernel-contract analyzer samples every family's chunk-carry budget
# against ONE module). The families re-import and re-export them, so the
# routing layers keep their existing spellings.

#: Dense-domain caps. Per-event work is ~W · 2^W · S² (closure sweeps)
#: plus 2^W · S (the arithmetic FORCE path), so the dense path is
#: reserved for genuinely small problems — which the reference's own
#: workload shapes are (window ≈ n_procs, domain ≈ 5 values; a few
#: crashed ops' never-retiring slots push long histories to W ≈ 10).
DENSE_MAX_SLOTS = 10
DENSE_MAX_STATES = 16
DENSE_MAX_CELLS = 8192  # 2^W · S

#: Mask mode has no state dimension (S² → 1), so it affords a wider
#: window: 2^12 bool cells + an int32 subset-sum lane per history.
MASK_DENSE_MAX_SLOTS = 12

#: Sort-ladder caps (ops/linear_scan.py re-exports these under its
#: historical names MAX_SLOTS / DEFAULT_N_CONFIGS). The window cap is
#: 4 mask words with a spare top bit for the all-ones empty-entry
#: sentinel — linear_scan's contract pins it.
SORT_MAX_SLOTS = 127
SORT_DEFAULT_CONFIGS = 256

#: Cycle-tier cap (ISSUE 13): dependency graphs beyond this many nodes
#: skip the MONOLITHIC closure kernel (make_cycle_closure keeps the
#: whole [N, N] slab resident). The adjacency slab at this cap is
#: proven against the VMEM budget by the kernel-contract analyzer
#: (cycle_adjacency_bytes).
CYCLE_MAX_NODES = 512

#: Blocked-closure cap (ISSUE 19): the tiled kernel
#: (make_cycle_closure_tiled) streams [T, N] panels instead of the
#: whole matrix, so the node ceiling rises 8× — the per-k-step panel
#: residency is what the kernel-contract analyzer proves now
#: (cycle_closure_tile_bytes, executed at THIS corner). Rows beyond
#: this cap skip the exact tier entirely (and say so: the
#: cycle-skipped-size annotation, checker/cycle.py).
CYCLE_MAX_NODES_TILED = 4096

#: Default closure tile edge. 256 is the largest pow2 whose panel set
#: fits the VMEM budget at the 4096-node cap ((3·T·N + T²)·4 ≈ 12.9 MB
#: at T=256, N=4096; T=512 would need 25 MB) — and every node bucket
#: the pow2+midpoint series emits above 512 (768, 1024, 1536, ...) is
#: a multiple of 256, so the default tile always divides the bucket.
CYCLE_TILE = 256


def scan_unroll() -> int:
    """Events per lax.scan step across the event-scan kernels (dense,
    mask, segment, sort) — an ablation knob for the on-chip sweep
    (scripts/calibrate_routing.py --unroll), JGRAFT_SCAN_UNROLL to
    override. Default 1 EVERYWHERE: CPU-mesh measurements did not
    survive re-measurement through the production path (a hand-built
    kernel probe showed unroll=2 at 1.49× on a B=4 × 15.7k-event
    launch, but the same shape through the bucketed production kernels
    measured unroll=1 faster, 11.2 s vs 16.0 s — the round-3 lesson
    about one-probe conclusions, again). Resolved at kernel-build time
    and part of every kernel-cache key."""
    v = os.environ.get("JGRAFT_SCAN_UNROLL")
    if v:
        return max(1, int(v))
    return 1


# ------------------------------------------------------- event-row layout


def macro_row_ints(macro_p: int = MACRO_MAX_OPENS) -> int:
    """int32 lanes of one macro-event row: [mtype, force_slot, n_opens]
    + macro_p × (slot, f, a, b); defaults to the widest row the encoder
    can emit (the MACRO_MAX_OPENS cap). Pure arithmetic on purpose —
    the kernel-contract analyzer (lint/flow/kernel_contract.py)
    executes it statically at the cap to re-prove the chunk event slabs
    and the Pallas lane-expanded block against the VMEM budgets."""
    return 3 + 4 * macro_p


def macro_cols(row, macro_p: int):
    """Split one macro-event row [3 + 4·P] (history/packing.py
    macro_compact layout) into (mtype, force_slot, n_opens,
    pslot [P], pf [P], pa [P], pb [P])."""
    pay = row[3:3 + 4 * macro_p].reshape(macro_p, 4)
    return (row[0], row[1], row[2],
            pay[:, 0], pay[:, 1], pay[:, 2], pay[:, 3])


def macro_select(slot_ids, pslot, valid):
    """Masked-scatter helpers for the vectorized multi-slot latch:
    eq [W, P] marks which payload lands in which slot register (slots
    within a macro are distinct — packing only recycles a slot at its
    FORCE — so at most one payload matches per slot), upd [W] which
    slots update at all."""
    eq = (slot_ids[:, None] == pslot[None, :]) & valid[None, :]
    return eq, eq.any(axis=1)


def macro_latch_i32(eq, upd, old, new):
    """old [W] int32 register ← payload values new [P] where upd."""
    return jnp.where(upd, (eq.astype(jnp.int32) * new[None, :]).sum(1),
                     old)


# --------------------------------------------------- shared FORCE/closure


def closure_fixpoint(W: int, sweep, F, active):
    """Iterate `sweep` (one pass over all slots) to the reachability
    fixpoint. Each productive sweep extends every pending linearization
    chain by ≥1 op and chains are ≤W long, so ≤W sweeps suffice; the
    change test is exact even when the frontier representation holds
    redundant entries (it compares the whole array). `active`
    short-circuits non-FORCE events."""

    def cond(c):
        return c[0]

    def body(c):
        _, it, F = c
        F0 = F
        F = sweep(F)
        return (jnp.any(F != F0) & (it < W), it + 1, F)

    _, _, F = lax.while_loop(cond, body, (active, jnp.int32(0), F))
    return F


def force_arith(F, slot_w):
    """Switch-free FORCE dispatch over a dense frontier (the ISSUE-4
    "dense slot dispatch" half): kill configurations missing the forced
    slot's bit, then recycle the bit by moving the bit=1 half of the
    butterfly onto the bit=0 half — both computed *arithmetically* from
    the dynamic slot id (the same style as the sort kernel's bitvec
    math) instead of the old `lax.switch` over W static branches, which
    under vmap lowered to select-over-all-branches: every scan step
    paid W× the one taken branch's [M, S] work. The down-shift by the
    dynamic bit weight is one `lax.dynamic_slice` of a zero-extended
    copy — static shapes, no reshape, no scatter; under vmap the
    batched start lowers to per-row slices (re-ablate on chip if that
    regresses — both the macro and the JGRAFT_MACRO_EVENTS=0 legacy
    stream share this dispatch, so the macro A/B stays a pure
    stream-length comparison).

    F: [M, S] bool (mask mode passes S=1); slot_w pre-clipped to
    [0, W). Returns (F', any_survivor)."""
    M, S = F.shape
    ids = jnp.arange(M, dtype=jnp.int32)
    has = ((ids >> slot_w) & 1) == 1            # [M] bit slot_w of m
    Fk = F & has[:, None]
    alive = jnp.any(Fk)
    ext = jnp.concatenate([Fk, jnp.zeros_like(Fk)], axis=0)  # [2M, S]
    shifted = lax.dynamic_slice(
        ext, (jnp.int32(1) << slot_w, jnp.int32(0)), (M, S))
    return jnp.where(has[:, None], False, shifted), alive


# ---------------------------------------------------------- stream step


def make_stream_step(n_slots: int, latch: Callable, macro_latch: Callable,
                     force_tail: Callable,
                     macro_p: Optional[int] = None) -> Callable:
    """The single definition of the per-event scan body every family
    shares: decode the event row (legacy [5] or macro [3 + 4·P]),
    compute the latch write masks, call the family's latch hook, then
    the family's closure+FORCE tail. This is where the old per-family
    ``if macro_p is None: ... else: ...`` twins collapsed to — a stream
    format change now happens in exactly one place.

    See the module docstring for the hook signatures; `n_slots` fixes
    the kernel's W (the hooks close over their own W-shaped state)."""
    slot_ids = jnp.arange(int(n_slots), dtype=jnp.int32)
    if macro_p is None:
        def scan_step(carry, ev):
            etype, slot, f, a, b = ev[0], ev[1], ev[2], ev[3], ev[4]
            is_open = etype == EV_OPEN
            is_force = etype == EV_FORCE
            upd = (slot_ids == slot) & is_open
            carry = latch(carry, slot, f, a, b, is_open, upd)
            carry = force_tail(carry, is_force, slot)
            return carry, None
    else:
        P = int(macro_p)

        def scan_step(carry, row):
            mtype, fslot, n, pslot, pf, pa, pb = macro_cols(row, P)
            is_force = mtype == EV_FORCE
            valid = jnp.arange(P, dtype=jnp.int32) < n
            eq, upd = macro_select(slot_ids, pslot, valid)
            carry = macro_latch(carry, pslot, pf, pa, pb, valid, n, eq,
                                upd)
            carry = force_tail(carry, is_force, fslot)
            return carry, None
    return scan_step


# -------------------------------------------------------------- drivers


@dataclass(frozen=True)
class KernelParts:
    """A family's lowered step parts, ready for either driver.

    init:      init(*operands) -> per-row scan carry (``n_operands``
               per-row operands, e.g. the dense kernels' val_of table;
               the sort kernel takes none).
    scan_step: the per-event body (from :func:`make_stream_step`).
    verdict:   carry -> (ok, overflow).
    """

    init: Callable
    scan_step: Callable
    verdict: Callable
    n_operands: int = 0


def monolithic_check(parts: KernelParts) -> Callable:
    """The reference driver: fn(events [E, R], *operands) ->
    (ok, overflow) — one `lax.scan` over the whole stream."""
    def check(events, *operands):
        carry, _ = lax.scan(parts.scan_step, parts.init(*operands),
                            events, unroll=scan_unroll())
        return parts.verdict(carry)

    return check


def chunk_step_fns(parts: KernelParts):
    """The chunk-carry schema + decided/exhausted flag semantics, in
    one place (this used to be duplicated between the dense and sort
    chunk builders). Returns single-row (init_one, step_one):

      init_one(*operands, n_ev) -> {"inner": scan carry, "left": int32}
      step_one(carry, events [chunk, R]) -> (carry', decided,
          exhausted, ok, overflow)

    Eviction soundness (the checker/linearizable.py contract): `ok` is
    monotone — it only ever ANDs in new conditions — and flips False
    exactly when the frontier dies, after which every event is a no-op
    on the dead frontier, so a `decided` (= ~ok) row's (ok, overflow)
    pair is frozen mid-scan. An `exhausted` row (events_left ≤ 0) only
    has EV_PAD no-ops left, so its current pair is final too. Either
    flag makes the row safe to evict: eviction only ever removes rows
    whose verdict is certain. Chaining step_one over E/chunk chunks
    applies the identical scan_step sequence as the monolithic
    `lax.scan`, so verdicts are bitwise-identical by construction
    (pinned by the tests/test_kernel_ir.py differentials)."""
    def init_one(*args):
        operands, n_ev = args[:-1], args[-1]
        return {"inner": parts.init(*operands),
                "left": jnp.asarray(n_ev, jnp.int32)}

    def step_one(carry, events):
        inner, _ = lax.scan(parts.scan_step, carry["inner"], events,
                            unroll=scan_unroll())
        left = carry["left"] - events.shape[0]
        ok, overflow = parts.verdict(inner)
        return ({"inner": inner, "left": left},
                ~ok, left <= 0, ok, overflow)

    return init_one, step_one


def batch_chunk_checker(parts: KernelParts, mesh=None, jit: bool = True):
    """Batch driver for the wavefront scheduler (checker/schedule.py):
    vmapped (init_fn, step_fn) over the batch axis, optionally wrapped
    in an explicit `shard_map` over `mesh` (see :func:`shard_chunk_fns`
    — relying on jit's GSPMD sharding propagation *placed* the carry
    sharded but compiled a ~3× slower per-chunk program than the
    explicit wrap on the CPU mesh). Callers pad the batch to a multiple
    of the mesh size (schedule._bucket_launch_rows)."""
    init_one, step_one = chunk_step_fns(parts)
    init_fn = jax.vmap(init_one)
    step_fn = jax.vmap(step_one)
    if mesh is not None:
        init_fn, step_fn = shard_chunk_fns(
            init_fn, step_fn, mesh, n_init_args=parts.n_operands + 1)
    if jit:
        init_fn = jax.jit(init_fn)
        step_fn = jax.jit(step_fn)
    return init_fn, step_fn


def shard_chunk_fns(init_fn, step_fn, mesh, n_init_args: int):
    """Wrap a vmapped (init_fn, step_fn) chunk-kernel pair in
    `shard_map` over the batch axis of `mesh`. P(axis) acts as a pytree
    prefix over the carry dict (every leaf is batch-leading), and the
    replication check is off for the same reason as the monolithic
    sharded checkers: the computation is per-shard independent by
    construction (parallel/mesh.py). Lazy import — parallel.mesh
    imports the ops package at load time."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import _SHARD_MAP_CHECK_KW, shard_map

    spec = P(mesh.axis_names[0])
    init_sm = shard_map(init_fn, mesh=mesh,
                        in_specs=(spec,) * n_init_args, out_specs=spec,
                        **{_SHARD_MAP_CHECK_KW: False})
    step_sm = shard_map(step_fn, mesh=mesh, in_specs=(spec, spec),
                        out_specs=(spec,) * 5,
                        **{_SHARD_MAP_CHECK_KW: False})
    return init_sm, step_sm


# ------------------------------------------------------- cycle closure


def make_cycle_closure(n_nodes: int):
    """Batched boolean transitive-closure kernel for the exact cycle
    tier (checker/cycle.py, ISSUE 13): ``closure(adj)`` with adj
    [B, N, N] int32 0/1 adjacency matrices (pow2-bucketed N, rows
    padded with zero matrices) returns (has_cycle [B] bool,
    closed [B, N, N]).

    The whole pass is repeated boolean matrix squaring — ``R ← R ∨
    R·R`` — as one batched int32 einsum inside a `lax.while_loop`:
    after k squarings R holds every path of length ≤ 2^k, so
    ceil(log2 N) iterations reach the full transitive closure (the
    loop also exits early when a squaring changes nothing); a set
    diagonal bit then witnesses a cycle. This is exactly the encoded
    substrate's shape — int32 matmul batched over independent rows —
    which is why the tier is essentially free where matmul is free
    (the MXU); off-TPU the caller routes to a host DFS on the same
    adjacency instead (checker/cycle.py, the PLATFORM_ROUTE idiom).
    Entries stay in {0, 1} (re-binarized every iteration), so the
    int32 row sums are bounded by N ≤ CYCLE_MAX_NODES — no overflow.
    """
    n = int(n_nodes)
    n_iter = max(1, (max(n, 2) - 1).bit_length())

    def closure(adj):
        def cond(c):
            i, _, changed = c
            return changed & (i < n_iter)

        def body(c):
            i, a, _ = c
            prod = jnp.einsum("bij,bjk->bik", a, a,
                              preferred_element_type=jnp.int32)
            nxt = jnp.minimum(a + jnp.minimum(prod, 1), 1)
            return (i + 1, nxt, jnp.any(nxt != a))

        _, closed, _ = lax.while_loop(
            cond, body, (jnp.int32(0), adj.astype(jnp.int32),
                         jnp.bool_(True)))
        diag = jnp.diagonal(closed, axis1=1, axis2=2)
        return jnp.any(diag > 0, axis=1), closed

    return jax.jit(closure)


def cycle_closure_tile(n_nodes: int, tile: int) -> int:
    """Effective tile edge for a bucket: the largest power of two ≤
    ``tile`` that divides ``n_nodes``.  Every bucket the pow2+midpoint
    series emits above 512 is a multiple of 256, so the shipped default
    (CYCLE_TILE) always survives intact; the clamp only matters for
    operator-forced JGRAFT_CYCLE_TILE values that don't divide a
    midpoint bucket (768 = 3·256 admits any pow2 ≤ 256, not 512)."""
    n, t = int(n_nodes), int(tile)
    t = min(t, n)
    if t >= 1:
        t = 1 << (t.bit_length() - 1)  # largest pow2 ≤ t
    while t > 1 and n % t:
        t //= 2
    return max(t, 1)


def make_cycle_closure_tiled(n_nodes: int, tile: int = CYCLE_TILE):
    """Blocked transitive-closure kernel (ISSUE 19): same contract as
    make_cycle_closure — ``closure(adj)`` over [B, N, N] int32 0/1
    adjacency, returns (has_cycle [B] bool, closed [B, N, N]) — but
    built as blocked Floyd–Warshall over [T, T] int32 tiles so the
    live working set per step is panels, not the whole matrix, and the
    node cap rises to CYCLE_MAX_NODES_TILED.

    One pass over the N/T diagonal blocks; for pivot block k (offset
    o = k·T):

      1. close the diagonal block D = A[o:o+T, o:o+T] by repeated
         boolean squaring (ceil(log2 T) iterations — all paths that
         stay inside the pivot block);
      2. fold the closed pivot into its row panel (R ← R ∨ D*·R) and
         column panel (C ← C ∨ C·D*);
      3. A ← A ∨ C·R, streamed one [T, N] row-panel product at a time
         so the largest materialized intermediate is a panel, never
         [N, N].

    This is the textbook blocked FW schedule: after processing pivot
    k, A[i, j] holds every path whose intermediate nodes lie in blocks
    ≤ k, so the final A is the full transitive closure — identical to
    the monolithic squaring (differentially pinned in
    tests/test_cycle_tiled.py).  Soundness is monotone: entries are
    only ever OR-ed with products of existing path bits, so every set
    bit is a real path at every step.  Entries re-binarize after every
    product (jnp.minimum(·, 1)), so int32 row sums stay ≤ N — no
    overflow at any cap.

    Per-k-step residency is what the kernel-contract analyzer proves
    now (cycle_closure_tile_bytes, executed at the
    (CYCLE_MAX_NODES_TILED, CYCLE_TILE) corner); the [B, N, N] slab
    itself lives in HBM like every other chunked carry.
    """
    n, t = int(n_nodes), int(tile)
    if n < 1 or t < 1 or n % t:
        raise ValueError(f"tile {t} does not divide node bucket {n}")
    nt = n // t
    diag_iters = max(1, (max(t, 2) - 1).bit_length())

    def closure(adj):
        a0 = adj.astype(jnp.int32)
        b = a0.shape[0]

        def sq_once(_i, d):
            p = jnp.einsum("bij,bjk->bik", d, d,
                           preferred_element_type=jnp.int32)
            return jnp.minimum(d + jnp.minimum(p, 1), 1)

        def pivot(kb, a):
            o = kb * t
            d = lax.dynamic_slice(a, (0, o, o), (b, t, t))
            d = lax.fori_loop(0, diag_iters, sq_once, d)
            row = lax.dynamic_slice(a, (0, o, 0), (b, t, n))
            row = jnp.minimum(row + jnp.minimum(
                jnp.einsum("bij,bjk->bik", d, row,
                           preferred_element_type=jnp.int32), 1), 1)
            a = lax.dynamic_update_slice(a, row, (0, o, 0))
            col = lax.dynamic_slice(a, (0, 0, o), (b, n, t))
            col = jnp.minimum(col + jnp.minimum(
                jnp.einsum("bij,bjk->bik", col, d,
                           preferred_element_type=jnp.int32), 1), 1)
            a = lax.dynamic_update_slice(a, col, (0, 0, o))

            def fold(ib, a):
                io = ib * t
                ci = lax.dynamic_slice(col, (0, io, 0), (b, t, t))
                ai = lax.dynamic_slice(a, (0, io, 0), (b, t, n))
                p = jnp.einsum("bij,bjk->bik", ci, row,
                               preferred_element_type=jnp.int32)
                ai = jnp.minimum(ai + jnp.minimum(p, 1), 1)
                return lax.dynamic_update_slice(a, ai, (0, io, 0))

            return lax.fori_loop(0, nt, fold, a)

        closed = lax.fori_loop(0, nt, pivot, a0)
        diag = jnp.diagonal(closed, axis1=1, axis2=2)
        return jnp.any(diag > 0, axis=1), closed

    return jax.jit(closure)


# ----------------------------------------------------- contract bindings
# Conservative per-row resident bytes of each family's chunked carry.
# Pure arithmetic on purpose: the graftcheck kernel-contract analyzer
# (lint/flow/kernel_contract.py) executes these statically at the cap
# corners above — ONE set of bindings for every family that chunks
# through the IR, replacing the per-family duplicates.


def dense_chunk_carry_bytes(n_slots: int, n_states: int) -> int:
    """Chunked domain/mask carry: frontier F [2^W, S] bool + hoisted
    transitions [W, S, S] bool (worst style) + slot registers + the
    events_left lane. Mask mode runs at S=1; its subset-sum lane is
    covered by the conservative register term."""
    return ((1 << n_slots) * n_states          # F
            + n_slots * n_states * n_states    # hoisted T (worst style)
            + 4 * n_slots * 4                  # slot registers (int32)
            + 8)                               # ok/dirty/events_left


def sort_chunk_carry_bytes(n_configs: int, n_slots: int) -> int:
    """Chunked sort carry: masks [C, K] uint32 + states [C] int32 +
    slot registers + flags + the events_left lane."""
    k = n_slots // 32 + 1
    return (n_configs * k * 4 + n_configs * 4   # masks + states
            + 3 * n_slots * 4 + n_slots         # slot regs + open
            + 8)                                # ok/overflow/dirty/left


def cycle_adjacency_bytes(n_nodes: int) -> int:
    """Per-row resident bytes of the cycle-closure kernel: the int32
    adjacency/closure matrix plus the squared-product buffer the einsum
    materializes (two [N, N] int32 slabs live across the while_loop
    body). Executed statically at CYCLE_MAX_NODES by the
    kernel-contract analyzer (lint/flow/kernel_contract.py)."""
    return 2 * n_nodes * n_nodes * 4


def cycle_closure_tile_bytes(n_nodes: int, tile: int) -> int:
    """Per-row resident int32 bytes of one pivot step of the blocked
    closure (make_cycle_closure_tiled): the [T, N] row panel, the
    [N, T] column panel, the closed [T, T] diagonal block, and one
    [T, N] product slab from the streamed fold.  This is the
    tile-granularity binding ISSUE 19 moves the cycle budget proof to
    — executed statically at (CYCLE_MAX_NODES_TILED, CYCLE_TILE) by
    the kernel-contract analyzer; the monolithic cycle_adjacency_bytes
    binding stays for the ≤ CYCLE_MAX_NODES arm, which still ships."""
    return (3 * tile * n_nodes + tile * tile) * 4


def cycle_closure_tiles(n_nodes: int, tile: int) -> int:
    """Tile-program count of one blocked-closure pass — bookkeeping for
    the cycle_tiles_run counter (checker/schedule.py) and bench rows:
    per pivot block one diagonal closure, N/T row-panel products, N/T
    column-panel products, and N/T streamed fold products of N/T tiles
    each."""
    nt = max(1, n_nodes // max(1, tile))
    return nt * (1 + 2 * nt + nt * nt)

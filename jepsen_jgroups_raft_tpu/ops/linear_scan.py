"""TPU frontier-search kernel for linearizability checking.

The Wing&Gong/Lowe linear search (knossos' :linear algorithm — the
reference's checker engine, register.clj:110-111 / SURVEY.md §3.4) recast as
a fixed-shape scan that XLA compiles onto the TPU vector unit:

  * A search **configuration** is (K-word uint32 bitmask over ≤W
    concurrency-window slots, int32 model state). The frontier is a
    fixed-capacity array of C configurations; empty entries carry an
    all-ones sentinel mask.
  * The packed event stream (history/packing.py) is scanned with `lax.scan`.
    OPEN events update per-slot op registers; FORCE events run a closure:
    expand every configuration by every open un-linearized slot — a single
    branch-free [C, W] evaluation of the model's vectorized step — then
    deduplicate by a multi-key `lax.sort` and compact, repeating under
    `lax.while_loop` until no novel configuration appears.
  * Dedup-by-sort is the memoization: it plays the role of knossos'
    visited-configuration hash set, but as a data-parallel primitive with
    no hashing and no false positives (soundness note in SURVEY.md §7.4.2).
  * Configurations that fail to linearize a FORCEd op are killed; an empty
    frontier ⇒ not linearizable. Frontier overflow (more than C distinct
    configurations) is reported, never silently dropped: the caller escalates
    to a bigger kernel or the unbounded CPU twin (checker/wgl_cpu.py).
  * `vmap` lifts everything over a batch of histories; `parallel/` shards
    the batch over the device mesh.

Masks are multi-word (K = W // 32 + 1 uint32 lanes, bit i of word j =
slot 32j+i), lifting the round-1 31-slot window cap: the reference's
documented runs use --concurrency 100 (reference doc/running.md:88), and
timeout-polluted histories hold slots open indefinitely — exactly the
regime that must stay on-device. K is chosen so the last word always has
at least one unused top bit, keeping the all-ones empty-entry sentinel
distinct from every reachable configuration (soundness: a fully-set mask
can never be silently dropped as "empty"), and letting the compaction
sort key on the last word alone to order sentinels after live entries.

Two measured-on-hardware design rules (round 2; each is >2× on v5e):

  * **No scatter, no gather.** TPU scatters serialize; a cumsum+scatter
    compaction made the whole kernel 4.5× slower than the pure-sort
    alternative used here: mark duplicates/sentinels, overwrite them with
    the sentinel, sort again, and slice the first C rows. Two sorts beat
    one scatter.
  * **Novelty by tag bit, not by count.** Each dedup round sorts a 0/1
    provenance tag behind the (mask, state) keys — parents 0, fresh
    candidates 1 — so "did this round reach a new configuration" is
    `any(kept & tag==1)`, exact even when the frontier holds duplicates.
    That lets the post-FORCE slot-bit recycling skip its own re-dedup
    entirely (duplicate parents merge for free at the next closure), which
    removed a per-event C-element sort that cost ~25% of the kernel.

Why closure only at FORCE events is sound: between two completions no
real-time precedence edge can appear (all open ops are mutually concurrent),
so deferring expansion from OPEN events to the next FORCE reaches the
identical configuration set — see history/packing.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# The shared step-parts substrate (PR 6 tentpole, ops/kernel_ir.py):
# this module keeps only the sort-frontier state lowering; the stream
# decode, macro latch helpers, chunk-carry schema and both drivers are
# the IR's. The caps re-export under their historical names: the hard
# window cap is 4 mask words with a spare top bit for the all-ones
# empty-entry sentinel (histories needing more concurrent slots fall
# back to the CPU checker, whose masks are arbitrary-precision).
from .kernel_ir import SORT_DEFAULT_CONFIGS as DEFAULT_N_CONFIGS
from .kernel_ir import SORT_MAX_SLOTS as MAX_SLOTS
from .kernel_ir import (KernelParts, batch_chunk_checker, macro_latch_i32,
                        make_stream_step, monolithic_check, scan_unroll)
from .kernel_ir import sort_chunk_carry_bytes  # noqa: F401  (re-export)

#: Windows ≤ SLOT_EXACT_MAX compile at their exact size — per-event closure
#: work is linear in C×W, and typical windows (≤ n_procs, e.g. 5) are far
#: below the smallest useful bucket, so snug shapes are a direct ~2× win.
#: Wider windows quantize to SLOT_BUCKETS to bound recompilation.
SLOT_EXACT_MAX = 16

#: Bucket rungs above SLOT_EXACT_MAX: word-boundary maxima (32k-1 slots for
#: k mask words). check_histories buckets each batch's real window up.
SLOT_BUCKETS = (31, 63, 95, 127)

# Empty-frontier-entry sentinel mask word. A NumPy (not jnp) scalar on
# purpose: a module-level jnp constant would initialize the JAX backend at
# import time, hanging importers when the accelerator is unreachable and
# defeating late platform pinning (cli --platform).
_SENT = np.uint32(0xFFFFFFFF)


def _dedup_compact(masks, states, tags, n_configs):
    """Deduplicate (mask-words…, state) tuples and compact the survivors
    into a fresh C-row frontier, scatter-free (see module docstring).

    masks: [N, K] uint32, states: [N] int32, tags: [N] int32 (0 = entry was
    already in the frontier, 1 = fresh candidate). Returns
    (masks' [C,K], states' [C], count, overflowed, grew) where `count` is
    the exact number of distinct live configurations and `grew` is whether
    any kept entry is tagged fresh — the closure's exact fixpoint test.
    """
    K = masks.shape[1]
    C = n_configs
    # Sort with the tag as the last key: among equal (mask, state) rows the
    # parent (tag 0) sorts first, so a candidate equal to an existing
    # configuration is always marked duplicate, never counted as novel.
    cols = tuple(masks[:, j] for j in range(K)) + (states, tags)
    sorted_cols = lax.sort(cols, num_keys=K + 2)
    sm = jnp.stack(sorted_cols[:K], axis=1)  # [N, K]
    ss = sorted_cols[K]
    st = sorted_cols[K + 1]
    dup = jnp.concatenate([
        jnp.array([False]),
        jnp.all(sm[1:] == sm[:-1], axis=1) & (ss[1:] == ss[:-1]),
    ])
    # Empty entries are all-ones; the last word alone suffices as the test
    # (its top bit is never set in a reachable config, by choice of K).
    keep = ~dup & (sm[:, K - 1] != _SENT)
    count = jnp.sum(keep)
    grew = jnp.any(keep & (st == 1))
    # Compaction: blank the dropped rows to the sentinel and re-sort keyed
    # on the last mask word (live < sentinel there, by construction), so
    # every kept row lands in the first `count` slots — no scatter.
    m2 = jnp.where(keep[:, None], sm, _SENT)
    s2 = jnp.where(keep, ss, 0)
    cols2 = (m2[:, K - 1],) + tuple(m2[:, j] for j in range(K - 1)) + (s2,)
    sorted2 = lax.sort(cols2, num_keys=1)
    out_m = jnp.stack(tuple(sorted2[1:K]) + (sorted2[0],), axis=1)[:C]
    out_s = sorted2[K][:C]
    return out_m, out_s, jnp.minimum(count, C), count > C, grew


def sort_step_parts(model, n_configs: int = DEFAULT_N_CONFIGS,
                    n_slots: int = MAX_SLOTS,
                    macro_p: Optional[int] = None):
    """The sort kernel decomposed for chunked execution: returns
    (init, scan_step, verdict) with `init() -> carry`, the per-event
    `scan_step`, and `verdict(carry) -> (valid, overflow)`. The
    monolithic checker and the chunked wavefront (checker/schedule.py)
    both drive this one step body, so they cannot diverge semantically.

    `model` supplies the vectorized `jax_step` and initial state; `n_configs`
    (C) and `n_slots` (W ≤ MAX_SLOTS) fix the kernel shape. `macro_p`
    switches `scan_step` to macro-event rows of 3 + 4·P lanes
    (history/packing.py macro_compact): a vectorized multi-slot latch
    of ≤P opens, then the identical closure+FORCE — same bitwise-
    identity argument as ops/dense_scan.dense_step_parts (the FORCE
    path here was always the arithmetic bitvec form).
    """
    if n_slots > MAX_SLOTS:
        raise ValueError(f"n_slots {n_slots} > {MAX_SLOTS}")
    C, W = int(n_configs), int(n_slots)
    K = W // 32 + 1  # last word always keeps ≥1 spare bit (sentinel safety)
    init_state = jnp.int32(model.init_state())
    slot_ids = jnp.arange(W, dtype=jnp.int32)
    slot_word = np.arange(W) // 32  # [W] static
    slot_bit = (jnp.uint32(1) << (jnp.arange(W, dtype=jnp.uint32) % 32))
    # [W, K] bit pattern that sets slot w's bit in its word, 0 elsewhere.
    word_onehot = jnp.asarray(
        (np.arange(K)[None, :] == slot_word[:, None]), dtype=jnp.uint32)
    set_bits = word_onehot * slot_bit[:, None]  # [W, K]
    sent_row = jnp.full((K,), _SENT, dtype=jnp.uint32)
    parent_tags = jnp.zeros((C,), dtype=jnp.int32)
    cand_tags = jnp.ones((C * W,), dtype=jnp.int32)

    def expand_once(masks, states, overflow, slot_f, slot_a, slot_b,
                    slot_open):
        live = masks[:, K - 1] != _SENT  # [C]
        s = states[:, None]
        m_w = masks[:, slot_word]  # [C, W] the word holding each slot's bit
        candidate_open = slot_open[None, :] & ((m_w & slot_bit[None, :]) == 0)
        ns, legal = model.jax_step(s, slot_f[None, :], slot_a[None, :],
                                   slot_b[None, :])
        good = live[:, None] & candidate_open & legal  # [C, W]
        cand = masks[:, None, :] | set_bits[None, :, :]  # [C, W, K]
        cand_m = jnp.where(good[:, :, None], cand, sent_row)  # [C, W, K]
        cand_s = jnp.where(good, ns, 0).astype(jnp.int32)
        all_m = jnp.concatenate([masks, cand_m.reshape(-1, K)])
        all_s = jnp.concatenate([states, cand_s.reshape(-1)])
        all_t = jnp.concatenate([parent_tags, cand_tags])
        nm, nstates, _, of, grew = _dedup_compact(all_m, all_s, all_t, C)
        return nm, nstates, grew, overflow | of

    def closure(masks, states, overflow, slot_f, slot_a, slot_b,
                slot_open, active):
        # Fixed point: iterate while a round reaches a novel configuration
        # (the tag test — exact even with duplicate parents, see module
        # docstring). Each productive round sets ≥1 more mask bit, so ≤W
        # rounds; `active` short-circuits non-FORCE events (the while body
        # never runs for them).
        def cond(c):
            return c[0]

        def body(c):
            _, it, masks, states, overflow = c
            nm, ns, grew, nof = expand_once(masks, states, overflow,
                                            slot_f, slot_a, slot_b,
                                            slot_open)
            return (grew & (it < W), it + 1, nm, ns, nof)

        _, _, masks, states, overflow = lax.while_loop(
            cond, body, (active, jnp.int32(0), masks, states, overflow)
        )
        return masks, states, overflow

    def force_tail(carry, is_force, slot):
        """Shared closure+FORCE tail — identical for the legacy and
        macro streams (the latch phases reach the same registers)."""
        (masks, states, slot_f, slot_a, slot_b, slot_open, ok, overflow,
         dirty) = carry
        # Closure only when an OPEN happened since the last closure: a
        # closed frontier stays closed under FORCE kill+clear (every
        # extension of a surviving configuration is a superset, so it
        # survived and cleared too) — back-to-back completions skip the
        # expansion loop entirely.
        masks, states, overflow = closure(
            masks, states, overflow, slot_f, slot_a, slot_b,
            slot_open, is_force & dirty)
        dirty = dirty & ~is_force

        # FORCE: survivors have the slot's bit; then the bit is recycled.
        # Liveness guard matters: sentinel entries have every bit set and
        # must not masquerade as survivors.
        bitvec = jnp.where(
            jnp.arange(K) == slot // 32,
            jnp.uint32(1) << (slot % 32).astype(jnp.uint32),
            jnp.uint32(0))  # [K]
        live = masks[:, K - 1] != _SENT
        has = jnp.any((masks & bitvec[None, :]) != 0, axis=1) & live
        killed_m = jnp.where((is_force & live & ~has)[:, None],
                             sent_row, masks)
        cleared_m = jnp.where((is_force & has)[:, None],
                              killed_m & ~bitvec[None, :], killed_m)
        alive = jnp.any(cleared_m[:, K - 1] != _SENT)
        ok = ok & (~is_force | alive)
        slot_open = slot_open & ~((slot_ids == slot) & is_force)
        # Clearing the recycled bit can merge configurations into
        # duplicates; they stay in place and merge for free at the next
        # closure's dedup (the tag-based fixpoint test is exact under
        # duplicates, so no per-event re-dedup is needed — measured ~25%
        # of kernel time when it was).
        return (cleared_m, states, slot_f, slot_a, slot_b, slot_open,
                ok, overflow, dirty)

    # IR hooks (ops/kernel_ir.make_stream_step): only the sort-frontier
    # register lowering lives here; decode + latch masks are the IR's.
    def latch(carry, slot, f, a, b, is_open, upd):
        (masks, states, slot_f, slot_a, slot_b, slot_open, ok,
         overflow, dirty) = carry
        slot_f = jnp.where(upd, f, slot_f)
        slot_a = jnp.where(upd, a, slot_a)
        slot_b = jnp.where(upd, b, slot_b)
        slot_open = jnp.where(upd, True, slot_open)
        dirty = dirty | is_open
        return (masks, states, slot_f, slot_a, slot_b, slot_open, ok,
                overflow, dirty)

    def macro_latch(carry, pslot, pf, pa, pb, valid, n, eq, upd):
        # Vectorized multi-slot latch (≤P opens, distinct slots).
        (masks, states, slot_f, slot_a, slot_b, slot_open, ok,
         overflow, dirty) = carry
        slot_f = macro_latch_i32(eq, upd, slot_f, pf)
        slot_a = macro_latch_i32(eq, upd, slot_a, pa)
        slot_b = macro_latch_i32(eq, upd, slot_b, pb)
        slot_open = slot_open | upd
        dirty = dirty | (n > 0)
        return (masks, states, slot_f, slot_a, slot_b, slot_open, ok,
                overflow, dirty)

    scan_step = make_stream_step(W, latch, macro_latch, force_tail,
                                 macro_p)

    def init():
        masks = jnp.full((C, K), _SENT, dtype=jnp.uint32).at[0].set(
            jnp.zeros((K,), dtype=jnp.uint32))
        states = jnp.zeros((C,), dtype=jnp.int32).at[0].set(init_state)
        return (
            masks, states,
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), bool),
            jnp.bool_(True), jnp.bool_(False), jnp.bool_(False),
        )

    def verdict(carry):
        # An overflowed run may have dropped configurations: a "False" can
        # be a false negative, so report unknown instead (caller escalates).
        return carry[6], carry[7]

    return init, scan_step, verdict


def make_history_checker(model, n_configs: int = DEFAULT_N_CONFIGS,
                         n_slots: int = MAX_SLOTS,
                         macro_p: Optional[int] = None):
    """Build a jittable single-history checker.

    Returns fn(events:[E,5] int32) -> (valid: bool, overflow: bool)
    (macro_p: [E_mac, 3+4·P] macro rows instead). See
    `sort_step_parts` for the kernel mechanics and shape knobs.
    """
    init, scan_step, verdict = sort_step_parts(model, n_configs, n_slots,
                                               macro_p)
    return monolithic_check(KernelParts(init, scan_step, verdict))


def bucket_slots(n: int) -> int:
    """Kernel window for a real window of n slots: exact when small (snug
    shapes are a ~2× kernel win), else the smallest SLOT_BUCKETS rung ≥ n."""
    if n <= SLOT_EXACT_MAX:
        return max(n, 1)
    for b in SLOT_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"window {n} exceeds MAX_SLOTS {MAX_SLOTS}")


_KERNEL_CACHE: dict = {}


def make_batch_checker(model, n_configs: int = DEFAULT_N_CONFIGS,
                       n_slots: int = MAX_SLOTS, jit: bool = True,
                       macro_p: Optional[int] = None):
    """vmapped batch variant: fn(events:[B,E,5]) -> (valid[B], overflow[B]).

    Kernels are cached by (model identity, C, W, P): jax.jit caches
    traces per function object, so handing it a fresh closure per call
    would recompile every time. Model identity = `Model.cache_key()`;
    `macro_p` selects the macro-event row format (a P bucket is a
    distinct compiled shape, like rows/events).
    """
    # scan_unroll() keys the cache (same invariant as dense_scan's):
    # the build closure resolves it at trace time, so an env change
    # mid-process must map to a distinct compiled kernel.
    key = (*model.cache_key(), int(n_configs), int(n_slots), jit,
           scan_unroll(), macro_p)
    fn = _KERNEL_CACHE.get(key)
    if fn is None:
        single = make_history_checker(model, n_configs, n_slots, macro_p)
        fn = jax.vmap(single)
        if jit:
            fn = jax.jit(fn)
        _KERNEL_CACHE[key] = fn
    return fn


def make_sort_chunk_checker(model, n_configs: int = DEFAULT_N_CONFIGS,
                            n_slots: int = MAX_SLOTS, jit: bool = True,
                            mesh=None, macro_p: Optional[int] = None):
    """Chunked twin of `make_batch_checker` for the wavefront scheduler
    (checker/schedule.py). Returns (init_fn, step_fn):

      init_fn(n_events [B] int32) -> carry (batch-leading pytree)
      step_fn(carry, events [B,chunk,5]) -> (carry', decided [B],
          exhausted [B], ok [B], overflow [B])

    Eviction soundness, sort-kernel flavor: `ok` is monotone and flips
    False exactly when the frontier empties — after which expansion
    produces no candidates, so `overflow` is frozen too. A `~ok` row's
    final (ok, overflow) pair is therefore already known mid-scan:
    (False, False) is a certain INVALID, (False, True) a certain
    escalate-to-CPU. `exhausted` rows (events_left ≤ 0) only have
    EV_PAD no-ops left, so their current pair is final as well. The
    scheduler maps the pairs exactly as the monolithic caller does —
    eviction never invents a verdict the monolithic scan would not
    have reported.

    `mesh`: wrap both fns in an explicit batch-axis `shard_map` (see
    ops/dense_scan._shard_chunk_fns — jit sharding propagation compiles
    a measurably slower program than the explicit wrap); callers pad
    the batch to a multiple of the mesh size."""
    key = ("chunk", *model.cache_key(), int(n_configs), int(n_slots), jit,
           scan_unroll(), mesh, macro_p)
    fns = _KERNEL_CACHE.get(key)
    if fns is None:
        init, scan_step, verdict = sort_step_parts(model, n_configs,
                                                   n_slots, macro_p)
        fns = batch_chunk_checker(KernelParts(init, scan_step, verdict),
                                  mesh=mesh, jit=jit)
        _KERNEL_CACHE[key] = fns
    return fns

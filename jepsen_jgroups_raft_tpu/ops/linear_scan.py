"""TPU frontier-search kernel for linearizability checking.

The Wing&Gong/Lowe linear search (knossos' :linear algorithm — the
reference's checker engine, register.clj:110-111 / SURVEY.md §3.4) recast as
a fixed-shape scan that XLA compiles onto the TPU vector unit:

  * A search **configuration** is (uint32 bitmask over ≤32 concurrency-window
    slots, int32 model state). The frontier is a fixed-capacity array of
    C configurations; empty entries carry a sentinel mask.
  * The packed event stream (history/packing.py) is scanned with `lax.scan`.
    OPEN events update per-slot op registers; FORCE events run a closure:
    expand every configuration by every open un-linearized slot — a single
    branch-free [C, W] evaluation of the model's vectorized step — then
    deduplicate by a 2-key `lax.sort` and compact, repeating under
    `lax.while_loop` until the frontier stops growing.
  * Dedup-by-sort is the memoization: it plays the role of knossos'
    visited-configuration hash set, but as a data-parallel primitive with
    no hashing and no false positives (soundness note in SURVEY.md §7.4.2).
  * Configurations that fail to linearize a FORCEd op are killed; an empty
    frontier ⇒ not linearizable. Frontier overflow (more than C distinct
    configurations) is reported, never silently dropped: the caller escalates
    to a bigger kernel or the unbounded CPU twin (checker/wgl_cpu.py).
  * `vmap` lifts everything over a batch of histories; `parallel/` shards
    the batch over the device mesh.

Why closure only at FORCE events is sound: between two completions no
real-time precedence edge can appear (all open ops are mutually concurrent),
so deferring expansion from OPEN events to the next FORCE reaches the
identical configuration set — see history/packing.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..history.packing import EV_FORCE, EV_OPEN

#: Hard window cap: masks are uint32, and bit 31 is reserved so that a
#: fully-linearized 31-slot mask can never equal the all-ones empty-entry
#: sentinel (a 32-slot config with every bit set WOULD collide with _SENT
#: and be silently dropped — a soundness hole). Histories needing more
#: concurrent slots (incl. never-retiring info ops) fall back to the CPU
#: checker, whose masks are arbitrary-precision.
MAX_SLOTS = 31

DEFAULT_N_CONFIGS = 256

# Empty-frontier-entry sentinel mask. A NumPy (not jnp) scalar on purpose:
# a module-level jnp constant would initialize the JAX backend at import
# time, hanging importers when the accelerator is unreachable and
# defeating late platform pinning (cli --platform).
_SENT = np.uint32(0xFFFFFFFF)


def _dedup_compact(masks, states, n_configs):
    """Sort (mask, state) pairs, drop duplicates & sentinels, compact the
    first n_configs into a fresh frontier. Returns (masks', states', count,
    overflowed)."""
    sm, ss = lax.sort((masks, states), num_keys=2)
    first = jnp.concatenate([jnp.array([True]), (sm[1:] != sm[:-1]) | (ss[1:] != ss[:-1])])
    keep = first & (sm != _SENT)
    pos = jnp.cumsum(keep) - 1
    count = jnp.sum(keep)
    overflow = count > n_configs
    idx = jnp.where(keep & (pos < n_configs), pos, n_configs)
    out_m = jnp.full((n_configs,), _SENT, dtype=jnp.uint32).at[idx].set(sm, mode="drop")
    out_s = jnp.zeros((n_configs,), dtype=jnp.int32).at[idx].set(ss, mode="drop")
    return out_m, out_s, jnp.minimum(count, n_configs), overflow


def make_history_checker(model, n_configs: int = DEFAULT_N_CONFIGS,
                         n_slots: int = MAX_SLOTS):
    """Build a jittable single-history checker.

    Returns fn(events:[E,5] int32) -> (valid: bool, overflow: bool).
    `model` supplies the vectorized `jax_step` and initial state; `n_configs`
    (C) and `n_slots` (W ≤ 32) fix the kernel shape.
    """
    if n_slots > MAX_SLOTS:
        raise ValueError(f"n_slots {n_slots} > {MAX_SLOTS}")
    C, W = int(n_configs), int(n_slots)
    init_state = jnp.int32(model.init_state())
    slot_ids = jnp.arange(W, dtype=jnp.int32)
    slot_bits = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))  # [W]

    def expand_once(masks, states, count, overflow, slot_f, slot_a, slot_b,
                    slot_open):
        live = masks != _SENT  # [C]
        m = masks[:, None]  # [C,1]
        s = states[:, None]
        candidate_open = slot_open[None, :] & ((m & slot_bits[None, :]) == 0)
        ns, legal = model.jax_step(s, slot_f[None, :], slot_a[None, :],
                                   slot_b[None, :])
        good = live[:, None] & candidate_open & legal  # [C,W]
        cand_m = jnp.where(good, m | slot_bits[None, :], _SENT)
        cand_s = jnp.where(good, ns, 0).astype(jnp.int32)
        all_m = jnp.concatenate([masks, cand_m.reshape(-1)])
        all_s = jnp.concatenate([states, cand_s.reshape(-1)])
        nm, nstates, ncount, of = _dedup_compact(all_m, all_s, C)
        return nm, nstates, ncount, overflow | of

    def closure(masks, states, count, overflow, slot_f, slot_a, slot_b,
                slot_open, active):
        # Fixed point: each round adds ≥1 bit to some mask or stops, so at
        # most W productive rounds; `active` short-circuits non-FORCE events
        # (the while body never runs for them).
        def cond(c):
            return c[0]

        def body(c):
            _, it, masks, states, count, overflow = c
            nm, ns, ncount, nof = expand_once(masks, states, count, overflow,
                                              slot_f, slot_a, slot_b,
                                              slot_open)
            grew = ncount > count
            return (grew & (it < W), it + 1, nm, ns, ncount, nof)

        _, _, masks, states, count, overflow = lax.while_loop(
            cond, body, (active, jnp.int32(0), masks, states, count, overflow)
        )
        return masks, states, count, overflow

    def scan_step(carry, ev):
        masks, states, count, slot_f, slot_a, slot_b, slot_open, ok, overflow = carry
        etype, slot, f, a, b = ev[0], ev[1], ev[2], ev[3], ev[4]
        is_open = etype == EV_OPEN
        is_force = etype == EV_FORCE

        onehot = slot_ids == slot  # [W]
        upd = onehot & is_open
        slot_f = jnp.where(upd, f, slot_f)
        slot_a = jnp.where(upd, a, slot_a)
        slot_b = jnp.where(upd, b, slot_b)
        slot_open = jnp.where(upd, True, slot_open)

        masks, states, count, overflow = closure(
            masks, states, count, overflow, slot_f, slot_a, slot_b,
            slot_open, is_force)

        # FORCE: survivors have the slot's bit; then the bit is recycled.
        # Liveness guard matters: sentinel entries have every bit set and
        # must not masquerade as survivors.
        bit = jnp.uint32(1) << slot.astype(jnp.uint32)
        live = masks != _SENT
        has = ((masks & bit) != 0) & live
        killed_m = jnp.where(is_force & live & ~has, _SENT, masks)
        cleared_m = jnp.where(is_force & has, killed_m & ~bit, killed_m)
        alive = jnp.any(cleared_m != _SENT)
        ok = ok & (~is_force | alive)
        slot_open = slot_open & ~(onehot & is_force)
        # Clearing the recycled bit can merge configurations; re-dedup so the
        # next closure's grew-by-count fixpoint test stays exact. (Idempotent
        # and cheap for non-FORCE events: one C-element sort.)
        masks, states, count, _ = _dedup_compact(cleared_m, states, C)
        return (masks, states, count, slot_f, slot_a, slot_b, slot_open,
                ok, overflow), None

    def check(events):
        masks = jnp.full((C,), _SENT, dtype=jnp.uint32).at[0].set(jnp.uint32(0))
        states = jnp.zeros((C,), dtype=jnp.int32).at[0].set(init_state)
        carry = (
            masks, states, jnp.int32(1),
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), bool),
            jnp.bool_(True), jnp.bool_(False),
        )
        carry, _ = lax.scan(scan_step, carry, events)
        ok, overflow = carry[7], carry[8]
        # An overflowed run may have dropped configurations: a "False" can
        # be a false negative, so report unknown instead (caller escalates).
        return ok, overflow

    return check


_KERNEL_CACHE: dict = {}


def make_batch_checker(model, n_configs: int = DEFAULT_N_CONFIGS,
                       n_slots: int = MAX_SLOTS, jit: bool = True):
    """vmapped batch variant: fn(events:[B,E,5]) -> (valid[B], overflow[B]).

    Kernels are cached by (model identity, C, W): jax.jit caches traces per
    function object, so handing it a fresh closure per call would recompile
    every time. Model identity = (class, init_state), which fully determines
    the kernel — jax_step is class-level code.
    """
    key = (type(model), model.init_state(), int(n_configs), int(n_slots), jit)
    fn = _KERNEL_CACHE.get(key)
    if fn is None:
        single = make_history_checker(model, n_configs, n_slots)
        fn = jax.vmap(single)
        if jit:
            fn = jax.jit(fn)
        _KERNEL_CACHE[key] = fn
    return fn

"""Dense-bitset frontier kernel: exact linearizability for small domains.

The sort-based kernel (ops/linear_scan.py) represents the frontier as an
explicit list of (mask, state) configurations and pays a sort-dedup per
closure round. For the workloads the reference actually runs, that is
overkill: a CAS register over a handful of values (reference
workload/register.clj:21-34 draws values from [0,5)) has a *reachable
state domain* enumerable straight from the history — the initial value
plus every written / cas-to value. When the domain S and the concurrency
window W are both small, the entire powerset-of-window × domain fits in a
**dense boolean frontier F[2^W, S]**: F[m, s] = "some linearization of
exactly the ops in mask m ends in state s".

This is the on-device visited-*bitset* form of the search (the shape
BASELINE.json's north star names): dedup is free (a bit can only be set
once), overflow cannot happen (the array IS the configuration space), and
every kernel operation is a static reshape, tiny matmul, or elementwise
op — no sort, no scatter, no gather. Measured ~10× over the sort kernel
on the north-star shape (W=5, S=6); it is selected automatically by the
checker whenever a model can enumerate the domain (`Model.dense_domain`)
and the [2^W, S] cells fit DENSE_MAX_CELLS, with the sort kernel as the
general-case fallback.

Mechanics per event (same event stream as linear_scan — packing.py):

  OPEN w:  latch (f, a, b) into slot registers, mark the slot open.
  closure: repeat until fixpoint (≤W sweeps): for each slot w (static
           unroll), configurations without bit w flow through the slot's
           transition matrix T_w[s, s'] = legal(s) & (step(s) == s') into
           the bit-w=1 half — a butterfly reshape exposing bit w as its
           own axis plus an [?, S] @ [S, S] matmul.
  FORCE w: survivors must hold bit w (mask with the bit column derived
           arithmetically from the dynamic slot id), then the bit is
           recycled by moving the bit-w=1 half onto the bit-w=0 half —
           one `dynamic_slice` down-shift (kernel_ir.force_arith;
           switch-free,
           ISSUE 4 — the old `lax.switch` evaluated all W branches
           under vmap).

The domain table `val_of[S]` is a per-history *input* (id 0 = initial
state), so one compiled kernel serves a whole batch of histories with
different value sets; padding repeats id 0, which is harmless (duplicate
ids transition identically; the search just mirrors them).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..history.packing import EncodedHistory
# The shared step-parts substrate (PR 6 tentpole): eligibility caps,
# macro-latch helpers, the arithmetic FORCE dispatch, the stream-step
# assembly and both drivers live in ops/kernel_ir.py — this module
# keeps only the dense state-representation lowering. The caps and
# helpers are re-exported here so routing layers and tests keep their
# historical import sites.
from .kernel_ir import (DENSE_MAX_CELLS, DENSE_MAX_SLOTS, DENSE_MAX_STATES,
                        MASK_DENSE_MAX_SLOTS, KernelParts,
                        batch_chunk_checker, closure_fixpoint, force_arith,
                        macro_latch_i32, make_stream_step, monolithic_check,
                        scan_unroll)
from .kernel_ir import dense_chunk_carry_bytes  # noqa: F401  (re-export)
from .kernel_ir import macro_row_ints  # noqa: F401  (re-export)


@dataclass(frozen=True)
class DensePlan:
    """How to run a batch on a dense kernel.

    kind "domain": frontier F[2^W, S] over an enumerated value domain;
    `val_of` [B, S] is the per-history id→value table (kernel input).
    kind "mask": frontier F[2^W] for order-independent models
    (model.mask_determined) — per-mask states are subset sums; `val_of`
    is a [B, 1] dummy so both kinds share the (events, val_of) calling
    convention through the batch/mesh plumbing.
    """

    kind: str
    n_slots: int
    n_states: int
    val_of: np.ndarray

    @property
    def kernel_tag(self) -> str:
        """Reporting label (checker results, bench JSON)."""
        return "dense" if self.kind == "domain" else "dense-mask"


def dense_plan(model, encs: Sequence[EncodedHistory]) -> Optional[DensePlan]:
    """Decide whether a batch can run on a dense kernel (domain mode
    first, mask mode second), or None → the general sort kernel. The
    kernel shape is the batch maximum; domain tables are padded with
    their own id-0 (initial) value."""
    if not encs:  # nothing to plan — and _pad_domains would max() over []
        return None
    W = max((e.n_slots for e in encs), default=0)
    domains = []
    for e in encs:
        d = model.dense_domain(e.events)
        if d is None:
            domains = None
            break
        domains.append(np.asarray(d, dtype=np.int32))
    if domains is not None:
        S = max((len(d) for d in domains), default=1)
        if W <= DENSE_MAX_SLOTS and S <= DENSE_MAX_STATES and \
                (1 << W) * S <= DENSE_MAX_CELLS:
            # S buckets to a power of two inside _pad_domains: domain
            # sizes drift batch to batch and each (W, S) pair is a fresh
            # XLA compile; padding states is cheap (S² sits in a tiny
            # matmul), stable shapes are not. W stays exact — its cost
            # is exponential.
            S_b, val_of = _pad_domains(domains, range(len(domains)))
            return DensePlan("domain", max(W, 1), S_b, val_of)
    if W <= MASK_DENSE_MAX_SLOTS and \
            all(model.mask_eligible(e.events) for e in encs):
        dummy = np.zeros((len(encs), 1), dtype=np.int32)
        return DensePlan("mask", max(W, 1), 1, dummy)
    return None


#: Don't launch a dense kernel for fewer histories than this — merge the
#: stragglers into the next-wider window group instead (launch + compile
#: amortization beats a snugger W for tiny groups).
DENSE_MIN_GROUP = 16

#: Past this event count a history counts as LONG: launch amortization
#: stops being the story and scan depth becomes it (see
#: _merge_long_groups for the round-5 policy reversal).
MERGE_MAX_EVENTS = 4096

#: Long histories merge into one launch only while the group's window
#: spread stays within this many slots of the widest member: per-step
#: cost has a b·B·2^W·S width term, so folding a W=5 history into a
#: W=12 launch would inflate its every step 128× — the depth saving
#: cannot repay that. The measured config-#4 win spans spread 3 (W
#: 6..9); beyond it, clusters launch separately (still merged within
#: each cluster).
MERGE_LONG_MAX_SPREAD = 3


def _merge_all_groups() -> bool:
    """Experimental (JGRAFT_MERGE_ALL=1, off by default): extend the
    merged-launch policy to SHORT histories too — one spread-capped
    cluster per window neighborhood instead of per-window launches.
    The same serial-depth argument applies (the north-star batch's 4
    window groups scan ~8200 sequential steps where one W=8 launch
    would scan ~2050 at ~1.9× the per-step cell work), but whether the
    chip is latency- or throughput-bound at B≈1000 × [256,4] frontiers
    is an open on-chip measurement (scripts/ab_merge_long.py --all);
    short histories also lack the uniform event lengths that make
    merging free for the long configs, so this stays opt-in until the
    chip says otherwise."""
    return os.environ.get("JGRAFT_MERGE_ALL", "0") == "1"


def _merge_long_groups() -> bool:
    """Round-5 policy REVERSAL of per-window launches for LONG
    histories. Launches serialize on a single TPU core, so per-window
    groups pay the SUM of their scan depths (config #4: 4 groups ×
    ~15-20k events ≈ 70k sequential steps), while one merged launch at
    the widest window pays max-E once (~20k steps) at a higher
    per-step width. At config-4 frontier sizes the depth cut wins:
    interleaved in-process A/B on v5e (scripts/ab_merge_long.py,
    2026-07-31, 5 reps each): merged min 2.348 s / median 2.514 s vs
    per-window 3.187 / 3.342 — 1.36× at min, every merged rep faster
    than every per-window rep. (The round-3 number that set the old
    policy — merged 1.9 s vs per-window 1.3 s — was a cross-process
    comparison, the methodology the tunneled chip later proved
    unusable: identical benches span 249-677 hist/s across processes.)
    The width term is real, so merging is bounded by
    MERGE_LONG_MAX_SPREAD — and the default is TPU-ONLY: the host mesh
    is throughput-bound at these widths, so the same merge that wins
    1.36× on the chip measured config-4 CPU at 0.61 hist/s vs 1.34
    per-window (2026-07-31 CPU suite) — the segment-routing asymmetry
    again. JGRAFT_MERGE_LONG=1 forces merged anywhere, =0 forbids."""
    forced = os.environ.get("JGRAFT_MERGE_LONG")
    if forced is not None:
        return forced == "1"
    import jax

    return jax.default_backend() == "tpu"


def _pad_domains(domains, idxs):
    """[len(idxs), S] id→value table from per-history domains, S bucketed
    to a power of two (stable compile shapes), rows padded with their own
    id-0 (initial) value."""
    ds = [domains[i] for i in idxs]
    S = max(len(d) for d in ds)
    S_b = 1
    while S_b < S:
        S_b *= 2
    val_of = np.empty((len(ds), S_b), dtype=np.int32)
    for r, d in enumerate(ds):
        val_of[r, : len(d)] = d
        val_of[r, len(d):] = d[0]
    return S_b, val_of


def dense_plans_grouped(model, encs: Sequence[EncodedHistory]):
    """Route each history of a batch to its cheapest dense kernel.

    Returns (groups, rest): `groups` is [(indices, DensePlan)] over the
    dense-eligible histories, partitioned by kernel kind and concurrency
    window — kernel cost is exponential in W and a real batch's windows
    spread with per-history crash counts (the north-star batch measures
    W=5..8), so snug per-group windows beat one batch-max kernel ~1.7×.
    `rest` holds the indices that need the sort-kernel ladder (window or
    domain beyond the dense caps); eligibility is per history, so one
    oversized history no longer drags the whole batch off the dense path.
    Each history's domain is scanned exactly once."""
    domains = [model.dense_domain(e.events) for e in encs]
    buckets: dict = {}
    rest: list = []
    for i, (e, d) in enumerate(zip(encs, domains)):
        W = max(e.n_slots, 1)
        if d is not None and W <= DENSE_MAX_SLOTS and \
                len(d) <= DENSE_MAX_STATES and \
                (1 << W) * len(d) <= DENSE_MAX_CELLS:
            buckets.setdefault(("domain", W), []).append(i)
        elif W <= MASK_DENSE_MAX_SLOTS and model.mask_eligible(e.events):
            buckets.setdefault(("mask", W), []).append(i)
        else:
            rest.append(i)
    groups: list = []

    def flush(kind, pending):
        """Emit (indices, plan) for one group, or None when the whole
        group sheds. The launch window is always recomputed from the
        group's OWN histories (never the loop's current bucket window —
        an early flush of short stragglers before a wide long-history
        bucket must not inherit the wide W; kernel cost is 2^W). Domain
        mode additionally re-checks the cell envelope: eligibility used
        each history's own W and unpadded |domain|, but the merged group
        launches at the widest W with S bucketed up to a power of two —
        which can exceed the cap (e.g. stragglers merged into a 2^10
        window with S padded 9→16 = 16384 cells, 2× the cap). The widest
        histories shed to the sort ladder rather than launch an
        oversized kernel."""
        w_eff = max(max(encs[i].n_slots for i in pending), 1)
        if kind == "mask":
            return (pending, DensePlan(
                "mask", w_eff, 1,
                np.zeros((len(pending), 1), dtype=np.int32)))
        S, val_of = _pad_domains(domains, pending)
        while (1 << w_eff) * S > DENSE_MAX_CELLS and pending:
            widest = max(pending, key=lambda i: encs[i].n_slots)
            pending.remove(widest)
            rest.append(widest)
            if pending:
                S, val_of = _pad_domains(domains, pending)
                w_eff = max(max(encs[i].n_slots for i in pending), 1)
        if not pending:
            return None
        return (pending, DensePlan("domain", w_eff, S, val_of))

    merge_long = _merge_long_groups()
    # JGRAFT_MERGE_LONG=0 is the absolute off-switch: it forbids the
    # experimental MERGE_ALL mode too (an operator pinning =0 on a host
    # must never get merged launches by adding the experiment knob).
    merge_all = (_merge_all_groups()
                 and os.environ.get("JGRAFT_MERGE_LONG") != "0")
    for kind in ("domain", "mask"):
        windows = sorted(w for k, w in buckets if k == kind)
        if merge_long or merge_all:
            # Merge long histories of this kind into window-proximate
            # cluster launches (see _merge_long_groups). Under the
            # experimental MERGE_ALL, SHORT histories cluster too — but
            # in a SEPARATE pool per event-length class: merging a
            # short history into a long launch would pad its event
            # stream E_long/E_short×, which no launch saving repays.
            # Shorts not pooled here keep the per-window path below.
            pools = []
            long_pool = [i for w in windows for i in buckets[(kind, w)]
                         if encs[i].n_events > MERGE_MAX_EVENTS]
            if long_pool:
                pools.append(long_pool)
            if merge_all:
                short_pool = [i for w in windows
                              for i in buckets[(kind, w)]
                              if encs[i].n_events <= MERGE_MAX_EVENTS]
                if short_pool:
                    pools.append(short_pool)
            pooled = set(i for p in pools for i in p)
            if pooled:
                for w in windows:
                    buckets[(kind, w)] = [
                        i for i in buckets[(kind, w)] if i not in pooled]
                windows = [w for w in windows if buckets[(kind, w)]]
            for pool in pools:
                by_w = sorted(pool, key=lambda i: encs[i].n_slots,
                              reverse=True)
                while by_w:
                    w_top = encs[by_w[0]].n_slots
                    cut = w_top - MERGE_LONG_MAX_SPREAD
                    # Greedy take, re-checking the launch cell envelope
                    # as members join (domains pad S to the cluster
                    # max, pow2-bucketed): a member whose domain would
                    # push 2^w_top · S_pad over the cap waits for a
                    # later, narrower cluster instead of forcing flush
                    # to shed the WIDEST member to the sort ladder —
                    # every history here is dense-eligible alone and
                    # must stay on the dense path. (A singleton always
                    # fits: per-history eligibility used its own W and
                    # unpadded S, and pow2 padding cannot double past
                    # the cap at these sizes.)
                    take, rest_pool, s_run = [], [], 1
                    for i in by_w:
                        if encs[i].n_slots < cut:
                            rest_pool.append(i)
                            continue
                        s_new = max(s_run, len(domains[i])
                                    if kind == "domain" else 1)
                        s_pad = 1
                        while s_pad < s_new:
                            s_pad *= 2
                        if take and (1 << w_top) * s_pad > DENSE_MAX_CELLS:
                            rest_pool.append(i)
                            continue
                        take.append(i)
                        s_run = s_new
                    by_w = rest_pool
                    g = flush(kind, take)
                    if g is not None:
                        groups.append(g)
        pending: list = []
        for w in windows:
            bucket = buckets[(kind, w)]
            long_bucket = any(encs[i].n_events > MERGE_MAX_EVENTS
                              for i in bucket)
            if long_bucket and pending:
                # Flush accumulated short stragglers FIRST: merging them
                # into the long launch would pad their event streams to
                # the long history's length (E dominates kernel work).
                g = flush(kind, pending)
                if g is not None:
                    groups.append(g)
                pending = []
            pending += bucket
            min_group = 1 if long_bucket else DENSE_MIN_GROUP
            if len(pending) >= min_group or w == windows[-1]:
                g = flush(kind, pending)
                if g is not None:
                    groups.append(g)
                pending = []
    return groups, rest


def _bit_table(M: int, W: int) -> np.ndarray:
    """[M, W] static table: bit w of mask m."""
    return (np.arange(M)[:, None] >> np.arange(W)[None, :]) & 1



def hoist_transitions() -> bool:
    """Whether the DOMAIN kernel keeps transition matrices in the scan
    carry (refreshed once per OPEN) instead of re-deriving them from
    model.jax_step inside every closure sweep. (The segment kernel
    stays carry-hoisted unconditionally: its auto route is TPU-only —
    where hoisted is the measured winner — and CPU reaches it only via
    the JGRAFT_SEGMENT=1 correctness soaks. The mask kernel's legality
    hoist won on BOTH platforms and has no style switch.) Backend-keyed
    at build time, measured 2026-07-31 both ways on idle hardware:

      * v5e: hoisted wins every affected config (config 4 merged
        2.415 → 2.15-2.33 s, config 5 segmented 4.7 → 3.96 s) — per
        step, fusion count is the wall and the hoist removes W
        jax_step+T builds from each sweep iteration.
      * CPU host: hoisted LOSES big at small batch (config 5 B=1
        monolithic: 3.6-4.1 s register-style vs 7.1-7.5 s hoisted,
        same host back-to-back) — the compiled scalar loop paid
        per-step carry traffic ([W,S,S] T threading + per-event row
        build) that the guarded closure never executed.

    JGRAFT_HOIST=1/0 forces either style (ablations); kernel caches
    key on the resolved value, so the in-process CPU degrade path
    rebuilds correctly after pin_cpu()."""
    forced = os.environ.get("JGRAFT_HOIST")
    if forced is not None:
        return forced == "1"
    import jax

    return jax.default_backend() == "tpu"


def dense_step_parts(model, n_slots: int, n_states: int,
                     hoist: Optional[bool] = None,
                     macro_p: Optional[int] = None):
    """The domain kernel decomposed for chunked execution: returns
    (init, scan_step, verdict) where `init(val_of) -> carry`,
    `scan_step` is the per-event body, and `verdict(carry) ->
    (valid, overflow)`. The monolithic checker is exactly
    `verdict(lax.scan(scan_step, init(val_of), events))` — one step
    body, two drivers, so the chunked wavefront (checker/schedule.py)
    can never diverge semantically from the reference scan.

    `macro_p`: when set, `scan_step` consumes MACRO-event rows of
    3 + 4·macro_p lanes (history/packing.py macro_compact) — up to
    macro_p opens latched in one vectorized masked scatter, then the
    identical closure+FORCE the one-event-per-step stream runs. The
    batched latch reaches the same pre-FORCE register state the legacy
    stream reaches one event at a time, and closure is a reachability
    fixpoint over exactly those registers, so verdicts are bitwise
    identical (pinned by tests/test_macro_events.py); None keeps the
    legacy [E, 5] row format (the JGRAFT_MACRO_EVENTS=0 ablation).

    Step shape note (round-5): a gather-based rewrite of this kernel
    (Jacobi closure over one [W,M,S] gather + einsum, gather-based
    FORCE) measured ~2× SLOWER on v5e than this butterfly form
    (config-4 5.2 s vs 2.4 s, counter suite 12.3 s vs 7.0 s, same
    session) — TPU gathers at these tiny shapes cost more than the
    fusion count they save, which is exactly why the design invariant
    in the module docstring says "no sort, no scatter, no gather".
    The transition-matrix placement (carry-hoisted vs in-sweep) is
    backend-keyed: see hoist_transitions()."""
    if hoist is None:
        hoist = hoist_transitions()
    W, S = int(n_slots), int(n_states)
    M = 1 << W
    slot_ids = jnp.arange(W, dtype=jnp.int32)

    def expand_w(w, F, T_w):
        """One slot's flow: configs without bit w linearize op w
        through its [S, S'] transition matrix."""
        Fb = F.reshape(M >> (w + 1), 2, 1 << w, S)
        src = Fb[:, 0].reshape(-1, S).astype(jnp.float32)
        contrib = (src @ T_w).reshape(M >> (w + 1), 1 << w, S) > 0
        return jnp.concatenate(
            [Fb[:, :1], (Fb[:, 1] | contrib)[:, None]], axis=1
        ).reshape(M, S)

    # The two carry styles (hoist_transitions) differ ONLY in how a
    # slot's transition matrix is produced — everything else (OPEN
    # latch, dirty gating, closure, FORCE kill+recycle, ok accounting)
    # is the shared scan skeleton below, so a semantic fix can never
    # apply to one style and miss the other.
    if hoist:
        extra0 = (jnp.zeros((W, S, S), bool),)

        def style_update(extra, upd, f, a, b, val_of):
            (T,) = extra
            ns, legal = model.jax_step(val_of, f, a, b)
            row = (ns[:, None] == val_of[None, :]) & legal[:, None]
            return (jnp.where(upd[:, None, None], row[None], T),)

        def style_macro_latch(extra, eq, upd, pf, pa, pb, val_of):
            # Per-payload transition rows, selected into the slot axis
            # by the (at-most-one-match) eq matrix — the batched twin
            # of style_update's single-row write.
            (T,) = extra
            ns, legal = jax.vmap(
                lambda f_, a_, b_: model.jax_step(val_of, f_, a_, b_)
            )(pf, pa, pb)                                 # [P, S] each
            rows = ((ns[:, :, None] == val_of[None, None, :]) &
                    legal[:, :, None])                    # [P, S, S']
            Tnew = jnp.tensordot(eq.astype(jnp.float32),
                                 rows.astype(jnp.float32),
                                 axes=([1], [0])) > 0     # [W, S, S']
            return (jnp.where(upd[:, None, None], Tnew, T),)

        def style_sweep(extra, slot_open, val_of):
            (T,) = extra
            Te = (T & slot_open[:, None, None]).astype(jnp.float32)

            def sweep(F):  # static unroll; expansions chain w ascending
                for w in range(W):
                    F = expand_w(w, F, Te[w])
                return F

            return sweep
    else:
        extra0 = (jnp.zeros((W,), jnp.int32), jnp.zeros((W,), jnp.int32),
                  jnp.zeros((W,), jnp.int32))

        def style_update(extra, upd, f, a, b, val_of):
            sf, sa, sb = extra
            return (jnp.where(upd, f, sf), jnp.where(upd, a, sa),
                    jnp.where(upd, b, sb))

        def style_macro_latch(extra, eq, upd, pf, pa, pb, val_of):
            sf, sa, sb = extra
            return (macro_latch_i32(eq, upd, sf, pf),
                    macro_latch_i32(eq, upd, sa, pa),
                    macro_latch_i32(eq, upd, sb, pb))

        def style_sweep(extra, slot_open, val_of):
            sf, sa, sb = extra

            def sweep(F):  # static unroll; expansions chain w ascending
                for w in range(W):
                    ns, legal = model.jax_step(val_of, sf[w], sa[w],
                                               sb[w])
                    T_w = ((ns[:, None] == val_of[None, :]) &
                           legal[:, None] &
                           slot_open[w]).astype(jnp.float32)
                    F = expand_w(w, F, T_w)
                return F

            return sweep

    # IR hooks (ops/kernel_ir.make_stream_step): the stream decode and
    # latch-mask math live in the IR; only the dense state lowering —
    # register/transition latch, the closure sweep, the frontier FORCE —
    # is defined here.
    def latch(carry, slot, f, a, b, is_open, upd):
        F, extra, slot_open, ok, dirty, val_of = carry
        extra = style_update(extra, upd, f, a, b, val_of)
        slot_open = jnp.where(upd, True, slot_open)
        dirty = dirty | is_open
        return (F, extra, slot_open, ok, dirty, val_of)

    def macro_latch(carry, pslot, pf, pa, pb, valid, n, eq, upd):
        # Vectorized multi-slot latch: ≤P opens masked-scattered into
        # the slot registers in one step.
        F, extra, slot_open, ok, dirty, val_of = carry
        extra = style_macro_latch(extra, eq, upd, pf, pa, pb, val_of)
        slot_open = slot_open | upd
        dirty = dirty | (n > 0)
        return (F, extra, slot_open, ok, dirty, val_of)

    def force_tail(carry, is_force, slot):
        """Shared closure+FORCE tail: identical for the legacy and
        macro streams (the whole soundness argument — the latch phases
        reach the same registers, then run THIS same code). Closure
        runs only when an OPEN happened since the last one: a closed
        frontier stays closed under FORCE kill+clear (extensions of a
        surviving config are supersets, so they survived and cleared
        too), so back-to-back completions skip the sweeps entirely."""
        F, extra, slot_open, ok, dirty, val_of = carry
        F = closure_fixpoint(W, style_sweep(extra, slot_open, val_of),
                             F, is_force & dirty)
        dirty = dirty & ~is_force
        F_forced, alive = force_arith(F, jnp.clip(slot, 0, W - 1))
        F = jnp.where(is_force, F_forced, F)
        ok = ok & (~is_force | alive)
        slot_open = slot_open & ~((slot_ids == slot) & is_force)
        return (F, extra, slot_open, ok, dirty, val_of)

    scan_step = make_stream_step(W, latch, macro_latch, force_tail,
                                 macro_p)

    def init(val_of):
        F = jnp.zeros((M, S), dtype=bool).at[0, 0].set(True)
        return (
            F, extra0, jnp.zeros((W,), bool),
            jnp.bool_(True), jnp.bool_(False), val_of,
        )

    def verdict(carry):
        # The dense frontier cannot overflow: the array is the whole
        # configuration space. Second output mirrors the sort kernel's
        # (valid, overflow) contract.
        return carry[3], jnp.bool_(False)

    return init, scan_step, verdict


def make_dense_history_checker(model, n_slots: int, n_states: int,
                               hoist: Optional[bool] = None,
                               macro_p: Optional[int] = None):
    """Build fn(events [E,5], val_of [S]) -> (valid, overflow=False)
    (macro_p: [E_mac, 3+4·P] macro rows instead). See
    `dense_step_parts` for the kernel mechanics."""
    init, scan_step, verdict = dense_step_parts(model, n_slots, n_states,
                                                hoist, macro_p)
    return monolithic_check(KernelParts(init, scan_step, verdict,
                                        n_operands=1))


def mask_step_parts(model, n_slots: int, macro_p: Optional[int] = None):
    """Mask-mode kernel decomposed for chunked execution — same
    (init, scan_step, verdict) contract as `dense_step_parts` (incl.
    the `macro_p` macro-event stream mode and its bitwise-identity
    argument); the calling-convention dummy `val_of` is accepted (and
    ignored) by `init` so both dense kinds share one chunk-driver
    signature.

    Mask-mode kernel for order-independent models (counter): the
    frontier is a bare bitset F[2^W] — config m's state is
    base + sums[m], where `sums` holds the subset sum of the open slots'
    deltas (maintained incrementally at OPEN/FORCE with one [M] op) and
    `base` absorbs the delta of every retired op. Legality reuses the
    model's own vectorized jax_step on the derived state vector.

    Returns fn(events [E,5], val_of [1] ignored) -> (valid, False) — the
    dummy second operand keeps both dense kinds on one calling convention
    through the batch/mesh plumbing. The frontier is carried as [M, 1] so
    the force branches are shared with the domain kernel."""
    W = int(n_slots)
    M = 1 << W
    slot_ids = jnp.arange(W, dtype=jnp.int32)
    bit_i32 = jnp.asarray(_bit_table(M, W), jnp.int32)   # [M, W]

    def expand_w(w, F, legal_all):
        Fb = F.reshape(M >> (w + 1), 2, 1 << w, 1)
        Lb = legal_all[w].reshape(M >> (w + 1), 2, 1 << w)
        grown = Fb[:, 1] | (Fb[:, 0] & Lb[:, 0][..., None])
        return jnp.concatenate([Fb[:, :1], grown[:, None]],
                               axis=1).reshape(M, 1)

    def force_tail(carry, is_force, slot):
        """Shared closure+FORCE tail (identical for legacy and macro
        streams; see dense_step_parts)."""
        (F, base, sums, slot_delta, slot_f, slot_a, slot_b, slot_open,
         ok, dirty) = carry
        # Per-slot legality over ALL M config states at once: state and
        # slot registers are closure-invariant, so this lifts the
        # model.jax_step calls out of the fixpoint loop entirely (the
        # old sweep re-evaluated them W times per iteration). [W, M].
        state = base + sums
        legal_all = jax.vmap(
            lambda f_, a_, b_: (model.jax_step(state, f_, a_, b_)[1])
        )(slot_f, slot_a, slot_b) & slot_open[:, None]

        def sweep(F):
            for w in range(W):
                F = expand_w(w, F, legal_all)
            return F

        # Closure only when dirtied by an OPEN since the last closure
        # (see the domain kernel's force_tail for why that is sound).
        F = closure_fixpoint(W, sweep, F, is_force & dirty)
        dirty = dirty & ~is_force

        F_forced, alive = force_arith(F, jnp.clip(slot, 0, W - 1))
        F = jnp.where(is_force, F_forced, F)
        ok = ok & (~is_force | alive)
        # Retire the forced op: its delta is now part of every
        # survivor's permanent prefix (base), and its slot leaves the
        # open set.
        onehot = slot_ids == slot
        col = jnp.take(bit_i32, jnp.clip(slot, 0, W - 1), axis=1)  # [M]
        old_d = jnp.sum(jnp.where(onehot, slot_delta, 0))
        base = base + jnp.where(is_force, old_d, 0)
        sums = jnp.where(is_force, sums - col * old_d, sums)
        slot_delta = jnp.where(onehot & is_force, 0, slot_delta)
        slot_open = slot_open & ~(onehot & is_force)
        return (F, base, sums, slot_delta, slot_f, slot_a, slot_b,
                slot_open, ok, dirty)

    def latch(carry, slot, f, a, b, is_open, upd):
        (F, base, sums, slot_delta, slot_f, slot_a, slot_b,
         slot_open, ok, dirty) = carry
        onehot = slot_ids == slot
        slot_f = jnp.where(upd, f, slot_f)
        slot_a = jnp.where(upd, a, slot_a)
        slot_b = jnp.where(upd, b, slot_b)
        slot_open = jnp.where(upd, True, slot_open)
        dirty = dirty | is_open
        # Maintain sums[m] = Σ_w bit_w(m) · slot_delta[w] as slot
        # w's delta changes from its stale value to this op's.
        col = jnp.take(bit_i32, jnp.clip(slot, 0, W - 1), axis=1)
        old_d = jnp.sum(jnp.where(onehot, slot_delta, 0))
        new_d = model.mask_delta(f, a, b)
        sums = jnp.where(is_open, sums + col * (new_d - old_d), sums)
        slot_delta = jnp.where(upd, new_d, slot_delta)
        return (F, base, sums, slot_delta, slot_f, slot_a, slot_b,
                slot_open, ok, dirty)

    def macro_latch(carry, pslot, pf, pa, pb, valid, n, eq, upd):
        (F, base, sums, slot_delta, slot_f, slot_a, slot_b,
         slot_open, ok, dirty) = carry
        sel = eq.astype(jnp.int32)
        # Pre-latch deltas of the opened slots (0 in practice — a
        # recycled slot's delta was zeroed at its FORCE — but the
        # legacy stream computes the general form, so mirror it).
        old_d = (sel * slot_delta[:, None]).sum(0)           # [P]
        new_d = jax.vmap(model.mask_delta)(pf, pa, pb)       # [P]
        slot_f = macro_latch_i32(eq, upd, slot_f, pf)
        slot_a = macro_latch_i32(eq, upd, slot_a, pa)
        slot_b = macro_latch_i32(eq, upd, slot_b, pb)
        slot_open = slot_open | upd
        dirty = dirty | (n > 0)
        cols = jnp.take(bit_i32, jnp.clip(pslot, 0, W - 1),
                        axis=1)                              # [M, P]
        sums = sums + (cols * jnp.where(valid, new_d - old_d,
                                        0)[None, :]).sum(axis=1)
        slot_delta = macro_latch_i32(eq, upd, slot_delta, new_d)
        return (F, base, sums, slot_delta, slot_f, slot_a, slot_b,
                slot_open, ok, dirty)

    scan_step = make_stream_step(W, latch, macro_latch, force_tail,
                                 macro_p)

    def init(val_of):
        del val_of  # calling-convention dummy (see docstring)
        F = jnp.zeros((M, 1), dtype=bool).at[0, 0].set(True)
        return (
            F, jnp.int32(model.init_state()),
            jnp.zeros((M,), jnp.int32), jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), bool),
            jnp.bool_(True), jnp.bool_(False),
        )

    def verdict(carry):
        return carry[8], jnp.bool_(False)

    return init, scan_step, verdict


def make_mask_dense_history_checker(model, n_slots: int,
                                    macro_p: Optional[int] = None):
    """fn(events [E,5], val_of [1] ignored) -> (valid, False); see
    `mask_step_parts` for the kernel mechanics."""
    init, scan_step, verdict = mask_step_parts(model, n_slots, macro_p)
    return monolithic_check(KernelParts(init, scan_step, verdict,
                                        n_operands=1))


def make_dense_single_checker(model, kind: str, n_slots: int,
                              n_states: int,
                              macro_p: Optional[int] = None):
    """Unified single-history factory: fn(events [E,5], val_of [S])
    (macro_p: macro rows of 3+4·P lanes instead of [E,5])."""
    if kind == "mask":
        return make_mask_dense_history_checker(model, n_slots, macro_p)
    return make_dense_history_checker(model, n_slots, n_states,
                                      macro_p=macro_p)


_KERNEL_CACHE: dict = {}


def make_dense_batch_checker(model, kind: str, n_slots: int, n_states: int,
                             jit: bool = True,
                             macro_p: Optional[int] = None):
    """vmapped: fn(events [B,E,5], val_of [B,S]) -> (valid[B], overflow[B]).
    `macro_p` selects the macro-event row format (and keys the cache —
    a P bucket is a distinct compiled shape, like rows/events)."""
    # scan_unroll() and hoist_transitions() key the cache: the build
    # closures resolve them at trace time, so an env/backend change
    # mid-process (ablation sweeps, CPU degrade after pin_cpu) must map
    # to a distinct compiled kernel.
    key = (*model.cache_key(), kind, int(n_slots), int(n_states), jit,
           scan_unroll(), hoist_transitions(), macro_p)
    fn = _KERNEL_CACHE.get(key)
    if fn is None:
        single = make_dense_single_checker(model, kind, n_slots, n_states,
                                           macro_p)
        fn = jax.vmap(single)
        if jit:
            fn = jax.jit(fn)
        _KERNEL_CACHE[key] = fn
    return fn


def make_dense_chunk_checker(model, kind: str, n_slots: int, n_states: int,
                             jit: bool = True, mesh=None,
                             macro_p: Optional[int] = None):
    """Chunked twin of `make_dense_batch_checker` for the wavefront
    scheduler (checker/schedule.py). `macro_p` selects the macro-event
    stream (events are then [B, chunk, 3+4·P] macro rows and `n_events`
    counts MACRO rows — the scheduler's exhaustion/span math already
    runs on whatever counts the launch carries). Returns
    (init_fn, step_fn):

      init_fn(val_of [B,S], n_events [B] int32) -> carry (pytree,
          batch-leading: the per-row scan carry + an `events_left` lane)
      step_fn(carry, events [B,chunk,5]) -> (carry',
          decided [B], exhausted [B], ok [B], overflow [B])

    `decided` = the row's verdict is already certain mid-scan. For the
    dense kernels that is exactly `~ok`: `ok` is monotone (it only ever
    ANDs in new conditions) and a dead frontier stays dead — every
    subsequent event is a no-op on an all-false F — so an invalid row's
    (ok, overflow) pair is frozen the moment it turns invalid.
    `exhausted` = the row's real events are all consumed (the remaining
    schedule is EV_PAD no-ops), so the current (ok, overflow) IS the
    final verdict. Either flag makes the row safe to evict: eviction
    only ever removes rows whose verdict is certain (the soundness
    contract in checker/linearizable.py is untouched).

    Chaining `step_fn` over E/chunk chunks applies the identical
    `scan_step` sequence as the monolithic `lax.scan`, so verdicts are
    bitwise-identical by construction (pinned by tests/test_chunked_scan
    differential tests).

    `mesh`: when given, both fns are wrapped in an explicit `shard_map`
    over the batch axis (pytree-prefix P(axis) specs; every carry leaf
    is batch-leading by vmap construction). Relying on jit's GSPMD
    sharding propagation instead *placed* the carry sharded but
    compiled a ~3x slower per-chunk program than the legacy shard_map
    path on the CPU mesh (probe: 5.5 s propagated vs 1.6 s shard_map
    vs 1.5 s legacy whole-scan on one 256x512 group) — the execution
    shape must be explicit, not inferred. Callers pad the batch to a
    multiple of the mesh size (schedule._bucket_launch_rows)."""
    key = ("chunk", *model.cache_key(), kind, int(n_slots), int(n_states),
           jit, scan_unroll(), hoist_transitions(), mesh, macro_p)
    fns = _KERNEL_CACHE.get(key)
    if fns is None:
        parts = (mask_step_parts(model, n_slots, macro_p)
                 if kind == "mask"
                 else dense_step_parts(model, n_slots, n_states,
                                       macro_p=macro_p))
        init, scan_step, verdict = parts
        fns = batch_chunk_checker(
            KernelParts(init, scan_step, verdict, n_operands=1),
            mesh=mesh, jit=jit)
        _KERNEL_CACHE[key] = fns
    return fns

"""Dense-bitset frontier kernel: exact linearizability for small domains.

The sort-based kernel (ops/linear_scan.py) represents the frontier as an
explicit list of (mask, state) configurations and pays a sort-dedup per
closure round. For the workloads the reference actually runs, that is
overkill: a CAS register over a handful of values (reference
workload/register.clj:21-34 draws values from [0,5)) has a *reachable
state domain* enumerable straight from the history — the initial value
plus every written / cas-to value. When the domain S and the concurrency
window W are both small, the entire powerset-of-window × domain fits in a
**dense boolean frontier F[2^W, S]**: F[m, s] = "some linearization of
exactly the ops in mask m ends in state s".

This is the on-device visited-*bitset* form of the search (the shape
BASELINE.json's north star names): dedup is free (a bit can only be set
once), overflow cannot happen (the array IS the configuration space), and
every kernel operation is a static reshape, tiny matmul, or elementwise
op — no sort, no scatter, no gather. Measured ~10× over the sort kernel
on the north-star shape (W=5, S=6); it is selected automatically by the
checker whenever a model can enumerate the domain (`Model.dense_domain`)
and the [2^W, S] cells fit DENSE_MAX_CELLS, with the sort kernel as the
general-case fallback.

Mechanics per event (same event stream as linear_scan — packing.py):

  OPEN w:  latch (f, a, b) into slot registers, mark the slot open.
  closure: repeat until fixpoint (≤W sweeps): for each slot w (static
           unroll), configurations without bit w flow through the slot's
           transition matrix T_w[s, s'] = legal(s) & (step(s) == s') into
           the bit-w=1 half — a butterfly reshape exposing bit w as its
           own axis plus an [?, S] @ [S, S] matmul.
  FORCE w: survivors must hold bit w (mask with the static bit column),
           then the bit is recycled by moving the bit-w=1 half onto the
           bit-w=0 half (the same butterfly, in reverse). The dynamic
           slot id selects among W static branches via `lax.switch`.

The domain table `val_of[S]` is a per-history *input* (id 0 = initial
state), so one compiled kernel serves a whole batch of histories with
different value sets; padding repeats id 0, which is harmless (duplicate
ids transition identically; the search just mirrors them).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory

#: Eligibility caps. Per-event work is ~W · 2^W · S² (closure sweeps) and
#: W · 2^W · S (the vmapped switch evaluates every branch), so the dense
#: path is reserved for genuinely small problems — which the reference's
#: own workload shapes are (window ≈ n_procs, domain ≈ 5 values; a few
#: crashed ops' never-retiring slots push long histories to W ≈ 10).
DENSE_MAX_SLOTS = 10
DENSE_MAX_STATES = 16
DENSE_MAX_CELLS = 8192  # 2^W · S


def dense_plan(model, encs: Sequence[EncodedHistory]):
    """Decide whether a batch can run on the dense kernel.

    Returns (n_slots, n_states, val_of[B, S]) or None. All histories must
    have an enumerable domain (model.dense_domain) and fit the caps; the
    kernel shape is the batch maximum, domains are padded with their own
    id-0 (initial) value.
    """
    domains = []
    for e in encs:
        d = model.dense_domain(e.events)
        if d is None:
            return None
        domains.append(np.asarray(d, dtype=np.int32))
    W = max((e.n_slots for e in encs), default=0)
    S = max((len(d) for d in domains), default=1)
    if W > DENSE_MAX_SLOTS or S > DENSE_MAX_STATES or (1 << W) * S > \
            DENSE_MAX_CELLS:
        return None
    # Bucket S to a power of two: domain sizes drift batch to batch (new
    # values appear) and each (W, S) pair is a fresh XLA compile; padding
    # states is cheap (S² sits in a tiny matmul), stable shapes are not.
    # W stays exact — its cost is exponential.
    S_b = 1
    while S_b < S:
        S_b *= 2
    S = S_b
    val_of = np.empty((len(domains), S), dtype=np.int32)
    for i, d in enumerate(domains):
        val_of[i, : len(d)] = d
        val_of[i, len(d):] = d[0]
    return max(W, 1), S, val_of


def make_dense_history_checker(model, n_slots: int, n_states: int):
    """Build fn(events [E,5], val_of [S]) -> (valid, overflow=False)."""
    W, S = int(n_slots), int(n_states)
    M = 1 << W
    slot_ids = jnp.arange(W, dtype=jnp.int32)
    # [M, W] static: bit w of mask m.
    bit_table = (np.arange(M)[:, None] >> np.arange(W)[None, :]) & 1

    def expand_w(w, F, val_of, slot_f, slot_a, slot_b, slot_open):
        """One slot's flow: configs without bit w linearize op w."""
        ns, legal = model.jax_step(val_of, slot_f[w], slot_a[w], slot_b[w])
        T = ((ns[:, None] == val_of[None, :]) & legal[:, None] &
             slot_open[w]).astype(jnp.float32)  # [S, S]
        Fb = F.reshape(M >> (w + 1), 2, 1 << w, S)
        src = Fb[:, 0].reshape(-1, S).astype(jnp.float32)
        contrib = (src @ T).reshape(M >> (w + 1), 1 << w, S) > 0
        return jnp.concatenate(
            [Fb[:, :1], (Fb[:, 1] | contrib)[:, None]], axis=1
        ).reshape(M, S)

    def closure(F, val_of, slot_f, slot_a, slot_b, slot_open, active):
        def cond(c):
            return c[0]

        def body(c):
            _, it, F = c
            F0 = F
            for w in range(W):  # static unroll; sweeps chain w ascending
                F = expand_w(w, F, val_of, slot_f, slot_a, slot_b,
                             slot_open)
            changed = jnp.any(F != F0)
            return (changed & (it < W), it + 1, F)

        _, _, F = lax.while_loop(cond, body, (active, jnp.int32(0), F))
        return F

    def scan_step(carry, ev):
        F, slot_f, slot_a, slot_b, slot_open, ok, val_of = carry
        etype, slot, f, a, b = ev[0], ev[1], ev[2], ev[3], ev[4]
        is_open = etype == EV_OPEN
        is_force = etype == EV_FORCE

        onehot = slot_ids == slot
        upd = onehot & is_open
        slot_f = jnp.where(upd, f, slot_f)
        slot_a = jnp.where(upd, a, slot_a)
        slot_b = jnp.where(upd, b, slot_b)
        slot_open = jnp.where(upd, True, slot_open)

        F = closure(F, val_of, slot_f, slot_a, slot_b, slot_open, is_force)

        # Dynamic slot id → one of W static butterfly branches. Under
        # vmap the switch lowers to select-over-all-branches; each branch
        # is a few [M, S] elementwise ops, so that stays cheap.
        slot_w = jnp.clip(slot, 0, W - 1)
        F_forced, alive = lax.switch(slot_w, force_branches, F)
        F = jnp.where(is_force, F_forced, F)
        ok = ok & (~is_force | alive)
        slot_open = slot_open & ~(onehot & is_force)
        return (F, slot_f, slot_a, slot_b, slot_open, ok, val_of), None

    # One lax.switch branch per slot: kill configurations missing bit w
    # (the FORCEd op must have linearized), then recycle the bit by moving
    # the bit-w=1 half of the butterfly onto the bit-w=0 half.
    def _mk_branch(w):
        has = jnp.asarray(bit_table[:, w], bool)

        def branch(F):
            Fk = F & has[:, None]
            alive = jnp.any(Fk)
            Fb = Fk.reshape(M >> (w + 1), 2, 1 << w, S)
            moved = jnp.concatenate(
                [Fb[:, 1:2], jnp.zeros_like(Fb[:, 1:2])], axis=1
            ).reshape(M, S)
            return moved, alive

        return branch

    force_branches = [_mk_branch(w) for w in range(W)]

    def check(events, val_of):
        F = jnp.zeros((M, S), dtype=bool).at[0, 0].set(True)
        carry = (
            F,
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), jnp.int32),
            jnp.zeros((W,), jnp.int32), jnp.zeros((W,), bool),
            jnp.bool_(True), val_of,
        )
        carry, _ = lax.scan(scan_step, carry, events)
        # The dense frontier cannot overflow: the array is the whole
        # configuration space. Second output mirrors the sort kernel's
        # (valid, overflow) contract.
        return carry[5], jnp.bool_(False)

    return check


_KERNEL_CACHE: dict = {}


def make_dense_batch_checker(model, n_slots: int, n_states: int,
                             jit: bool = True):
    """vmapped: fn(events [B,E,5], val_of [B,S]) -> (valid[B], overflow[B])."""
    key = (type(model), model.init_state(), int(n_slots), int(n_states), jit)
    fn = _KERNEL_CACHE.get(key)
    if fn is None:
        single = make_dense_history_checker(model, n_slots, n_states)
        fn = jax.vmap(single)
        if jit:
            fn = jax.jit(fn)
        _KERNEL_CACHE[key] = fn
    return fn

"""Segmented scan: long-history linearizability as parallel segment runs.

The blockwise/streaming treatment SURVEY.md §5.7/§7.4.4 calls for. A
single 100k-op history packs to a ~155k-event stream, and the dense
kernel (ops/dense_scan.py) scans it strictly sequentially — one device,
per-event latency-bound, zero batch parallelism (the round-2 BASELINE
row: 60.3 s). But the scan has *provable cut points*: at any event
boundary where **no live op is open** (live = an op whose FORCE is still
coming; crashed ops never force), every surviving configuration's mask
is a subset of the currently-open *crashed* slots — everything else was
forced and had its bit recycled. Real histories are full of these
quiescent boundaries (a measured config-#5 stream: 8.6k cuts, mean gap
18 events), because client processes spend most of wall-clock time
between ops at the reference's request rates (reference raft.clj:19-22:
10 req/s/thread vs ~ms op latency).

So: cut the stream at quiescent boundaries into K segments, and run all
segments CONCURRENTLY, each vmapped over a small basis of possible
start configurations:

    basis(k) = { (mask m, state s) : m ⊆ C_k, s < S }

where C_k is the crashed-open slot set at cut k (|C_k| ≤ max crashes —
the same quantity that bounds the window; measured ≤ 3 at the cuts of
the config-#5 stream). Crashed slots never close, so C_k ⊆ C_{k+1} and
the composition is well-defined. Each (segment, seed) run produces the
final frontier F_seed[M, S]; because every kernel update (closure OR,
force kill+shift) distributes over union, the segment's effect on ANY
start frontier is the union of its effects on the seeds — each segment
is a join-morphism, fully described by its seed→frontier table. The
host then composes the K tables left to right (tiny boolean relation
chain): VALID iff a nonempty frontier survives to the end. This is
exact — same verdict as the monolithic scan, proven by the differential
tests — not an approximation.

Segment starts re-emit an OPEN event per slot in C_k (copied from the
slot's original OPEN row) so the slot registers re-latch; an OPEN does
not change the frontier, so this is free of semantic drift.

Cost shape: sequential depth drops from E to ~E/K while per-step work
grows by the basis width (≤ 2^c · S) — the classic depth-for-FLOPs
trade, and the right one on a TPU where the monolithic scan leaves the
VPU idle. Histories with no quiescent cuts (fully saturated
concurrency) fall back to the monolithic kernel: `plan` returns None.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..history.packing import EV_FORCE, EV_OPEN, EncodedHistory
from .dense_scan import _pad_domains
# Caps and the shared closure/FORCE machinery come straight from the
# kernel IR (not via dense_scan re-exports): the kernel-contract
# analyzer resolves this module's cap expressions by loading the
# sibling the import names, and it does not chase re-export chains.
from .kernel_ir import (DENSE_MAX_CELLS, DENSE_MAX_SLOTS, DENSE_MAX_STATES,
                        closure_fixpoint, force_arith, scan_unroll)

#: Segment the stream only when it is long enough to be worth the basis
#: overhead; shorter histories take the plain dense kernel.
LONG_HISTORY_MIN_EVENTS = 8192

#: Target events per segment. Depth/width balance: smaller blocks = more
#: parallelism but more basis-duplicated work and a bigger [K·nb, M, S]
#: carry. ~1-2k events/segment measured best on both CPU mesh and v5e.
DEFAULT_BLOCK_EVENTS = 1536

#: Cap on the per-segment seed basis (2^crashed · S). Beyond this the
#: frontier-carry blowup outweighs the depth win; such histories fall
#: back to the monolithic kernel.
MAX_BASIS = 256

#: CPU cost gate: the basis multiplies total cell work by NB (the depth
#: win buys wall-clock only where per-step width is near-free, i.e. the
#: TPU VPU). On the host, take the segmented path only when one step's
#: cell volume NB·2^W·S stays under this budget (config-#5 shape: 32·
#: 256·4 = 32k ✓; a W=10 16-history batch: 64·1024·4 = 262k ✗ → the
#: monolithic mesh path is faster there on CPU).
CPU_STEP_CELL_BUDGET = 1 << 16


@dataclass
class SegmentPlan:
    """Host-side plan for one long history's segmented run."""

    starts: np.ndarray          # [K] segment start event index
    ends: np.ndarray            # [K] segment end event index (exclusive)
    crash_sets: list            # [K] tuple of crashed-open slot ids at start
    open_rows: list             # [K] tuple of OPEN row indices for crash_sets
    n_slots: int
    n_states: int
    val_of: np.ndarray          # [S] id→value table


def _live_opens(events: np.ndarray) -> np.ndarray:
    """[E] bool per row: True for OPEN rows whose op is later FORCEd
    (live); False for OPEN rows of crashed ops (slot never closes) and
    for non-OPEN rows."""
    E = events.shape[0]
    live = np.zeros((E,), dtype=bool)
    seen_force: dict = {}
    for i in range(E - 1, -1, -1):
        t, s = int(events[i, 0]), int(events[i, 1])
        if t == EV_FORCE:
            seen_force[s] = True
        elif t == EV_OPEN:
            live[i] = seen_force.get(s, False)
            seen_force[s] = False
    return live


def find_cuts(events: np.ndarray):
    """Quiescent boundaries of an event stream.

    Returns (positions, crash_sets, open_rows): cut i is *before* event
    `positions[i]`; `crash_sets[i]` is the tuple of crashed-open slots
    there and `open_rows[i]` their original OPEN row indices. The stream
    start (position 0, empty crash set) is always cut 0.
    """
    live_open = _live_opens(events)
    positions = [0]
    crash_sets = [()]
    open_rows = [()]
    live = 0
    crashed: dict = {}  # slot -> OPEN row
    for i in range(events.shape[0]):
        t, s = int(events[i, 0]), int(events[i, 1])
        if t == EV_OPEN:
            if live_open[i]:
                live += 1
            else:
                crashed[s] = i
        elif t == EV_FORCE:
            live -= 1
        if live == 0:
            positions.append(i + 1)
            cs = tuple(sorted(crashed))
            crash_sets.append(cs)
            open_rows.append(tuple(crashed[c] for c in cs))
    return positions, crash_sets, open_rows


def plan_segments(model, enc: EncodedHistory,
                  block_events: int = DEFAULT_BLOCK_EVENTS,
                  min_events: int = LONG_HISTORY_MIN_EVENTS,
                  ) -> Optional[SegmentPlan]:
    """Decide whether (and how) to run a history segmented. None → use
    the monolithic kernel (stream too short, no usable cuts, basis too
    wide, or model/domain not dense-eligible)."""
    if enc.n_events < min_events:
        return None
    W = max(enc.n_slots, 1)
    domain = model.dense_domain(enc.events)
    if domain is None or W > DENSE_MAX_SLOTS or \
            len(domain) > DENSE_MAX_STATES or \
            (1 << W) * len(domain) > DENSE_MAX_CELLS:
        return None
    S, val_of = _pad_domains([np.asarray(domain, np.int32)], [0])
    positions, crash_sets, open_rows = find_cuts(enc.events)
    nb = 2 ** max(len(c) for c in crash_sets) * S
    if nb > MAX_BASIS:
        return None
    if jax.default_backend() != "tpu" and \
            nb * (1 << W) * S > CPU_STEP_CELL_BUDGET:
        return None
    # Greedy: next cut ≥ block_events past the segment start.
    starts, ends, segs_cs, segs_or = [0], [], [()], [()]
    for p, cs, orow in zip(positions[1:], crash_sets[1:], open_rows[1:]):
        if p - starts[-1] >= block_events and p < enc.n_events:
            ends.append(p)
            starts.append(p)
            segs_cs.append(cs)
            segs_or.append(orow)
    ends.append(enc.n_events)
    if len(starts) < 2:
        return None
    return SegmentPlan(np.asarray(starts), np.asarray(ends), segs_cs,
                       segs_or, W, S, val_of[0])


def make_segment_kernel(model, n_slots: int, n_states: int, n_events: int):
    """fn(events [K,E,5], val_of [K,S], seed_mask [K,NB], seed_state
    [K,NB]) -> F_final [K,NB,M,S] bool. One run per (segment, seed):
    the dense-domain scan seeded at configuration (mask, state) instead
    of (0, initial); seed_mask < 0 → empty frontier (basis padding).
    Shares the dense kernel's event semantics exactly (same scan_step
    dataflow as ops/dense_scan.make_dense_history_checker; cited there
    against the reference's knossos search, SURVEY.md §3.4)."""
    W, S, E = int(n_slots), int(n_states), int(n_events)
    M = 1 << W
    slot_ids = jnp.arange(W, dtype=jnp.int32)

    def expand_w(w, F, Te):
        Fb = F.reshape(M >> (w + 1), 2, 1 << w, S)
        src = Fb[:, 0].reshape(-1, S).astype(jnp.float32)
        contrib = (src @ Te[w]).reshape(M >> (w + 1), 1 << w, S) > 0
        return jnp.concatenate(
            [Fb[:, :1], (Fb[:, 1] | contrib)[:, None]], axis=1
        ).reshape(M, S)

    def scan_step(carry, ev):
        F, T, slot_open, dirty, val_of = carry
        etype, slot, f, a, b = ev[0], ev[1], ev[2], ev[3], ev[4]
        is_open = etype == EV_OPEN
        is_force = etype == EV_FORCE

        onehot = slot_ids == slot
        upd = onehot & is_open
        # Transition matrices live in the carry, refreshed once per
        # OPEN — not re-derived from model.jax_step W times per closure
        # sweep (same round-5 hoist as the dense kernel; measured there).
        ns, legal = model.jax_step(val_of, f, a, b)
        row = (ns[:, None] == val_of[None, :]) & legal[:, None]  # [S, S']
        T = jnp.where(upd[:, None, None], row[None], T)
        slot_open = jnp.where(upd, True, slot_open)
        dirty = dirty | is_open

        Te = (T & slot_open[:, None, None]).astype(jnp.float32)

        def sweep(F):
            for w in range(W):
                F = expand_w(w, F, Te)
            return F

        F = closure_fixpoint(W, sweep, F, is_force & dirty)
        dirty = dirty & ~is_force

        # Switch-free dispatch (ops/kernel_ir.force_arith): the old
        # lax.switch evaluated all W branches under the segment vmap.
        F_forced, _ = force_arith(F, jnp.clip(slot, 0, W - 1))
        F = jnp.where(is_force, F_forced, F)
        slot_open = slot_open & ~(onehot & is_force)
        return (F, T, slot_open, dirty, val_of), None

    def run_one(events, val_of, seed_mask, seed_state):
        # Seeded frontier; a dead seed (mask < 0) contributes nothing.
        F = ((jnp.arange(M)[:, None] == seed_mask) &
             (jnp.arange(S)[None, :] == seed_state) & (seed_mask >= 0))
        carry = (
            F,
            jnp.zeros((W, S, S), bool), jnp.zeros((W,), bool),
            jnp.bool_(False), val_of,
        )
        carry, _ = lax.scan(scan_step, carry, events,
                            unroll=scan_unroll())
        return carry[0]

    over_basis = jax.vmap(run_one, in_axes=(None, None, 0, 0))
    over_segments = jax.vmap(over_basis, in_axes=(0, 0, 0, 0))
    return jax.jit(over_segments)


_SEG_KERNEL_CACHE: dict = {}


def _segment_kernel(model, W: int, S: int, E: int):
    # scan_unroll() in the key: see dense_scan.make_dense_batch_checker.
    key = (*model.cache_key(), W, S, E, scan_unroll())
    fn = _SEG_KERNEL_CACHE.get(key)
    if fn is None:
        fn = make_segment_kernel(model, W, S, E)
        _SEG_KERNEL_CACHE[key] = fn
    return fn


def _build_segment_arrays(enc: EncodedHistory, plan: SegmentPlan,
                          E_seg: int, NB: int, S: int):
    """Materialize one history's segment/basis inputs.

    events [K,E_seg,5] (re-OPEN prologue + slice, EV_PAD tail),
    seed_mask/seed_state [K,NB] (padded -1), basis index maps for the
    host composition. `S` is the BATCH state count, not the history's
    own: state-table padding duplicates the id-0 value, so the kernel
    can land frontier bits on duplicate state ids — the basis (and the
    composition lookups) must cover them."""
    K = len(plan.starts)
    events = np.zeros((K, E_seg, 5), dtype=np.int32)
    seed_mask = np.full((K, NB), -1, dtype=np.int32)
    seed_state = np.zeros((K, NB), dtype=np.int32)
    basis_index: list = []  # per segment: {(mask, state): basis row}
    for k in range(K):
        s0, e0 = int(plan.starts[k]), int(plan.ends[k])
        pro = len(plan.open_rows[k])
        # Prologue: re-latch each crashed-open slot's registers.
        for j, row in enumerate(plan.open_rows[k]):
            events[k, j] = enc.events[row]
        events[k, pro:pro + (e0 - s0)] = enc.events[s0:e0]
        # Basis: every subset of the crashed set × every state id.
        cs = plan.crash_sets[k]
        idx: dict = {}
        b = 0
        for sub in range(1 << len(cs)):
            mask = 0
            for j, slot in enumerate(cs):
                if sub >> j & 1:
                    mask |= 1 << slot
            for st in range(S):
                seed_mask[k, b] = mask
                seed_state[k, b] = st
                idx[(mask, st)] = b
                b += 1
        basis_index.append(idx)
    return events, seed_mask, seed_state, basis_index


def check_segmented(enc: EncodedHistory, model,
                    block_events: int = DEFAULT_BLOCK_EVENTS,
                    min_events: int = LONG_HISTORY_MIN_EVENTS,
                    ) -> Optional[dict]:
    """Check one long history via the segmented scan. None → caller
    should use the monolithic path."""
    [r] = check_segmented_batch([enc], model, block_events, min_events)
    return r


def check_segmented_batch(encs: Sequence[EncodedHistory], model,
                          block_events: int = DEFAULT_BLOCK_EVENTS,
                          min_events: int = LONG_HISTORY_MIN_EVENTS,
                          ) -> list:
    """Batch form: all eligible histories' segments fly in ONE kernel
    launch (the segment axis is the batch axis — config #4's 16×10k
    histories become ~160 concurrent segment scans). Returns a result
    dict per history, or None per history that should take the
    monolithic path."""
    plans = [plan_segments(model, e, block_events, min_events)
             for e in encs]
    live = [i for i, p in enumerate(plans) if p is not None]
    results: list = [None] * len(encs)
    if not live:
        return results
    # One compiled shape across histories: bucket everything — then
    # RE-CHECK the basis gates with the batch-bucketed S/W. plan_segments
    # gated each history against its OWN domain size; batching a
    # small-domain many-crash history with a wide-domain one multiplies
    # the first's basis by the batch S and can blow past MAX_BASIS /
    # the CPU budget the gates were measured to protect. Offenders fall
    # back to the monolithic path (result None); shrinking `live` can
    # shrink S, so iterate to stability.
    while True:
        W = max(plans[i].n_slots for i in live)
        S = max(plans[i].n_states for i in live)
        shed = []
        for i in live:
            p = plans[i]
            nb_i = max(1 << len(c) for c in p.crash_sets) * S
            if nb_i > MAX_BASIS or (
                    jax.default_backend() != "tpu" and
                    nb_i * (1 << W) * S > CPU_STEP_CELL_BUDGET):
                shed.append(i)
        if not shed:
            break
        live = [i for i in live if i not in shed]
        if not live:
            return results
    E_seg = 1
    NB = 1
    for i in live:
        p = plans[i]
        pro = max((len(c) for c in p.crash_sets), default=0)
        seg_len = int((p.ends - p.starts).max()) + pro
        E_seg = max(E_seg, seg_len)
        NB = max(NB, max(1 << len(c) for c in p.crash_sets) * S)
    E_seg = _pow2(E_seg)
    NB = _pow2(NB)

    rows_events, rows_val, rows_mask, rows_state = [], [], [], []
    maps = []
    for i in live:
        p = plans[i]
        ev, sm, ss, bidx = _build_segment_arrays(encs[i], p, E_seg, NB, S)
        # Re-bucket this history's S up to the batch S (harmless pad:
        # duplicate id-0 values transition identically).
        val = np.full((len(ev), S), p.val_of[0], dtype=np.int32)
        val[:, :len(p.val_of)] = p.val_of
        rows_events.append(ev)
        rows_val.append(val)
        rows_mask.append(sm)
        rows_state.append(ss)
        maps.append((len(ev), bidx, p))
    events = np.concatenate(rows_events)
    val_of = np.concatenate(rows_val)
    seed_mask = np.concatenate(rows_mask)
    seed_state = np.concatenate(rows_state)

    # The segment axis is embarrassingly parallel — shard it over the
    # device mesh (computation follows data; dead padded segments cost
    # one seed check). This is what makes a SINGLE long history use the
    # whole mesh, which the monolithic scan never could.
    kernel = _segment_kernel(model, W, S, E_seg)
    from ..parallel.mesh import make_mesh
    mesh = make_mesh()
    n_dev = mesh.devices.size
    K_tot = events.shape[0]
    K_pad = ((K_tot + n_dev - 1) // n_dev) * n_dev
    if K_pad != K_tot:
        events = np.concatenate(
            [events, np.zeros((K_pad - K_tot,) + events.shape[1:],
                              events.dtype)])
        val_of = np.concatenate(
            [val_of, np.tile(val_of[-1:], (K_pad - K_tot, 1))])
        seed_mask = np.concatenate(
            [seed_mask, np.full((K_pad - K_tot, NB), -1, np.int32)])
        seed_state = np.concatenate(
            [seed_state, np.zeros((K_pad - K_tot, NB), np.int32)])
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    ax = mesh.axis_names[0]
    sh3 = NamedSharding(mesh, P(ax, None, None))
    sh2 = NamedSharding(mesh, P(ax, None))
    F = np.asarray(kernel(  # lint: allow(host-sync) — host composition next
        _jax.device_put(events, sh3), _jax.device_put(val_of, sh2),
        _jax.device_put(seed_mask, sh2),
        _jax.device_put(seed_state, sh2)))[:K_tot]

    # Host composition: chain each history's segment relations.
    row = 0
    for i, (K, bidx, p) in zip(live, maps):
        reach = {(0, 0)}
        for k in range(K):
            acc = None
            for (m, st) in reach:
                b = bidx[k].get((m, st))
                if b is None:
                    # A reachable config outside the planned basis would
                    # be a soundness bug (cut spaces are nested) — fail
                    # loudly rather than report a verdict.
                    raise AssertionError(
                        f"segment {k}: config ({m},{st}) outside basis")
                f = F[row + k, b]
                acc = f if acc is None else (acc | f)
            if acc is None or not acc.any():
                reach = set()
                break
            ms, sts = np.nonzero(acc)
            reach = set(zip(ms.tolist(), sts.tolist()))
        valid = bool(reach)
        results[i] = {
            "valid": valid,
            "segments": K,
            "basis": NB,
            "n_slots": p.n_slots,
        }
        row += K
    return results


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b

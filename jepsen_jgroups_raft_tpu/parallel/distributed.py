"""Multi-host distributed checking runtime (ISSUE 7 tentpole).

The reference scales its SUT over multiple hosts with JGroups (SURVEY.md
§5.8); the checker backend's multi-host analogue is the JAX distributed
runtime: one process per host, all of the slice's chips visible through
one global device list, the batch axis sharded over every device — ICI
inside a host/slice, DCN between hosts. The harness stays a single
control process (like the reference's control node); only verification
fans out.

Three layers live here, smallest dependency first:

* **Runtime** — `maybe_init_distributed` initializes `jax.distributed`
  from the standard cluster env (``JAX_COORDINATOR_ADDRESS`` /
  ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``), parsed defensively (a
  malformed value warns, records a degrade note, and returns False —
  it must never crash an importer or CLI the way a bare ``int()``
  would), or — opt-in via ``JGRAFT_DISTRIBUTED_AUTODETECT=1`` — from an
  autodetectable cluster environment (bare ``jax.distributed
  .initialize()``, which recognizes SLURM/GKE-style launchers; the
  attempt is wrapped, a non-cluster host just returns False). No-op and
  False on single-host runs, idempotent everywhere.

* **Exchange** — the cross-process transport for host-side values
  (verdict codes, counters). Two flavors, picked by capability: real
  multi-host accelerator pods run device collectives over the global
  mesh (`check_batch_global` below — the pjit/NamedSharding pattern of
  SNIPPETS [1]–[3]); hosts whose backend cannot run multiprocess
  computations (this box's CPU backend: jaxlib answers
  "Multiprocess computations aren't implemented on the CPU backend")
  use the *coordination service* — the gRPC KV store + barriers every
  `jax.distributed` cluster already carries (`exchange_bytes` /
  `exchange_i64` / `barrier`). `collectives_supported()` probes which
  world this is, once. Exchange calls are SPMD-disciplined: every
  process must make the same sequence of calls (each call burns one
  slot of a shared tag counter and two barriers).

* **Sharded wavefront** — `run_sharded` is the seam
  `checker.linearizable.check_encoded` routes through when the process
  is part of a cluster: rows are split into per-process contiguous
  shards (`shard_bounds`, boundaries aligned to the host's mesh
  fan-out via `placement_granularity` — the autotuner's `mesh_fanout`
  plan dimension feeding cross-host placement), each process runs the
  ordinary chunked wavefront on ONLY its shard (per-host packing: its
  event tensors are born on its shard and its host CPU does only its
  share of the encode/pack work), and the per-row verdict codes are
  exchanged so every process returns the full batch's verdicts.
  Soundness is the batch-axis independence argument of
  doc/checker-design.md §8, restated for hosts in §10: a row's verdict
  is a function of that row's event stream alone, so the shard-local
  scan is bitwise-identical to the single-process scan of the same
  rows (pinned by tests/test_distributed.py).
"""

from __future__ import annotations

import itertools
import logging
import os
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..platform import env_int, note_degraded

_log = logging.getLogger(__name__)

#: Wire timeout for the coordination-service exchange (barriers + KV
#: gets). Generous: a barrier waits for the SLOWEST shard's check, and
#: an escalated CPU-ladder row can take minutes.
DEFAULT_TIMEOUT_MS = 600_000


def distributed_enabled() -> bool:
    """Master gate for the distributed wavefront seam.
    ``JGRAFT_DISTRIBUTED=0`` pins single-process behavior even inside a
    cluster (the ablation / escape hatch); parsed defensively."""
    return env_int("JGRAFT_DISTRIBUTED", 1, minimum=0) != 0


def exchange_timeout_ms() -> int:
    return env_int("JGRAFT_DISTRIBUTED_TIMEOUT_MS", DEFAULT_TIMEOUT_MS,
                   minimum=1_000)


# ---------------------------------------------------------------- runtime


def parse_cluster_env() -> Optional[Tuple[str, int, int]]:
    """(coordinator, n_processes, process_id) from the standard JAX
    cluster env, or None when absent OR malformed. Malformed values
    warn and record a degrade note instead of raising: a typo'd
    ``JAX_NUM_PROCESSES`` used to surface as a ``ValueError`` out of
    ``int()`` at CLI/bench start — the single-host degrade must be
    loud, not fatal."""
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc_raw = os.environ.get("JAX_NUM_PROCESSES")
    if not coord or not nproc_raw:
        return None
    pid_raw = os.environ.get("JAX_PROCESS_ID", "0")
    try:
        nproc = int(nproc_raw.strip())
        pid = int(pid_raw.strip() or "0")
    except ValueError:
        note = (f"cluster env malformed (JAX_NUM_PROCESSES={nproc_raw!r}, "
                f"JAX_PROCESS_ID={pid_raw!r}) — running single-process")
        _log.warning("distributed: %s", note)
        note_degraded(note)
        return None
    if nproc < 1 or not 0 <= pid < nproc:
        note = (f"cluster env inconsistent (num_processes={nproc}, "
                f"process_id={pid}) — running single-process")
        _log.warning("distributed: %s", note)
        note_degraded(note)
        return None
    return coord, nproc, pid


def maybe_init_distributed() -> bool:
    """Initialize `jax.distributed` when a cluster environment is
    present. Returns True iff the distributed runtime is (now)
    initialized. Idempotent; safe from bench/CLI entry points.

    Resolution order: the explicit env triple (defensively parsed —
    see `parse_cluster_env`); then, ONLY when
    ``JGRAFT_DISTRIBUTED_AUTODETECT=1``, a bare
    ``jax.distributed.initialize()`` whose launcher autodetection
    covers SLURM/GKE-style clusters (off by default: the bare call is
    a no-op ValueError on a plain host, but autodetection mis-firing
    inside an unrelated batch scheduler would wedge single-host runs
    waiting for phantom peers). Every failure path returns False with
    a warning + degrade note rather than raising."""
    import jax

    if is_initialized():
        return True
    env = parse_cluster_env()
    if env is not None:
        coord, nproc, pid = env
        try:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=nproc, process_id=pid)
        except Exception as e:  # unreachable coordinator, double init race
            note = (f"jax.distributed.initialize failed for "
                    f"{coord} ({type(e).__name__}: {e}) — "
                    "running single-process"[:300])
            _log.warning("distributed: %s", note)
            note_degraded(note)
            return False
        return True
    if env_int("JGRAFT_DISTRIBUTED_AUTODETECT", 0, minimum=0):
        try:
            jax.distributed.initialize()
            return True
        except Exception as e:
            _log.warning("distributed: cluster autodetection found no "
                         "cluster (%s: %s) — running single-process",
                         type(e).__name__, str(e)[:200])
            return False
    return False


def is_initialized() -> bool:
    """Whether the distributed runtime is already up. jax grew a public
    `jax.distributed.is_initialized` only after this pin's 0.4.x, so
    fall back to the coordination-service client's existence (every
    initialized process holds one) — re-calling initialize on an
    already-up runtime raises, which the idempotency contract of
    `maybe_init_distributed` must absorb without a spurious degrade
    note."""
    import jax

    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        try:
            return bool(probe())
        except Exception as e:  # noqa: BLE001 — fall to the client probe
            _log.debug("distributed: is_initialized probe failed "
                       "(%s: %s); falling back to client check",
                       type(e).__name__, e)
    try:
        from jax._src.distributed import global_state
    except ImportError:
        return False
    return getattr(global_state, "client", None) is not None


def process_count() -> int:
    """Processes in the cluster; 1 when uninitialized/single-host."""
    try:
        import jax

        return int(jax.process_count())
    except Exception as e:  # noqa: BLE001 — broken jax: act single-host
        _log.debug("distributed: process_count unavailable (%s: %s); "
                   "assuming single-process", type(e).__name__, e)
        return 1


def process_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception as e:  # noqa: BLE001 — broken jax: act single-host
        _log.debug("distributed: process_index unavailable (%s: %s); "
                   "assuming process 0", type(e).__name__, e)
        return 0


def wavefront_active() -> bool:
    """Whether the checker should run the sharded distributed wavefront:
    a multi-process runtime is up and the env gate allows it."""
    return distributed_enabled() and process_count() > 1


# --------------------------------------------------------------- sharding


def shard_bounds(n_rows: int, n_shards: Optional[int] = None,
                 index: Optional[int] = None,
                 granularity: int = 1) -> Tuple[int, int]:
    """Contiguous [lo, hi) row range of shard `index` out of `n_shards`
    over `n_rows` rows (defaults: this process in the cluster).

    Boundaries are the balanced cuts ``i·n // n_shards`` rounded DOWN
    to a multiple of `granularity` (the last boundary stays exactly
    `n_rows`): with granularity = the host's mesh fan-out, every
    non-final shard's row count divides evenly over its local device
    mesh, so the shard-local row buckets match the shapes the
    single-process path compiles. Shards can be empty when
    n_rows < n_shards — callers must tolerate a zero-row shard."""
    if n_shards is None:
        n_shards = process_count()
    if index is None:
        index = process_index()
    if not 0 <= index < n_shards:
        raise ValueError(f"shard index {index} out of range {n_shards}")
    g = max(1, int(granularity))

    def cut(i: int) -> int:
        if i >= n_shards:
            return n_rows
        return min(n_rows, (i * n_rows // n_shards) // g * g)

    return cut(index), cut(index + 1)


def placement_granularity() -> int:
    """Row granularity of the cross-host split: the host's mesh fan-out
    (`parallel.mesh.chunk_sharding` — the same quantity that
    outer-bounds the autotuner's `mesh_fanout` plan dimension), so each
    host's shard splits evenly over its local devices. 1 when fan-out
    is gated off or the host has one device."""
    from .mesh import chunk_sharding

    sharding = chunk_sharding()
    mesh = getattr(sharding, "mesh", None)
    return int(mesh.size) if mesh is not None else 1


# --------------------------------------------------------------- exchange

#: Exchange sequence counter. Every process makes the same sequence of
#: exchange/barrier calls (SPMD discipline — documented contract of
#: `run_sharded` and the bench), so a per-process counter yields
#: cluster-identical tags without any coordination of its own.
_SEQ = itertools.count()


def _coord_client():
    """The coordination-service client `jax.distributed` brought up —
    the gRPC KV store + barrier transport. jax's public surface does
    not re-export it, so this reaches into jax._src (stable across the
    0.4.x line; guarded so a rename degrades loudly, not cryptically)."""
    try:
        from jax._src.distributed import global_state
    except ImportError as e:  # pragma: no cover - jax internals moved
        raise RuntimeError(
            "jax coordination-service client unavailable "
            f"({type(e).__name__}: {e}); cannot exchange across "
            "processes") from e
    client = getattr(global_state, "client", None)
    if client is None:
        raise RuntimeError("jax.distributed is not initialized — no "
                           "coordination-service client to exchange through")
    return client


def barrier(name: str) -> None:
    """Cluster-wide barrier over the coordination service (works on
    every backend — no device collective involved)."""
    _coord_client().wait_at_barrier(f"jgraft/b/{name}", exchange_timeout_ms())


def exchange_bytes(payload: bytes, tag: Optional[str] = None) -> List[bytes]:
    """All-gather one bytes payload per process via the coordination
    service's KV store: set own key, barrier, read every key, barrier,
    then process 0 deletes the keys (a long-lived daemon must not grow
    the coordinator's store without bound). Returns the payloads in
    process order. Every process must call this the same number of
    times in the same order (the shared tag counter and the two
    barriers both assume it).

    Wire format: base64 through the STRING KV API, with one framing
    byte so the stored value is never empty. Both quirks are
    load-bearing on the pinned jaxlib (0.4.36, reproduced): the
    ``*_bytes`` KV variants SEGFAULT the interpreter outright, and an
    empty shard's payload (legal — `shard_bounds` granularity rounding
    can produce a zero-row shard) must still round-trip."""
    import base64

    client = _coord_client()
    n, pid = process_count(), process_index()
    tag = tag or f"x{next(_SEQ)}"
    timeout = exchange_timeout_ms()
    base = f"jgraft/kv/{tag}"
    wire = base64.b64encode(b"\x01" + payload).decode("ascii")
    client.key_value_set(f"{base}/{pid}", wire)
    client.wait_at_barrier(f"{base}/set", timeout)
    out = [base64.b64decode(
        client.blocking_key_value_get(f"{base}/{i}", timeout))[1:]
        for i in range(n)]
    client.wait_at_barrier(f"{base}/got", timeout)
    if pid == 0:
        for i in range(n):
            try:
                client.key_value_delete(f"{base}/{i}")
            except Exception as e:  # noqa: BLE001 — cleanup only; the
                # values were already read by every process
                _log.debug("distributed: kv cleanup of %s/%d failed "
                           "(%s: %s)", base, i, type(e).__name__, e)
    return out


def exchange_i64(arr: Sequence[int], tag: Optional[str] = None) \
        -> List[np.ndarray]:
    """All-gather one int64 vector per process (verdict codes, counter
    totals). Shards may contribute different lengths (uneven row
    shards)."""
    payload = np.asarray(arr, dtype="<i8").tobytes()
    return [np.frombuffer(raw, dtype="<i8") for raw
            in exchange_bytes(payload, tag=tag)]


# ------------------------------------------------------ sharded wavefront

#: Verdict wire codes (checker.base VALID/INVALID/UNKNOWN).
_CODE_INVALID, _CODE_VALID, _CODE_UNKNOWN = 0, 1, 2


def _verdict_code(result: dict) -> int:
    from ..checker.base import INVALID, VALID

    v = result.get("valid?")
    if v is VALID:
        return _CODE_VALID
    if v is INVALID:
        return _CODE_INVALID
    return _CODE_UNKNOWN


def _remote_result(code: int, owner: int) -> dict:
    """Result stub for a row checked by another process: the verdict is
    exact (it rode the wire); without a shared result store the
    explanation detail (witness, timing, kernel tag) stays on the
    owning host's artifacts. With a store configured (ISSUE 11
    tentpole (d)) `run_sharded` upgrades the stub from the owning
    host's published detail record."""
    from ..checker.base import INVALID, UNKNOWN, VALID

    valid = (VALID if code == _CODE_VALID
             else INVALID if code == _CODE_INVALID else UNKNOWN)
    return {"valid?": valid, "algorithm": "jax",
            "kernel": "remote-shard", "process": owner,
            "decided-tier": "remote-shard"}


def _detail_exchange(model, algorithm: str):
    """(store, key_fn) for the cross-host result-detail exchange, or
    (None, None) — inert unless JGRAFT_RESULT_STORE (or the cluster
    dir) names a directory every host shares, and only usable when the
    caller supplied the model the detail keys hash over."""
    if model is None:
        return None, None
    try:
        from ..service.store import detail_fingerprint, detail_store
    except ImportError as e:  # pragma: no cover — partial checkout
        _log.debug("distributed: detail store unavailable (%s)", e)
        return None, None
    store = detail_store()
    if store is None:
        return None, None
    return store, lambda enc: detail_fingerprint(model, algorithm, enc)


def run_sharded(encs: Sequence, check_local: Callable[[list], List[dict]],
                granularity: Optional[int] = None, model=None,
                algorithm: str = "auto") -> List[dict]:
    """The distributed wavefront driver: check only this process's row
    shard through `check_local` (the ordinary single-process pass —
    chunked wavefront, escalation ladder, everything), then exchange
    per-row verdict codes so every process returns the FULL batch's
    results in submission order. Local rows carry their full result
    dicts; remote rows carry `_remote_result` stubs — unless a shared
    result store is configured (`model` given + JGRAFT_RESULT_STORE /
    the cluster dir), in which case each process publishes its local
    rows' full details before the verdict exchange and reads the
    owners' details for remote rows after it (ISSUE 11 tentpole (d):
    witnesses and minimized counterexamples follow the verdict). The
    exchange's barriers order every publish before every read, so a
    shared filesystem needs no extra synchronization; a missing or
    degraded detail record degrades that row to the PR 7 stub, never
    to an error.

    SPMD contract: every process must call with the same batch (same
    row count, same order) — the bench and the `check` CLI satisfy it
    by construction (same inputs, same code path). Placement: shard
    boundaries align to `placement_granularity` so each host's rows
    split evenly over its local mesh."""
    n, pid = process_count(), process_index()
    if n <= 1:  # no cluster: the "shard" is the whole batch, no wire
        return check_local(list(encs))
    g = placement_granularity() if granularity is None else granularity
    lo, hi = shard_bounds(len(encs), n, pid, granularity=g)
    local = check_local(list(encs[lo:hi]))
    store, key_fn = _detail_exchange(model, algorithm)
    if store is not None:
        for enc, res in zip(encs[lo:hi], local):
            if isinstance(res, dict) and "valid?" in res:
                # degraded rows are refused by the store's own gate
                store.put_detail(key_fn(enc), res)
    codes = exchange_i64([_verdict_code(r) for r in local])
    results: List[dict] = []
    for p in range(n):
        plo, phi = shard_bounds(len(encs), n, p, granularity=g)
        if p == pid:
            results.extend(local)
        else:
            if len(codes[p]) != phi - plo:
                raise RuntimeError(
                    f"shard {p} exchanged {len(codes[p])} verdicts for "
                    f"{phi - plo} rows — processes disagree on the batch "
                    "(the SPMD contract of run_sharded is broken)")
            for row, c in zip(range(plo, phi), codes[p]):
                stub = _remote_result(int(c), p)
                if store is not None:
                    detail = store.get_detail(key_fn(encs[row]))
                    if detail is not None \
                            and detail.get("valid?") == stub["valid?"]:
                        # the full verdict rode the store; keep the
                        # owner attribution on top of it
                        detail["process"] = p
                        detail["detail-source"] = "result-store"
                        stub = detail
                results.append(stub)
    return results


# ------------------------------------------------- global-mesh collectives

_COLLECTIVES: Optional[bool] = None


def collectives_supported() -> bool:
    """Whether this backend can run ONE computation spanning every
    process's devices (real multi-host accelerator pods: yes; this
    box's CPU backend: jaxlib refuses with "Multiprocess computations
    aren't implemented"). Probed once with a tiny global-mesh psum —
    itself a collective, so every process must reach the probe
    together (same SPMD discipline as the exchange layer). False on
    single-process runs (nothing to span)."""
    global _COLLECTIVES
    if _COLLECTIVES is not None:
        return _COLLECTIVES
    if process_count() <= 1:
        return False
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        mesh = global_mesh()
        axis = mesh.axis_names[0]
        ones = np.ones((len(jax.local_devices()),), dtype=np.int32)
        garr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(axis)), ones)
        total = jax.jit(
            lambda x: x.sum(),
            out_shardings=NamedSharding(mesh, P()))(garr)
        _COLLECTIVES = int(total) == len(jax.devices())
    except Exception as e:  # noqa: BLE001 — any refusal means "route
        _log.info("distributed: global-mesh collectives unavailable "
                  "(%s: %s) — exchanging via the coordination service",
                  type(e).__name__, str(e)[:200])
        _COLLECTIVES = False
    return _COLLECTIVES


def global_mesh(axis_name: Optional[str] = None):
    """1-D mesh over EVERY process's devices, in global device order
    (the call-site mesh of the SNIPPETS [1]–[3] pattern)."""
    import jax
    from jax.sharding import Mesh

    from .mesh import BATCH_AXIS

    return Mesh(np.asarray(jax.devices()), (axis_name or BATCH_AXIS,))


def check_batch_global(model, encs: Sequence) -> Tuple[int, int]:
    """One logical dense check sharded over the GLOBAL mesh — the
    TPU-pod execution shape of the tentpole: per-host packing
    (`history.packing.pack_*_batch_shard` — each process compacts and
    fills ONLY its row shard at batch-globally agreed shapes, so the
    event tensor is born on its shard), `NamedSharding` assembly via
    `jax.make_array_from_process_local_data`, and the sharded dense
    kernel's verdict `psum` riding DCN. Returns the global
    (n_valid, n_unknown) counts, identical on every process.

    Requires `collectives_supported()`; hosts without multiprocess
    computations (CPU meshes on this jax) must use `run_sharded`,
    whose exchange rides the coordination service instead."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..history.packing import (macro_events_on, pack_batch_shard,
                                   pack_macro_batch_shard)
    from ..ops.dense_scan import dense_plan
    from .mesh import sharded_dense_checker

    if not collectives_supported():
        raise RuntimeError(
            "global-mesh collectives unsupported on this backend — "
            "use run_sharded (coordination-service exchange) instead")
    encs = list(encs)
    plan = dense_plan(model, encs)
    if plan is None:
        raise ValueError("check_batch_global needs a dense-eligible batch "
                         "(run_sharded handles the general routing)")
    mesh = global_mesh()
    axis = mesh.axis_names[0]
    n, pid = process_count(), process_index()
    # Pad the batch so it splits exactly: a multiple of the global
    # device count is automatically a multiple of the (equal-size)
    # per-process device groups. Pad rows are EV_PAD no-op histories.
    d_global = int(mesh.devices.size)
    B = len(encs)
    B_pad = -(-B // d_global) * d_global
    lo, hi = shard_bounds(B_pad, n, pid)
    pack = (pack_macro_batch_shard if macro_events_on()
            else pack_batch_shard)
    batch = pack(encs, pid, n, n_rows=B_pad)
    local_ev = batch["events"]
    val_of = np.zeros((hi - lo,) + plan.val_of.shape[1:],
                      dtype=plan.val_of.dtype)
    real = np.zeros((hi - lo,), dtype=bool)
    n_real = max(0, min(hi, B) - lo)
    val_of[:n_real] = plan.val_of[lo:lo + n_real]
    val_of[n_real:] = plan.val_of[:1]
    real[:n_real] = True
    g_events = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis, None, None)), local_ev)
    g_val = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis, None)), val_of)
    g_real = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis)), real)
    fn = sharded_dense_checker(model, mesh, plan.kind, plan.n_slots,
                               plan.n_states, axis,
                               macro_p=batch.get("macro_p"))
    _, _, n_valid, n_unknown = fn(g_events, g_val, g_real)
    # psum outputs are replicated scalars — addressable on every host.
    return int(n_valid), int(n_unknown)  # lint: allow(host-sync)

"""Multi-host initialization for the checker backend.

The reference scales its SUT over multiple hosts with JGroups (SURVEY.md
§5.8); the checker backend's multi-host analogue is a JAX distributed
runtime: one process per host, all chips of the slice in one global mesh,
batch sharded over every device, ICI inside a host/slice and DCN between
hosts. The harness stays a single control process (like the reference's
control node) and only the verification fans out.

`maybe_init_distributed` is a no-op unless the standard JAX cluster env
(``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``) or an
autodetectable cluster environment is present, so single-host runs (and the
CPU test mesh) never pay for it.
"""

from __future__ import annotations

import os


def maybe_init_distributed() -> bool:
    """Initialize jax.distributed when cluster env vars are set.

    Returns True if the distributed runtime is (now) initialized.
    Idempotent; safe to call from bench/CLI entry points.
    """
    import jax

    if getattr(jax.distributed, "is_initialized", None) and jax.distributed.is_initialized():
        return True
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES")
    if not coord or not nproc:
        return False
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
    )
    return True

"""Local multi-process launcher for the distributed checking topology.

One process per "host", coordinated over localhost gRPC — the CPU-mesh
recipe of ISSUE 7: on a TPU-less box the N-process topology runs with
``--xla_force_host_platform_device_count`` splitting the virtual CPU
devices between processes, exercising exactly the runtime
(`jax.distributed` init, shard-local packing, coordination-service
verdict exchange) a real pod uses — on a pod the operator instead runs
the same command once per host with the standard cluster env set (see
doc/running.md "Multi-host checking").

Consumers: ``bench.py --distributed N`` (the parent side lives here so
the subprocess/socket lifetimes sit inside the lint scan scope),
``scripts/ab_distributed.py``, and tests/test_distributed.py.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from ..platform import cpu_subprocess_env, env_int


def free_coordinator_port() -> int:
    """Ephemeral localhost port for the cluster coordinator."""
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def cluster_child_env(process_id: int, n_processes: int, port: int,
                      vdevs: Optional[int] = None,
                      extra: Optional[Dict[str, str]] = None) -> dict:
    """Environment for one child of the local CPU-mesh topology: the
    standard JAX cluster triple over a localhost coordinator, the TPU
    tunnel disarmed (`platform.cpu_subprocess_env` — a wedged relay
    otherwise hangs the child inside sitecustomize before any of our
    code runs), and an optional per-process virtual device count
    (`vdevs`, also exported as ``JGRAFT_BENCH_VDEVS`` so bench.py's
    cpu pin respects the split instead of raising it back to 8)."""
    env = cpu_subprocess_env()
    # The child pins its own platform/device count; an inherited
    # XLA_FLAGS count would override it (pin_cpu only ever raises).
    env.pop("XLA_FLAGS", None)
    env.update({
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": str(n_processes),
        "JAX_PROCESS_ID": str(process_id),
    })
    if vdevs:
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={vdevs}"
        env["JGRAFT_BENCH_VDEVS"] = str(vdevs)
    if extra:
        env.update(extra)
    return env


def launch_local_cluster(n_processes: int, command: Sequence[str],
                         vdevs: Optional[int] = None,
                         env_extra: Optional[Dict[str, str]] = None,
                         timeout_s: float = 1800.0) -> List[Tuple[int, str]]:
    """Run `command` as an N-process localhost cluster; returns one
    (returncode, combined-output) pair per process, in process order.
    Children that outlive `timeout_s` (wedged coordinator, a peer
    crashing out of a barrier) are killed with the timeout noted in
    their output — the launcher never hangs its caller, and no child
    survives this call (kill + reap on every path)."""
    port = free_coordinator_port()
    procs: List[subprocess.Popen] = []
    outs: List[Tuple[int, str]] = []
    try:
        for pid in range(n_processes):
            env = cluster_child_env(pid, n_processes, port, vdevs,
                                    env_extra)
            procs.append(subprocess.Popen(
                list(command), env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                out = (out or "") + f"\n[killed: no exit in {timeout_s:.0f}s]"
            outs.append((p.returncode, out))
        return outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def run_distributed_bench(argv: Sequence[str]) -> int:
    """Parent side of ``bench.py --distributed N``: strip the flag,
    spawn the N-process CPU-mesh topology running the SAME bench argv,
    and forward process 0's output (the JSON-line contract — every
    process computes the globally merged counts, so one emitter
    suffices). The children's intended platform defaults to cpu (this
    launcher IS the CPU-mesh recipe; a pod runs bench.py per host
    without it), so the degraded-platform gate stays quiet unless the
    operator pinned something else. Exit: 0 when every process exited
    0, else 1 (with the failing processes' output tails on stderr)."""
    argv = list(argv)
    i = argv.index("--distributed")
    try:
        n = int(argv[i + 1])
        if n < 1:
            raise ValueError(n)
    except (IndexError, ValueError):
        print('{"metric": "histories_per_sec", "value": 0.0, '
              '"unit": "hist/s", "vs_baseline": 0.0, '
              '"error": "--distributed needs a positive process count"}',
              flush=True)
        return 2
    child_argv = [sys.executable, os.path.abspath(argv[0])] \
        + argv[1:i] + argv[i + 2:]
    vdevs = env_int("JGRAFT_DISTRIBUTED_VDEVS", max(1, 8 // n), minimum=1)
    extra: Dict[str, str] = {}
    if not os.environ.get("JGRAFT_BENCH_PLATFORM"):
        extra["JGRAFT_BENCH_PLATFORM"] = "cpu"
    outs = launch_local_cluster(n, child_argv, vdevs=vdevs, env_extra=extra)
    rc0, out0 = outs[0]
    sys.stdout.write(out0)
    sys.stdout.flush()
    failed = [pid for pid, (rc, _) in enumerate(outs) if rc != 0]
    for pid in failed:
        print(f"# distributed worker {pid} exited "
              f"{outs[pid][0]}:\n{outs[pid][1][-2000:]}",
              file=sys.stderr, flush=True)
    return 0 if not failed else 1

"""Device-mesh parallelism for the checker backend.

The reference's parallelism inventory (SURVEY.md §2.4) maps the
key-sharded `independent/checker` decomposition onto the batch dimension:
every history is an independent linearizability problem, so the natural
TPU scale-out is a 1-D mesh with the batch sharded across devices and the
verdict aggregation riding ICI collectives (`psum`), the role NCCL
all-reduce plays in the reference's world (SURVEY.md §5.8).
"""

from .mesh import (  # noqa: F401
    check_batch_sharded,
    make_mesh,
    sharded_batch_checker,
)

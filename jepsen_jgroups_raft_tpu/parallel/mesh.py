"""Batch-sharded checking over a `jax.sharding.Mesh`.

Design (TPU-first, per SURVEY.md §2.4/§5.8): histories are independent
problems, so the batch axis shards cleanly over a 1-D device mesh — the
analogue of the reference's per-key `independent/checker` decomposition
(reference workload/register.clj:106-117), with XLA inserting the
collectives. Two entry points:

  * `sharded_batch_checker` — `shard_map` over the mesh: each device scans
    its local shard with the vmapped frontier kernel (ops/linear_scan.py),
    then a `psum` over the mesh axis aggregates the verdict counts. This is
    the "full step" the driver dry-runs multi-chip.
  * `check_batch_sharded` — convenience wrapper: pads the batch to a
    multiple of the mesh size, lays out the input with `NamedSharding`,
    runs, and unpads.

Multi-host: the same mesh spans hosts transparently once
`jax.distributed.initialize` has run (see `parallel/distributed.py`);
in-slice traffic rides ICI, cross-host batch distribution rides DCN.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax ≥ 0.6: top-level export, replication check kwarg is check_vma
    from jax import shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_CHECK_KW = "check_rep"
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..history.packing import pad_batch_bucketed
from ..ops.dense_scan import make_dense_single_checker, scan_unroll
from ..ops.linear_scan import DEFAULT_N_CONFIGS, MAX_SLOTS, make_history_checker

BATCH_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = BATCH_AXIS,
              devices: Optional[list] = None) -> Mesh:
    """1-D mesh over the first `n_devices` of `devices` (default: ALL
    devices — in a multi-process runtime that is every process's
    devices, the global mesh of parallel/distributed.py; pass
    `jax.local_devices()` or use `local_mesh` for a host-local one)."""
    devs = jax.devices() if devices is None else list(devices)
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n]), (axis_name,))


def local_mesh(n_devices: Optional[int] = None,
               axis_name: str = BATCH_AXIS) -> Mesh:
    """1-D mesh over THIS process's devices only. Identical to
    `make_mesh` single-process; in a cluster it is the host-local ICI
    mesh the sharded wavefront fans out over (host numpy arrays can
    only be `device_put` onto addressable devices — a global-mesh
    sharding would reject them)."""
    return make_mesh(n_devices, axis_name, devices=jax.local_devices())


def launch_fan_out() -> bool:
    """Whether the chunked wavefront scheduler may spread a launch's
    rows over the device mesh (`chunk_sharding`). Default on: each
    group chunk then executes exactly like the legacy `shard_map` path
    — the chunk kernels are wrapped in an explicit batch-axis
    `shard_map` (ops/dense_scan._shard_chunk_fns), every device scans
    its row shard, and the per-event ops need no collectives — which on
    the 2-core north-star host is a measured ~2.2× over any
    single-device execution of the same work (mesh-sharded 116 s vs
    250 s unsharded monolithic; Python-level per-device group *slicing*
    was tried first and only reached ~1.4–1.6× overlap with round-robin
    collect bubbles on top, and jit GSPMD sharding propagation without
    the explicit wrap compiled a ~3× slower per-chunk program).
    JGRAFT_GROUP_DEVICES=0 forbids fan-out for ablation (whole-group,
    default-device launches); JGRAFT_GROUP_DEVICES=N caps the fan-out
    mesh at N devices (see `chunk_sharding`)."""
    return os.environ.get("JGRAFT_GROUP_DEVICES") != "0"


def chunk_sharding(n_devices: Optional[int] = None):
    """Batch-axis `NamedSharding` for the chunked wavefront scheduler's
    per-launch arrays (checker/schedule.py), spanning every default-
    backend device — or None (default single-device placement) when
    `launch_fan_out` is gated off or only one device exists. One
    sharding object serves every launch and every recompaction bucket:
    `jax.device_put` under it re-lays out any batch-leading array, so a
    shrinking active set stays mesh-wide without fresh placement
    policy. Groups dispatched asynchronously under the SAME sharding
    still pipeline: each device queues every live group's current
    chunk, so the host blocking on one group's flags never idles the
    ring — the pipelined-dispatch half of the ISSUE-3 tentpole.

    `JGRAFT_GROUP_DEVICES=N` (N ≥ 2) caps the mesh at the first N
    devices: the chunked path pays a per-launch partition rendezvous
    per device, so on hosts where devices are *virtual* (pin_cpu's
    host-platform device split — 8 vdevs sharing 2 physical cores) a
    snugger mesh buys the same core parallelism at a fraction of the
    per-launch overhead. 0 disables fan-out entirely; 1 is clamped to
    single-device placement (None).

    `n_devices` is the per-launch override the autotuner uses
    (checker/autotune.py `mesh_fanout`): it caps the mesh like the env
    knob but per call, so two window groups of one batch can fan out
    differently. The env knob still applies as the outer bound — an
    operator pinning JGRAFT_GROUP_DEVICES=0 must never get fanned-out
    launches from a stale persisted plan."""
    from ..platform import env_int

    if not launch_fan_out():
        return None
    # LOCAL devices only: the wavefront scheduler device_puts host
    # numpy slices under this sharding, which requires every shard to
    # be addressable — in a multi-process runtime each host fans its
    # row shard over its own ICI mesh (parallel/distributed.py owns
    # the cross-host split). Identical to jax.devices() single-process.
    devs = jax.local_devices()
    cap = env_int("JGRAFT_GROUP_DEVICES", len(devs), minimum=0)
    if n_devices is not None:
        cap = min(cap, max(int(n_devices), 0))
    devs = devs[:max(cap, 1)]
    if len(devs) < 2:
        return None
    return NamedSharding(Mesh(np.asarray(devs), (BATCH_AXIS,)),
                         P(BATCH_AXIS))


# jit caches per function object, so rebuilding the shard_map closure per
# call would recompile every launch; cache by (model identity, shapes, mesh).
_CACHE: dict = {}


def sharded_batch_checker(model, mesh: Mesh,
                          n_configs: int = DEFAULT_N_CONFIGS,
                          n_slots: int = MAX_SLOTS,
                          axis_name: str = BATCH_AXIS,
                          macro_p: Optional[int] = None):
    """Build fn(events:[B,E,5], real:[B] bool) ->
    (ok[B], overflow[B], n_valid, n_unknown).

    B must be a multiple of the mesh size (use `check_batch_sharded` for
    automatic padding). ok/overflow stay sharded over the batch axis;
    n_valid/n_unknown are scalar `psum` aggregates (the ICI collective).
    `real` masks padding rows out of the aggregates — EV_PAD histories are
    trivially valid, so counting them would silently inflate n_valid.
    `macro_p` selects the macro-event row format ([B, E_mac, 3+4·P];
    history/packing.py) — a distinct compiled shape, so it keys the
    kernel cache like every other bucketed dim.
    """
    # scan_unroll() in the key: the wrapped kernel bakes it in at trace
    # time (same invariant as every ops/ kernel cache).
    key = (*model.cache_key(), int(n_configs), int(n_slots),
           tuple(mesh.devices.flat), axis_name, scan_unroll(), macro_p)
    fn = _CACHE.get(key)
    if fn is not None:
        return fn

    single = make_history_checker(model, n_configs, n_slots, macro_p)
    vm = jax.vmap(single)

    def local_step(ev, real):  # ev: [B/n, E, 5] local shard
        ok, overflow = vm(ev)
        n_valid = jax.lax.psum(jnp.sum(ok & ~overflow & real), axis_name)
        n_unknown = jax.lax.psum(jnp.sum(overflow & real), axis_name)
        return ok, overflow, n_valid, n_unknown

    # check_vma=False (check_rep on older jax): the scan carry inside
    # the kernel starts from unvarying constants, which the replication
    # checker rejects even though the computation is per-shard
    # independent by construction.
    mapped = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name)),
        out_specs=(P(axis_name), P(axis_name), P(), P()),
        **{_SHARD_MAP_CHECK_KW: False},
    )
    fn = jax.jit(mapped)
    _CACHE[key] = fn
    return fn


def sharded_dense_checker(model, mesh: Mesh, kind: str, n_slots: int,
                          n_states: int, axis_name: str = BATCH_AXIS,
                          macro_p: Optional[int] = None):
    """Dense-bitset variant of `sharded_batch_checker`:
    fn(events [B,E,5], val_of [B,S], real [B] bool) -> (ok[B],
    overflow[B], n_valid, n_unknown). Same mesh layout; the per-history
    domain table (or the mask-mode dummy) and the padding mask shard with
    the batch; `macro_p` keys the macro-event row format."""
    key = ("dense", kind, *model.cache_key(), int(n_slots),
           int(n_states), tuple(mesh.devices.flat), axis_name,
           scan_unroll(), macro_p)
    fn = _CACHE.get(key)
    if fn is not None:
        return fn

    vm = jax.vmap(make_dense_single_checker(model, kind, n_slots, n_states,
                                            macro_p))

    def local_step(ev, val_of, real):
        ok, overflow = vm(ev, val_of)
        n_valid = jax.lax.psum(jnp.sum(ok & real), axis_name)
        n_unknown = jax.lax.psum(jnp.sum(overflow & real), axis_name)
        return ok, overflow, n_valid, n_unknown

    mapped = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=(P(axis_name), P(axis_name), P(), P()),
        **{_SHARD_MAP_CHECK_KW: False},
    )
    fn = jax.jit(mapped)
    _CACHE[key] = fn
    return fn


def _real_mask(B_real: int, B_padded: int) -> np.ndarray:
    """[B_padded] bool: True for real rows, False for EV_PAD padding."""
    mask = np.zeros((B_padded,), dtype=bool)
    mask[:B_real] = True
    return mask



def _run_once(model, events: np.ndarray, mesh: Mesh, n_configs: int,
              n_slots: int, macro_p: Optional[int] = None):
    """One sharded launch at a fixed frontier capacity, with mesh-size
    padding handled. B is bucketed (pow2+midpoint series) so escalation rungs
    (whose subset sizes vary run to run) hit the jit cache instead of
    recompiling per call."""
    axis_name = mesh.axis_names[0]
    events, _, B = pad_batch_bucketed(events, floor_e=None,
                                      multiple_b=mesh.devices.size)
    sharding = NamedSharding(mesh, P(axis_name, None, None))
    msharding = NamedSharding(mesh, P(axis_name))
    dev_events = jax.device_put(events, sharding)
    dev_mask = jax.device_put(_real_mask(B, events.shape[0]), msharding)
    fn = sharded_batch_checker(model, mesh, n_configs, n_slots, axis_name,
                               macro_p)
    ok, overflow, _, _ = fn(dev_events, dev_mask)
    # One sharded launch per rung; the ladder blocks here by design.
    return np.asarray(ok)[:B], np.asarray(overflow)[:B]  # lint: allow(host-sync)


def check_batch_sharded(model, events: np.ndarray, mesh: Optional[Mesh] = None,
                        n_configs: Optional[int] = None,
                        n_slots: int = MAX_SLOTS,
                        dense: Optional[tuple] = None,
                        defer: bool = False,
                        macro_p: Optional[int] = None):
    """Check a packed event batch across the mesh.

    events: [B, E, 5] int32 (history/packing.py layout), or a macro
    batch [B, E_mac, 3+4·P] with `macro_p=P` (pack_macro_batch). Pads B
    up to a multiple of the mesh size with EV_PAD histories (trivially
    valid, no FORCE events → sliced off afterwards). Returns (ok[B],
    overflow[B], n_valid, n_unknown) host values corrected for padding.

    `defer=True` returns a zero-arg finalizer instead: the dense-plan
    launch is dispatched asynchronously and the finalizer blocks for the
    host values — callers with several window groups launch them all and
    block once, so a tunneled chip pipelines the groups instead of paying
    a round trip per group (the capacity ladder must block per rung to
    decide escalation, so its finalizer is pre-resolved).

    `dense` — a `ops.dense_scan.DensePlan` — routes the batch to the
    dense-bitset kernel (domain or mask mode): exact, ladder-free, ~10×+
    on small-domain / order-independent workloads.

    Capacity ladder otherwise (unless `n_configs` pins one rung): kernel
    cost is linear in the frontier capacity and "valid" at small capacity
    is final (overflow can only lose configurations — false-INVALID,
    never false-VALID), so the whole batch runs at C=64 and only the
    overflowed minority re-runs at full capacity.
    """
    mesh = mesh or make_mesh()
    if dense is not None:
        axis_name = mesh.axis_names[0]
        events, (val_of,), B = pad_batch_bucketed(
            events, (dense.val_of,), floor_e=None,
            multiple_b=mesh.devices.size)
        sharding = NamedSharding(mesh, P(axis_name, None, None))
        vsharding = NamedSharding(mesh, P(axis_name, None))
        msharding = NamedSharding(mesh, P(axis_name))
        fn = sharded_dense_checker(model, mesh, dense.kind, dense.n_slots,
                                   dense.n_states, axis_name, macro_p)
        mask = _real_mask(B, events.shape[0])
        ok, overflow, n_valid, _ = fn(jax.device_put(events, sharding),
                                      jax.device_put(val_of, vsharding),
                                      jax.device_put(mask, msharding))

        def finalize(ok=ok, n_valid=n_valid, B=B):
            return (np.asarray(ok)[:B], np.zeros((B,), bool),
                    int(n_valid), 0)

        return finalize if defer else finalize()
    ladder = ([n_configs] if n_configs else
              [64, DEFAULT_N_CONFIGS] if DEFAULT_N_CONFIGS > 64
              else [DEFAULT_N_CONFIGS])
    B = events.shape[0]
    ok = np.zeros((B,), dtype=bool)
    overflow = np.zeros((B,), dtype=bool)
    remaining = np.arange(B)
    for rung, C in enumerate(ladder):
        r_ok, r_ovf = _run_once(model, events[remaining], mesh, C, n_slots,
                                macro_p)
        ok[remaining] = r_ok
        overflow[remaining] = r_ovf
        # escalate only undecided rows: overflowed AND not proven valid
        escalate = remaining[r_ovf & ~r_ok]
        if rung + 1 >= len(ladder) or escalate.size == 0:
            break
        remaining = escalate
    # ok counts as valid even when the frontier overflowed: the witnessed
    # linearization is real. Only overflowed-and-not-ok is undecided.
    n_valid = int(np.sum(ok))
    n_unknown = int(np.sum(overflow & ~ok))
    out = (ok, overflow, n_valid, n_unknown)
    return (lambda: out) if defer else out

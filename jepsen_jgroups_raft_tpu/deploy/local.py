"""Localhost multi-process cluster: the native SUT under real-fault tests.

Capability equivalent of the reference's Server DB record
(server.clj:164-222) with the docker/LXC node replaced by a local process:
  setup      → spawn raft_server with the member list (start-daemon
               analogue, server.clj:147-156), block on the client port
               (server.clj:158-161)
  kill       → SIGKILL until dead (definitely-stop!, server.clj:119-127)
  pause      → SIGSTOP / SIGCONT (grepkill! :stop/:cont, server.clj:221-222)
  primaries  → probe every member's local leader view, dedupe
               (server.clj:188-196); may return 2+ during partitions
  log files  → per-node server.log (server.clj:181-183)
  membership → consensus add/remove through an alive member — what the
               reference does by shelling the jgroups-raft CLI over SSH
               (membership.clj:22-35,57-60,96-98)

Partitions use the server's transport-level block hook (BlockNet): the same
bidirectional packet cut an iptables grudge produces, injectable without
root. For real multi-host clusters deploy.ssh provides the iptables path.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import tempfile
import time
from pathlib import Path
from typing import Dict, Iterable, Optional, Tuple

from ..core.db import Net
from ..native import SERVER_BIN, ensure_built
from ..native.client import CONN_ERRORS, NativeConn, make_conn_factory
from .base import RaftDB


def _free_ports(n: int) -> list:
    """`n` distinct free ports. All probe sockets stay OPEN until every
    port is collected: closing each probe before the next bind lets the
    kernel recycle a just-freed port into a later probe of the SAME
    allocation — a 120-run hell campaign dealt one 7-node cluster
    duplicate client ports exactly that way (round-5 finding; the node
    died at bind and setup timed out)."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            # adopt into the cleanup list BEFORE bind: a bind that
            # raises used to leak the just-created socket (created but
            # not yet listed — graftcheck flow-resource-leak finding).
            socks.append(s)
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()




def wait_for_port(host: str, port: int, timeout: float = 20.0) -> None:
    """Block until the node's client port accepts — the harness's
    "port bound implies the channel connected" liveness gate
    (server.clj:158-161, await 20 s server.clj:92-101)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=0.5):
                return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"port {host}:{port} not up after {timeout}s")


class LocalCluster:
    """Allocates ports, spawns/kills raft_server processes, and resolves
    node names for clients."""

    def __init__(self, names: Iterable[str], sm: str = "map",
                 workdir: Optional[str] = None, election_ms: int = 150,
                 heartbeat_ms: int = 50, repl_timeout_ms: int = 10000,
                 host: str = "127.0.0.1", server_bin: Optional[str] = None,
                 compact_every: int = 0):
        ensure_built()
        self.server_bin = str(server_bin or SERVER_BIN)
        self.host = host
        self.sm = sm
        self.election_ms = election_ms
        self.heartbeat_ms = heartbeat_ms
        self.repl_timeout_ms = repl_timeout_ms
        self.compact_every = compact_every
        self.workdir = Path(workdir or tempfile.mkdtemp(prefix="raft-sut-"))
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.ports: Dict[str, Tuple[int, int]] = {}
        self.procs: Dict[str, subprocess.Popen] = {}
        names = list(names)
        # One batched allocation: every probe socket held open until all
        # ports are dealt, so no two nodes of THIS batch can receive the
        # same port. Later batches (grow-added members) re-check against
        # the recorded ports in _alloc.
        ports = _free_ports(2 * len(names))
        for i, n in enumerate(names):
            self.ports[n] = (ports[2 * i], ports[2 * i + 1])

    def _alloc(self, name: str) -> None:
        if name in self.ports:
            return
        # Late-added member (grow nemesis): a fresh batch can be dealt a
        # port RECORDED for a currently-dead node (its sockets are
        # unbound, so the kernel may reuse them) — colliding the moment
        # the kill nemesis restarts that node. Retry until disjoint.
        taken = {p for pair in self.ports.values() for p in pair}
        for _ in range(64):
            pair = tuple(_free_ports(2))
            if not taken & set(pair):
                self.ports[name] = pair
                return
        raise RuntimeError(f"no ports disjoint from {sorted(taken)}")

    def spec(self, name: str) -> str:
        self._alloc(name)
        cport, pport = self.ports[name]
        return f"{name}={self.host}:{cport}:{pport}"

    def resolve(self, name: str) -> Tuple[str, int]:
        self._alloc(name)
        return self.host, self.ports[name][0]

    def log_path(self, name: str) -> Path:
        return self.workdir / f"{name}.log"

    # ---- lifecycle ------------------------------------------------------

    def running(self, name: str) -> bool:
        p = self.procs.get(name)
        return p is not None and p.poll() is None

    def start_node(self, name: str, members: Iterable[str],
                   wait: bool = True) -> str:
        """Idempotent start (skip if already running, server.clj:143-146).
        `members` is the node-name set; the member list passed to the
        daemon is members ∪ {self} (server.clj:136-140). Returns
        :already-running / :started for the Kill-protocol's restart
        classification (server.clj:199-214)."""
        if self.running(name):
            return "already-running"
        names = sorted(set(members) | {name})
        members_arg = ",".join(self.spec(n) for n in names)
        # `with`: a Popen that raises (missing/denied binary) used to
        # leak the log handle (graftcheck flow-resource-leak finding);
        # the spawned child keeps its own dup of the fd.
        with open(self.log_path(name), "ab") as log:
            self.procs[name] = subprocess.Popen(
                [self.server_bin, "--name", name, "--members", members_arg,
                 "--sm", self.sm, "--log-dir", str(self.workdir / "raftlog"),
                 "--election-ms", str(self.election_ms),
                 "--heartbeat-ms", str(self.heartbeat_ms),
                 "--repl-timeout-ms", str(self.repl_timeout_ms)]
                + (["--compact-every", str(self.compact_every)]
                   if self.compact_every else []),
                stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True)
        if wait:
            wait_for_port(*((self.resolve(name))))
        return "started"

    def _signal(self, name: str, sig: int) -> None:
        p = self.procs.get(name)
        if p is not None and p.poll() is None:
            try:
                os.kill(p.pid, sig)
            except ProcessLookupError:
                pass

    def kill_node(self, name: str) -> None:
        """SIGKILL until the process is gone (definitely-stop! loop,
        server.clj:119-127)."""
        p = self.procs.get(name)
        if p is None:
            return
        for _ in range(50):
            if p.poll() is not None:
                break
            try:
                os.kill(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                break
            time.sleep(0.02)
        p.wait()

    def pause_node(self, name: str) -> None:
        self._signal(name, signal.SIGSTOP)

    def resume_node(self, name: str) -> None:
        self._signal(name, signal.SIGCONT)

    def shutdown(self) -> None:
        for n in list(self.procs):
            self.kill_node(n)

    # ---- admin plane ----------------------------------------------------

    def admin(self, name: str, timeout: float = 3.0) -> NativeConn:
        host, port = self.resolve(name)
        return NativeConn(host, port, timeout)

    def probe(self, name: str, timeout: float = 2.0):
        """(leader, term) as seen by `name`; None if unreachable."""
        conn = None
        try:
            conn = self.admin(name, timeout)
            return conn.probe()
        except CONN_ERRORS:
            return None  # unreachable/restarting node: no local view
        finally:
            if conn is not None:
                conn.close()

    def views_probe(self):
        from .base import collect_views
        return collect_views(self.probe, self.procs)

    def conn_factory(self):
        return make_conn_factory(self.resolve)


class LocalRaftDB(RaftDB):
    """DB/Kill/Pause/Primary/LogFiles protocols over a LocalCluster."""

    def _alive(self, node):
        return self.cluster.running(node)

    def teardown(self, test, node):
        self.cluster.kill_node(node)
        # remove jar+logs analogue (server.clj:175-179): drop the raft log so
        # the next test starts clean
        logdir = self.cluster.workdir / "raftlog" / node
        if logdir.exists():
            for p in logdir.iterdir():
                p.unlink()

    def log_files(self, test, node):
        p = self.cluster.log_path(node)
        return [str(p)] if p.exists() else []


class BlockNet(Net):
    """Partition via the servers' transport-level block hook — the
    observable equivalent of jepsen.net's iptables grudge (bidirectional
    packet drop between the grudge's node sets)."""

    def __init__(self, cluster: LocalCluster):
        self.cluster = cluster

    def partition(self, test, grudge: dict) -> None:
        for node, enemies in grudge.items():
            if not enemies:
                continue
            try:
                conn = self.cluster.admin(node)
            except CONN_ERRORS:
                continue  # dead node: already cut off
            try:
                conn.admin_block(enemies)
            except CONN_ERRORS:
                pass  # mid-fault node: its transport is already cut
            finally:
                conn.close()

    def heal(self, test) -> None:
        nodes = set(test.get("members") or test["nodes"]) | set(
            self.cluster.procs)
        for node in sorted(nodes):
            try:
                conn = self.cluster.admin(node)
            except CONN_ERRORS:
                continue  # dead node: nothing to heal
            try:
                conn.admin_unblock()
            except CONN_ERRORS:
                pass  # node died mid-heal; restart clears blocks
            finally:
                conn.close()

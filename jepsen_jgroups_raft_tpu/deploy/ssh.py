"""SSH deployment tier: run the native SUT on real remote hosts.

Capability equivalent of the reference's remote-control surface
(jepsen.control + control.util, SURVEY.md §2.3): exec/upload over
ssh/scp subprocesses, daemonized server start with pid files
(cu/start-daemon! analogue, server.clj:147-156), loop-kill
(definitely-stop!, server.clj:119-127), SIGSTOP pause (grepkill!,
server.clj:221-222), and iptables partitions (jepsen.net's grudge
strategy) — management of a dedicated chain so healing never disturbs
other firewall rules.

Command construction is pure (module-level functions) so the control
logic is unit-testable without hosts; SshRemote is the thin executor.
Nodes are hostnames; the client port is fixed at 9000 like the
reference's hardcoded endpoint (server.clj:124,143,160), peers on 9100.
"""

from __future__ import annotations

import shlex
import subprocess
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from ..core.db import Net
from ..native import SERVER_BIN, ensure_built
from ..native.client import CONN_ERRORS, NativeConn, make_conn_factory
from .base import RaftDB

REMOTE_DIR = "/opt/raft"          # install dir (server.clj:25-32)
REMOTE_BIN = f"{REMOTE_DIR}/raft_server"
REMOTE_LOG = f"{REMOTE_DIR}/server.log"
REMOTE_PID = f"{REMOTE_DIR}/server.pid"
CLIENT_PORT = 9000
PEER_PORT = 9100
CHAIN = "JGRAFT_NEMESIS"          # dedicated iptables chain


def _paths(remote_dir: str):
    """(bin, log, pid) under a remote install dir — parameterized so a
    test tier can point nodes at a scratch dir instead of /opt/raft."""
    return (f"{remote_dir}/raft_server", f"{remote_dir}/server.log",
            f"{remote_dir}/server.pid")


# ---------------------------------------------------------------- commands
# Pure builders: each returns a shell line to run ON THE NODE.

def start_daemon_cmd(name: str, members_arg: str, sm: str,
                     election_ms: int, heartbeat_ms: int,
                     repl_timeout_ms: int,
                     remote_dir: str = REMOTE_DIR,
                     compact_every: int = 0) -> str:
    """Daemonize with nohup + pid file + log redirect (start-daemon!
    analogue). Idempotent: refuses if the pid file points at a live
    process (server.clj:143-146)."""
    rbin, rlog, rpid = _paths(remote_dir)
    argv = [
        rbin, "--name", name, "--members", members_arg, "--sm", sm,
        "--log-dir", f"{remote_dir}/raftlog",
        "--election-ms", str(election_ms),
        "--heartbeat-ms", str(heartbeat_ms),
        "--repl-timeout-ms", str(repl_timeout_ms)]
    if compact_every:
        argv += ["--compact-every", str(compact_every)]
    args = " ".join(shlex.quote(a) for a in argv)
    return (f"mkdir -p {remote_dir}/raftlog; "
            f"if [ -f {rpid} ] && kill -0 $(cat {rpid}) "
            f"2>/dev/null; then echo already-running; else "
            f"nohup {args} >> {rlog} 2>&1 & echo $! > {rpid}; "
            f"echo started; fi")


def kill_cmd(remote_dir: str = REMOTE_DIR) -> str:
    """SIGKILL until gone (definitely-stop! loop, server.clj:119-127)."""
    rpid = _paths(remote_dir)[2]
    return (f"if [ -f {rpid} ]; then "
            f"for i in $(seq 1 50); do "
            f"kill -0 $(cat {rpid}) 2>/dev/null || break; "
            f"kill -9 $(cat {rpid}) 2>/dev/null; sleep 0.1; done; "
            f"rm -f {rpid}; fi; echo killed")


def pause_cmd(remote_dir: str = REMOTE_DIR) -> str:
    return f"kill -STOP $(cat {_paths(remote_dir)[2]}); echo paused"


def resume_cmd(remote_dir: str = REMOTE_DIR) -> str:
    return f"kill -CONT $(cat {_paths(remote_dir)[2]}); echo resumed"


def teardown_cmd(remote_dir: str = REMOTE_DIR) -> str:
    """Remove binary + logs (server.clj:175-179)."""
    return f"rm -rf {remote_dir}; echo cleaned"


def iptables_setup_cmds() -> List[str]:
    """Create the dedicated chain and hook it into INPUT (idempotent)."""
    return [
        f"iptables -N {CHAIN} 2>/dev/null || true",
        f"iptables -C INPUT -j {CHAIN} 2>/dev/null || "
        f"iptables -I INPUT -j {CHAIN}",
    ]


def iptables_partition_cmds(enemies: Iterable[str]) -> List[str]:
    """DROP all packets from each enemy host — run on the grudge-holding
    node; with the same grudge mirrored on the enemy side this is the
    bidirectional cut jepsen's partitioner produces."""
    return [f"iptables -A {CHAIN} -s {shlex.quote(e)} -j DROP"
            for e in sorted(set(enemies))]


def iptables_heal_cmds() -> List[str]:
    return [f"iptables -F {CHAIN} 2>/dev/null || true"]


# ---------------------------------------------------------------- executor

class SshRemote:
    """Thin ssh/scp wrapper (jepsen.control's exec/upload)."""

    def __init__(self, host: str, user: str = "root",
                 key: Optional[str] = None, connect_timeout: int = 10):
        self.host = host
        self.user = user
        self.key = key
        self.connect_timeout = connect_timeout

    def _ssh_base(self) -> List[str]:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", "UserKnownHostsFile=/dev/null",
               "-o", f"ConnectTimeout={self.connect_timeout}"]
        if self.key:
            cmd += ["-i", self.key]
        cmd.append(f"{self.user}@{self.host}")
        return cmd

    def exec(self, shell_line: str, check: bool = True,
             timeout: float = 60.0) -> subprocess.CompletedProcess:
        proc = subprocess.run(self._ssh_base() + [shell_line],
                              capture_output=True, text=True,
                              timeout=timeout)
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"ssh {self.host}: {shell_line!r} failed "
                f"({proc.returncode}): {proc.stderr.strip()}")
        return proc

    def upload(self, local: str, remote: str, timeout: float = 120.0) -> None:
        cmd = ["scp", "-o", "StrictHostKeyChecking=no",
               "-o", "UserKnownHostsFile=/dev/null"]
        if self.key:
            cmd += ["-i", self.key]
        cmd += [local, f"{self.user}@{self.host}:{remote}"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(f"scp to {self.host} failed: "
                               f"{proc.stderr.strip()}")

    def download(self, remote: str, local: str,
                 timeout: float = 120.0) -> bool:
        cmd = ["scp", "-o", "StrictHostKeyChecking=no",
               "-o", "UserKnownHostsFile=/dev/null"]
        if self.key:
            cmd += ["-i", self.key]
        cmd += [f"{self.user}@{self.host}:{remote}", local]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
        return proc.returncode == 0


# ---------------------------------------------------------------- cluster

class RemoteRaftCluster:
    """Remote-host cluster: node names ARE hostnames (the reference's
    --nodes-file model, doc/running.md:88)."""

    def __init__(self, nodes: Iterable[str], sm: str = "map",
                 ssh_user: str = "root", ssh_key: Optional[str] = None,
                 election_ms: int = 300, heartbeat_ms: int = 100,
                 repl_timeout_ms: int = 30000,
                 log_download_dir: Optional[str] = None,
                 remote_dir: str = REMOTE_DIR,
                 client_port: int = CLIENT_PORT,
                 peer_port: int = PEER_PORT,
                 compact_every: int = 0):
        ensure_built()
        self.nodes = list(nodes)
        self.sm = sm
        self.remote_dir = remote_dir
        self.client_port = client_port
        self.peer_port = peer_port
        self.election_ms = election_ms
        self.heartbeat_ms = heartbeat_ms
        self.repl_timeout_ms = repl_timeout_ms
        self.compact_every = compact_every
        self.remotes: Dict[str, SshRemote] = {
            n: SshRemote(n, user=ssh_user, key=ssh_key) for n in self.nodes}
        self.installed: set = set()
        self.log_download_dir = Path(log_download_dir or "store/node-logs")

    def remote(self, node: str) -> SshRemote:
        if node not in self.remotes:
            r0 = next(iter(self.remotes.values()))
            self.remotes[node] = SshRemote(node, user=r0.user, key=r0.key)
        return self.remotes[node]

    def spec(self, name: str) -> str:
        return f"{name}={name}:{self.client_port}:{self.peer_port}"

    def members_arg(self, names: Iterable[str]) -> str:
        return ",".join(self.spec(n) for n in sorted(set(names)))

    def resolve(self, name: str) -> Tuple[str, int]:
        return name, self.client_port

    def install(self, node: str) -> None:
        """Upload the server binary (install-server!, server.clj:60-65).
        The binary is built once on the control node (build-server!
        analogue — ensure_built in __init__)."""
        if node in self.installed:
            return
        r = self.remote(node)
        rbin = _paths(self.remote_dir)[0]
        r.exec(f"mkdir -p {self.remote_dir}")
        r.upload(str(SERVER_BIN), rbin)
        r.exec(f"chmod +x {rbin}")
        for cmd in iptables_setup_cmds():
            r.exec(cmd, check=False)
        self.installed.add(node)

    def start_node(self, name: str, members: Iterable[str]) -> str:
        self.install(name)
        out = self.remote(name).exec(start_daemon_cmd(
            name, self.members_arg(set(members) | {name}), self.sm,
            self.election_ms, self.heartbeat_ms, self.repl_timeout_ms,
            remote_dir=self.remote_dir,
            compact_every=self.compact_every))
        return out.stdout.strip()

    def kill_node(self, name: str) -> None:
        self.remote(name).exec(kill_cmd(self.remote_dir), check=False)

    def pause_node(self, name: str) -> None:
        self.remote(name).exec(pause_cmd(self.remote_dir), check=False)

    def resume_node(self, name: str) -> None:
        self.remote(name).exec(resume_cmd(self.remote_dir), check=False)

    def probe(self, name: str, timeout: float = 2.0):
        conn = None
        try:
            conn = NativeConn(name, self.client_port, timeout)
            return conn.probe()
        except CONN_ERRORS:
            return None  # unreachable/rebooting node: no local view
        finally:
            if conn is not None:
                conn.close()

    def views_probe(self):
        from .base import collect_views
        return collect_views(self.probe, self.nodes)

    def admin(self, name: str, timeout: float = 15.0) -> NativeConn:
        return NativeConn(name, self.client_port, timeout)

    def conn_factory(self):
        return make_conn_factory(self.resolve)

    def shutdown(self) -> None:
        for n in self.nodes:
            try:
                self.kill_node(n)
            except (OSError, subprocess.SubprocessError):
                pass  # ssh unreachable/timed out: node is dying anyway


class RemoteRaftDB(RaftDB):
    """Same protocol surface as LocalRaftDB, over SSH. Aliveness for
    membership routing is probe reachability (the base default)."""

    def setup(self, test, node):
        super().setup(test, node)
        from .local import wait_for_port
        wait_for_port(node, self.cluster.client_port, timeout=30.0)

    def teardown(self, test, node):
        self.cluster.kill_node(node)
        self.cluster.remote(node).exec(teardown_cmd(self.cluster.remote_dir),
                                       check=False)
        self.cluster.installed.discard(node)

    def log_files(self, test, node):
        """Download the node's server.log (db/LogFiles, server.clj:181-183)
        into this run's store directory when one exists."""
        root = Path(test.get("store_dir") or self.cluster.log_download_dir)
        dest = root / "node-logs" / f"{node}-server.log"
        dest.parent.mkdir(parents=True, exist_ok=True)
        rlog = _paths(self.cluster.remote_dir)[1]
        if self.cluster.remote(node).download(rlog, str(dest)):
            return [str(dest)]
        return []


class IptablesNet(Net):
    """Real-packet partitions: DROP rules in the dedicated chain on both
    sides of the grudge (jepsen.net's bidirectional cut)."""

    def __init__(self, cluster: RemoteRaftCluster):
        self.cluster = cluster

    def partition(self, test, grudge: dict) -> None:
        for node, enemies in grudge.items():
            if not enemies:
                continue
            r = self.cluster.remote(node)
            for cmd in iptables_partition_cmds(enemies):
                try:
                    r.exec(cmd, check=False)
                except (OSError, subprocess.SubprocessError):
                    pass  # dead node is already cut off

    def heal(self, test) -> None:
        # Flush EVERY node, not just current members: a node removed from
        # membership while DROP rules were active would otherwise come back
        # permanently partitioned when re-added.
        nodes = set(test["nodes"]) | set(test.get("members") or ())
        for node in sorted(nodes):
            r = self.cluster.remote(node)
            for cmd in iptables_heal_cmds():
                try:
                    r.exec(cmd, check=False)
                except (OSError, subprocess.SubprocessError):
                    pass  # unreachable node heals when it returns

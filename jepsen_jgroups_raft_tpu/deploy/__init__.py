"""Deployment tier: run the native SUT as real OS processes.

Equivalent of the reference's node-lifecycle layer (src/jepsen/jgroups/
server.clj) — install/start/stop/kill/pause daemons, probe leaders, collect
logs — with two backends:

  deploy.local  — every "node" is a local process (the §4 implication (b)
                  fake cluster: real processes, real sockets, real signals,
                  no SSH), faults injected via signals + the transport-level
                  block hook.
  deploy.ssh    — remote control over ssh/scp subprocesses (jepsen.control
                  analogue) for real multi-host clusters.
"""

"""Shared DB-protocol implementation over a raft cluster object.

Both deployment tiers (local processes, ssh remote hosts) expose the same
cluster contract — start_node/kill_node/pause_node/resume_node, probe,
admin, spec — so the jepsen.db protocol family (reference
server.clj:164-222) is implemented once here and parameterized by the
cluster. Tier subclasses override only what genuinely differs: readiness
waits, teardown cleanup, and log collection.
"""

from __future__ import annotations

import inspect
import random
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from ..core.db import DB


def collect_views(probe, members, timeout: float = 0.75) -> list:
    """[(node, leader, term)] for every reachable member — the snapshot
    the opt-in majority election checker consumes. Shared by both
    cluster tiers (`views_probe` on LocalCluster / RemoteRaftCluster);
    unreachable or leaderless nodes are absent, which is the tolerated
    staleness case.

    Probes run CONCURRENTLY with a sub-second per-node timeout: a views
    op runs inside a worker's operation slot, and sequential 2 s-default
    probes of a 5-node partitioned cluster would block that worker ~10 s
    — past the workloads' operation timeout, skewing op mix and latency
    stats during faults (round-3 advisor finding).

    `probe` contract: ``probe(node) -> (leader, term) | None``; a
    ``timeout=`` keyword is passed when the callable accepts one (both
    in-repo cluster probes do), otherwise the probe's own default
    timeout applies (ADVICE r4: external probes without the kwarg must
    not TypeError)."""
    members = list(members)
    if not members:
        return []
    try:
        sig = inspect.signature(probe)
        takes_timeout = "timeout" in sig.parameters or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values())
    except (TypeError, ValueError):  # signature-opaque (C/builtin):
        takes_timeout = True         # optimistic, with call-time retry

    def call(n, _tt=takes_timeout):
        if _tt:
            try:
                return probe(n, timeout=timeout)
            except TypeError:
                # Signature-opaque callable that turned out not to take
                # the kwarg (round-5 review: introspection alone still
                # crashed exactly the case the fix targets). A genuine
                # TypeError from inside a timeout-taking probe re-raises
                # below on the retry.
                pass
        return probe(n)

    with ThreadPoolExecutor(max_workers=len(members)) as pool:
        views = pool.map(call, members)
    out = []
    for n, v in zip(members, views):
        if v is not None and v[0] is not None:
            out.append((n, v[0], int(v[1])))
    return out


class RaftDB(DB):
    def __init__(self, cluster, seed: Optional[int] = None):
        self.cluster = cluster
        self.rng = random.Random(seed)

    def _members(self, test) -> List[str]:
        ms = test.get("members")
        return sorted(ms) if ms else list(test["nodes"])

    def _alive(self, node: str) -> bool:
        """Is the node worth routing an admin op through? Overridden per
        tier (process liveness locally; probe reachability remotely)."""
        return self.cluster.probe(node) is not None

    # ---- lifecycle -------------------------------------------------------

    def setup(self, test, node):
        self.cluster.start_node(node, set(self._members(test)) | {node})

    def kill(self, test, node):
        self.cluster.kill_node(node)

    def start(self, test, node):
        self.cluster.start_node(node, set(self._members(test)) | {node})

    def pause(self, test, node):
        self.cluster.pause_node(node)

    def resume(self, test, node):
        self.cluster.resume_node(node)

    # ---- Primary ---------------------------------------------------------

    def primaries(self, test):
        """Every member's local leader view, deduped non-null — may
        legitimately return 2+ during partitions (server.clj:188-196)."""
        views = []
        for n in self._members(test):
            view = self.cluster.probe(n)
            if view is not None and view[0] and view[0] not in views:
                views.append(view[0])
        return views

    # ---- membership via consensus through an alive member ---------------
    # (the CLI-over-SSH path, membership.clj:22-35; kill-before-remove and
    # majority guards live in the nemesis)

    def _via(self, test, exclude=()) -> Optional[str]:
        candidates = [n for n in self._members(test)
                      if n not in exclude and self._alive(n)]
        return self.rng.choice(candidates) if candidates else None

    def add_member(self, test, node):
        via = self._via(test, exclude={node})
        if via is None:
            raise RuntimeError("no alive member to run add through")
        conn = self.cluster.admin(via, timeout=15.0)
        try:
            conn.admin_add(self.cluster.spec(node))
        finally:
            conn.close()

    def remove_member(self, test, node):
        via = self._via(test, exclude={node})
        if via is None:
            raise RuntimeError("no alive member to run remove through")
        conn = self.cluster.admin(via, timeout=15.0)
        try:
            conn.admin_remove(node)
        finally:
            conn.close()

"""Command-line entry point: run tests, browse results.

Equivalent of the reference's CLI layer (src/jepsen/jgroups/raft.clj:94-101
wiring jepsen.cli/run! with single-test-cmd + serve-cmd):

  python -m jepsen_jgroups_raft_tpu test  [flags]   — compose + run a test
  python -m jepsen_jgroups_raft_tpu serve [flags]   — results web server

Flags mirror the reference's cli-opts (raft.clj:14-51) plus the jepsen
built-ins the docs exercise (--node/--nodes-file, --concurrency,
--time-limit, --test-count; doc/running.md:88,152). The state machine is
selected from the workload exactly like identify-state-machine
(server.clj:103-109). Exit status is 0 iff every run's history verified
(jepsen.cli behavior: a failed analysis fails the command).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core.compose import DEFAULTS, compose_test
from .core.runner import run_test
from .nemesis.package import FAULTS, SCHEDULES, SPECIALS
from .workload import WORKLOADS

# workload → native state machine (identify-state-machine, server.clj:103-109)
# The scenario tier's set/queue live in one register of the replicated
# map (CAS retry loops — workload/set.py, workload/queue.py), so they
# ride the "map" SM on every deployment tier.
WORKLOAD_SM = {
    "single-register": "map",
    "multi-register": "map",
    "counter": "counter",
    "election": "election",
    "set": "map",
    "queue": "map",
}


def _add_test_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--workload", "-w", default=DEFAULTS["workload"],
                   choices=sorted(WORKLOADS),
                   help="workload name (raft.clj:29-33)")
    p.add_argument("--nemesis", default=None,
                   help="comma-separated faults %s, workload-paired "
                        "schedules %s, or special %s "
                        "(raft.clj:35-39, nemesis.clj:8-29); set/queue "
                        "default to their paired schedule when omitted"
                        % (sorted(FAULTS), sorted(SCHEDULES),
                           sorted(SPECIALS)))
    p.add_argument("--rate", type=float, default=DEFAULTS["rate"],
                   help="approximate ops/sec (raft.clj:19-22)")
    p.add_argument("--ops-per-key", type=int, default=DEFAULTS["ops_per_key"],
                   help="op cap per key (raft.clj:24-27)")
    p.add_argument("--interval", type=float, default=DEFAULTS["interval"],
                   help="seconds between nemesis ops (raft.clj:41-44)")
    p.add_argument("--operation-timeout", type=float,
                   default=DEFAULTS["operation_timeout"],
                   help="client op timeout, seconds (raft.clj:48-51)")
    p.add_argument("--stale-reads", action="store_true",
                   help="allow dirty local reads (raft.clj:14-17; "
                        "quorum_reads = not stale_reads, raft.clj:92)")
    p.add_argument("--weak-election", action="store_true",
                   help="election workload: drop back to the reference-"
                        "parity single-client model (leader.clj:58-62) "
                        "instead of the default cross-node majority "
                        "checker")
    p.add_argument("--time-limit", type=float, default=DEFAULTS["time_limit"],
                   help="main-phase duration, seconds")
    p.add_argument("--quiesce", type=float, default=DEFAULTS["quiesce"],
                   help="post-phase quiet period, seconds (raft.clj:86-90's "
                        "sleep 10)")
    p.add_argument("--concurrency", type=int, default=DEFAULTS["concurrency"],
                   help="client worker count")
    p.add_argument("--test-count", type=int, default=1,
                   help="number of runs")
    p.add_argument("--node", action="append", default=None,
                   help="node name (repeatable)")
    p.add_argument("--nodes-file", default=None,
                   help="file with one node name per line")
    p.add_argument("--store", default="store",
                   help="results directory root")
    p.add_argument("--algorithm", default="auto",
                   choices=["auto", "jax", "pallas", "cpu", "dfs", "race"],
                   help="linearizability engine (:algorithm :jax analogue; "
                        "race = kernel vs DFS, first finisher wins, the "
                        "knossos.competition analogue)")
    p.add_argument("--consistency", default="linearizable",
                   choices=["linearizable", "sequential", "session"],
                   help="consistency ladder rung for the workload's "
                        "frontier checker (checker/consistency.py): "
                        "weaker rungs drop real-time edges, keep "
                        "per-process order, and decide measurably "
                        "cheaper")
    p.add_argument("--platform", default=None,
                   choices=["cpu", "tpu"],
                   help="pin the JAX backend for checking (e.g. cpu when "
                        "no accelerator is reachable); default: JAX's "
                        "platform autodetection")
    p.add_argument("--deploy", default="local",
                   choices=["local", "inmemory", "ssh"],
                   help="SUT deployment tier: local native processes, "
                        "in-process fake, or ssh remote hosts")
    p.add_argument("--ssh-user", default="root")
    p.add_argument("--ssh-private-key", default=None,
                   help="identity file for the ssh tier (running.md:88)")
    p.add_argument("--election-ms", type=int, default=300)
    p.add_argument("--heartbeat-ms", type=int, default=100)
    p.add_argument("--repl-timeout-ms", type=int, default=30000,
                   help="server-side replication timeout "
                        "(server/src/jgroups/raft/server.clj:37)")
    p.add_argument("--compact-every", type=int, default=0,
                   help="server snapshots + compacts its log after this "
                        "many applied entries (0 = off); lagging/new "
                        "members catch up via InstallSnapshot")


def _nodes_from(args) -> list:
    if args.node:
        return list(args.node)
    if args.nodes_file:
        lines = Path(args.nodes_file).read_text().splitlines()
        return [ln.strip() for ln in lines if ln.strip()]
    return [f"n{i}" for i in range(1, 6)]


def _build_deployment(args, nodes):
    """Returns (db, net, conn_factory, shutdown_fn)."""
    sm = WORKLOAD_SM[args.workload]
    if args.deploy == "inmemory":
        from .core.db import InMemoryDB, InMemoryNet
        from .sut.inmemory import InMemoryCluster, LatencyPlan
        cluster = InMemoryCluster(nodes, LatencyPlan())
        return (InMemoryDB(cluster), InMemoryNet(cluster), cluster.conn,
                cluster.shutdown)
    if args.deploy == "ssh":
        from .deploy.ssh import RemoteRaftCluster, RemoteRaftDB, IptablesNet
        cluster = RemoteRaftCluster(
            nodes, sm=sm, ssh_user=args.ssh_user,
            ssh_key=args.ssh_private_key,
            election_ms=args.election_ms, heartbeat_ms=args.heartbeat_ms,
            repl_timeout_ms=args.repl_timeout_ms,
            compact_every=args.compact_every)
        return (RemoteRaftDB(cluster), IptablesNet(cluster),
                cluster.conn_factory(), cluster.shutdown)
    from .deploy.local import BlockNet, LocalCluster, LocalRaftDB
    cluster = LocalCluster(
        nodes, sm=sm, election_ms=args.election_ms,
        heartbeat_ms=args.heartbeat_ms,
        repl_timeout_ms=args.repl_timeout_ms,
        compact_every=args.compact_every)
    return (LocalRaftDB(cluster), BlockNet(cluster), cluster.conn_factory(),
            cluster.shutdown)


def cmd_test(args) -> int:
    if args.platform:
        # Must land before the first backend initialization (the checker's
        # first device use); config update after `import jax` is fine.
        import jax
        jax.config.update("jax_platforms", args.platform)
    nodes = _nodes_from(args)
    ok = True
    for i in range(args.test_count):
        db, net, conn_factory, shutdown = _build_deployment(args, nodes)
        opts = {
            "nodes": nodes,
            "workload": args.workload,
            "nemesis": args.nemesis,
            "rate": args.rate,
            "ops_per_key": args.ops_per_key,
            "interval": args.interval,
            "operation_timeout": args.operation_timeout,
            "stale_reads": args.stale_reads,
            "time_limit": args.time_limit,
            "quiesce": args.quiesce,
            "concurrency": args.concurrency,
            "conn_factory": conn_factory,
            "store_root": args.store,
            "algorithm": args.algorithm,
            "consistency": args.consistency,
        }
        if args.workload == "election":
            # Default-on majority model: wired whenever the deployment
            # can snapshot every node's view (local + ssh clusters can);
            # --weak-election drops back to reference parity.
            opts["weak_election"] = args.weak_election
            probe = getattr(db, "cluster", None)
            probe = getattr(probe, "views_probe", None)
            if probe is not None:
                opts["views_probe"] = probe
        test = compose_test(opts, db=db, net=net)
        try:
            test = run_test(test)
        finally:
            shutdown()
        res = test["results"]
        # Strict: "unknown" (checker budget exceeded / checker crashed) is
        # NOT a pass — jepsen's CLI likewise fails the command on any
        # non-true analysis.
        verdict = res.get("valid?")
        valid = verdict is True
        ok = ok and valid
        label = {True: "VALID", False: "INVALID"}.get(verdict,
                                                      f"UNKNOWN ({verdict})")
        print(f"run {i + 1}/{args.test_count}: {label}  "
              f"store={test.get('store_dir')}")
        if not valid:
            print(json.dumps(res, indent=2, default=str)[:4000])
    # Everything looks good! ヽ('ー`)ノ — or not.
    print("Everything looks good!" if ok else "Analysis invalid! (ノಥ益ಥ)ノ")
    return 0 if ok else 1


def cmd_serve(args) -> int:
    from .core.serve import serve
    return serve(args.store, host=args.host, port=args.port)


def cmd_serve_checker(args) -> int:
    """graftd: the always-on multi-tenant checking daemon (service/) —
    queued admission, cross-request batching over the chunked scan,
    degrade-to-CPU resilience. Trace records land in the same store/
    layout the `serve` browser reads."""
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    from .service.http import serve_checker
    return serve_checker(store_root=args.store, host=args.host,
                         port=args.port, queue_capacity=args.queue,
                         batch_wait=(args.batch_wait_ms / 1000.0
                                     if args.batch_wait_ms is not None
                                     else None),
                         n_workers=args.workers,
                         cluster_dir=args.cluster_dir,
                         replica_id=args.replica_id)


def cmd_check(args) -> int:
    """Re-verify recorded runs: store → load → per-key split → one
    on-device batch (BASELINE config #3's shape). Accepts run dirs or
    store roots (every run dir beneath them)."""
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    run_dirs = []
    for p in args.paths:
        p = Path(p)
        if (p / "history.jsonl").exists():
            run_dirs.append(p)
        else:
            run_dirs.extend(sorted(
                d.parent for d in p.glob("**/history.jsonl")
                if not d.parent.name == "latest"))
    if not run_dirs:
        print("no run dirs (history.jsonl) found", file=sys.stderr)
        return 2
    from .checker.recorded import check_recorded
    summary = check_recorded(run_dirs, workload=args.workload,
                             algorithm=args.algorithm)
    print(json.dumps(summary, indent=2, default=str))
    return 0 if summary["valid?"] is True else 1


def cmd_search(args) -> int:
    """graftsearch (ISSUE 20): coverage-guided scenario search. Default
    mode runs the open-ended generation loop and prints the run report;
    --recall K plants K known violations first and reports
    found-vs-missed per CPU-minute."""
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    from .search.driver import SearchDriver, search_config_from_env
    from .search.recall import run_recall

    overrides = {}
    for flag, key in (("population", "population"),
                      ("generations", "generations"),
                      ("survivors", "survivors"),
                      ("edit_space", "edit_space"),
                      ("seed", "seed"),
                      ("corpus_dir", "corpus_dir")):
        v = getattr(args, flag)
        if v is not None:
            overrides[key] = v
    if args.arm is not None:
        overrides["guided"] = args.arm == "guided"
    overrides["families"] = tuple(
        f.strip() for f in args.families.split(",") if f.strip())
    overrides["consistency"] = args.consistency
    overrides["n_ops"] = args.n_ops
    if args.service_url:
        overrides["service_url"] = args.service_url
    cfg = search_config_from_env(**overrides)
    if args.recall:
        rep = run_recall(cfg, k=args.recall).to_dict()
    else:
        rep = SearchDriver(cfg).run()
    print(json.dumps(rep, indent=2, default=str))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="jepsen_jgroups_raft_tpu",
        description="TPU-native distributed-systems test harness")
    sub = ap.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("test", help="run a test (single-test-cmd analogue)")
    _add_test_flags(t)
    t.set_defaults(fn=cmd_test)
    s = sub.add_parser("serve", help="results web server (serve-cmd)")
    s.add_argument("--store", default="store")
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--port", type=int, default=8080)
    s.set_defaults(fn=cmd_serve)
    sc = sub.add_parser("serve-checker",
                        help="graftd: always-on multi-tenant checking "
                             "daemon (HTTP+JSON, cross-request batching)")
    sc.add_argument("--store", default="store",
                    help="trace-record root (browsable via `serve`)")
    sc.add_argument("--host", default="0.0.0.0")
    sc.add_argument("--port", type=int, default=8091)
    sc.add_argument("--queue", type=int, default=None,
                    help="admission queue capacity "
                         "(default: JGRAFT_SERVICE_QUEUE or 64)")
    sc.add_argument("--batch-wait-ms", type=int, default=None,
                    help="batch-formation linger "
                         "(default: JGRAFT_SERVICE_BATCH_WAIT_MS or 50)")
    sc.add_argument("--workers", type=int, default=None,
                    help="worker shards — one per host/device group; "
                         "batches route to the least-loaded shard "
                         "(default: JGRAFT_SERVICE_WORKERS or 1)")
    sc.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="pin the JAX backend for checking")
    sc.add_argument("--cluster-dir", default=None,
                    help="shared cluster directory (result store + "
                         "leases + per-replica journals; default: "
                         "JGRAFT_SERVICE_CLUSTER_DIR or single-replica)")
    sc.add_argument("--replica-id", default=None,
                    help="stable replica identity inside the cluster "
                         "dir (default: JGRAFT_SERVICE_REPLICA_ID; keep "
                         "it stable across restarts so the replica "
                         "replays its own journal)")
    sc.set_defaults(fn=cmd_serve_checker)
    se = sub.add_parser(
        "search",
        help="graftsearch: coverage-guided scenario search over graftd "
             "(mutation registry + verdict-signal fitness + minimized "
             "corpus under store/search/)")
    se.add_argument("--families",
                    default="register,set,queue,list-append",
                    help="comma-separated model families to search")
    se.add_argument("--population", type=int, default=None,
                    help="candidates per generation "
                         "(default: JGRAFT_SEARCH_POP or 48)")
    se.add_argument("--generations", type=int, default=None,
                    help="default: JGRAFT_SEARCH_GENERATIONS or 8")
    se.add_argument("--survivors", type=int, default=None,
                    help="survivor pool size "
                         "(default: JGRAFT_SEARCH_SURVIVORS or 12)")
    se.add_argument("--edit-space", type=int, default=None,
                    help="mutation edit-seed space "
                         "(default: JGRAFT_SEARCH_EDIT_SPACE or 24)")
    se.add_argument("--seed", type=int, default=None,
                    help="run seed (default: JGRAFT_SEARCH_SEED or 0); "
                         "same seed => identical corpus fingerprints")
    se.add_argument("--corpus-dir", default=None,
                    help="corpus root (default: JGRAFT_SEARCH_DIR or "
                         "store/search)")
    se.add_argument("--arm", choices=["guided", "random"], default=None,
                    help="override JGRAFT_SEARCH_GUIDED (random = the "
                         "blind-mutation ablation arm)")
    se.add_argument("--consistency", default="linearizable")
    se.add_argument("--n-ops", type=int, default=20,
                    help="base-history length per scenario")
    se.add_argument("--recall", type=int, default=None, metavar="K",
                    help="plant K known violations and report recall "
                         "per CPU-minute instead of open-ended search")
    se.add_argument("--service-url", default=None,
                    help="evaluate through a running graftd daemon "
                         "(binary frames for non-transactional "
                         "workloads); default: in-process service")
    se.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    se.set_defaults(fn=cmd_search)
    c = sub.add_parser("check",
                       help="re-verify recorded runs as one device batch")
    c.add_argument("paths", nargs="+",
                   help="run dirs or store roots to load")
    c.add_argument("--workload", default=None, choices=sorted(WORKLOADS),
                   help="override the workload recorded in test.json")
    c.add_argument("--algorithm", default="auto",
                   choices=["auto", "jax", "pallas", "cpu", "dfs", "race"])
    c.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    c.set_defaults(fn=cmd_check)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Client protocol.

Equivalent of jepsen.client/Client as the reference's workloads implement it
(reference register.clj:53-89, counter.clj:61-98, leader.clj:24-45):

  open(test, node)    — bind a fresh client instance to one node; called
                        once per worker thread. Returns the bound client.
  setup(test)         — one-time data-plane setup after open.
  invoke(test, op)    — execute one operation synchronously; return the
                        completed op (type ok/fail/info, value filled in).
                        Implementations raise client errors; the worker
                        wraps invoke in `with_errors` to apply the
                        definite/indefinite taxonomy.
  teardown(test)      — undo setup.
  close(test)         — release the connection.
"""

from __future__ import annotations

from ..history.ops import Op


class Client:
    def open(self, test: dict, node: str) -> "Client":
        return self

    def setup(self, test: dict) -> None:
        return None

    def invoke(self, test: dict, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        return None

    def close(self, test: dict) -> None:
        return None

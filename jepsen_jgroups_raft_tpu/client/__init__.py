"""Client protocol and error taxonomy.

Equivalent surface: jepsen.client/Client (open!/setup!/invoke!/teardown!/
close!) and the reference's error taxonomy (workload/client.clj).
"""

from .base import Client  # noqa: F401
from .errors import (  # noqa: F401
    ClientTimeout,
    ConnectFailed,
    NotLeader,
    SocketBroken,
    classify_error,
    with_errors,
)

"""Definite/indefinite error taxonomy.

Equivalent of the reference's workload/client.clj:6-63. The load-bearing
distinction: a **definite** failure means the op certainly did not execute
(safe to record ``fail`` — the checker drops it); an **indefinite** failure
means the op may have executed (must record ``info`` — the checker treats
it as forever-concurrent). Mis-classifying an indefinite error as definite
makes the checker unsound; the reverse merely slows it down (reference
doc/intro.md:35-41 — info ops are the checker-pressure problem).

Mapping mirrored from the reference (client.clj:14-44), translated to this
framework's exception vocabulary:
  timeout            → indefinite  (request may be executing server-side)
  connection refused → definite    (never reached a server)
  socket broken      → indefinite  (request may have been received)
  not-leader         → definite    (server rejected without executing)

Idempotent ops (reads/inspects — per-workload sets, reference
register.clj:72, counter.clj:80, leader.clj:39) are safe to record as
``fail`` even on indefinite errors: re-executing or not executing a read
changes nothing the model can observe.
"""

from __future__ import annotations

import socket
from typing import Iterable, Tuple

from ..history.ops import FAIL, INFO, Op


class ClientTimeout(TimeoutError):
    """Operation timed out — indefinite."""


class ConnectFailed(ConnectionError):
    """Could not reach the server — definite."""


class NotLeader(Exception):
    """Server refused because it is not the Raft leader — definite."""


class SocketBroken(OSError):
    """Connection died mid-request — indefinite."""


def classify_error(exc: BaseException) -> Tuple[bool, str, str]:
    """exception → (definite?, kind, description)."""
    if isinstance(exc, NotLeader):
        return True, "no-leader", str(exc) or "not the leader"
    if isinstance(exc, (ClientTimeout, TimeoutError, socket.timeout)):
        return False, "timeout", str(exc) or "operation timed out"
    if isinstance(exc, (ConnectFailed, ConnectionRefusedError)):
        return True, "connect", str(exc) or "connection refused"
    if isinstance(exc, (SocketBroken, ConnectionError, OSError)):
        return False, "socket", str(exc) or "socket error"
    raise exc  # not a client error: let it surface (jepsen rethrows too)


def with_errors(invoke_fn, test: dict, op: Op,
                idempotent: Iterable[str] = ()) -> Op:
    """Run ``invoke_fn(test, op)``; translate client errors into the
    completed-op taxonomy (reference client.clj:52-63): definite failure or
    idempotent op ⇒ ``fail``; otherwise ⇒ ``info``."""
    try:
        return invoke_fn(test, op)
    except BaseException as exc:  # classify_error re-raises non-client errs
        definite, kind, desc = classify_error(exc)
        ctype = FAIL if (definite or op.f in set(idempotent)) else INFO
        return op.replace(type=ctype, error=f"{kind}: {desc}")

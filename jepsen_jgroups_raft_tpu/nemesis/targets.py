"""Victim-class selection for fault targeting.

Equivalent of jepsen.nemesis.combined's target specs as configured by the
reference (nemesis.clj:48-58): partitions target
[:primaries :majority :majorities-ring :one]; kill/pause target
[:primaries :minority :one]. Node-set targets return a list of victim
nodes; partition targets return a *grudge* (node -> unreachable peers).
"""

from __future__ import annotations

import random
from typing import Dict, List, Sequence, Set

PARTITION_TARGETS = ("primaries", "majority", "majorities-ring", "one")
NODE_TARGETS = ("primaries", "minority", "one")


def pick_nodes(kind: str, nodes: Sequence[str], primaries: Sequence[str],
               rng: random.Random) -> List[str]:
    """Choose victim nodes for kill/pause faults."""
    nodes = list(nodes)
    if not nodes:
        return []
    if kind == "one":
        return [rng.choice(nodes)]
    if kind == "primaries":
        return [p for p in primaries if p in nodes] or [rng.choice(nodes)]
    if kind == "minority":
        k = max(1, (len(nodes) - 1) // 2)
        return rng.sample(nodes, k)
    if kind == "all":
        return nodes
    raise ValueError(f"unknown node target {kind!r}")


def complete_grudge(components: Sequence[Set[str]]) -> Dict[str, Set[str]]:
    """Components (disjoint node sets) -> symmetric grudge: every node
    refuses packets from every node outside its component."""
    grudge: Dict[str, Set[str]] = {}
    all_nodes = set().union(*components) if components else set()
    for comp in components:
        others = all_nodes - set(comp)
        for n in comp:
            grudge[n] = set(others)
    return grudge


def partition_grudge(kind: str, nodes: Sequence[str],
                     primaries: Sequence[str],
                     rng: random.Random) -> Dict[str, Set[str]]:
    """Build the grudge for a partition target kind."""
    nodes = list(nodes)
    if len(nodes) < 2:
        return {}
    if kind == "one":
        iso = rng.choice(nodes)
        return complete_grudge([{iso}, set(nodes) - {iso}])
    if kind == "primaries":
        iso = {p for p in primaries if p in nodes}
        if not iso or iso == set(nodes):
            iso = {rng.choice(nodes)}
        return complete_grudge([iso, set(nodes) - iso])
    if kind == "majority":
        shuffled = rng.sample(nodes, len(nodes))
        k = len(nodes) // 2 + 1
        return complete_grudge([set(shuffled[:k]), set(shuffled[k:])])
    if kind == "majorities-ring":
        return majorities_ring_grudge(nodes, rng)
    raise ValueError(f"unknown partition target {kind!r}")


def majorities_ring_grudge(nodes: Sequence[str],
                           rng: random.Random) -> Dict[str, Set[str]]:
    """Overlapping-majorities ring (jepsen nemesis/partition-majorities-ring):
    arrange nodes in a random ring; each node talks only to itself and the
    ⌊n/2⌋ nearest ring neighbors — every node sees a majority, but no two
    nodes see the same one. The nastiest partition for leader elections."""
    ring = rng.sample(list(nodes), len(nodes))
    n = len(ring)
    half = n // 2
    grudge: Dict[str, Set[str]] = {}
    for i, node in enumerate(ring):
        visible = {ring[(i + d) % n] for d in range(-(half // 2 + half % 2),
                                                    half // 2 + 1)}
        # ensure a strict majority including self
        j = 1
        while len(visible) <= n // 2:
            visible.add(ring[(i + j) % n])
            j += 1
        grudge[node] = set(ring) - visible
    return grudge

"""Fault injection.

Equivalent surface: jepsen.nemesis + jepsen.nemesis.combined as the
reference uses them (nemesis/nemesis.clj, nemesis/membership.clj):
partition / kill / pause / membership fault packages with targeted victim
classes, schedules, and final-generator healing.
"""

from .base import Nemesis, NoopNemesis, ComposedNemesis, compose_nemeses  # noqa: F401

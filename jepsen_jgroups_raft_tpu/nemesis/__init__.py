"""Fault injection.

Equivalent surface: jepsen.nemesis + jepsen.nemesis.combined as the
reference uses them (nemesis/nemesis.clj, nemesis/membership.clj):
partition / kill / pause / membership fault packages with targeted victim
classes, schedules, and final-generator healing.
"""

from .base import Nemesis, NoopNemesis, ComposedNemesis, compose_nemeses  # noqa: F401
from .faults import KillNemesis, PartitionNemesis, PauseNemesis  # noqa: F401
from .membership import GrowUntilFull, MemberNemesis  # noqa: F401
from .package import (  # noqa: F401
    FAULTS,
    Package,
    SPECIALS,
    compose_packages,
    kill_package,
    member_package,
    parse_nemesis_spec,
    partition_package,
    pause_package,
    setup_nemesis,
)
from .targets import (  # noqa: F401
    complete_grudge,
    majorities_ring_grudge,
    partition_grudge,
    pick_nodes,
)

"""Membership nemesis: grow/shrink the cluster at runtime.

Equivalent of the reference's nemesis/membership.clj — resize the cluster
"as a human operator would": issue a consensus add/remove through a live
member, update the shared membership set, and start/stop the node's
process. Guardrails mirrored from the reference:

  * never shrink below a majority of the full node set (membership.clj:37-40,
    80-81) — removing more would let the remnant lose quorum forever;
  * kill the node BEFORE removing it (membership.clj:87-92): a live node
    that processes its own removal can restart and fail to rejoin;
  * 15 s timeouts around both operations, converted into op values rather
    than harness crashes (membership.clj:50-51, 75-76, 118-135).

The generator is a staggered shrink/grow flip-flop (membership.clj:105-111);
the final generator grows the cluster back to full strength
(membership.clj:142-157).
"""

from __future__ import annotations

import random
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Optional

from ..generator.base import Generator
from ..history.ops import Op
from .base import Nemesis

GROW = "grow"
SHRINK = "shrink"


class MemberNemesis(Nemesis):
    fs = (GROW, SHRINK)

    def __init__(self, db, seed: Optional[int] = None,
                 op_timeout: float = 15.0):
        self.db = db
        self.rng = random.Random(seed)
        self.op_timeout = op_timeout
        # one worker: membership ops are serial anyway, and an abandoned
        # (timed-out) op must finish before the next one starts
        self._pool = ThreadPoolExecutor(1)
        #: nodes killed for a shrink whose remove AND rollback-start both
        #: failed: still listed in the members set (so GrowUntilFull sees
        #: a full membership and never re-grows them) but dead — teardown
        #: retries the restart so a run cannot end with a permanently
        #: dead voting member.
        self.unhealed: set = set()

    def invoke(self, test, op: Op) -> Op:
        if op.f == GROW:
            task = self._grow
        elif op.f == SHRINK:
            task = self._shrink
        else:
            raise ValueError(f"member nemesis: unknown f {op.f!r}")
        # Bounded like the reference's util/timeout wrappers
        # (membership.clj:50-51,75-76): a wedged consensus op becomes an
        # op value, never a stuck nemesis thread.
        fut = self._pool.submit(task, test)
        try:
            return op.replace(value=fut.result(self.op_timeout))
        except FutureTimeout:
            return op.replace(value={"error": f"timed out after "
                                              f"{self.op_timeout}s"})
        except Exception as e:  # convert failures into op values
            return op.replace(value={"error": repr(e)})

    def _grow(self, test):
        members = test["members"]
        spare = sorted(set(test["nodes"]) - set(members))
        if not spare:
            return "cluster is already full"
        node = self.rng.choice(spare)
        # Consensus add through a live member, then start the process
        # (membership.clj:47-70: add first so the joiner is a voting
        # member by the time it boots). The shared set is only updated
        # once the add committed; if the subsequent start fails the node
        # is still a (dead) voting member, so keep it in the set — the
        # final generator / kill-teardown restarts whatever is listed.
        self.db.add_member(test, node)
        members.add(node)
        self.db.start(test, node)
        return {"added": node, "members": sorted(members)}

    def _shrink(self, test):
        members = test["members"]
        majority = len(test["nodes"]) // 2 + 1
        if len(members) - 1 < majority:
            # membership.clj:37-40: refuse; the remnant could lose quorum.
            return "will not shrink below majority"
        node = self.rng.choice(sorted(members))
        # Kill BEFORE removing (membership.clj:87-92). Deliberately NOT
        # restarted on the success path: the node is leaving the cluster
        # dead, and the final generator (GrowUntilFull → grow → db.start)
        # is the healing side of the shrink/grow flip-flop.
        self.db.kill(test, node)  # lint: allow(unhealed)
        try:
            # Removal is healed by regrowth, not by an inline add_member:
            # GrowUntilFull re-adds removed nodes until the membership is
            # full again (the reference's final generator).
            self.db.remove_member(test, node)  # lint: allow(unhealed)
        except Exception:
            # Roll back the kill: without this, a failed remove leaves a
            # permanently-dead voting member that no healing path restarts
            # (GrowUntilFull sees the membership as full).
            try:
                self.db.start(test, node)
            except Exception:
                # Rollback failed too. Register the orphan so teardown
                # retries the restart — before this (graftcheck
                # flow-unhealed-fault finding) the node stayed a dead
                # voting member forever: still in `members`, so the
                # final generator never regrew it.
                self.unhealed.add(node)
            raise
        members.discard(node)
        return {"removed": node, "members": sorted(members)}

    def teardown(self, test):
        # wait=True: an abandoned (timed-out) op may still be running and
        # can register into self.unhealed at its end — retrying before it
        # finishes would miss that node (the op's own db calls are
        # timeout-bounded, so this terminates; same assumption as the
        # one-worker serialization note in __init__).
        self._pool.shutdown(wait=True)
        for node in sorted(self.unhealed):
            try:
                self.db.start(test, node)
                self.unhealed.discard(node)
            except Exception:
                pass  # node unreachable; nothing left to drive it with


class GrowUntilFull(Generator):
    """Generator: emit grow ops until the membership set is full
    (membership.clj final generator, bounded by the caller's time limit)."""

    def op(self, test, ctx):
        if set(test["members"]) >= set(test["nodes"]):
            return None
        return {"f": GROW, "value": None}, self

    def update(self, test, ctx, event):
        return self

"""Partition / kill / pause nemeses.

Equivalents of the jepsen.nemesis.combined partition-package and db-package
nemeses the reference composes (nemesis.clj:31-46). Each nemesis resolves
its victim class at invoke time (op.value carries the target kind) using
the DB's current primaries — matching how the combined packages re-probe
leaders per fault.
"""

from __future__ import annotations

import random
from typing import Optional

from ..history.ops import Op
from .base import Nemesis
from .targets import partition_grudge, pick_nodes


def _member_nodes(test) -> list:
    """Current live membership — fault targeting follows the shared
    membership set, not the static node list (raft.clj:70)."""
    if test.get("members"):
        return sorted(test["members"])
    return list(test["nodes"])


class PartitionNemesis(Nemesis):
    """start-partition / stop-partition via the Net boundary."""

    fs = ("start-partition", "stop-partition")

    def __init__(self, net, db=None, seed: Optional[int] = None):
        self.net = net
        self.db = db
        self.rng = random.Random(seed)

    def invoke(self, test, op: Op) -> Op:
        if op.f == "start-partition":
            kind = op.value or "majority"
            nodes = _member_nodes(test)
            primaries = self.db.primaries(test) if self.db else []
            grudge = partition_grudge(kind, nodes, primaries, self.rng)
            self.net.partition(test, grudge)
            cut = {n: sorted(g) for n, g in grudge.items() if g}
            return op.replace(value={"kind": kind, "grudge": cut})
        if op.f == "stop-partition":
            self.net.heal(test)
            return op.replace(value="healed")
        raise ValueError(f"partition nemesis: unknown f {op.f!r}")

    def teardown(self, test):
        # Never leave the network cut after a run.
        try:
            self.net.heal(test)
        except Exception:
            pass


class _DbToggleNemesis(Nemesis):
    """Shared shape of the DB-protocol fault pairs: a `start_f` op picks
    victims by target kind and applies `do`; a `stop_f` op applies `undo`
    to everything still afflicted (or every node, with value "all" — the
    final-generator heal); teardown undoes any leftovers."""

    start_f = ""
    stop_f = ""
    done_key = ""    # op-value key listing newly afflicted nodes
    undone_key = ""  # op-value key listing healed nodes

    def __init__(self, db, seed: Optional[int] = None):
        self.db = db
        self.rng = random.Random(seed)
        self.afflicted: set = set()

    @property
    def fs(self):  # type: ignore[override]
        return (self.start_f, self.stop_f)

    def _do(self, test, node):
        raise NotImplementedError

    def _undo(self, test, node):
        raise NotImplementedError

    def invoke(self, test, op: Op) -> Op:
        nodes = _member_nodes(test)
        if op.f == self.start_f:
            kind = op.value or "one"
            victims = pick_nodes(kind, nodes, self.db.primaries(test),
                                 self.rng)
            for n in victims:
                self._do(test, n)
                self.afflicted.add(n)
            return op.replace(value={"kind": kind, self.done_key: victims})
        if op.f == self.stop_f:
            targets = nodes if op.value == "all" else sorted(self.afflicted)
            undone = []
            for n in targets:
                self._undo(test, n)
                self.afflicted.discard(n)
                undone.append(n)
            return op.replace(value={self.undone_key: undone})
        raise ValueError(f"{self.start_f} nemesis: unknown f {op.f!r}")

    def teardown(self, test):
        for n in sorted(self.afflicted):
            try:
                self._undo(test, n)
            except Exception:
                pass
        self.afflicted.clear()


class KillNemesis(_DbToggleNemesis):
    """kill / restart via the DB's Kill protocol (db/kill! + db/start!,
    reference server.clj:198-218)."""

    start_f = "kill"
    stop_f = "restart"
    done_key = "killed"
    undone_key = "restarted"

    def _do(self, test, node):
        self.db.kill(test, node)

    def _undo(self, test, node):
        self.db.start(test, node)


class PauseNemesis(_DbToggleNemesis):
    """pause / resume via the DB's Pause protocol (SIGSTOP/SIGCONT,
    reference server.clj:221-222)."""

    start_f = "pause"
    stop_f = "resume"
    done_key = "paused"
    undone_key = "resumed"

    def _do(self, test, node):
        self.db.pause(test, node)

    def _undo(self, test, node):
        self.db.resume(test, node)

"""Partition / kill / pause nemeses.

Equivalents of the jepsen.nemesis.combined partition-package and db-package
nemeses the reference composes (nemesis.clj:31-46). Each nemesis resolves
its victim class at invoke time (op.value carries the target kind) using
the DB's current primaries — matching how the combined packages re-probe
leaders per fault.
"""

from __future__ import annotations

import random
from typing import Optional

from ..history.ops import Op
from .base import Nemesis
from .targets import partition_grudge, pick_nodes


def _member_nodes(test) -> list:
    """Current live membership — fault targeting follows the shared
    membership set, not the static node list (raft.clj:70)."""
    if test.get("members"):
        return sorted(test["members"])
    return list(test["nodes"])


class PartitionNemesis(Nemesis):
    """start-partition / stop-partition via the Net boundary."""

    fs = ("start-partition", "stop-partition")

    def __init__(self, net, db=None, seed: Optional[int] = None):
        self.net = net
        self.db = db
        self.rng = random.Random(seed)

    def invoke(self, test, op: Op) -> Op:
        if op.f == "start-partition":
            kind = op.value or "majority"
            nodes = _member_nodes(test)
            primaries = self.db.primaries(test) if self.db else []
            grudge = partition_grudge(kind, nodes, primaries, self.rng)
            self.net.partition(test, grudge)
            cut = {n: sorted(g) for n, g in grudge.items() if g}
            return op.replace(value={"kind": kind, "grudge": cut})
        if op.f == "stop-partition":
            self.net.heal(test)
            return op.replace(value="healed")
        raise ValueError(f"partition nemesis: unknown f {op.f!r}")

    def teardown(self, test):
        # Never leave the network cut after a run.
        try:
            self.net.heal(test)
        except Exception:
            pass


class KillNemesis(Nemesis):
    """kill / restart via the DB's Kill protocol (db/kill! + db/start!,
    reference server.clj:198-218). `restart` restarts everything the
    nemesis killed (and, with value "all", every node — the final-generator
    heal)."""

    fs = ("kill", "restart")

    def __init__(self, db, seed: Optional[int] = None):
        self.db = db
        self.rng = random.Random(seed)
        self.down: set = set()

    def invoke(self, test, op: Op) -> Op:
        nodes = _member_nodes(test)
        if op.f == "kill":
            kind = op.value or "one"
            victims = pick_nodes(kind, nodes, self.db.primaries(test),
                                 self.rng)
            for n in victims:
                self.db.kill(test, n)
                self.down.add(n)
            return op.replace(value={"kind": kind, "killed": victims})
        if op.f == "restart":
            targets = nodes if op.value == "all" else sorted(self.down)
            restarted = []
            for n in targets:
                self.db.start(test, n)
                self.down.discard(n)
                restarted.append(n)
            return op.replace(value={"restarted": restarted})
        raise ValueError(f"kill nemesis: unknown f {op.f!r}")

    def teardown(self, test):
        for n in sorted(self.down):
            try:
                self.db.start(test, n)
            except Exception:
                pass
        self.down.clear()


class PauseNemesis(Nemesis):
    """pause / resume via the DB's Pause protocol (SIGSTOP/SIGCONT,
    reference server.clj:221-222)."""

    fs = ("pause", "resume")

    def __init__(self, db, seed: Optional[int] = None):
        self.db = db
        self.rng = random.Random(seed)
        self.paused: set = set()

    def invoke(self, test, op: Op) -> Op:
        nodes = _member_nodes(test)
        if op.f == "pause":
            kind = op.value or "one"
            victims = pick_nodes(kind, nodes, self.db.primaries(test),
                                 self.rng)
            for n in victims:
                self.db.pause(test, n)
                self.paused.add(n)
            return op.replace(value={"kind": kind, "paused": victims})
        if op.f == "resume":
            targets = nodes if op.value == "all" else sorted(self.paused)
            resumed = []
            for n in targets:
                self.db.resume(test, n)
                self.paused.discard(n)
                resumed.append(n)
            return op.replace(value={"resumed": resumed})
        raise ValueError(f"pause nemesis: unknown f {op.f!r}")

    def teardown(self, test):
        for n in sorted(self.paused):
            try:
                self.db.resume(test, n)
            except Exception:
                pass
        self.paused.clear()
